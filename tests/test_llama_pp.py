"""Pipeline-parallel Llama: same params, same numbers as the scanned
model, trains under the Trainer with stage-sharded params."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tpucfn.mesh import MeshSpec, build_mesh
from tpucfn.models.llama import Llama, LlamaConfig, causal_lm_loss
from tpucfn.models.llama_pp import pipelined_llama_apply, pp_sharding_rules
from tpucfn.parallel import shard_batch
from tpucfn.train import Trainer


@pytest.fixture()
def mesh_pp4d2():
    return build_mesh(MeshSpec(pipeline=4, data=2))


def _cfg(n_layers=4):
    return dataclasses.replace(LlamaConfig.tiny(), n_layers=n_layers)


def _tokens(b=8, s=16, vocab=256, seed=0):
    rs = np.random.RandomState(seed)
    return rs.randint(0, vocab, (b, s)).astype(np.int32)


def test_pp_forward_matches_scanned(mesh_pp4d2):
    cfg = _cfg()
    model = Llama(cfg)
    toks = jnp.asarray(_tokens())
    params = model.init(jax.random.key(0), toks)["params"]
    ref = model.apply({"params": params}, toks)
    out = jax.jit(
        lambda p, t: pipelined_llama_apply(cfg, mesh_pp4d2, p, t, num_microbatches=4)
    )(params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_pp_requires_scanned_params(mesh_pp4d2):
    cfg = dataclasses.replace(_cfg(), scan_layers=False)
    with pytest.raises(ValueError, match="scan_layers"):
        pp_sharding_rules(cfg)


def test_pp_training_learns_with_stage_sharded_params(mesh_pp4d2):
    cfg = _cfg()
    model = Llama(cfg)
    sample = jnp.zeros((8, 16), jnp.int32)

    def init_fn(rng):
        return model.init(rng, sample)["params"], {}

    def loss_fn(params, mstate, batch, rng):
        logits = pipelined_llama_apply(cfg, mesh_pp4d2, params, batch["tokens"],
                                       num_microbatches=4)
        loss, acc = causal_lm_loss(logits, batch["tokens"])
        return loss, ({"accuracy": acc}, mstate)

    trainer = Trainer(mesh_pp4d2, pp_sharding_rules(cfg), loss_fn,
                      optax.adamw(3e-3), init_fn)
    state = trainer.init(jax.random.key(0))

    # block params live stage-sharded: 4 layers / pipeline=4 -> 1 per stage
    # (spec also carries fsdp/tensor entries — size-1 axes on this mesh)
    qk = state.params["layers"]["attn"]["q_proj"]["kernel"]
    assert qk.sharding.spec[0] == "pipeline"
    assert qk.addressable_shards[0].data.shape[0] == 1

    batch = shard_batch(mesh_pp4d2, {"tokens": _tokens()})
    first = None
    for _ in range(15):
        state, m = trainer.step(state, batch)
        first = first if first is not None else float(m["loss"])
    assert float(m["loss"]) < first * 0.9


def test_pp_gradients_match_scanned(mesh_pp4d2):
    cfg = _cfg()
    model = Llama(cfg)
    toks = jnp.asarray(_tokens(b=4))
    params = model.init(jax.random.key(1), toks)["params"]

    def loss_pp(p):
        logits = pipelined_llama_apply(cfg, mesh_pp4d2, p, toks, num_microbatches=2)
        return causal_lm_loss(logits, toks)[0]

    def loss_ref(p):
        return causal_lm_loss(model.apply({"params": p}, toks), toks)[0]

    g_pp = jax.jit(jax.grad(loss_pp))(params)
    g_ref = jax.jit(jax.grad(loss_ref))(params)
    qk_pp = np.asarray(g_pp["layers"]["attn"]["q_proj"]["kernel"])
    qk_ref = np.asarray(g_ref["layers"]["attn"]["q_proj"]["kernel"])
    np.testing.assert_allclose(qk_pp, qk_ref, atol=5e-4)
    emb_pp = np.asarray(g_pp["embed_tokens"]["embedding"])
    emb_ref = np.asarray(g_ref["embed_tokens"]["embedding"])
    np.testing.assert_allclose(emb_pp, emb_ref, atol=5e-4)


# ---- composition: PP × FSDP / TP / SP (VERDICT r1 item 5) ----------------


def _forward_on_mesh(mesh, cfg, params, toks, context_parallel=False, m=2):
    return jax.jit(
        lambda p, t: pipelined_llama_apply(cfg, mesh, p, t,
                                           num_microbatches=m,
                                           context_parallel=context_parallel)
    )(params, toks)


def _sharded_params(mesh, cfg, params):
    from tpucfn.parallel.sharding import named_sharding_tree

    return jax.device_put(params, named_sharding_tree(
        mesh, pp_sharding_rules(cfg), params))


def test_pp_fsdp_forward_matches_scanned():
    """Stage params additionally sharded over fsdp: XLA gathers on use
    inside the stage body (gather-on-use ZeRO-3)."""
    mesh = build_mesh(MeshSpec(pipeline=2, fsdp=2, data=2))
    cfg = _cfg()
    model = Llama(cfg)
    toks = jnp.asarray(_tokens())
    params = model.init(jax.random.key(0), toks)["params"]
    ref = model.apply({"params": params}, toks)
    sharded = _sharded_params(mesh, cfg, params)
    qk = sharded["layers"]["attn"]["q_proj"]["kernel"]
    # layer dim over pipeline AND model dim over fsdp
    assert qk.addressable_shards[0].data.shape[0] == cfg.n_layers // 2
    assert qk.addressable_shards[0].data.shape[1] == qk.shape[1] // 2
    out = _forward_on_mesh(mesh, cfg, sharded, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_pp_tensor_forward_matches_scanned():
    mesh = build_mesh(MeshSpec(pipeline=2, tensor=2, data=2))
    cfg = dataclasses.replace(_cfg(), n_heads=4, n_kv_heads=4)
    model = Llama(cfg)
    toks = jnp.asarray(_tokens())
    params = model.init(jax.random.key(0), toks)["params"]
    ref = model.apply({"params": params}, toks)
    out = _forward_on_mesh(mesh, cfg, _sharded_params(mesh, cfg, params), toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_pp_ring_context_forward_matches_scanned():
    """PP × SP: one manual region over {pipeline, context} — the stage
    body runs ring attention directly, RoPE offsets from axis_index."""
    mesh = build_mesh(MeshSpec(pipeline=2, context=2, data=2))
    cfg = _cfg()
    model = Llama(cfg)
    toks = jnp.asarray(_tokens(b=4, s=32))
    params = model.init(jax.random.key(0), toks)["params"]
    ref = model.apply({"params": params}, toks)
    out = _forward_on_mesh(mesh, cfg, _sharded_params(mesh, cfg, params), toks,
                           context_parallel=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_pp_ring_context_grads_match_scanned():
    """PP × SP gradients: the flat {pipeline, context} manual region
    transposes cleanly (the nested-shard_map form did not — see
    llama_pp.py docstring)."""
    mesh = build_mesh(MeshSpec(pipeline=2, context=2, data=2))
    cfg = _cfg()
    model = Llama(cfg)
    toks = jnp.asarray(_tokens(b=4, s=32))
    params = model.init(jax.random.key(1), toks)["params"]

    def loss_pp(p):
        logits = pipelined_llama_apply(cfg, mesh, p, toks, num_microbatches=2,
                                       context_parallel=True)
        return causal_lm_loss(logits, toks)[0]

    def loss_ref(p):
        return causal_lm_loss(model.apply({"params": p}, toks), toks)[0]

    g_pp = jax.jit(jax.grad(loss_pp))(params)
    g_ref = jax.jit(jax.grad(loss_ref))(params)
    np.testing.assert_allclose(
        np.asarray(g_pp["layers"]["attn"]["q_proj"]["kernel"]),
        np.asarray(g_ref["layers"]["attn"]["q_proj"]["kernel"]), atol=5e-4)


def test_pp_fsdp_tensor_training_matches_replicated():
    """Full composition under the Trainer: PP×FSDP×TP training step
    numerics equal the plain scanned model on a DP-only mesh."""
    cfg = dataclasses.replace(_cfg(), n_heads=4, n_kv_heads=4)
    model = Llama(cfg)
    sample = jnp.zeros((8, 16), jnp.int32)
    toks = _tokens()

    def init_fn(rng):
        return model.init(rng, sample)["params"], {}

    losses = {}
    for name, spec_kw, pp in [
        ("pp_fsdp_tp", dict(pipeline=2, fsdp=2, tensor=2), True),
        ("plain", dict(data=8), False),
    ]:
        mesh = build_mesh(MeshSpec(**spec_kw))

        if pp:
            def loss_fn(params, mstate, batch, rng, mesh=mesh):
                logits = pipelined_llama_apply(cfg, mesh, params,
                                               batch["tokens"],
                                               num_microbatches=2)
                loss, acc = causal_lm_loss(logits, batch["tokens"])
                return loss, ({"accuracy": acc}, mstate)
            rules = pp_sharding_rules(cfg)
        else:
            def loss_fn(params, mstate, batch, rng):
                logits = model.apply({"params": params}, batch["tokens"])
                loss, acc = causal_lm_loss(logits, batch["tokens"])
                return loss, ({"accuracy": acc}, mstate)
            from tpucfn.models.llama import sharding_rules as llama_rules
            rules = llama_rules(cfg)

        trainer = Trainer(mesh, rules, loss_fn, optax.adamw(3e-3), init_fn)
        state = trainer.init(jax.random.key(0))
        batch = shard_batch(mesh, {"tokens": toks})
        for _ in range(5):
            state, m = trainer.step(state, batch)
        losses[name] = float(m["loss"])
    np.testing.assert_allclose(losses["pp_fsdp_tp"], losses["plain"], rtol=2e-3)


def test_bubble_fraction():
    from tpucfn.parallel import bubble_fraction

    assert bubble_fraction(4, 4) == pytest.approx(3 / 7)
    assert bubble_fraction(32, 4) == pytest.approx(3 / 35)
    assert bubble_fraction(8, 1) == 0.0


# ---- 1F1B schedule (VERDICT r1 item 5: "GPipe/1F1B") ---------------------


def _grad_diff(g_a, g_b, path):
    a, b = g_a, g_b
    for k in path:
        a, b = a[k], b[k]
    return float(np.max(np.abs(np.asarray(a) - np.asarray(b))))


def test_1f1b_loss_and_grads_match_sequential():
    from tpucfn.models.llama_pp import pipelined_llama_value_and_grad

    mesh = build_mesh(MeshSpec(pipeline=4, data=2))
    cfg = _cfg()
    model = Llama(cfg)
    toks = jnp.asarray(_tokens())
    params = model.init(jax.random.key(1), toks)["params"]

    def loss_ref(p):
        return causal_lm_loss(model.apply({"params": p}, toks), toks)[0]

    l_ref, g_ref = jax.jit(jax.value_and_grad(loss_ref))(params)
    l_pp, g_pp = jax.jit(lambda p, t: pipelined_llama_value_and_grad(
        cfg, mesh, p, t, num_microbatches=4))(params, toks)

    np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-5)
    for path in [("layers", "attn", "q_proj", "kernel"),
                 ("layers", "mlp", "down_proj", "kernel"),
                 ("embed_tokens", "embedding"),
                 ("lm_head", "kernel"), ("final_norm", "scale")]:
        assert _grad_diff(g_pp, g_ref, path) < 1e-5, path


def test_1f1b_interleaved_matches_sequential():
    """Virtual-stage (interleaved) 1F1B on the real model: P=2 devices x
    V=2 chunks of 1 layer each, loss+grads == sequential (VERDICT r3 #8).
    The params tree is untouched — chunking happens inside the call."""
    from tpucfn.models.llama_pp import pipelined_llama_value_and_grad

    mesh = build_mesh(MeshSpec(pipeline=2, data=4))
    cfg = _cfg(n_layers=4)
    model = Llama(cfg)
    toks = jnp.asarray(_tokens())
    params = model.init(jax.random.key(1), toks)["params"]

    def loss_ref(p):
        return causal_lm_loss(model.apply({"params": p}, toks), toks)[0]

    l_ref, g_ref = jax.jit(jax.value_and_grad(loss_ref))(params)
    l_pp, g_pp = jax.jit(lambda p, t: pipelined_llama_value_and_grad(
        cfg, mesh, p, t, num_microbatches=4, num_virtual=2))(params, toks)

    np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-5)
    for path in [("layers", "attn", "q_proj", "kernel"),
                 ("layers", "mlp", "down_proj", "kernel"),
                 ("embed_tokens", "embedding"),
                 ("lm_head", "kernel"), ("final_norm", "scale")]:
        assert _grad_diff(g_pp, g_ref, path) < 1e-5, path


def test_1f1b_composes_with_fsdp_tp_and_context():
    from tpucfn.models.llama_pp import pipelined_llama_value_and_grad
    from tpucfn.parallel.sharding import named_sharding_tree

    for mesh_kw, cfg_kw, cp, s in [
        (dict(pipeline=2, fsdp=2, tensor=2), dict(n_heads=4, n_kv_heads=4),
         False, 16),
        (dict(pipeline=2, context=2, data=2), {}, True, 32),
    ]:
        cfg = dataclasses.replace(_cfg(), **cfg_kw)
        model = Llama(cfg)
        toks = jnp.asarray(_tokens(b=8, s=s))
        params = model.init(jax.random.key(1), toks)["params"]

        def loss_ref(p):
            return causal_lm_loss(model.apply({"params": p}, toks), toks)[0]

        l_ref, g_ref = jax.jit(jax.value_and_grad(loss_ref))(params)
        mesh = build_mesh(MeshSpec(**mesh_kw))
        sharded = jax.device_put(params, named_sharding_tree(
            mesh, pp_sharding_rules(cfg), params))
        l_pp, g_pp = jax.jit(lambda p, t: pipelined_llama_value_and_grad(
            cfg, mesh, p, t, num_microbatches=2, context_parallel=cp)
        )(sharded, toks)
        np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-5)
        assert _grad_diff(g_pp, g_ref,
                          ("layers", "attn", "q_proj", "kernel")) < 1e-5
        assert _grad_diff(g_pp, g_ref, ("embed_tokens", "embedding")) < 1e-5


def test_1f1b_more_micros_than_twice_stages():
    """M > 2P exercises stash-slot reuse (the 2P-1 ring buffer wraps)."""
    from tpucfn.models.llama_pp import pipelined_llama_value_and_grad

    mesh = build_mesh(MeshSpec(pipeline=2, data=4))
    cfg = _cfg(n_layers=2)
    model = Llama(cfg)
    toks = jnp.asarray(_tokens(b=16))
    params = model.init(jax.random.key(1), toks)["params"]

    def loss_ref(p):
        return causal_lm_loss(model.apply({"params": p}, toks), toks)[0]

    l_ref, g_ref = jax.jit(jax.value_and_grad(loss_ref))(params)
    l_pp, g_pp = jax.jit(lambda p, t: pipelined_llama_value_and_grad(
        cfg, mesh, p, t, num_microbatches=8))(params, toks)
    np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-5)
    assert _grad_diff(g_pp, g_ref, ("layers", "attn", "q_proj", "kernel")) < 1e-5


def test_1f1b_z_loss_matches_sequential():
    from tpucfn.models.llama_pp import pipelined_llama_value_and_grad

    mesh = build_mesh(MeshSpec(pipeline=2, data=4))
    cfg = _cfg(n_layers=2)
    model = Llama(cfg)
    toks = jnp.asarray(_tokens())
    params = model.init(jax.random.key(1), toks)["params"]

    def loss_ref(p):
        return causal_lm_loss(model.apply({"params": p}, toks), toks,
                              z_loss=1e-3)[0]

    l_ref, g_ref = jax.jit(jax.value_and_grad(loss_ref))(params)
    l_pp, g_pp = jax.jit(lambda p, t: pipelined_llama_value_and_grad(
        cfg, mesh, p, t, num_microbatches=4, z_loss=1e-3))(params, toks)
    np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-5)
    assert _grad_diff(g_pp, g_ref, ("lm_head", "kernel")) < 1e-5


def test_pp_ring_flash_hops_forward_and_grads():
    """PP × SP with hop_attention='flash': the Pallas kernel runs inside
    the {pipeline, context} manual region; forward and grads must match
    the scanned reference."""
    mesh = build_mesh(MeshSpec(pipeline=2, context=2, data=2))
    cfg = _cfg()
    model = Llama(cfg)
    toks = jnp.asarray(_tokens(b=4, s=32))
    params = model.init(jax.random.key(0), toks)["params"]
    ref = model.apply({"params": params}, toks)
    sharded = _sharded_params(mesh, cfg, params)
    out = jax.jit(lambda p, t: pipelined_llama_apply(
        cfg, mesh, p, t, num_microbatches=2, context_parallel=True,
        hop_attention="flash"))(sharded, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)

    def loss_pp(p):
        logits = pipelined_llama_apply(cfg, mesh, p, toks, num_microbatches=2,
                                       context_parallel=True,
                                       hop_attention="flash")
        return causal_lm_loss(logits, toks)[0]

    def loss_ref(p):
        return causal_lm_loss(model.apply({"params": p}, toks), toks)[0]

    g_pp = jax.jit(jax.grad(loss_pp))(params)
    g_ref = jax.jit(jax.grad(loss_ref))(params)
    np.testing.assert_allclose(
        np.asarray(g_pp["layers"]["attn"]["q_proj"]["kernel"]),
        np.asarray(g_ref["layers"]["attn"]["q_proj"]["kernel"]), atol=5e-4)


def test_pp_moe_expert_sharded_forward_and_grads():
    """PP × EP: MoE blocks inside the pipeline stage body with the
    expert axis auto-sharded; forward and expert-weight grads match the
    scanned reference.

    PRECONDITION: exact parity holds only in the no-drop regime —
    MoEMLP computes capacity and drop order per call, so once any token
    is dropped, per-microbatch (64-token) routing legitimately diverges
    from the full-batch (128-token) reference. capacity_factor=2.0 with
    this seed drops nothing; if this test starts failing after a
    routing/seed change, check drop fractions before suspecting the
    pipeline. MoE aux losses are sow()-dropped under both paths' plain
    apply. 1F1B×MoE remains untested (PARITY known-gaps)."""
    from tpucfn.models.moe import MoEConfig

    cfg = dataclasses.replace(
        _cfg(), moe=MoEConfig(n_experts=4, top_k=2, capacity_factor=2.0))
    model = Llama(cfg)
    toks = jnp.asarray(_tokens())
    params = model.init(jax.random.key(0), toks)["params"]
    ref = model.apply({"params": params}, toks)

    mesh = build_mesh(MeshSpec(pipeline=2, expert=2, data=2))
    sharded = _sharded_params(mesh, cfg, params)
    out = jax.jit(lambda p, t: pipelined_llama_apply(
        cfg, mesh, p, t, num_microbatches=2))(sharded, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)

    def loss_pp(p):
        return causal_lm_loss(pipelined_llama_apply(
            cfg, mesh, p, toks, num_microbatches=2), toks)[0]

    def loss_ref(p):
        return causal_lm_loss(model.apply({"params": p}, toks), toks)[0]

    g_pp = jax.jit(jax.grad(loss_pp))(params)
    g_ref = jax.jit(jax.grad(loss_ref))(params)
    np.testing.assert_allclose(
        np.asarray(g_pp["layers"]["mlp"]["experts/gate_proj/kernel"]),
        np.asarray(g_ref["layers"]["mlp"]["experts/gate_proj/kernel"]),
        atol=5e-4)


# ---- MoE aux under PP schedules + 1F1B metrics (VERDICT r2 item 4) -------


def _moe_cfg(n_layers=2):
    from tpucfn.models.moe import MoEConfig

    return dataclasses.replace(
        _cfg(n_layers), moe=MoEConfig(n_experts=4, top_k=2,
                                      capacity_factor=2.0))


def _per_micro_seq_loss(model, toks, num_micro, z_loss=0.0):
    """Sequential reference with the SAME per-microbatch routing as the
    pipeline: apply the full model per microbatch (identical token
    groups => identical MoE routing, so parity is exact even if tokens
    were dropped) and average CE + sown aux over microbatches."""
    from tpucfn.models.moe import collect_moe_aux

    mb = toks.shape[0] // num_micro

    def loss(p):
        total = 0.0
        for j in range(num_micro):
            t = jax.lax.dynamic_slice_in_dim(toks, j * mb, mb, axis=0)
            logits, lcl = model.apply({"params": p}, t, mutable=["losses"])
            ce = causal_lm_loss(logits, t, z_loss=z_loss)[0]
            total = total + ce + collect_moe_aux(lcl)
        return total / num_micro

    return loss


def test_1f1b_moe_loss_and_grads_match_sequential():
    """1F1B x MoE: loss INCLUDING the aux load-balancing/z losses and
    grads (expert weights, router, embed) match the per-micro sequential
    reference — the sow() collection cannot cross the shard_map
    boundary, so the aux rides the schedule's stage_aux plumbing."""
    from tpucfn.models.llama_pp import pipelined_llama_value_and_grad

    mesh = build_mesh(MeshSpec(pipeline=2, expert=2, data=2))
    cfg = _moe_cfg()
    model = Llama(cfg)
    toks = jnp.asarray(_tokens())
    params = model.init(jax.random.key(1), toks)["params"]

    loss_ref = _per_micro_seq_loss(model, toks, num_micro=2)
    l_ref, g_ref = jax.jit(jax.value_and_grad(loss_ref))(params)
    l_pp, g_pp = jax.jit(lambda p, t: pipelined_llama_value_and_grad(
        cfg, mesh, p, t, num_microbatches=2))(params, toks)

    np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-5)
    for path in [("layers", "mlp", "experts/gate_proj/kernel"),
                 ("layers", "mlp", "router", "kernel"),
                 ("layers", "attn", "q_proj", "kernel"),
                 ("embed_tokens", "embedding")]:
        assert _grad_diff(g_pp, g_ref, path) < 2e-5, path


def _ep_cfg(n_layers=2, capacity_factor=4.0):
    """Generous capacity: with cap = cf*T_loc*k/E >= T_loc nothing can
    drop even under worst-case local routing imbalance, so the layer
    OUTPUT equals single-device routing exactly (only aux statistics
    are shard-local)."""
    from tpucfn.models.moe import MoEConfig

    return dataclasses.replace(
        _cfg(n_layers), moe=MoEConfig(n_experts=4, top_k=2,
                                      capacity_factor=capacity_factor))


def test_gpipe_expert_parallel_logits_match_plain():
    """PP x EP (one flat manual region over {pipeline, expert}, explicit
    all-to-all dispatch inline in the stage body): logits equal the
    plain scanned model in the no-drop regime."""
    mesh = build_mesh(MeshSpec(pipeline=2, expert=2, data=2))
    cfg = _ep_cfg()
    model = Llama(cfg)
    toks = jnp.asarray(_tokens(b=8, s=32))
    params = model.init(jax.random.key(0), toks)["params"]
    ref = model.apply({"params": params}, toks)

    out, aux = jax.jit(lambda p, t: pipelined_llama_apply(
        cfg, mesh, p, t, num_microbatches=2, with_aux=True,
        expert_parallel=True))(params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)
    assert float(aux) > 0.0 and np.isfinite(float(aux))


def test_1f1b_expert_parallel_matches_gpipe_expert_parallel():
    """Schedule equivalence under EP: 1F1B's manual backward with the
    all-to-all dispatch in the stage body produces the same loss (CE +
    shard-mean aux) and grads as differentiating through the GPipe
    schedule with the same expert_parallel semantics."""
    from tpucfn.models.llama_pp import pipelined_llama_value_and_grad

    mesh = build_mesh(MeshSpec(pipeline=2, expert=2, data=2))
    cfg = _ep_cfg()
    model = Llama(cfg)
    toks = jnp.asarray(_tokens(b=8, s=32))
    params = model.init(jax.random.key(1), toks)["params"]

    def loss_gp(p):
        logits, aux = pipelined_llama_apply(
            cfg, mesh, p, toks, num_microbatches=2, with_aux=True,
            expert_parallel=True)
        return causal_lm_loss(logits, toks)[0] + aux

    l_gp, g_gp = jax.jit(jax.value_and_grad(loss_gp))(params)
    l_pp, g_pp = jax.jit(lambda p, t: pipelined_llama_value_and_grad(
        cfg, mesh, p, t, num_microbatches=2,
        expert_parallel=True))(params, toks)

    np.testing.assert_allclose(float(l_pp), float(l_gp), rtol=1e-5)
    for path in [("layers", "mlp", "experts/gate_proj/kernel"),
                 ("layers", "mlp", "experts/down_proj/kernel"),
                 ("layers", "mlp", "router", "kernel"),
                 ("layers", "attn", "q_proj", "kernel"),
                 ("embed_tokens", "embedding")]:
        assert _grad_diff(g_pp, g_gp, path) < 2e-5, path


def test_gpipe_expert_parallel_with_context_logits_match_plain():
    """PP x EP x CP over one mesh: manual {pipeline, expert, context},
    microbatch rows split over expert AND sequence split over context
    (ring attention in the stage body). No-drop regime => logits equal
    the plain model."""
    mesh = build_mesh(MeshSpec(pipeline=2, expert=2, context=2))
    cfg = _ep_cfg()
    model = Llama(cfg)
    toks = jnp.asarray(_tokens(b=8, s=32))
    params = model.init(jax.random.key(0), toks)["params"]
    ref = model.apply({"params": params}, toks)

    out, aux = jax.jit(lambda p, t: pipelined_llama_apply(
        cfg, mesh, p, t, num_microbatches=2, with_aux=True,
        expert_parallel=True, context_parallel=True))(params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)
    assert np.isfinite(float(aux))


def test_1f1b_interleaved_expert_parallel_matches_gpipe():
    """Interleaved (V=2) x EP: the chunked expert-weight layout
    (PV, L/PV, E/ep, ...) and the selective grad reduction produce the
    same loss and grads as differentiating through GPipe with the same
    expert_parallel semantics (per-micro per-expert-shard routing is
    schedule-independent)."""
    from tpucfn.models.llama_pp import pipelined_llama_value_and_grad

    mesh = build_mesh(MeshSpec(pipeline=2, expert=2, data=2))
    cfg = _ep_cfg(4)
    model = Llama(cfg)
    toks = jnp.asarray(_tokens(b=8, s=32))
    params = model.init(jax.random.key(1), toks)["params"]

    def loss_gp(p):
        logits, aux = pipelined_llama_apply(
            cfg, mesh, p, toks, num_microbatches=2, with_aux=True,
            expert_parallel=True)
        return causal_lm_loss(logits, toks)[0] + aux

    l_gp, g_gp = jax.jit(jax.value_and_grad(loss_gp))(params)
    l_pp, g_pp = jax.jit(lambda p, t: pipelined_llama_value_and_grad(
        cfg, mesh, p, t, num_microbatches=2, num_virtual=2,
        expert_parallel=True))(params, toks)

    np.testing.assert_allclose(float(l_pp), float(l_gp), rtol=1e-5)
    for path in [("layers", "mlp", "experts/gate_proj/kernel"),
                 ("layers", "mlp", "router", "kernel"),
                 ("layers", "attn", "q_proj", "kernel"),
                 ("embed_tokens", "embedding")]:
        assert _grad_diff(g_pp, g_gp, path) < 2e-5, path


def test_1f1b_interleaved_moe_matches_sequential():
    """Interleaved (V=2) x MoE: the stage_aux plumbing under the circular
    flight schedule — loss incl. aux and grads == per-micro sequential."""
    from tpucfn.models.llama_pp import pipelined_llama_value_and_grad

    mesh = build_mesh(MeshSpec(pipeline=2, expert=2, data=2))
    cfg = _moe_cfg(4)
    model = Llama(cfg)
    toks = jnp.asarray(_tokens())
    params = model.init(jax.random.key(1), toks)["params"]

    loss_ref = _per_micro_seq_loss(model, toks, num_micro=2)
    l_ref, g_ref = jax.jit(jax.value_and_grad(loss_ref))(params)
    l_pp, g_pp = jax.jit(lambda p, t: pipelined_llama_value_and_grad(
        cfg, mesh, p, t, num_microbatches=2, num_virtual=2))(params, toks)

    np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-5)
    for path in [("layers", "mlp", "experts/gate_proj/kernel"),
                 ("layers", "mlp", "router", "kernel"),
                 ("layers", "attn", "q_proj", "kernel"),
                 ("embed_tokens", "embedding")]:
        assert _grad_diff(g_pp, g_ref, path) < 2e-5, path


def test_1f1b_interleaved_context_parallel_matches_sequential():
    """Interleaved (V=2) x ring-attention context parallelism: the
    reduce_axes path under the flight schedule."""
    from tpucfn.models.llama_pp import pipelined_llama_value_and_grad

    cfg = _cfg(4)
    model = Llama(cfg)
    toks = jnp.asarray(_tokens(b=8, s=32))
    params = model.init(jax.random.key(1), toks)["params"]

    def loss_ref(p):
        return causal_lm_loss(model.apply({"params": p}, toks), toks)[0]

    l_ref, g_ref = jax.jit(jax.value_and_grad(loss_ref))(params)
    mesh = build_mesh(MeshSpec(pipeline=2, context=2, data=2))
    sharded = _sharded_params(mesh, cfg, params)
    l_pp, g_pp = jax.jit(lambda p, t: pipelined_llama_value_and_grad(
        cfg, mesh, p, t, num_microbatches=2, context_parallel=True,
        num_virtual=2))(sharded, toks)
    np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-5)
    assert _grad_diff(g_pp, g_ref, ("layers", "attn", "q_proj", "kernel")) < 1e-5
    assert _grad_diff(g_pp, g_ref, ("embed_tokens", "embedding")) < 1e-5


def test_gpipe_moe_aux_matches_sequential():
    """GPipe x MoE with_aux: (logits, aux) and AD grads through the
    schedule's aux accumulator match the per-micro reference."""
    from tpucfn.models.moe import collect_moe_aux

    mesh = build_mesh(MeshSpec(pipeline=2, expert=2, data=2))
    cfg = _moe_cfg()
    model = Llama(cfg)
    toks = jnp.asarray(_tokens())
    params = model.init(jax.random.key(0), toks)["params"]

    logits, aux = jax.jit(lambda p, t: pipelined_llama_apply(
        cfg, mesh, p, t, num_microbatches=2, with_aux=True))(params, toks)

    # aux reference: mean over the two 4-example microbatches
    mb = toks.shape[0] // 2
    aux_ref = 0.0
    for j in range(2):
        _, lcl = model.apply({"params": params}, toks[j * mb:(j + 1) * mb],
                             mutable=["losses"])
        aux_ref = aux_ref + collect_moe_aux(lcl)
    aux_ref = aux_ref / 2
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)

    # grads: CE + aux through AD over the gpipe schedule vs reference
    def loss_pp(p):
        logits, aux = pipelined_llama_apply(
            cfg, mesh, p, toks, num_microbatches=2, with_aux=True)
        return causal_lm_loss(logits, toks)[0] + aux

    loss_ref = _per_micro_seq_loss(model, toks, num_micro=2)
    g_pp = jax.jit(jax.grad(loss_pp))(params)
    g_ref = jax.jit(jax.grad(loss_ref))(params)
    for path in [("layers", "mlp", "router", "kernel"),
                 ("layers", "mlp", "experts/down_proj/kernel")]:
        assert _grad_diff(g_pp, g_ref, path) < 2e-5, path


def test_1f1b_accuracy_matches_sequential():
    from tpucfn.models.llama_pp import pipelined_llama_value_and_grad

    mesh = build_mesh(MeshSpec(pipeline=4, data=2))
    cfg = _cfg()
    model = Llama(cfg)
    toks = jnp.asarray(_tokens())
    params = model.init(jax.random.key(1), toks)["params"]

    _, acc_ref = causal_lm_loss(model.apply({"params": params}, toks), toks)
    _, metrics, _ = jax.jit(lambda p, t: pipelined_llama_value_and_grad(
        cfg, mesh, p, t, num_microbatches=4, with_metrics=True))(params, toks)
    np.testing.assert_allclose(float(metrics["accuracy"]), float(acc_ref),
                               rtol=1e-6)


def test_1f1b_accuracy_under_context_parallel():
    """Accuracy psums over the context axis like the loss does."""
    from tpucfn.models.llama_pp import pipelined_llama_value_and_grad

    mesh = build_mesh(MeshSpec(pipeline=2, context=2, data=2))
    cfg = _cfg()
    model = Llama(cfg)
    toks = jnp.asarray(_tokens(b=4, s=32))
    params = model.init(jax.random.key(1), toks)["params"]

    _, acc_ref = causal_lm_loss(model.apply({"params": params}, toks), toks)
    _, metrics, _ = jax.jit(lambda p, t: pipelined_llama_value_and_grad(
        cfg, mesh, p, t, num_microbatches=2, context_parallel=True,
        with_metrics=True))(params, toks)
    np.testing.assert_allclose(float(metrics["accuracy"]), float(acc_ref),
                               rtol=1e-6)


# ---- MoE × context parallelism: block-local routing -----------------------
#
# Under CP each context shard routes its own (mb, S/C) tokens (capacity
# ∝ S/C).  Per-token top-k is unchanged, so in the no-drop regime the MoE
# OUTPUT equals full-sequence routing (tests reuse the plain model as the
# logits reference); the aux convention is the mean over context shards.


def _blockwise_cp_loss(cfg, toks, num_micro, chunks, z_loss=0.0):
    """Explicit reference for MoE under CP: full-sequence attention, MoE
    aux collected per context-shard chunk and averaged over chunks.  Hand
    -rolled from the same sublayer modules (identical param tree) so the
    pipeline has an independent target."""
    import flax.linen as nn

    from tpucfn.models.layers import CausalSelfAttention, RMSNorm
    from tpucfn.models.moe import MoEMLP, collect_moe_aux

    embed = nn.Embed(cfg.vocab_size, cfg.dim, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype)
    attn = CausalSelfAttention(
        n_heads=cfg.n_heads, n_kv_heads=cfg.n_kv_heads,
        head_dim=cfg.head_dim, rope_theta=cfg.rope_theta, max_seq=cfg.max_seq,
        dtype=cfg.dtype, param_dtype=cfg.param_dtype)
    norm = RMSNorm(cfg.norm_eps, cfg.dtype)
    moe = MoEMLP(cfg.ffn_dim, cfg.moe, cfg.dtype, cfg.param_dtype)
    head = nn.DenseGeneral(cfg.vocab_size, use_bias=False, dtype=jnp.float32,
                           param_dtype=cfg.param_dtype)
    mb_n = toks.shape[0] // num_micro

    def loss(p):
        total = 0.0
        for j in range(num_micro):
            t = toks[j * mb_n:(j + 1) * mb_n]
            x = embed.apply({"params": p["embed_tokens"]}, t)
            aux = 0.0
            for layer in range(cfg.n_layers):
                lp = jax.tree.map(lambda a: a[layer], p["layers"])
                h = attn.apply(
                    {"params": lp["attn"]},
                    norm.apply({"params": lp["input_norm"]}, x),
                    q_offset=jnp.zeros((), jnp.int32))
                x = x + h
                normed = norm.apply({"params": lp["post_attn_norm"]}, x)
                s_loc = normed.shape[1] // chunks
                outs = []
                for c in range(chunks):
                    out, lcl = moe.apply(
                        {"params": lp["mlp"]},
                        normed[:, c * s_loc:(c + 1) * s_loc],
                        mutable=["losses"])
                    outs.append(out)
                    aux = aux + collect_moe_aux(lcl) / chunks
                x = x + jnp.concatenate(outs, axis=1)
            logits = head.apply(
                {"params": p["lm_head"]},
                norm.apply({"params": p["final_norm"]}, x).astype(jnp.float32))
            ce = causal_lm_loss(logits, t, z_loss=z_loss)[0]
            total = total + ce + aux
        return total / num_micro

    return loss


def test_gpipe_moe_cp_matches_blockwise_reference():
    """GPipe × MoE × CP: logits equal the plain model (no-drop regime),
    aux and AD grads match the blockwise-routing reference."""
    mesh = build_mesh(MeshSpec(pipeline=2, context=2, data=2))
    cfg = _moe_cfg()
    model = Llama(cfg)
    toks = jnp.asarray(_tokens(b=4, s=32))
    params = model.init(jax.random.key(0), toks)["params"]

    logits, aux = jax.jit(lambda p, t: pipelined_llama_apply(
        cfg, mesh, p, t, num_microbatches=2, context_parallel=True,
        with_aux=True))(params, toks)
    ref_logits = model.apply({"params": params}, toks)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               atol=2e-4)

    def loss_pp(p):
        lg, ax = pipelined_llama_apply(
            cfg, mesh, p, toks, num_microbatches=2, context_parallel=True,
            with_aux=True)
        return causal_lm_loss(lg, toks)[0] + ax

    loss_ref = _blockwise_cp_loss(cfg, toks, num_micro=2, chunks=2)
    l_pp = jax.jit(loss_pp)(params)
    l_ref = jax.jit(loss_ref)(params)
    np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-5)

    g_pp = jax.jit(jax.grad(loss_pp))(params)
    g_ref = jax.jit(jax.grad(loss_ref))(params)
    for path in [("layers", "mlp", "router", "kernel"),
                 ("layers", "mlp", "experts/down_proj/kernel"),
                 ("layers", "attn", "q_proj", "kernel")]:
        assert _grad_diff(g_pp, g_ref, path) < 2e-5, path


def test_1f1b_moe_cp_loss_and_grads_match_blockwise_reference():
    from tpucfn.models.llama_pp import pipelined_llama_value_and_grad

    mesh = build_mesh(MeshSpec(pipeline=2, context=2, data=2))
    cfg = _moe_cfg()
    model = Llama(cfg)
    toks = jnp.asarray(_tokens(b=4, s=32))
    params = model.init(jax.random.key(1), toks)["params"]

    loss_ref = _blockwise_cp_loss(cfg, toks, num_micro=2, chunks=2)
    l_ref, g_ref = jax.jit(jax.value_and_grad(loss_ref))(params)
    l_pp, g_pp = jax.jit(lambda p, t: pipelined_llama_value_and_grad(
        cfg, mesh, p, t, num_microbatches=2, context_parallel=True))(
        params, toks)

    np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-5)
    for path in [("layers", "mlp", "experts/gate_proj/kernel"),
                 ("layers", "mlp", "router", "kernel"),
                 ("layers", "attn", "q_proj", "kernel"),
                 ("embed_tokens", "embedding")]:
        assert _grad_diff(g_pp, g_ref, path) < 2e-5, path
