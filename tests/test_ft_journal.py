"""Coordinator crash-safety (ISSUE 12): the write-ahead run journal,
replay, crash-point injection, and fleet adoption.

The acceptance properties pinned here in fast tests:

* replay of EVERY byte prefix of a recorded journal yields a valid
  state (the torn final record is the crash boundary, by design);
* a checksum-corrupt record anywhere else refuses loudly;
* a coordinator crash injected between a decision's intent and commit
  records neither drops nor doubles the restart on adoption, and the
  restart budget continues from its pre-crash value.
"""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

from tpucfn.bootstrap import EnvContract
from tpucfn.ft import (
    GangCoordinator,
    GangRestart,
    JournalError,
    JournalWriter,
    RestartBudget,
    SoloRestart,
    replay_journal,
)
from tpucfn.ft.journal import (
    AdoptedProcess,
    crash_point,
    decode_record,
    encode_record,
    journal_path,
    write_rc,
)
from tpucfn.launch import Launcher, LocalTransport
from tpucfn.obs import MetricRegistry

REPO = Path(__file__).resolve().parent.parent


def _contract(tmp_path, n=2) -> EnvContract:
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("".join("127.0.0.1:0\n" for _ in range(n)))
    return EnvContract(
        workers_path=str(hostfile), workers_count=n, worker_chip_count=1,
        coordinator="127.0.0.1:1234", host_id=0, storage=str(tmp_path),
        generation=1)


def _launcher(tmp_path, n=2, **kw) -> Launcher:
    return Launcher(_contract(tmp_path, n), LocalTransport(), **kw)


def _events(ft_dir) -> list[dict]:
    p = Path(ft_dir) / "events.jsonl"
    if not p.is_file():
        return []
    return [json.loads(s) for s in p.read_text().splitlines() if s.strip()]


# -- record framing ---------------------------------------------------------

def test_record_roundtrip_and_checksum():
    rec = {"seq": 1, "kind": "run_start", "argv": ["a", "b"], "ts": 1.5}
    line = encode_record(rec)
    assert decode_record(line) == rec
    # a flipped payload byte fails the checksum
    bad = line[:12] + ("x" if line[12] != "x" else "y") + line[13:]
    assert decode_record(bad) is None
    # garbage framing is None, not an exception
    assert decode_record("nonsense") is None
    assert decode_record("") is None


def test_writer_appends_and_replay_reconstructs(tmp_path):
    p = tmp_path / "journal.jsonl"
    with JournalWriter(p) as j:
        j.append("run_start", argv=["x"], hosts=2, policy="gang",
                 max_restarts=3)
        j.append("gang_launched", first=True, pids={"0": 11, "1": 12})
        j.append("incident_open", incident=1,
                 failures=[{"host": 0, "kind": "crash", "rc": 9}])
        j.append("restart_intent", incident=1, action="gang_restart",
                 hosts=[], budget_used=1)
        j.append("gang_launched", first=False, pids={"0": 21, "1": 22})
        j.append("restart_commit", incident=1, action="gang_restart")
        j.append("host_exit", host=1, rc=0)
    st, records, torn = replay_journal(p)
    assert not torn and len(records) == 7
    assert st.started and st.done_rc is None
    assert st.budget_used == 1 and st.incident == 1
    assert st.procs == {0: 21} and st.finished == {1: 0}
    assert st.pending is None  # committed


def test_unknown_kind_refused_at_append(tmp_path):
    with JournalWriter(tmp_path / "j.jsonl") as j:
        with pytest.raises(ValueError, match="JOURNAL_KINDS"):
            j.append("restart_intnet")  # the typo the tuple exists for


def test_every_byte_prefix_replays_to_valid_state(tmp_path):
    """The acceptance property: any prefix — including one cut mid-
    record — replays without error, and the state is monotone in the
    prefix length (seq never decreases)."""
    p = tmp_path / "journal.jsonl"
    with JournalWriter(p) as j:
        j.append("run_start", argv=["x"], hosts=2, policy="solo",
                 max_restarts=2)
        j.append("gang_launched", first=True, pids={"0": 11, "1": 12})
        j.append("incident_open", incident=1,
                 failures=[{"host": 1, "kind": "crash", "rc": 1}])
        j.append("restart_intent", incident=1, action="solo_restart",
                 hosts=[1], budget_used=1)
        j.append("solo_launched", host=1, pid=33)
        j.append("restart_commit", incident=1, action="solo_restart")
        j.append("host_exit", host=0, rc=0)
        j.append("host_exit", host=1, rc=0)
        j.append("done", rc=0)
    data = p.read_bytes()
    prev_seq = 0
    for cut in range(len(data) + 1):
        q = tmp_path / "prefix.jsonl"
        q.write_bytes(data[:cut])
        st, records, torn = replay_journal(q)
        assert 0 <= st.seq <= 9
        assert st.seq == len(records)
        assert st.seq >= prev_seq  # monotone in the prefix length
        prev_seq = st.seq
        if st.pending is not None:
            assert st.pending.action == "solo_restart"
            assert st.seq >= 4
        if cut == len(data):
            assert st.done_rc == 0 and not torn
    # the full replay agrees with the writer
    st, _, _ = replay_journal(p)
    assert st.seq == 9 and st.budget_used == 1


def test_corrupt_middle_record_refuses_loudly(tmp_path):
    p = tmp_path / "journal.jsonl"
    with JournalWriter(p) as j:
        for _ in range(3):
            j.append("incident_open", incident=1, failures=[])
    lines = p.read_text().splitlines()
    lines[1] = lines[1][:-3] + "xxx"  # corrupt the MIDDLE record
    p.write_text("\n".join(lines) + "\n")
    with pytest.raises(JournalError, match="corrupt"):
        replay_journal(p)
    # ...but the same damage on the FINAL record is the crash boundary
    with JournalWriter(tmp_path / "j2.jsonl") as j:
        for _ in range(3):
            j.append("incident_open", incident=1, failures=[])
    p2 = tmp_path / "j2.jsonl"
    lines = p2.read_text().splitlines()
    lines[-1] = lines[-1][:-3] + "xxx"
    p2.write_text("\n".join(lines) + "\n")
    st, records, torn = replay_journal(p2)
    assert torn and len(records) == 2


def test_sequence_gap_is_corruption(tmp_path):
    p = tmp_path / "journal.jsonl"
    with JournalWriter(p) as j:
        j.append("incident_open", incident=1, failures=[])
        j.append("incident_open", incident=2, failures=[])
        j.append("incident_open", incident=3, failures=[])
    lines = p.read_text().splitlines()
    del lines[1]  # a validly-checksummed stream with a missing middle
    p.write_text("\n".join(lines) + "\n")
    with pytest.raises(JournalError, match="sequence gap"):
        replay_journal(p)


# -- crash points -----------------------------------------------------------

def test_crash_point_sigkills_once(tmp_path):
    script = (
        "import os, sys\n"
        "sys.path.insert(0, os.environ['REPO'])\n"
        "from tpucfn.ft.journal import crash_point\n"
        "crash_point('here', os.environ['MARKER_DIR'])\n"
        "print('survived')\n")
    env = {**os.environ, "REPO": str(REPO), "TPUCFN_CRASH_AT": "here",
           "MARKER_DIR": str(tmp_path)}
    r1 = subprocess.run([sys.executable, "-c", script], env=env,
                        capture_output=True, text=True, timeout=30)
    assert r1.returncode == -signal.SIGKILL
    assert (tmp_path / "crashed-here").is_file()
    # second incarnation: the marker makes the same label a no-op
    r2 = subprocess.run([sys.executable, "-c", script], env=env,
                        capture_output=True, text=True, timeout=30)
    assert r2.returncode == 0 and "survived" in r2.stdout
    # unrelated label never fires
    env2 = {**env, "TPUCFN_CRASH_AT": "elsewhere"}
    r3 = subprocess.run([sys.executable, "-c", script], env=env2,
                        capture_output=True, text=True, timeout=30)
    assert r3.returncode == 0


def test_crash_point_noop_without_env(tmp_path):
    os.environ.pop("TPUCFN_CRASH_AT", None)
    crash_point("anything", tmp_path)  # must simply return


# -- adopted process handles -------------------------------------------------

def test_adopted_process_liveness_and_signals(tmp_path):
    p = subprocess.Popen([sys.executable, "-c",
                          "import time; time.sleep(30)"])
    try:
        a = AdoptedProcess(p.pid, ft_dir=tmp_path)
        assert a.poll() is None
        a.terminate()
        # the test process is the real parent: reap the zombie so the
        # pid actually disappears (in production init/--supervise does)
        p.wait()
        # no rc file, but WE sent the TERM: the exit is attributed to it
        assert a.wait(timeout=10) == -signal.SIGTERM
        assert a.poll() == -signal.SIGTERM
    finally:
        p.kill()
        p.wait()


def test_adopted_process_reads_reaper_rc_file(tmp_path):
    p = subprocess.Popen([sys.executable, "-c", "import sys; sys.exit(0)"])
    p.wait()
    write_rc(tmp_path, p.pid, 0)
    a = AdoptedProcess(p.pid, ft_dir=tmp_path)
    assert a.poll() == 0  # a clean adopted exit reads clean, not CRASH


def test_adopted_process_unknown_death_degrades_to_failure(tmp_path):
    p = subprocess.Popen([sys.executable, "-c", "import sys; sys.exit(3)"])
    p.wait()  # dead, and nobody wrote an rc file
    t = {"now": 100.0}
    a = AdoptedProcess(p.pid, ft_dir=tmp_path, rc_grace_s=2.0,
                       clock=lambda: t["now"])
    assert a.poll() is None  # inside the reaper grace: not judged yet
    t["now"] += 2.5
    assert a.poll() == 1  # unexplained death is a failure, never clean


# -- fleet adoption ---------------------------------------------------------

# Two-host protocol for the crash drills: host 1 sleeps until killed,
# its SECOND incarnation writes h1_done and exits; host 0 (the healthy
# host adoption must not disturb) exits clean once h1_done appears.
CRASH_WORKER = (
    "import os, pathlib, sys, time\n"
    "fd = pathlib.Path(os.environ['FLAG_DIR'])\n"
    "if os.environ['TPUCFN_HOST_ID'] == '1':\n"
    "    if (fd / 'second_1').exists():\n"
    "        (fd / 'h1_done').write_text('x'); sys.exit(0)\n"
    "    (fd / 'second_1').write_text('x')\n"
    "    time.sleep(30); sys.exit(1)\n"
    "deadline = time.time() + 30\n"
    "while not (fd / 'h1_done').exists():\n"
    "    time.sleep(0.02)\n"
    "    assert time.time() < deadline\n"
    "sys.exit(0)\n")


def _be_subreaper():
    """Make the test process the child subreaper so a killed
    coordinator subprocess's workers reparent to US (not init) and can
    be reaped into rc files — exactly what --supervise does in
    production.  Returns an undo callable."""
    import ctypes

    libc = ctypes.CDLL(None, use_errno=True)
    assert libc.prctl(36, 1, 0, 0, 0) == 0  # PR_SET_CHILD_SUBREAPER
    return lambda: libc.prctl(36, 0, 0, 0, 0)


def _reap_orphans_into_rc(ft_dir, pids):
    """Background reaper for the orphans we inherited as subreaper:
    per-pid waitpid (never waitpid(-1) — that would steal the adopting
    coordinator's own children) landing real rcs in <ft>/rc/."""
    import threading

    def reap(pid):
        try:
            _, status = os.waitpid(pid, 0)
        except ChildProcessError:
            return  # reaped before orphaning (its parent saw it die)
        rc = (-os.WTERMSIG(status) if os.WIFSIGNALED(status)
              else os.WEXITSTATUS(status))
        write_rc(ft_dir, pid, rc)

    threads = [threading.Thread(target=reap, args=(p,), daemon=True)
               for p in pids]
    for t in threads:
        t.start()
    return threads


def _run_crashing_coordinator(tmp_path, crash_at, *, budget=3):
    """Run a SoloRestart coordinator in a SUBPROCESS with a crash point
    armed; the scripted chaos kills host 1 at t=0.4s, so the incident's
    intent is in flight when the crash label fires.  Returns the
    subprocess result (expected: SIGKILL) and the ft dir."""
    ft_dir = tmp_path / "ft"
    script = f"""
import os, sys
sys.path.insert(0, {str(REPO)!r})
from tpucfn.bootstrap import EnvContract
from tpucfn.ft import (ChaosEvent, ChaosSpec, GangCoordinator,
                       RestartBudget, SoloRestart)
from tpucfn.launch import Launcher, LocalTransport

tmp = {str(tmp_path)!r}
hostfile = os.path.join(tmp, 'hostfile')
contract = EnvContract(workers_path=hostfile, workers_count=2,
                       worker_chip_count=1, coordinator='127.0.0.1:1234',
                       host_id=0, storage=tmp, generation=1)
launcher = Launcher(contract, LocalTransport())
coord = GangCoordinator(
    launcher, [sys.executable, '-c', {CRASH_WORKER!r}],
    policy=SoloRestart(RestartBudget({budget})),
    ft_dir={str(ft_dir)!r}, poll_interval=0.01, term_grace_s=0.5,
    chaos=ChaosSpec(events=(ChaosEvent(action='kill', at_s=0.4,
                                       host=1),)))
sys.exit(coord.run())
"""
    (tmp_path / "hostfile").write_text("127.0.0.1:0\n127.0.0.1:0\n")
    env = {**os.environ, "FLAG_DIR": str(tmp_path),
           "TPUCFN_CRASH_AT": crash_at}
    # No capture_output: the coordinator's workers inherit its pipes,
    # so capturing would block this call until the ORPHANS exit — the
    # exact confusion adoption exists to clean up.
    return subprocess.run([sys.executable, "-c", script], env=env,
                          timeout=60), ft_dir


def _adopting_coordinator(tmp_path, ft_dir, *, budget=3, registry=None):
    return GangCoordinator(
        _launcher(tmp_path, n=2), [sys.executable, "-c", CRASH_WORKER],
        policy=SoloRestart(RestartBudget(budget)),
        registry=registry, ft_dir=ft_dir, poll_interval=0.01,
        term_grace_s=0.5)


def _journal_pids(records) -> list[int]:
    pids = []
    for r in records:
        if r["kind"] == "gang_launched":
            pids.extend(r["pids"].values())
        elif r["kind"] == "solo_launched":
            pids.append(r["pid"])
    return pids


def test_crash_between_intent_and_act_restarts_exactly_once(tmp_path):
    """TPUCFN_CRASH_AT=after_intent: the budget draw is journaled, the
    relaunch never ran.  Adoption must perform the solo restart ONCE,
    keep the healthy host's process untouched, and continue the budget
    at 1 — not reset it, not draw a second slot."""
    undo = _be_subreaper()
    try:
        r, ft_dir = _run_crashing_coordinator(tmp_path, "after_intent")
        assert r.returncode == -signal.SIGKILL
        st, records0, _ = replay_journal(journal_path(ft_dir))
        assert st.pending is not None and not st.pending.launched
        assert st.pending.action == "solo_restart"
        assert st.budget_used == 1
        host0_pid_before = st.procs[0]
        _reap_orphans_into_rc(ft_dir, _journal_pids(records0))
        os.environ["FLAG_DIR"] = str(tmp_path)
        registry = MetricRegistry()
        try:
            coord = _adopting_coordinator(tmp_path, ft_dir,
                                          registry=registry)
            assert coord.run() == 0
        finally:
            del os.environ["FLAG_DIR"]
    finally:
        undo()
    assert coord._adopted
    assert coord.policy.budget.used == 1  # continued, not reset/redrawn
    st2, records, _ = replay_journal(journal_path(ft_dir))
    assert st2.done_rc == 0
    # exactly one intent, one commit, one solo launch for incident 1
    intents = [x for x in records if x["kind"] == "restart_intent"]
    commits = [x for x in records if x["kind"] == "restart_commit"]
    solos = [x for x in records if x["kind"] == "solo_launched"]
    assert len(intents) == 1 and len(commits) == 1
    assert commits[0]["incident"] == intents[0]["incident"]
    assert len(solos) == 1 and solos[0]["host"] == 1
    # the healthy host kept its ORIGINAL pid through adoption
    adopted = next(e for e in _events(ft_dir)
                   if e["kind"] == "coordinator_adopted")
    assert 0 in adopted["hosts"]
    gang_launches = [x for x in records if x["kind"] == "gang_launched"]
    assert len(gang_launches) == 1  # only the original first launch
    assert gang_launches[0]["pids"]["0"] == host0_pid_before
    recovered = [e for e in _events(ft_dir) if e["kind"] == "recovered"]
    assert len(recovered) == 1 and recovered[0]["adopted"] is True
    v = registry.varz()["metrics"]
    assert v["coordinator_adoptions_total"] == 1
    assert v["ft_solo_restarts_total"] == 1


def test_crash_between_act_and_commit_does_not_double_restart(tmp_path):
    """TPUCFN_CRASH_AT=before_commit: the relaunch ALREADY ran when the
    coordinator died.  Adoption must only write the commit — the
    already-relaunched host keeps running; no second restart."""
    undo = _be_subreaper()
    try:
        r, ft_dir = _run_crashing_coordinator(tmp_path, "before_commit")
        assert r.returncode == -signal.SIGKILL
        st, records0, _ = replay_journal(journal_path(ft_dir))
        assert st.pending is not None and st.pending.launched
        solos_before = [x for x in records0
                        if x["kind"] == "solo_launched"]
        assert len(solos_before) == 1
        relaunched_pid = solos_before[0]["pid"]
        _reap_orphans_into_rc(ft_dir, _journal_pids(records0))
        os.environ["FLAG_DIR"] = str(tmp_path)
        try:
            coord = _adopting_coordinator(tmp_path, ft_dir)
            assert coord.run() == 0
        finally:
            del os.environ["FLAG_DIR"]
    finally:
        undo()
    assert coord.policy.budget.used == 1
    st2, records2, _ = replay_journal(journal_path(ft_dir))
    assert st2.done_rc == 0
    solos = [x for x in records2 if x["kind"] == "solo_launched"]
    assert len(solos) == 1 and solos[0]["pid"] == relaunched_pid
    assert sum(1 for x in records2
               if x["kind"] == "restart_commit") == 1
    recovered = [e for e in _events(ft_dir) if e["kind"] == "recovered"]
    assert len(recovered) == 1


def test_finished_journal_starts_fresh_and_rotates(tmp_path):
    """A done journal is history, not a fleet: the next run must launch
    fresh, rotate the old journal aside, and start a new one."""
    coord = GangCoordinator(
        _launcher(tmp_path, n=1), [sys.executable, "-c", "pass"],
        ft_dir=tmp_path / "ft", poll_interval=0.01)
    assert coord.run() == 0
    jp = journal_path(tmp_path / "ft")
    st, _, _ = replay_journal(jp)
    assert st.done_rc == 0
    coord2 = GangCoordinator(
        _launcher(tmp_path, n=1), [sys.executable, "-c", "pass"],
        ft_dir=tmp_path / "ft", poll_interval=0.01)
    assert coord2.run() == 0
    assert not coord2._adopted
    assert (jp.parent / "journal-prev.jsonl").is_file()
    st2, _, _ = replay_journal(jp)
    assert st2.done_rc == 0 and st2.adoptions == 0


def test_no_adopt_forces_fresh_launch(tmp_path):
    """adopt=False over an unfinished journal: fresh run, old journal
    rotated, nothing adopted (the operator's --no-adopt escape)."""
    ft_dir = tmp_path / "ft"
    (ft_dir / "journal").mkdir(parents=True)
    with JournalWriter(journal_path(ft_dir)) as j:
        j.append("run_start", argv=["x"], hosts=1, policy="gang",
                 max_restarts=1)
        j.append("gang_launched", first=True, pids={"0": 999999})
    coord = GangCoordinator(
        _launcher(tmp_path, n=1), [sys.executable, "-c", "pass"],
        ft_dir=ft_dir, poll_interval=0.01, adopt=False)
    assert coord.run() == 0
    assert not coord._adopted
    assert (ft_dir / "journal" / "journal-prev.jsonl").is_file()


def test_corrupt_journal_refuses_adoption_loudly(tmp_path):
    ft_dir = tmp_path / "ft"
    (ft_dir / "journal").mkdir(parents=True)
    with JournalWriter(journal_path(ft_dir)) as j:
        j.append("run_start", argv=["x"], hosts=1, policy="gang",
                 max_restarts=1)
        j.append("gang_launched", first=True, pids={"0": 4242})
        j.append("incident_open", incident=1, failures=[])
    jp = journal_path(ft_dir)
    lines = jp.read_text().splitlines()
    lines[1] = lines[1][:-4] + "zzzz"
    jp.write_text("\n".join(lines) + "\n")
    coord = GangCoordinator(
        _launcher(tmp_path, n=1), [sys.executable, "-c", "pass"],
        ft_dir=ft_dir, poll_interval=0.01)
    with pytest.raises(JournalError):
        coord.run()


def test_adoption_attaches_live_fleet_and_finishes_clean(tmp_path):
    """The core adoption path without any pending incident: a journal
    names two live pids; the adopting coordinator attaches (no launch),
    the reaper's rc files tell it the exits were clean, rc 0."""
    ft_dir = tmp_path / "ft"
    (ft_dir / "journal").mkdir(parents=True)
    procs = [subprocess.Popen([sys.executable, "-c",
                               "import time; time.sleep(0.6)"])
             for _ in range(2)]
    with JournalWriter(journal_path(ft_dir)) as j:
        j.append("run_start", argv=["x"], hosts=2, policy="gang",
                 max_restarts=2)
        j.append("gang_launched", first=True,
                 pids={str(i): p.pid for i, p in enumerate(procs)})
    # we ARE the parent of these fakes: reap them and land rc files the
    # way the --supervise reaper would
    import threading

    def reap():
        for p in procs:
            write_rc(ft_dir, p.pid, p.wait())

    t = threading.Thread(target=reap, daemon=True)
    t.start()
    registry = MetricRegistry()
    coord = GangCoordinator(
        _launcher(tmp_path, n=2), [sys.executable, "-c", "pass"],
        policy=GangRestart(RestartBudget(2)), registry=registry,
        ft_dir=ft_dir, poll_interval=0.01, term_grace_s=0.5)
    launches = []
    coord.launcher.launch = lambda *a, **k: launches.append(1) or []
    assert coord.run() == 0
    t.join(timeout=5)
    assert coord._adopted and launches == []  # attached, never spawned
    adopted = next(e for e in _events(ft_dir)
                   if e["kind"] == "coordinator_adopted")
    assert adopted["hosts"] == [0, 1] and adopted["dead"] == []
    assert registry.varz()["metrics"]["coordinator_adoptions_total"] == 1


def test_adoption_raises_failure_for_host_dead_while_down(tmp_path):
    """A journaled pid that is GONE at adoption (no rc file) is exactly
    one CRASH failure through the normal detect→decide path — the
    restart budget pays for it like any other crash."""
    ft_dir = tmp_path / "ft"
    (ft_dir / "journal").mkdir(parents=True)
    live = subprocess.Popen([sys.executable, "-c",
                             "import time; time.sleep(0.8)"])
    dead = subprocess.Popen([sys.executable, "-c", "pass"])
    dead.wait()
    write_rc(ft_dir, dead.pid, 7)  # the reaper saw it crash with rc 7
    with JournalWriter(journal_path(ft_dir)) as j:
        j.append("run_start", argv=["x"], hosts=2, policy="solo",
                 max_restarts=2)
        j.append("gang_launched", first=True,
                 pids={"0": live.pid, "1": dead.pid})
    import threading

    threading.Thread(
        target=lambda: write_rc(ft_dir, live.pid, live.wait()),
        daemon=True).start()
    registry = MetricRegistry()
    coord = GangCoordinator(
        _launcher(tmp_path, n=2),
        [sys.executable, "-c", "pass"],  # the solo relaunch exits clean
        policy=SoloRestart(RestartBudget(2)), registry=registry,
        ft_dir=ft_dir, poll_interval=0.01, term_grace_s=0.5)
    assert coord.run() == 0
    detect = next(e for e in _events(ft_dir) if e["kind"] == "detect")
    assert detect["failures"][0]["host"] == 1
    assert detect["failures"][0]["kind"] == "crash"
    assert detect["failures"][0]["rc"] == 7
    assert "coordinator was down" in detect["failures"][0]["detail"]
    v = registry.varz()["metrics"]
    assert v["ft_solo_restarts_total"] == 1
    assert coord.policy.budget.used == 1


def test_journal_status_feeds_snapshot_and_health(tmp_path):
    ft_dir = tmp_path / "ft"
    coord = GangCoordinator(
        _launcher(tmp_path, n=1), [sys.executable, "-c", "pass"],
        ft_dir=ft_dir, poll_interval=0.01)
    assert coord.run() == 0
    snap = json.loads((ft_dir / "supervisor.json").read_text())
    assert snap["adopted"] is False
    assert snap["journal"]["records"] >= 3  # run_start, launch, ..., done
    assert snap["journal"]["pending_intent"] is False
    healthy, detail = coord.health()
    assert healthy and detail["adopted"] is False
    assert detail["journal"]["records"] == snap["journal"]["records"]


# -- review-pass pins -------------------------------------------------------

def test_repair_torn_tail_truncates_only_the_final_record(tmp_path):
    """Appending to an adopted journal must not glue the next record
    onto a torn partial line — that garbled line would no longer be
    final, and the NEXT replay would refuse the whole journal as
    corrupt.  repair_torn_tail drops exactly the crash boundary."""
    from tpucfn.ft.journal import repair_torn_tail

    jp = tmp_path / "journal" / "journal.jsonl"
    with JournalWriter(jp) as j:
        j.append("run_start", argv=["x"], hosts=1, policy="gang",
                 max_restarts=1)
        j.append("gang_launched", first=True, pids={"0": 4242})
    clean = jp.read_bytes()
    assert repair_torn_tail(jp) is False  # no-op on a clean journal
    assert jp.read_bytes() == clean
    torn = encode_record({"seq": 3, "ts": 0.0, "kind": "incident_open"})
    jp.write_bytes(clean + torn[: len(torn) // 2].encode())
    assert repair_torn_tail(jp) is True
    assert jp.read_bytes() == clean
    with JournalWriter(jp, start_seq=2) as j:
        j.append("incident_open", incident=1, failures=[])
    st, _, torn_flag = replay_journal(jp)
    assert st.seq == 3 and not torn_flag
    # a torn final line WITH a trailing newline is still the tolerated
    # crash boundary, exactly as replay treats it
    jp.write_bytes(clean + torn[: len(torn) // 2].encode() + b"\n")
    assert repair_torn_tail(jp) is True
    assert jp.read_bytes() == clean


def test_repair_torn_tail_refuses_corrupt_middle(tmp_path):
    from tpucfn.ft.journal import repair_torn_tail

    jp = tmp_path / "journal" / "journal.jsonl"
    with JournalWriter(jp) as j:
        j.append("run_start", argv=["x"], hosts=1, policy="gang",
                 max_restarts=1)
        j.append("gang_launched", first=True, pids={"0": 4242})
        j.append("incident_open", incident=1, failures=[])
    lines = jp.read_text().splitlines()
    lines[1] = lines[1][:-4] + "zzzz"
    jp.write_text("\n".join(lines) + "\n")
    with pytest.raises(JournalError):
        repair_torn_tail(jp)


def test_adoption_over_torn_tail_keeps_the_journal_replayable(tmp_path):
    """End to end: adopt over a journal whose final record is torn (the
    SIGKILL-mid-append crash boundary) — the adopting run must repair
    the tail before appending, so a SECOND replay (the next adoption,
    or the supervise loop's post-exit check) still accepts it."""
    ft_dir = tmp_path / "ft"
    (ft_dir / "journal").mkdir(parents=True)
    proc = subprocess.Popen([sys.executable, "-c",
                             "import time; time.sleep(0.6)"])
    with JournalWriter(journal_path(ft_dir)) as j:
        j.append("run_start", argv=["x"], hosts=1, policy="gang",
                 max_restarts=1)
        j.append("gang_launched", first=True, pids={"0": proc.pid})
    jp = journal_path(ft_dir)
    with open(jp, "ab") as f:  # SIGKILL mid-append: a partial line
        f.write(b'deadbeef {"seq":3,"ts":0.0,"kind":"incid')
    import threading

    threading.Thread(
        target=lambda: write_rc(ft_dir, proc.pid, proc.wait()),
        daemon=True).start()
    coord = GangCoordinator(
        _launcher(tmp_path, n=1), [sys.executable, "-c", "pass"],
        policy=GangRestart(RestartBudget(1)),
        ft_dir=ft_dir, poll_interval=0.01, term_grace_s=0.5)
    assert coord.run() == 0
    assert coord._adopted
    st, _, torn_flag = replay_journal(jp)  # must NOT raise JournalError
    assert not torn_flag and st.done_rc == 0


def test_replay_gang_launch_completes_a_solo_intent(tmp_path):
    """The elastic-shrink path can upgrade a SOLO intent to a gang
    relaunch (the lost host left the contract): the gang_launched act
    must mark the intent launched, or adoption would redo it solo —
    double-restarting fresh ranks at host_ids the re-converged
    contract no longer has."""
    jp = tmp_path / "journal" / "journal.jsonl"
    with JournalWriter(jp) as j:
        j.append("run_start", argv=["x"], hosts=2, policy="solo",
                 max_restarts=2)
        j.append("gang_launched", first=True, pids={"0": 11, "1": 12})
        j.append("incident_open", incident=1, failures=[])
        j.append("restart_intent", incident=1, action="solo_restart",
                 hosts=[1], budget_used=1, planned=False)
        j.append("shrink", lost=[1], to_hosts=[0])
        j.append("gang_launched", first=False, pids={"0": 21})
    st, _, _ = replay_journal(jp)
    assert st.pending is not None
    assert st.pending.launched is True  # only the commit is owed


def test_partial_solo_intent_relaunches_only_the_missing_hosts(tmp_path):
    """A multi-host SOLO intent whose first solo_launched landed before
    the crash: adoption must relaunch ONLY the hosts still missing —
    redoing the already-relaunched host would be the double the
    intent/commit pair exists to prevent."""
    ft_dir = tmp_path / "ft"
    (ft_dir / "journal").mkdir(parents=True)
    relaunched0 = subprocess.Popen([sys.executable, "-c",
                                    "import time; time.sleep(0.8)"])
    dead1 = subprocess.Popen([sys.executable, "-c", "pass"])
    dead1.wait()
    write_rc(ft_dir, dead1.pid, 9)  # the reaper saw host 1 crash
    with JournalWriter(journal_path(ft_dir)) as j:
        j.append("run_start", argv=["x"], hosts=2, policy="solo",
                 max_restarts=4)
        j.append("gang_launched", first=True,
                 pids={"0": 77777, "1": dead1.pid})
        j.append("incident_open", incident=1, failures=[])
        j.append("restart_intent", incident=1, action="solo_restart",
                 hosts=[0, 1], budget_used=2, planned=False)
        j.append("solo_launched", host=0, pid=relaunched0.pid)
    import threading

    threading.Thread(
        target=lambda: write_rc(ft_dir, relaunched0.pid,
                                relaunched0.wait()),
        daemon=True).start()
    registry = MetricRegistry()
    coord = GangCoordinator(
        _launcher(tmp_path, n=2), [sys.executable, "-c", "pass"],
        policy=SoloRestart(RestartBudget(4)), registry=registry,
        ft_dir=ft_dir, poll_interval=0.01, term_grace_s=0.5)
    assert coord.run() == 0
    _, records, _ = replay_journal(journal_path(ft_dir))
    solo = [r["host"] for r in records if r["kind"] == "solo_launched"]
    assert solo == [0, 1]  # pre-crash 0, adoption's 1 — never 0 again
    assert relaunched0.poll() == 0  # the pre-crash relaunch was left alone
    assert registry.varz()["metrics"]["ft_solo_restarts_total"] == 1


def test_adoption_gives_the_reaper_grace_to_land_a_clean_rc(tmp_path):
    """A rank that finished rc 0 while the coordinator was down, whose
    rc file the supervise reaper lands a beat AFTER adoption starts
    (the reaper re-enters waitpid only after spawning the new
    coordinator): adoption must wait out the race instead of misreading
    the clean exit as a CRASH and burning a budget slot relaunching a
    host that was already done."""
    ft_dir = tmp_path / "ft"
    (ft_dir / "journal").mkdir(parents=True)
    done = subprocess.Popen([sys.executable, "-c", "pass"])
    done.wait()
    with JournalWriter(journal_path(ft_dir)) as j:
        j.append("run_start", argv=["x"], hosts=1, policy="solo",
                 max_restarts=1)
        j.append("gang_launched", first=True, pids={"0": done.pid})
    import threading

    def late_rc():
        time.sleep(0.3)
        write_rc(ft_dir, done.pid, 0)

    threading.Thread(target=late_rc, daemon=True).start()
    coord = GangCoordinator(
        _launcher(tmp_path, n=1), [sys.executable, "-c", "pass"],
        policy=SoloRestart(RestartBudget(1)),
        ft_dir=ft_dir, poll_interval=0.01, term_grace_s=0.5)
    assert coord.run() == 0
    assert coord.policy.budget.used == 0  # no budget burned
    assert all(e["kind"] != "detect" for e in _events(ft_dir))


def test_writer_terminates_a_newlineless_valid_final_record(tmp_path):
    """A crash can truncate the journal at EXACTLY the final record's
    newline: the record is VALID (repair_torn_tail rightly keeps it),
    but appending straight after it would glue the next record onto
    the same line — silently losing one of the two on the next replay.
    The writer terminates the line before its first append."""
    from tpucfn.ft.journal import repair_torn_tail

    jp = tmp_path / "journal" / "journal.jsonl"
    with JournalWriter(jp) as j:
        j.append("run_start", argv=["x"], hosts=1, policy="gang",
                 max_restarts=1)
        j.append("gang_launched", first=True, pids={"0": 4242})
    data = jp.read_bytes()
    assert data.endswith(b"\n")
    jp.write_bytes(data[:-1])  # the crash ate exactly the newline
    assert repair_torn_tail(jp) is False  # the record IS valid: kept
    with JournalWriter(jp, start_seq=2) as j:
        j.append("adopted", hosts=[0], dead=[], pending=None)
    st, recs, torn = replay_journal(jp)
    assert not torn and st.seq == 3 and len(recs) == 3
    assert st.adoptions == 1  # nothing glued, nothing lost


# -- spawn-window hazard (ISSUE 13 satellite) --------------------------------

def test_launching_record_replays_and_clears(tmp_path):
    """`launching` marks hosts whose spawn was imminent; the pid-bearing
    launch records (and host_exit) clear them."""
    p = tmp_path / "j.jsonl"
    with JournalWriter(p) as j:
        j.append("run_start", argv=["x"], hosts=2, max_restarts=1)
        j.append("launching", hosts=[0, 1], first=True)
    st, _, _ = replay_journal(p)
    assert st.launching == {0, 1}
    with JournalWriter(p, start_seq=st.seq) as j:
        j.append("gang_launched", first=True, pids={"0": 11, "1": 12})
    st, _, _ = replay_journal(p)
    assert st.launching == set()
    with JournalWriter(p, start_seq=st.seq) as j:
        j.append("launching", hosts=[1])
        j.append("solo_launched", host=1, pid=13)
    st, _, _ = replay_journal(p)
    assert st.launching == set()


def _spawn_window_journal(tmp_path, ft_dir):
    """A predecessor that died INSIDE the spawn window: run_start +
    launching recorded, no pid record for host 0."""
    ft_dir.mkdir(parents=True, exist_ok=True)
    with JournalWriter(journal_path(ft_dir)) as j:
        j.append("run_start", argv=["w"], hosts=1, policy="gang",
                 max_restarts=3)
        j.append("launching", hosts=[0], first=True)


def _write_heartbeat(ft_dir, host, pid):
    with open(Path(ft_dir) / f"hb-host{host:03d}.jsonl", "a") as f:
        f.write(json.dumps({"host_id": host, "pid": pid,
                            "t": time.time(), "seq": 1, "step": 0}) + "\n")


def test_adoption_waits_spawn_grace_for_unjournaled_rank(tmp_path):
    """The hazard closed: a rank spawned-but-never-journaled is adopted
    through its first heartbeat instead of being relaunched over."""
    import threading

    ft_dir = tmp_path / "ft"
    _spawn_window_journal(tmp_path, ft_dir)
    orphan = subprocess.Popen([sys.executable, "-c",
                               "import time; time.sleep(30)"])
    try:
        t = threading.Timer(
            0.5, lambda: _write_heartbeat(ft_dir, 0, orphan.pid))
        t.start()
        coord = GangCoordinator(
            _launcher(tmp_path, n=1), [sys.executable, "-c", "pass"],
            policy=GangRestart(RestartBudget(1)), ft_dir=ft_dir,
            poll_interval=0.01, adopt_spawn_grace_s=5.0)
        assert coord._startup_adopt() is True
        assert coord._adopted
        # the spawned-but-unjournaled rank was found, not condemned
        assert coord._procs[0].pid == orphan.pid
        assert coord._adopt_failures == []
    finally:
        orphan.kill()
        orphan.wait()


def test_adoption_condemns_silent_spawn_window_after_grace(tmp_path):
    """No heartbeat ever arrives: after the bounded grace, the host is
    raised as exactly one CRASH through the normal detect path (it may
    simply never have spawned)."""
    ft_dir = tmp_path / "ft"
    _spawn_window_journal(tmp_path, ft_dir)
    t0 = time.monotonic()
    coord = GangCoordinator(
        _launcher(tmp_path, n=1), [sys.executable, "-c", "pass"],
        policy=GangRestart(RestartBudget(1)), ft_dir=ft_dir,
        poll_interval=0.01, adopt_spawn_grace_s=0.4)
    assert coord._startup_adopt() is True
    waited = time.monotonic() - t0
    assert waited >= 0.4  # the grace was actually applied
    assert [f.host_id for f in coord._adopt_failures] == [0]


def test_adoption_event_carries_journal_replay_ms(tmp_path):
    """ISSUE 13 satellite: the adopter measures its replay time and
    attributes it through the adoption event (and, for a completed
    pending intent, the recovered/goodput_incident rows)."""
    ft_dir = tmp_path / "ft"
    ft_dir.mkdir(parents=True)
    live = subprocess.Popen([sys.executable, "-c",
                             "import time; time.sleep(30)"])
    try:
        with JournalWriter(journal_path(ft_dir)) as j:
            j.append("run_start", argv=["w"], hosts=1, policy="gang",
                     max_restarts=3)
            j.append("gang_launched", first=True,
                     pids={"0": live.pid})
        coord = GangCoordinator(
            _launcher(tmp_path, n=1), [sys.executable, "-c", "pass"],
            policy=GangRestart(RestartBudget(1)), ft_dir=ft_dir,
            poll_interval=0.01)
        assert coord._startup_adopt() is True
        assert coord._journal_replay_ms is not None
        adopted = next(e for e in _events(ft_dir)
                       if e["kind"] == "coordinator_adopted")
        assert adopted["journal_replay_ms"] == coord._journal_replay_ms
    finally:
        live.kill()
        live.wait()


def test_adoption_spawn_grace_applies_to_relaunch_window(tmp_path):
    """Third-review pin: a RELAUNCH spawn window (crashed rank, intent
    drawn, `launching` journaled, killed before the pid record) leaves
    st.procs and the heartbeat file carrying the DEAD predecessor's
    pid — the grace must wait for a beat naming a DIFFERENT pid and
    adopt the spawned rank, not condemn it against the stale pid."""
    import threading

    ft_dir = tmp_path / "ft"
    ft_dir.mkdir(parents=True)
    stale = subprocess.Popen([sys.executable, "-c", "pass"])
    stale.wait()  # a real, dead pid — the crashed incarnation
    with JournalWriter(journal_path(ft_dir)) as j:
        j.append("run_start", argv=["w"], hosts=1, policy="gang",
                 max_restarts=3)
        j.append("gang_launched", first=True, pids={"0": stale.pid})
        j.append("launching", hosts=[0])  # the relaunch, mid-spawn
    _write_heartbeat(ft_dir, 0, stale.pid)  # old incarnation's last beat
    orphan = subprocess.Popen([sys.executable, "-c",
                               "import time; time.sleep(30)"])
    try:
        t = threading.Timer(
            0.5, lambda: _write_heartbeat(ft_dir, 0, orphan.pid))
        t.start()
        coord = GangCoordinator(
            _launcher(tmp_path, n=1), [sys.executable, "-c", "pass"],
            policy=GangRestart(RestartBudget(1)), ft_dir=ft_dir,
            poll_interval=0.01, adopt_spawn_grace_s=5.0)
        assert coord._startup_adopt() is True
        assert coord._procs[0].pid == orphan.pid
        assert coord._adopt_failures == []
    finally:
        orphan.kill()
        orphan.wait()


# -- journal compaction (ISSUE 15 satellite) --------------------------------

def _long_journal(tmp_path, records=30):
    """A journal with `records` total records whose replayed state has
    real content in every compactable field."""
    ft_dir = tmp_path / "ft"
    (ft_dir / "journal").mkdir(parents=True)
    with JournalWriter(journal_path(ft_dir)) as j:
        j.append("run_start", argv=["x"], hosts=2, policy="solo",
                 max_restarts=9)
        j.append("launching", hosts=[0, 1], first=True)
        j.append("gang_launched", first=True,
                 pids={"0": 111, "1": 222},
                 starts={"0": 1000, "1": 2000})
        j.append("chaos_fired", index=0, action="kill", host=1)
        j.append("incident_open", incident=1,
                 failures=[{"host": 1, "kind": "crash", "rc": 9}])
        j.append("restart_intent", incident=1, action="solo_restart",
                 hosts=[1], budget_used=1)
        n_solo = records - 7
        for i in range(n_solo):
            j.append("solo_launched", host=1, pid=300 + i, start=5000 + i)
        j.append("input_restarted", host=1, restarts=2)
    return ft_dir


def test_compact_journal_folds_state_and_replays_identically(tmp_path):
    from tpucfn.ft.journal import compact_journal

    ft_dir = _long_journal(tmp_path, records=30)
    before, recs_before, _ = replay_journal(journal_path(ft_dir))
    assert len(recs_before) == 30
    assert compact_journal(journal_path(ft_dir), max_records=10)
    after, recs_after, torn = replay_journal(journal_path(ft_dir))
    # one snapshot record now replays to the IDENTICAL state
    assert len(recs_after) == 1 and recs_after[0]["kind"] == "snapshot"
    assert not torn
    assert after.to_json() == before.to_json()
    assert after.seq == before.seq
    assert after.pending is not None
    assert after.pending.action == "solo_restart"
    assert after.pending.launched  # the solo_launched records landed
    assert after.proc_starts == before.proc_starts
    # forensics: the pre-compaction bytes were archived
    assert (journal_path(ft_dir).parent
            / "journal-compacted.jsonl").exists()


def test_compact_journal_appends_continue_contiguously(tmp_path):
    from tpucfn.ft.journal import compact_journal

    ft_dir = _long_journal(tmp_path)
    st0, _, _ = replay_journal(journal_path(ft_dir))
    assert compact_journal(journal_path(ft_dir), max_records=5)
    with JournalWriter(journal_path(ft_dir), start_seq=st0.seq) as j:
        j.append("host_exit", host=1, rc=0)
        j.append("done", rc=0)
    st, recs, _ = replay_journal(journal_path(ft_dir))
    assert st.done_rc == 0 and st.seq == st0.seq + 2
    assert [r["kind"] for r in recs] == ["snapshot", "host_exit", "done"]


def test_compact_journal_below_threshold_is_a_noop(tmp_path):
    from tpucfn.ft.journal import compact_journal

    ft_dir = _long_journal(tmp_path, records=30)
    raw = journal_path(ft_dir).read_bytes()
    assert not compact_journal(journal_path(ft_dir), max_records=100)
    assert journal_path(ft_dir).read_bytes() == raw


def test_compact_journal_skips_finished_runs(tmp_path):
    from tpucfn.ft.journal import compact_journal

    ft_dir = _long_journal(tmp_path)
    st0, _, _ = replay_journal(journal_path(ft_dir))
    with JournalWriter(journal_path(ft_dir), start_seq=st0.seq) as j:
        j.append("done", rc=0)
    assert not compact_journal(journal_path(ft_dir), max_records=5)


def test_snapshot_mid_journal_refuses_as_spliced(tmp_path):
    from tpucfn.ft.journal import CoordinatorState

    ft_dir = tmp_path / "ft"
    (ft_dir / "journal").mkdir(parents=True)
    st = CoordinatorState()
    p = journal_path(ft_dir)
    with open(p, "w") as f:
        f.write(encode_record({"seq": 1, "kind": "run_start",
                               "argv": ["x"], "hosts": 1}))
        f.write(encode_record({"seq": 5, "kind": "snapshot",
                               "state": st.to_json()}))
    with pytest.raises(JournalError, match="spliced|first"):
        replay_journal(p)


def test_adoption_compacts_past_the_threshold(tmp_path):
    """The wired path: an adopting coordinator with a tiny compaction
    threshold folds the journal before appending its own records."""
    ft_dir = tmp_path / "ft"
    (ft_dir / "journal").mkdir(parents=True)
    procs = [subprocess.Popen([sys.executable, "-c",
                               "import time; time.sleep(0.6)"])
             for _ in range(2)]
    with JournalWriter(journal_path(ft_dir)) as j:
        j.append("run_start", argv=["x"], hosts=2, policy="gang",
                 max_restarts=2)
        for k in range(10):
            j.append("launching", hosts=[0, 1], first=k == 0)
            j.append("gang_launched", first=k == 0,
                     pids={str(i): p.pid for i, p in enumerate(procs)})
    import threading

    def reap():
        for p in procs:
            write_rc(ft_dir, p.pid, p.wait())

    threading.Thread(target=reap, daemon=True).start()
    coord = GangCoordinator(
        _launcher(tmp_path, n=2), [sys.executable, "-c", "pass"],
        policy=GangRestart(RestartBudget(2)), ft_dir=ft_dir,
        poll_interval=0.01, term_grace_s=0.5,
        journal_compact_records=5)
    launches = []
    coord.launcher.launch = lambda *a, **k: launches.append(1) or []
    assert coord.run() == 0
    assert coord._adopted and launches == []
    st, recs, _ = replay_journal(journal_path(ft_dir))
    assert recs[0]["kind"] == "snapshot"
    # snapshot + adopted + host_exits + done, NOT the 21 old records
    assert len(recs) < 10
    assert st.done_rc == 0
    adopted = next(r for r in recs if r["kind"] == "adopted")
    assert adopted["compacted"] is True


# -- pid start-time identity (ISSUE 15 satellite) ---------------------------

def test_pid_start_time_is_stable_and_differs_across_processes():
    from tpucfn.ft.journal import pid_start_time

    mine = pid_start_time(os.getpid())
    assert isinstance(mine, int)
    assert pid_start_time(os.getpid()) == mine  # stable for a lifetime
    p = subprocess.Popen([sys.executable, "-c",
                          "import time; time.sleep(0.5)"])
    try:
        theirs = pid_start_time(p.pid)
        assert isinstance(theirs, int) and theirs != mine
    finally:
        p.kill()
        p.wait()
    assert pid_start_time(999999999) is None  # gone: no identity


def test_adopted_process_refuses_a_recycled_pid():
    """A live pid whose start time disagrees with the journaled one is
    an unrelated process: the handle reads it as dead (rc degrades, no
    rc file) and NEVER signals it."""
    from tpucfn.ft.journal import pid_start_time

    p = subprocess.Popen([sys.executable, "-c",
                          "import time; time.sleep(5)"])
    try:
        real = pid_start_time(p.pid)
        honest = AdoptedProcess(p.pid, start_time=real)
        assert honest.poll() is None  # same identity: alive
        recycled = AdoptedProcess(p.pid, start_time=real + 12345)
        assert recycled.poll() == 1  # identity mismatch: dead-unwatched
        recycled.kill()  # must NOT touch the innocent live process
        assert p.poll() is None
    finally:
        p.kill()
        p.wait()


def test_gang_launch_journals_start_times_and_replay_carries_them(tmp_path):
    ft_dir = tmp_path / "ft"
    coord = GangCoordinator(
        _launcher(tmp_path, n=2), [sys.executable, "-c", "pass"],
        policy=GangRestart(RestartBudget(0)), ft_dir=ft_dir,
        poll_interval=0.01, term_grace_s=0.5)
    assert coord.run() == 0
    st, recs, _ = replay_journal(journal_path(ft_dir))
    launched = next(r for r in recs if r["kind"] == "gang_launched")
    assert set(launched["starts"]) == {"0", "1"}
    assert all(isinstance(s, int) for s in launched["starts"].values())
    # host_exit pops them back out of the replayed state
    assert st.proc_starts == {}


def test_adoption_condemns_recycled_pid_as_dead_unwatched(tmp_path):
    """The cross-reboot shape: the journal names OUR OWN live pid (the
    ultimate recycled-pid stand-in) with a WRONG start time — adoption
    must treat the rank as dead-unwatched (a CRASH through the normal
    path) instead of attaching to a stranger; with the RIGHT start time
    it attaches."""
    from tpucfn.ft.journal import pid_start_time

    me = os.getpid()
    for wrong, expect_dead in ((True, True), (False, False)):
        ft_dir = tmp_path / ("ft-wrong" if wrong else "ft-right")
        (ft_dir / "journal").mkdir(parents=True)
        start = pid_start_time(me) + (999 if wrong else 0)
        with JournalWriter(journal_path(ft_dir)) as j:
            j.append("run_start", argv=["x"], hosts=1, policy="solo",
                     max_restarts=1)
            j.append("gang_launched", first=True, pids={"0": me},
                     starts={"0": start})
        coord = GangCoordinator(
            _launcher(tmp_path, n=1), [sys.executable, "-c", "pass"],
            policy=SoloRestart(RestartBudget(1)), ft_dir=ft_dir,
            poll_interval=0.01, term_grace_s=0.5)
        coord._startup_adopt()
        if expect_dead:
            assert 0 not in coord._procs
            assert [f.host_id for f in coord._adopt_failures] == [0]
        else:
            assert 0 in coord._procs
            assert coord._procs[0].pid == me
            assert coord._adopt_failures == []
        if coord._journal is not None:
            coord._journal.close()
