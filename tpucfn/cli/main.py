"""``tpucfn`` CLI — the user-facing command surface.

Command-for-command parity with the reference's documented workflow
(SURVEY.md §1 L6, §3.1-§3.5):

    reference                              tpucfn
    ------------------------------------   ------------------------------------
    aws cloudformation create-stack        tpucfn create-stack --name p --accelerator v4-32
      --template-body …deeplearning.template  [--spec cluster.json]
    (stack Outputs: master DNS)            printed outputs: coordinator, env file
    aws cloudformation describe-stacks     tpucfn status --name p
    aws cloudformation update-stack        tpucfn resize --name p --accelerator v4-64
    aws cloudformation delete-stack        tpucfn delete --name p
    launch.py -n $N -H $HOSTFILE cmd…      tpucfn launch --name p -- python train.py …
    (ssh master; env already exported)     tpucfn env --name p   (print/export contract)

State lives in ``--state-dir`` (default ``~/.tpucfn``) through the fake
control plane. ``--backend fake`` (default) "provisions" local state —
the single-host path used with the real TPU chip and in CI;
``--backend gcp`` drives real TPU queued resources via gcloud
(tpucfn/provision/gcp.py; needs TPUCFN_GCP_PROJECT/_ZONE).
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

from tpucfn.bootstrap import converge
from tpucfn.launch import Launcher, LocalTransport, SSHTransport
from tpucfn.provision import FakeControlPlane, Provisioner
from tpucfn.spec import ClusterSpec


def _slo_objective(s: str) -> float:
    """argparse type for ``--slo-objective``: the fraction must leave a
    nonzero error budget (burn rate divides by 1 − objective), so 0 and
    1 are usage errors, not tracebacks from SLOTracker's constructor."""
    try:
        v = float(s)
    except ValueError:
        raise argparse.ArgumentTypeError(f"not a number: {s!r}")
    if not 0.0 < v < 1.0:
        raise argparse.ArgumentTypeError(
            f"objective must be in (0, 1) exclusive, got {v} — 1.0 has "
            "no error budget to burn")
    return v


def _control_plane(args):
    if getattr(args, "backend", "fake") == "gcp":
        from tpucfn.provision import GcpQueuedResourceControlPlane

        return GcpQueuedResourceControlPlane()
    state = Path(args.state_dir).expanduser() / "control_plane.json"
    # steps_to_provision=1: CLI ticks are driven by wait_active polling.
    return FakeControlPlane(steps_to_provision=1, state_file=str(state))


def _run_dir(args, name: str) -> Path:
    return Path(args.state_dir).expanduser() / "clusters" / name


def cmd_create_stack(args) -> int:
    if args.spec:
        spec = ClusterSpec.load(args.spec)
    else:
        if not args.name:
            print("error: --name (or --spec file) required", file=sys.stderr)
            return 2
        spec = ClusterSpec(
            name=args.name,
            accelerator=args.accelerator,
            storage_path=args.storage or "",
        )
    prov = Provisioner(_control_plane(args))
    rec = prov.create(spec)
    contract = converge(rec, _run_dir(args, spec.name))
    print(f"CREATE_COMPLETE {spec.name}")
    print(f"  accelerator:  {spec.accelerator} ({spec.num_hosts} hosts, "
          f"{spec.num_chips} chips)")
    print(f"  coordinator:  {contract.coordinator}")
    print(f"  hostfile:     {contract.workers_path}")
    print(f"  env file:     {_run_dir(args, spec.name) / 'env.sh'}")
    print(f"  next:         tpucfn launch --name {spec.name} -- python train.py")
    return 0


def cmd_status(args) -> int:
    rec = _control_plane(args).describe(args.name)
    print(f"{args.name}: {rec.state.value} gen={rec.generation}")
    for h in rec.hosts:
        print(f"  host{h.host_id} {h.address} {'healthy' if h.healthy else 'DEAD'}")
    return 0


def cmd_delete(args) -> int:
    Provisioner(_control_plane(args)).delete(args.name)
    print(f"DELETE_COMPLETE {args.name}")
    return 0


def cmd_resize(args) -> int:
    prov = Provisioner(_control_plane(args))
    rec = prov.resize(args.name, args.accelerator)
    converge(rec, _run_dir(args, args.name))
    print(f"RESIZE_COMPLETE {args.name} -> {args.accelerator} "
          f"({len(rec.hosts)} hosts, gen={rec.generation})")
    print("  running jobs must be re-launched; they resume from their "
          "latest checkpoint")
    return 0


def cmd_env(args) -> int:
    rec = _control_plane(args).describe(args.name)
    contract = converge(rec, _run_dir(args, args.name))
    for k, v in sorted(contract.to_env().items()):
        print(f"export {k}={v!r}")
    return 0


def cmd_launch(args) -> int:
    rec = _control_plane(args).describe(args.name)
    from tpucfn.provision.control_plane import ClusterState

    if rec.state is not ClusterState.ACTIVE:
        print(f"error: cluster {args.name} is {rec.state.value}, not ACTIVE",
              file=sys.stderr)
        return 1
    contract = converge(rec, _run_dir(args, args.name))
    transport = SSHTransport() if args.transport == "ssh" else LocalTransport()
    ft_dir = _run_dir(args, args.name) / "ft" if args.ft else None
    if args.supervise:
        # Self-supervision (ISSUE 12): re-exec this same invocation
        # (minus the supervise flags) under the jax-free supervise
        # loop.  A crashed coordinator is relaunched and ADOPTS the
        # running fleet through the write-ahead journal; a finished
        # run's rc propagates.
        if not args.ft:
            print("error: --supervise needs --ft (the write-ahead journal "
                  "and fleet adoption live under the ft dir)",
                  file=sys.stderr)
            return 2
        from tpucfn.launch.supervise import (run_supervised,
                                             supervised_cli_argv)

        child = supervised_cli_argv(sys.argv[1:])
        print(f"supervising coordinator (up to {args.supervise_restarts} "
              f"restart(s); journal under {ft_dir}/journal)",
              file=sys.stderr)
        rc = run_supervised(child, ft_dir=ft_dir,
                            max_restarts=args.supervise_restarts)
        print(f"launch finished rc={rc}")
        return rc
    if args.input_hosts and args.input_hosts >= contract.workers_count:
        print(f"error: --input-hosts {args.input_hosts} leaves no trainer "
              f"in a {contract.workers_count}-host cluster", file=sys.stderr)
        return 2
    if args.input_hosts and not args.input_cmd:
        # No shipped job switches on TPUCFN_ROLE, so defaulting to the
        # trainer argv would silently run a ROGUE extra trainer (a
        # second "rank 0" writing the same run dir) while the trainers
        # degrade to local loading — the feature must refuse loudly,
        # not no-op.
        print("error: --input-hosts needs --input-cmd (e.g. "
              "--input-cmd 'python -m tpucfn.cli data serve --shards D "
              "--batch-size B') — input hosts must run the input "
              "service, not a copy of the trainer argv", file=sys.stderr)
        return 2
    input_argv = None
    if args.input_cmd:
        import shlex

        input_argv = shlex.split(args.input_cmd)
    # Provisioner policy loop (ISSUE 18): all usage validation first —
    # the controller observes the goodput ledgers and actuates through
    # the coordinator, so both planes must exist.
    if args.provision_policy and not args.ft:
        print("error: --provision-policy needs --ft (the controller "
              "actuates through the gang coordinator's planned-restart "
              "machinery)", file=sys.stderr)
        return 2
    if args.provision_policy and not args.input_hosts:
        print("error: --provision-policy needs --input-hosts N (growing "
              "the input plane is the one actuator it owns; with no "
              "input hosts there is nothing to provision)", file=sys.stderr)
        return 2
    if args.defer_input_plane and not args.input_hosts:
        print("error: --defer-input-plane needs --input-hosts N (it "
              "reserves those hosts for the provisioner instead of "
              "spawning them at launch)", file=sys.stderr)
        return 2
    # All usage validation happens BEFORE any server binds: an error
    # early-return below must not leak a bound artifact-server port
    # (its close() lives in the later try/finally).
    argv = list(args.cmd)
    if argv and argv[0] == "--":
        argv = argv[1:]
    if not argv:
        print("error: no command given (use: tpucfn launch --name X -- cmd…)",
              file=sys.stderr)
        return 2
    inject = None
    if args.kill_host_after:
        host_s, _, secs = args.kill_host_after.partition(":")
        try:
            inject = (int(host_s), float(secs))
        except ValueError:
            print(f"error: --kill-host-after wants HOST:SECONDS (e.g. 1:30), "
                  f"got {args.kill_host_after!r}", file=sys.stderr)
            return 2
        if not 0 <= inject[0] < len(contract.hosts()):
            print(f"error: --kill-host-after host {inject[0]} out of range "
                  f"(cluster has {len(contract.hosts())} hosts)", file=sys.stderr)
            return 2
    # Chaos plane (ISSUE 15): a launch-level chaos spec replays against
    # the gang coordinator — kills, hangs, AND the net_* gray-failure
    # ops, which land on the --chaos-proxy instances this process runs.
    # Spec parsing is pure validation and must precede every bind.
    chaos_spec = None
    if args.chaos:
        if not args.ft:
            print("error: --chaos needs --ft (chaos specs replay against "
                  "the gang coordinator's supervision clock)",
                  file=sys.stderr)
            return 2
        from tpucfn.ft.chaos import ChaosSpec

        raw = args.chaos
        try:
            if not raw.lstrip().startswith("{"):
                raw = Path(raw).read_text()
            chaos_spec = ChaosSpec.from_json(raw)
        except (OSError, ValueError, TypeError) as e:
            print(f"error: bad --chaos spec: {e}", file=sys.stderr)
            return 2
    proxy_specs: list[tuple[int, str]] = []
    for raw in args.chaos_proxy or []:
        parts = raw.split(":")
        if len(parts) != 3 or not parts[0].isdigit() \
                or not parts[2].isdigit():
            print("error: --chaos-proxy wants LISTEN:HOST:PORT (e.g. "
                  f"7651:127.0.0.1:7641), got {raw!r}", file=sys.stderr)
            return 2
        proxy_specs.append((int(parts[0]), f"{parts[1]}:{parts[2]}"))
    if chaos_spec is not None and not proxy_specs \
            and any(e.action.startswith("net_")
                    for e in chaos_spec.events):
        # a net fault with nowhere to land is a usage error HERE, not a
        # coordinator exception minutes into the run
        print("error: --chaos spec schedules net_* events — they need "
              "at least one --chaos-proxy LISTEN:HOST:PORT to land on",
              file=sys.stderr)
        return 2
    # Fleet warm start (ISSUE 13): the coordinator process runs the
    # jax-free artifact server and fans its address out to every host
    # (TPUCFN_COMPILE_CACHE_ADDRS) — host 0 compiles once, peers fetch;
    # every ft relaunch re-derives the same env, so restart MTTR stops
    # repaying the compile.  Without the flag, nothing changes (pinned).
    cc_server = None
    cc_addrs = None
    registry = None
    if args.obs_port or args.compile_cache:
        # One supervisor registry for everything this process hosts —
        # created before the artifact server so its compilecache_*
        # counters land on the same /metrics the obs endpoint serves.
        from tpucfn.obs import MetricRegistry

        registry = MetricRegistry(labels={"role": "supervisor"})
    if args.compile_cache:
        from tpucfn.compilecache.service import (ArtifactServer,
                                                 DEFAULT_COMPILE_CACHE_PORT)

        cc_dir = args.compile_cache_dir or str(
            _run_dir(args, args.name) / "compilecache")
        cc_server = ArtifactServer(
            cc_dir, host="0.0.0.0",
            port=args.compile_cache_port or DEFAULT_COMPILE_CACHE_PORT,
            registry=registry)
        cc_server.start()
        # The server runs in THIS process: the advertised host must be
        # an address of THIS machine as the fleet sees it.  The
        # coordinator-host default matches the documented deployment
        # (run `tpucfn launch` on host 0); anywhere else, say so.
        advertise = (args.compile_cache_advertise
                     or ("127.0.0.1" if args.transport == "local"
                         else contract.coordinator.rsplit(":", 1)[0]))
        cc_addrs = [f"{advertise}:{cc_server.port}"]
        print(f"compile-artifact server: {cc_addrs[0]} (store {cc_dir})",
              file=sys.stderr)
    net_proxies = []
    if proxy_specs:
        from tpucfn.net.proxy import ChaosProxy

        try:
            for listen, upstream in proxy_specs:
                p = ChaosProxy(upstream, host="0.0.0.0", port=listen,
                               registry=registry)
                p.start()
                net_proxies.append(p)
                print(f"chaos proxy: :{p.port} -> {upstream}",
                      file=sys.stderr)
        except BaseException:
            for p in net_proxies:
                p.close()
            if cc_server is not None:
                cc_server.close()
            raise
    launcher = Launcher(contract, transport,
                        obs_base_port=args.obs_port or None,
                        ft_dir=str(ft_dir) if ft_dir else None,
                        ft_heartbeat_s=(args.ft_heartbeat_interval
                                        if args.ft else None),
                        input_hosts=args.input_hosts,
                        input_port=args.input_port or None,
                        input_argv=input_argv,
                        # Local fleets run every host on loopback but the
                        # fake control plane's hostfile says 10.0.0.x —
                        # advertising those would make every trainer burn
                        # the connect-retry window and degrade to local.
                        input_advertise_host=("127.0.0.1"
                                              if args.transport != "ssh"
                                              else None),
                        compile_cache_addrs=cc_addrs,
                        defer_input_plane=args.defer_input_plane)
    from tpucfn.launch import run_with_restarts

    obs_srv = None
    monitor = None
    # The launched gang is hosts()[:workers_count] (Launcher.launch's
    # precedence rule) — what the monitor judges and whose ports serve.
    n_launched = len(contract.hosts()[:contract.workers_count])
    try:
        # Anything that can raise between the artifact server binding
        # and the main try/finally (monitor dirs, the obs port — an
        # EADDRINUSE here is routine) must not leak the bound server
        # and its accept thread.
        if args.ft:
            # The fault-tolerance plane (ISSUE 4): heartbeat monitor
            # over the dir every rank writes into (Launcher fans out
            # TPUCFN_FT_DIR).
            import random

            from tpucfn.ft import (GangCoordinator, HeartbeatMonitor,
                                   MonitorConfig, RestartBudget,
                                   policy_from_name)

            # Startup grace must cover runtime boot (jax import + data
            # staging + first compile can be tens of seconds), not just
            # a few heartbeat intervals — a booting gang that has not
            # beaten yet is not hung, and phantom hang incidents burn
            # the restart budget.  Crash detection (process exit) is
            # unaffected by it.
            monitor = HeartbeatMonitor(
                ft_dir, expected_hosts=n_launched,
                config=MonitorConfig(
                    interval_s=args.ft_heartbeat_interval,
                    startup_grace_s=args.ft_startup_grace))
        # /healthz late-binds to the coordinator once it exists so the
        # probe carries journal/adoption state (ISSUE 12) on top of the
        # monitor's fleet view; before that (and without --ft) it falls
        # back to the monitor or plain liveness.
        coord_ref: dict = {}

        def _health_fn():
            c = coord_ref.get("coord")
            if c is not None:
                return c.health()
            if monitor is not None:
                return monitor.health()
            return True, {}

        if args.obs_port:
            # The supervisor is a fleet role too: it owns the base
            # port, the per-host ranks get base+1+host_id
            # (launcher.host_env).  With --ft its /healthz answers from
            # the heartbeat monitor's fleet view — 503 the moment any
            # host goes DEAD.
            from tpucfn.obs import start_obs_server

            obs_srv = start_obs_server(
                registry, port=args.obs_port, role="supervisor",
                health_fn=_health_fn if args.ft else None)
            print(f"supervisor obs endpoint: {obs_srv.url()} "
                  f"(hosts at ports {args.obs_port + 1}..."
                  f"{args.obs_port + n_launched})", file=sys.stderr)
    except BaseException:
        for p in net_proxies:
            p.close()
        if cc_server is not None:
            cc_server.close()
        raise
    try:
        if args.ft:
            from tpucfn.ft import StragglerGuard

            budget = RestartBudget(
                args.ft_restart_budget if args.ft_restart_budget is not None
                else args.restarts,
                backoff_s=args.ft_backoff, rng=random.Random(args.ft_seed))

            # Elastic shrink (ISSUE 7): before relaunching a failed
            # host, ask the control plane whether it still owns a
            # healthy machine at that address — `tpucfn kill-host` (or
            # a real backend losing capacity) makes the next recovery
            # re-converge at N-1 instead of relaunching a ghost.
            cp = _control_plane(args)

            import time as _time

            _reacquire_cache: dict = {"t": -10.0, "healthy": frozenset()}

            def _reacquire(addr: str, _name=args.name, _cp=cp) -> bool:
                # One describe() snapshot per incident burst (1s TTL),
                # not one per probed host: the coordinator checks every
                # host during a drain, and on a real backend that would
                # be N API round-trips inside the preemption lead time.
                now = _time.monotonic()
                if now - _reacquire_cache["t"] > 1.0:
                    _reacquire_cache["healthy"] = frozenset(
                        h.address for h in _cp.describe(_name).hosts
                        if h.healthy)
                    _reacquire_cache["t"] = now
                return addr in _reacquire_cache["healthy"]

            provision_policy = None
            goodput_dir = None
            if args.provision_policy:
                from tpucfn.provision import (PolicyConfig,
                                              provision_policy_from_name)

                # Must be the SAME dir the trainers' GoodputLedger
                # writes into (examples/common.py: run_dir/goodput) —
                # the controller reads what the fleet reports.
                goodput_dir = (Path(args.provision_goodput_dir)
                               if args.provision_goodput_dir
                               else _run_dir(args, args.name) / "goodput")
                provision_policy = provision_policy_from_name(
                    args.provision_policy,
                    PolicyConfig(
                        grow_threshold=args.provision_grow_threshold,
                        shrink_threshold=args.provision_shrink_threshold,
                        cooldown_s=args.provision_cooldown,
                        max_input_hosts=args.input_hosts))

            coordinator = GangCoordinator(
                launcher, argv,
                policy=policy_from_name(args.ft_policy, budget),
                monitor=monitor, ft_dir=ft_dir, registry=registry,
                kill_host_after=inject,
                ckpt_dir=_run_dir(args, args.name) / "ckpt",
                drain_grace_s=args.ft_drain_grace,
                allow_shrink=not args.ft_no_shrink,
                reacquire_check=_reacquire,
                max_ckpt_retries=args.ft_max_ckpt_retries,
                straggler_guard=StragglerGuard(
                    hysteresis_s=args.ft_straggler_hysteresis,
                    flap_budget=args.ft_straggler_flap_budget),
                restart_input_hosts=args.ft_restart_input_hosts,
                adopt=(True if args.adopt
                       else False if args.no_adopt else "auto"),
                chaos=chaos_spec,
                net_proxies=net_proxies,
                provision_policy=provision_policy,
                goodput_dir=goodput_dir,
                provision_interval_s=args.provision_interval)
            coord_ref["coord"] = coordinator
            rc = coordinator.run()
        else:
            rc = run_with_restarts(launcher, argv, max_restarts=args.restarts,
                                   kill_host_after=inject, registry=registry)
    finally:
        if obs_srv is not None:
            obs_srv.close()
        for p in net_proxies:
            p.close()
        if cc_server is not None:
            cc_server.close()
    print(f"launch finished rc={rc}")
    return rc


def cmd_chaos_proxy(args) -> int:
    """Run the network fault-injection proxy standalone (ISSUE 15):
    ``tpucfn chaos proxy --listen P --upstream H:P --spec faults.json``
    fronts any fleet plane's port and injects the scheduled gray
    failures (latency/throttle/stall/partition/tear/rst) at their
    seeded, deterministic times.  SIGTERM (or ``--serve-for``) ends it
    with a stats JSON line — the same operational shape as ``tpucfn
    data serve`` and ``compilecache serve``."""
    import json as _json
    import signal as _signal
    import time as _time

    from tpucfn.net.proxy import ChaosProxy, NetFaultSchedule

    host, _, port = args.upstream.rpartition(":")
    if not port.isdigit():
        print(f"error: --upstream wants HOST:PORT, got {args.upstream!r}",
              file=sys.stderr)
        return 2
    schedule = None
    if args.spec:
        raw = args.spec
        try:
            if not raw.lstrip().startswith("{"):
                raw = Path(raw).read_text()
            schedule = NetFaultSchedule.from_json(raw)
            if args.seed is not None:
                schedule = NetFaultSchedule(faults=schedule.faults,
                                            seed=args.seed)
        except (OSError, ValueError, TypeError) as e:
            print(f"error: bad --spec: {e}", file=sys.stderr)
            return 2
    from tpucfn.obs import MetricRegistry

    registry = MetricRegistry(labels={"role": "chaosproxy"})
    proxy = ChaosProxy(args.upstream, host=args.host, port=args.listen,
                       schedule=schedule, registry=registry)
    stop = [False]

    def _on_term(signum, frame):
        # ONE plain GIL-atomic store (the PR 8 signal lesson); the main
        # loop notices and closes.
        stop[0] = True

    try:
        _signal.signal(_signal.SIGTERM, _on_term)
    except ValueError:
        pass  # not the main thread (embedded use)
    t0 = _time.monotonic()
    try:
        proxy.start()
        print(f"chaos proxy listening on {proxy.address} -> "
              f"{args.upstream}"
              + (f" ({len(schedule.faults)} scheduled fault(s), "
                 f"seed {schedule.seed})" if schedule else ""),
              file=sys.stderr)
        deadline = (t0 + args.serve_for) if args.serve_for > 0 else None
        while not stop[0]:
            if deadline is not None and _time.monotonic() >= deadline:
                break
            _time.sleep(0.2)
    finally:
        proxy.close()
    m = registry.varz()["metrics"]
    print(_json.dumps({
        "served_s": round(_time.monotonic() - t0, 3),
        "connections": m.get("net_proxy_connections_total", 0),
        "faults_fired": m.get("net_proxy_faults_fired_total", 0),
        "forwarded_bytes": m.get("net_proxy_forwarded_bytes_total", 0),
        "dropped_bytes": m.get("net_proxy_dropped_bytes_total", 0),
        "fired": proxy.fired,
    }))
    return 0


def cmd_kill_host(args) -> int:
    """Fault injection (SURVEY.md §5): mark a host dead so monitors and
    tests can exercise the recovery path."""
    _control_plane(args).kill_host(args.name, args.host)
    print(f"host {args.host} of {args.name} marked dead")
    return 0


def cmd_heal(args) -> int:
    prov = Provisioner(_control_plane(args))
    rec = prov.ensure_healthy(args.name)
    converge(rec, _run_dir(args, args.name))
    print(f"{args.name}: {rec.state.value} gen={rec.generation} "
          f"({len(rec.hosts)} healthy hosts)")
    return 0


def cmd_convert_dataset(args) -> int:
    """Pack a real dataset into tpurecord shards (≈ MXNet's im2rec step
    the reference assumed had already happened off-cluster)."""
    from tpucfn.data.convert import (
        convert_cifar_binary,
        convert_image_tree,
        convert_token_jsonl,
    )

    if args.kind == "image-tree":
        paths = convert_image_tree(args.src, args.out, num_shards=args.num_shards)
    elif args.kind == "recordio":
        from tpucfn.data.recordio import convert_recordio

        paths = convert_recordio(args.src, args.out,
                                 num_shards=args.num_shards)
    elif args.kind == "token-jsonl":
        paths = convert_token_jsonl(args.src, args.out,
                                    seq_len=args.seq_len,
                                    num_shards=args.num_shards)
    else:
        paths = convert_cifar_binary(args.src, args.out,
                                     num_shards=args.num_shards,
                                     train=not args.test_split)
    print(f"wrote {len(paths)} shards to {args.out}")
    if args.publish:
        from tpucfn.data.store import store_for_url
        from tpucfn.data.convert import upload_shards

        store, prefix = store_for_url(args.publish)
        sidecars = [p for p in Path(args.out).glob("*.json")]
        upload_shards([*paths, *sidecars], store, prefix)
        print(f"published {len(paths) + len(sidecars)} objects to {args.publish}")
    return 0


def cmd_stage_data(args) -> int:
    """Sync a dataset prefix down to a local cache (≈ `aws s3 sync`)."""
    from tpucfn.data.store import stage_url

    paths = stage_url(args.url, args.dest)
    print(f"staged {len(paths)} shards into {args.dest}")
    return 0


def cmd_data_serve(args) -> int:
    """Run the disaggregated input plane's service on this host
    (ISSUE 11 tentpole): per connected trainer, the exact
    ShardedDataset/MultiProcessLoader stage the trainer would run
    locally, streamed as ready batches.  jax is never imported — input
    hosts are pure CPU/RAM capacity.

    Under the ``tpucfn launch --input-hosts N`` fan-out everything
    defaults from the env contract (bind port from TPUCFN_INPUT_PORT,
    trainer count from TPUCFN_WORKERS_COUNT, heartbeats into
    TPUCFN_FT_DIR, /metrics on TPUCFN_OBS_PORT); standalone use passes
    the flags explicitly."""
    import json as _json
    import signal as _signal
    import time as _time

    from tpucfn.data.service import INPUT_PORT_ENV, InputService

    shards = sorted(Path(args.shards).glob("*.tpurec"))
    if not shards:
        print(f"error: no *.tpurec shards under {args.shards}",
              file=sys.stderr)
        return 2
    num_trainers = args.num_trainers
    if num_trainers is None:
        raw = os.environ.get("TPUCFN_WORKERS_COUNT", "").strip()
        if not raw:
            print("error: --num-trainers required outside a `tpucfn "
                  "launch --input-hosts` fan-out (TPUCFN_WORKERS_COUNT "
                  "unset)", file=sys.stderr)
            return 2
        num_trainers = int(raw)
    port = args.port
    if port is None:
        port = int(os.environ.get(INPUT_PORT_ENV, "0") or 0)

    from tpucfn.obs import MetricRegistry, start_obs_server
    from tpucfn.obs.trace import Tracer

    host_id = int(os.environ.get("TPUCFN_HOST_ID", "0") or 0)
    registry = MetricRegistry(labels={"role": "input",
                                      "host": str(host_id)})
    hb = obs_srv = None
    # Fleet timeline (ISSUE 20): with a trace dir (flag, or the
    # launcher's TPUCFN_TRACE_DIR fan-out) every served batch lands an
    # input_serve span whose (trace_id, span_id, origin) context rides
    # the batch frame's header — the remote parent of the trainer's
    # data_wait.  Unset ⇒ Tracer(None), zero wire or file cost.
    trace_dir = (args.trace_dir
                 or os.environ.get("TPUCFN_TRACE_DIR", "").strip() or None)
    tracer = Tracer(trace_dir, host_id=host_id, role="input")
    service = InputService(
        shards, num_trainers=num_trainers,
        batch_size_per_process=args.batch_size, seed=args.seed,
        num_epochs=args.num_epochs, host=args.host, port=port,
        queue_batches=args.queue_batches, mp_workers=args.mp_workers,
        sndbuf_bytes=args.sndbuf_kb * 1024 if args.sndbuf_kb else None,
        send_deadline_s=args.send_deadline,
        registry=registry, shuffle=not args.no_shuffle,
        cache_in_memory=not args.stream,
        num_workers=args.workers, tracer=tracer)
    try:
        service.start()
        print(f"input service listening on {service.address} "
              f"({len(shards)} shards, {num_trainers} trainer stream(s))",
              file=sys.stderr)
        obs_srv = start_obs_server(registry, port=args.obs_port,
                                   role="input", host_id=host_id,
                                   tracer=tracer)
        if obs_srv is not None:
            print(f"obs endpoint: {obs_srv.url()}", file=sys.stderr)
        # Under the ft fan-out an input host is a first-class fleet
        # member: it beats like any rank, and its death is routed as
        # input_degraded (trainers fall back to local loading) instead
        # of a gang incident.
        ft_dir = os.environ.get("TPUCFN_FT_DIR", "").strip()
        if ft_dir:
            from tpucfn.ft.heartbeat import HeartbeatWriter

            hb = HeartbeatWriter(
                ft_dir, host_id, role="input",
                interval_s=float(
                    os.environ.get("TPUCFN_FT_HEARTBEAT_S", "1.0") or 1.0))
            hb.start()

        def _on_term(signum, frame):
            # one lock-free store; wait_idle notices and the main
            # thread runs the real close (a handler must never take
            # this object's locks — the PR 8 drain lesson)
            service.request_close()
            print("SIGTERM: input service closing", file=sys.stderr)

        try:
            _signal.signal(_signal.SIGTERM, _on_term)
        except ValueError:
            pass  # not the main thread (embedded use)
        t0 = _time.monotonic()
        service.wait_idle(args.idle_exit if args.idle_exit > 0 else None)
    finally:
        service.close()
        tracer.close()
        if hb is not None:
            hb.stop()
        if obs_srv is not None:
            obs_srv.close()
    m = registry.varz()["metrics"]
    print(_json.dumps({
        "served_s": round(_time.monotonic() - t0, 3),
        "batches_streamed": m.get("input_batches_streamed_total", 0),
        "bytes_streamed": m.get("input_bytes_streamed_total", 0),
        "connections": m.get("input_connections_total", 0),
        "stream_errors": m.get("input_stream_errors_total", 0),
    }))
    return 0


def cmd_compilecache_serve(args) -> int:
    """Run the fleet compiled-artifact server standalone (ISSUE 13):
    the input-role-host / host-0 deployment shape, jax-free — the
    ``tpucfn launch --compile-cache`` coordinator-hosted form is the
    other.  Serves GET/CLAIM/PUT over the PR 11 framing; SIGTERM (or
    ``--serve-for``) ends it, printing a stats JSON line."""
    import json as _json
    import signal as _signal
    import time as _time

    from tpucfn.compilecache.service import (ArtifactServer,
                                             DEFAULT_COMPILE_CACHE_PORT)
    from tpucfn.compilecache.store import default_store_dir

    from tpucfn.obs import MetricRegistry

    host_id = int(os.environ.get("TPUCFN_HOST_ID", "0") or 0)
    registry = MetricRegistry(labels={"role": "compilecache",
                                      "host": str(host_id)})
    # Fleet timeline (ISSUE 20): artifact_serve spans record the
    # requesting trainer's compile_fetch context as their remote
    # parent.  Unset ⇒ Tracer(None), no cost.
    from tpucfn.obs.trace import Tracer

    trace_dir = (getattr(args, "trace_dir", None)
                 or os.environ.get("TPUCFN_TRACE_DIR", "").strip() or None)
    tracer = Tracer(trace_dir, host_id=host_id, role="compilecache")
    server = ArtifactServer(
        args.dir or default_store_dir(), host=args.host,
        port=args.port if args.port is not None
        else DEFAULT_COMPILE_CACHE_PORT,
        device_kind=args.device_kind or None,
        jax_version=args.jax_version or None,
        registry=registry, tracer=tracer)
    stop = [False]

    def _on_term(signum, frame):
        # ONE plain GIL-atomic store (the PR 8 signal lesson — an
        # Event.set() takes a lock); the main loop does the close.
        stop[0] = True

    try:
        _signal.signal(_signal.SIGTERM, _on_term)
    except ValueError:
        pass  # not the main thread (embedded use)
    t0 = _time.monotonic()
    try:
        server.start()
        print(f"compile-artifact server listening on {server.address} "
              f"(store {server.store.dir})", file=sys.stderr)
        deadline = (t0 + args.serve_for) if args.serve_for > 0 else None
        while not stop[0]:
            if deadline is not None and _time.monotonic() >= deadline:
                break
            _time.sleep(0.2)
    finally:
        server.close()
        tracer.close()
    m = registry.varz()["metrics"]
    print(_json.dumps({
        "served_s": round(_time.monotonic() - t0, 3),
        "entries": len(server.store.keys()),
        "gets": m.get("compilecache_gets_total", 0),
        "hits": m.get("compilecache_hits_total", 0),
        "publishes": m.get("compilecache_publishes_total", 0),
        "claims_granted": m.get("compilecache_claims_granted_total", 0),
        "handshake_refusals": m.get(
            "compilecache_handshake_refusals_total", 0),
    }))
    return 0


def _parse_bytes(s: str) -> int:
    """'512M', '2G', '100K', or a plain byte count."""
    s = s.strip()
    mult = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30}.get(s[-1:].lower())
    if mult is not None:
        return int(float(s[:-1]) * mult)
    return int(s)


def cmd_compilecache_gc(args) -> int:
    """Cap a compile-artifact store at ``--max-bytes`` (ISSUE 14
    satellite): live entries evict LRU by meta atime (reads touch it),
    claimed keys are never evicted, racing publishers' orphan payloads
    and stale tmp files older than ``--orphan-age`` sweep out.  Prints
    the stats JSON line; jax-free (safe from cron on any host sharing
    the dir)."""
    import json as _json

    from tpucfn.compilecache.store import ArtifactStore, default_store_dir

    store = ArtifactStore(args.dir or default_store_dir())
    try:
        max_bytes = _parse_bytes(args.max_bytes)
        if max_bytes < 0:
            raise ValueError(max_bytes)
    except ValueError:
        print(f"error: bad --max-bytes {args.max_bytes!r} "
              "(use a non-negative N, NK, NM, or NG)", file=sys.stderr)
        return 2
    stats = store.gc(max_bytes, orphan_age_s=args.orphan_age)
    print(_json.dumps({"dir": str(store.dir), "max_bytes": max_bytes,
                       **stats}))
    return 0


def cmd_compilecache_stats(args) -> int:
    """Query a running artifact server's stats (entries, live claims,
    fleet identity) — the operator's is-the-warm-start-plane-working
    probe."""
    import json as _json

    from tpucfn.compilecache.service import ArtifactClient
    from tpucfn.data.service import ServiceError

    try:
        print(_json.dumps(ArtifactClient(args.addr).stats()))
    except ServiceError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0


def cmd_serve(args) -> int:
    """Continuous-batching inference over a workload of token-id
    prompts (``--prompts`` JSONL with {"tokens": [...]} rows, or
    ``--synthetic N`` random prompts) and print the serving metrics
    snapshot as one JSON line.  Net-new vs the reference (training-only
    harness); the serving counterpart of ``launch``.

    ``--replicas N`` (ISSUE 9) runs N engine replicas behind a
    :class:`~tpucfn.serve.router.ReplicaRouter` — health-driven
    failover, deadline-budgeted retry (``--retry-budget``), optional
    hedging (``--hedge-ms``), graceful drain on SIGTERM.

    ``--spec-draft PRESET`` (ISSUE 14) pairs each engine (or the
    ``--spec-replicas`` subset) with a draft engine for speculative
    decoding: greedy output stays bit-identical, throughput rides the
    measured acceptance rate, and the adaptive controller bounds the
    worst case at plain decode plus an amortized probe."""
    import json as _json
    import signal as _signal

    import numpy as np

    from tpucfn.serve import AdmissionError, Server
    from tpucfn.serve.engine import ServeEngine, demo_llama_engine

    # Host identity: under `tpucfn launch` every rank carries
    # TPUCFN_HOST_ID — without it a serve gang's trace files collide on
    # one name and the hosts' /metrics label sets are indistinguishable.
    host_id = int(os.environ.get("TPUCFN_HOST_ID", "0") or 0)
    from tpucfn.obs import MetricRegistry as _MetricRegistry

    registry = _MetricRegistry(labels={"role": "server",
                                       "host": str(host_id)})
    # Fleet warm start (ISSUE 13): installed BEFORE the first engine is
    # built, so every replica's prefill/decode programs — including a
    # probation relaunch's — fetch serialized executables instead of
    # recompiling.  Env unset ⇒ None, engines build their plain jits.
    from tpucfn.compilecache import configure_from_env as _cc_configure

    cc_client = _cc_configure(registry=registry)

    cfg, engine = demo_llama_engine(args.preset, seed=args.seed,
                                    max_batch=args.max_batch,
                                    cache_len=args.cache_len,
                                    prefill_width=args.max_prefill_batch)

    # Speculative decoding (ISSUE 14): each selected engine is paired
    # with its OWN draft engine (per-replica caches) at the target's
    # exact slot layout.  Unset ⇒ spec_set is empty and every engine is
    # the plain object itself — the byte-identical default.
    spec_set: set = set()
    if args.spec_draft:
        spec_set = set(range(max(args.replicas, 1)))
        if args.spec_replicas:
            spec_set = {int(t) for t in args.spec_replicas.split(",")
                        if t.strip()}
            bad = [i for i in spec_set if not 0 <= i < args.replicas]
            if bad:
                print(f"error: --spec-replicas {bad} outside "
                      f"0..{args.replicas - 1}", file=sys.stderr)
                return 2

    def _maybe_spec(i, eng):
        if i not in spec_set:
            return eng
        from tpucfn.serve.spec import SpecDecoder

        if args.spec_draft == "self":
            draft = ServeEngine.from_llama(
                cfg, engine.params, max_batch=args.max_batch,
                cache_len=eng.cache_len,
                prefill_width=args.max_prefill_batch)
        else:
            _, draft = demo_llama_engine(
                args.spec_draft,
                seed=(args.seed if args.spec_draft_seed is None
                      else args.spec_draft_seed),
                max_batch=args.max_batch, cache_len=eng.cache_len,
                prefill_width=args.max_prefill_batch)
        return SpecDecoder(eng, draft, k=args.spec_k,
                           adaptive=args.spec_adaptive)

    rs = np.random.RandomState(args.seed)
    if args.prompts:
        prompts = []
        with open(args.prompts) as f:
            for line in f:
                if line.strip():
                    prompts.append([int(t) for t in
                                    _json.loads(line)["tokens"]])
    else:
        lo, _, hi = (args.prompt_len or "4:32").partition(":")
        prompts = [
            rs.randint(0, cfg.vocab_size,
                       rs.randint(int(lo), int(hi or lo) + 1)).tolist()
            for _ in range(args.synthetic)]
    if not prompts:
        print("error: no prompts (use --prompts file or --synthetic N)",
              file=sys.stderr)
        return 2

    from tpucfn.obs import (FlightRecorder, ProfileCapture, Tracer,
                            register_device_gauges, start_obs_server)

    # The forensics plane for serve hosts (ISSUE 6): the ring feeds
    # /flightrecorder (where the gang coordinator captures survivors at
    # detect time) regardless of any on-disk dirs; the exit dump and
    # the on-demand profiler need a place on disk, which the serve CLI
    # only has when --trace-dir names the run's trace/ (their siblings
    # flight/ and profile/ match what `obs postmortem` reads).
    flight = FlightRecorder(host_id=host_id, role="server")
    register_device_gauges(registry)
    profiler = None
    if args.trace_dir:
        artifacts_root = Path(args.trace_dir).resolve().parent
        flight.install_dump_handlers(artifacts_root / "flight")
    tracer = obs_srv = hb = server = router = None
    reqs = []
    try:
        # Inside the try from the first resource on: a failed port bind
        # must not leak the tracer it was preceded by (and the tracer
        # truncates the per-run trace file — open it only once the run
        # is actually going to happen).
        tracer = Tracer(args.trace_dir, host_id=host_id, role="server",
                        truncate=True) if args.trace_dir else Tracer(None)
        if cc_client is not None:
            # late-bind: the compile_fetch spans of replicas built
            # below land in this run's trace file
            cc_client.tracer = tracer
        if args.trace_dir:
            profiler = ProfileCapture(artifacts_root / "profile",
                                      tracer=tracer)
        # --obs-port wins; otherwise the launcher-assigned
        # TPUCFN_OBS_PORT applies (a serve gang under `tpucfn launch
        # --obs-port` must bind the ports the supervisor printed);
        # neither -> no endpoint.
        obs_srv = start_obs_server(registry, port=args.obs_port,
                                   role="server", host_id=host_id,
                                   flight=flight, profiler=profiler)
        if obs_srv is not None:
            print(f"obs endpoint: {obs_srv.url()}", file=sys.stderr)
        # Gang supervision (ISSUE 9): under the `tpucfn launch --ft`
        # fan-out a serve host writes heartbeats like any trainer rank —
        # a dead serve host becomes an ft incident with flight capture
        # and relaunch through the existing GangCoordinator.
        hb = None
        ft_dir = os.environ.get("TPUCFN_FT_DIR", "").strip()
        if ft_dir:
            from tpucfn.ft.heartbeat import HeartbeatWriter

            hb = HeartbeatWriter(
                ft_dir, host_id, role="server",
                interval_s=float(
                    os.environ.get("TPUCFN_FT_HEARTBEAT_S", "1.0") or 1.0))
            hb.start()

        if args.replicas > 1:
            from tpucfn.serve import ReplicaRouter
            from tpucfn.serve.router import ReplicaTracer

            engines = [engine] + [
                ServeEngine.from_llama(cfg, engine.params,
                                       max_batch=args.max_batch,
                                       cache_len=args.cache_len,
                                       prefill_width=args.max_prefill_batch)
                for _ in range(args.replicas - 1)]
            # Wrapped OUTSIDE the factory so a probation relaunch
            # reuses the same engine pair (and its jit caches) instead
            # of recompiling a fresh draft.
            engines = [_maybe_spec(i, e) for i, e in enumerate(engines)]

            class _FlightTee:
                """Replica samples land in the replica's OWN ring (what
                the router captures from survivors at incident time)
                AND, tagged with the replica index, in the host-level
                ring `flight` — the one /flightrecorder serves and the
                gang coordinator captures when this HOST survives an
                incident.  Without the tee the host ring is empty in
                router mode and survivor forensics regress (PR 6)."""

                def __init__(self, replica: int):
                    self.replica = replica
                    self.ring = FlightRecorder(host_id=replica,
                                               role="replica")

                def record(self, kind, **fields):
                    flight.record(kind, replica=self.replica, **fields)
                    return self.ring.record(kind, **fields)

                def snapshot(self):
                    return self.ring.snapshot()

            def _replica(i: int) -> Server:
                # private registry + per-replica ring; the shared
                # registry carries the router_* series instead (two
                # replicas' serve_* counters on one registry would fuse)
                return Server(engines[i], num_blocks=args.num_blocks,
                              block_size=args.block_size,
                              max_queued_tokens=args.max_queued_tokens,
                              prefix_cache=args.prefix_cache,
                              max_prefill_batch=args.max_prefill_batch,
                              ttft_slo_s=args.slo_ttft,
                              tpot_slo_s=args.slo_tpot,
                              slo_objective=args.slo_objective,
                              tracer=ReplicaTracer(tracer, i),
                              flight=_FlightTee(i))

            serve_ft = (Path(ft_dir) / "serve" if ft_dir
                        else (artifacts_root / "serve-ft"
                              if args.trace_dir else None))
            router = ReplicaRouter(
                _replica, args.replicas, registry=registry,
                ft_dir=serve_ft, retry_budget=args.retry_budget,
                hedge_ms=args.hedge_ms, slo_shed=args.slo_shed,
                drain_grace_s=args.drain_grace)
        else:
            server = Server(_maybe_spec(0, engine),
                            num_blocks=args.num_blocks,
                            block_size=args.block_size,
                            max_queued_tokens=args.max_queued_tokens,
                            registry=registry, tracer=tracer,
                            prefix_cache=args.prefix_cache,
                            max_prefill_batch=args.max_prefill_batch,
                            ttft_slo_s=args.slo_ttft,
                            tpot_slo_s=args.slo_tpot,
                            slo_objective=args.slo_objective,
                            slo_shed=args.slo_shed,
                            flight=flight)

        def _on_term(signum, frame):
            # Graceful drain (ISSUE 9 satellite): a preempted serve host
            # finishes the decodes it accepted (bounded by the grace)
            # instead of dropping them; admission closes immediately.
            # wait=False: only arm the deadline — the serving loops
            # enforce it, a signal handler must not block.  Router mode
            # goes through drain_all so the health sweep cannot
            # auto-relaunch drained replicas and keep decoding past the
            # preemption.
            if router is not None:
                router.drain_all(args.drain_grace, wait=False)
            else:
                server.drain(args.drain_grace, wait=False)
            print(f"SIGTERM: draining (grace {args.drain_grace:g}s)",
                  file=sys.stderr)

        try:
            _signal.signal(_signal.SIGTERM, _on_term)
        except ValueError:
            pass  # not the main thread (embedded use): no drain hook

        front = router if router is not None else server
        if router is not None:
            router.start()
        for p in prompts:
            try:
                reqs.append(front.submit(
                    p, max_new_tokens=args.max_new,
                    temperature=args.temperature,
                    deadline_s=args.deadline_s))
            except AdmissionError as e:
                print(f"rejected ({e.status}): {e}", file=sys.stderr)
        if router is not None:
            for r in reqs:
                r.done.wait()
            router.stop()
        else:
            server.run_until_idle()
    finally:
        # Same contract as cmd_launch/run_train_loop: a failing run must
        # still release the bound obs port and the open trace file.
        if tracer is not None:
            tracer.close()
        if obs_srv is not None:
            obs_srv.close()
        if hb is not None:
            hb.stop()
    ok = sum(1 for r in reqs if r.error is None)
    print(f"served {ok}/{len(prompts)} requests "
          f"({len(prompts) - len(reqs)} rejected at submit)",
          file=sys.stderr)
    if router is not None:
        print(_json.dumps({"router": router.snapshot()}))
    else:
        print(_json.dumps({**server.metrics.snapshot(),
                           "slo": server.slo.snapshot()}))
    # Partial failure is failure: scripts wrapping this must see expired/
    # rejected requests in the exit code, not just in the JSON.
    return 0 if ok == len(prompts) else 1


def cmd_obs(args) -> int:
    """Aggregate per-host metrics JSONL + trace JSONL into one fleet
    view: merged step timeline, per-host straggler report, request
    latency breakdown.  The read side of the observability plane — the
    answer to "which of my 64 hosts is slow and why" without tailing 64
    files (ISSUE 2)."""
    import json as _json
    import time as _time

    from tpucfn.obs.aggregate import (
        JsonlTailer,
        apply_clock_skew,
        control_timeline,
        estimate_clock_skew,
        host_straggler_report,
        merge_step_timeline,
        render_table,
        request_breakdown,
        select_skew_reference_beats,
        step_spans_by_host,
    )
    from tpucfn.ft.heartbeat import HB_GLOB
    from tpucfn.obs.goodput import host_id_from_path

    if not args.run_dir:
        print("error: --run-dir required", file=sys.stderr)
        return 2
    run_dir = Path(args.run_dir).expanduser()
    logs_dir = Path(args.logs_dir) if args.logs_dir else run_dir / "logs"
    trace_dir = Path(args.trace_dir) if args.trace_dir else run_dir / "trace"
    ft_dir = run_dir / "ft"

    # Incremental tail state (ISSUE 5 satellite): --watch keeps per-file
    # byte offsets and appends only NEW complete lines each tick instead
    # of re-reading every file from byte 0; one-shot mode is simply the
    # first poll.
    tailer = JsonlTailer()
    by_host: dict[str, list[dict]] = {}
    events_by_file: dict = {}
    hb_by_host: dict[int, list[dict]] = {}
    hb_last: dict[int, tuple] = {}  # host -> (seq, step) of last KEPT beat
    # Per-domain recompute cache: a tick that tails nothing new must not
    # redo O(run-length) merge/skew/sort work (the same discipline the
    # incremental tailer applies to the read side).
    cache = {"skew": {}, "events": [], "report": None}

    def _extend_sorted(_k, lst: list, recs: list) -> int:
        # per-file start order, as read_trace_dir does: spans recorded
        # retroactively (queue_wait) land in timeline order.  Sorted
        # HERE so only files that produced records this tick re-sort;
        # untouched files reuse their list as-is.
        lst.extend(recs)
        lst.sort(key=lambda e: e.get("start", 0.0))
        return len(recs)

    def _keep_hb(host: int, lst: list, recs: list) -> int:
        """Accumulate only the beats estimate_clock_skew can use as
        reference points (shared rule: select_skew_reference_beats) so
        hours of 2 Hz beats do not pile up in watch-mode memory.
        Returns how many were kept (skew may change)."""
        kept, hb_last[host] = select_skew_reference_beats(
            recs, hb_last.get(host, (None, None)))
        lst.extend(kept)
        return len(kept)

    def one_pass() -> dict:
        new_logs = new_trace = new_hb = False
        if logs_dir.is_dir():
            new_logs = tailer.poll_into(
                sorted(logs_dir.glob("*.jsonl")), by_host,
                key_fn=lambda p: p.stem)
        if trace_dir.is_dir():
            new_trace = tailer.poll_into(
                sorted(trace_dir.glob("trace-*.jsonl")), events_by_file,
                extend=_extend_sorted)
        # Heartbeats ride the same incremental tailer as everything
        # else, compacted to the skew-reference beats on arrival.
        if ft_dir.is_dir():
            new_hb = tailer.poll_into(
                sorted(ft_dir.glob(HB_GLOB)), hb_by_host,
                key_fn=host_id_from_path, extend=_keep_hb,
                on_drop=lambda h: hb_last.pop(h, None))
        if not (new_logs or new_trace or new_hb) and cache["report"]:
            return cache["report"]  # idle tick: nothing to redo
        # Cross-host span ordering is skew-tolerant (ISSUE 5 satellite):
        # heartbeat wall-times give the reference points when the ft
        # plane ran; lockstep step spans otherwise.  The estimate is
        # APPLIED, not just reported — downstream views see events on
        # the corrected fleet clock (ts_adj), in corrected order.
        # Both the estimate and the corrected merge are cached: only a
        # tick that tailed new trace/heartbeat records pays for them.
        if new_trace or new_hb or cache["report"] is None:
            events = []
            for p in sorted(events_by_file):
                events.extend(events_by_file[p])
            skew = estimate_clock_skew(events, hb_by_host or None)
            if any(skew.values()):
                events = apply_clock_skew(events, skew)
            cache["skew"], cache["events"] = skew, events
        skew, events = cache["skew"], cache["events"]
        # Trainer trace spans feed the same views when the metrics JSONL
        # is absent (span-only runs); with both present the metrics JSONL
        # wins for the timeline (same host under two labels must not be
        # counted as two hosts) and the spans add a second report.
        span_hosts = step_spans_by_host(events)
        timeline_src = by_host or span_hosts
        report = {
            "logs_dir": str(logs_dir),
            "trace_dir": str(trace_dir),
            "hosts": sorted(timeline_src),
            "clock_skew_s": skew,
            "timeline": merge_step_timeline(timeline_src, key="step_time",
                                            last=args.steps),
            "stragglers": host_straggler_report(
                timeline_src, keys=("step_time", "data_wait_time")),
        }
        if span_hosts and by_host:
            report["trace_stragglers"] = host_straggler_report(
                span_hosts, keys=("step_time", "data_wait_time"))
        rows, agg = request_breakdown(events)
        report["requests"], report["request_aggregate"] = rows, agg
        # Control-plane spans on the same corrected clock (ISSUE 13):
        # recoveries, profiler captures, compile-artifact fetches.
        report["control"] = control_timeline(events)
        cache["report"] = report
        return report

    def show(report: dict) -> None:
        if args.json:
            print(_json.dumps(report))
            return
        print(f"# fleet view  logs={report['logs_dir']} "
              f"trace={report['trace_dir']}")
        if len(report.get("clock_skew_s", {})) >= 2:
            print("clock skew (s vs fleet median): " + "  ".join(
                f"{h}={s:+.3f}" for h, s in
                sorted(report["clock_skew_s"].items())))
        if report["timeline"]:
            print(f"\n== merged step timeline (last {args.steps}) ==")
            print(render_table(report["timeline"],
                               ["step", "hosts", "min", "median", "max",
                                "straggler"]))
        straggler_cols = ["host", "records", "mean_step_time",
                          "mean_data_wait_time", "vs_fleet_median", "slow"]
        if report["stragglers"]:
            print("\n== per-host stragglers ==")
            print(render_table(report["stragglers"], straggler_cols))
        if report.get("trace_stragglers"):
            print("\n== per-host stragglers (trace spans) ==")
            print(render_table(report["trace_stragglers"], straggler_cols))
        if report.get("control"):
            print("\n== control events (recoveries / captures / "
                  "artifact fetches) ==")
            print(render_table(report["control"],
                               ["ts", "host", "role", "span", "dur_s",
                                "detail"], float_fmt="{:.3f}"))
        if report["requests"]:
            print("\n== request latency breakdown ==")
            cols = ["host", "request", "queue_wait_s", "prefill_s",
                    "decode_s", "ttft_s", "total_s", "generated", "outcome"]
            if any(r.get("spec_propose_s") or r.get("spec_verify_s")
                   for r in report["requests"]):
                # Speculative rounds ran (ISSUE 14): show the decode
                # split — the read side of the spec_propose/spec_verify
                # spans, same contract as the control timeline.
                cols[5:5] = ["spec_propose_s", "spec_verify_s"]
            print(render_table(report["requests"], cols))
            agg = report["request_aggregate"]
            print(f"\n{agg['completed']}/{agg['requests']} completed; "
                  "p50/p95 (s): " + "  ".join(
                      f"{k.removesuffix('_s')}="
                      f"{(agg[k]['p50'] or 0):.4f}/{(agg[k]['p95'] or 0):.4f}"
                      for k in ("queue_wait_s", "prefill_s", "decode_s",
                                "ttft_s", "total_s")))
        if not (report["timeline"] or report["stragglers"]
                or report["requests"]):
            print("no metrics or trace JSONL found "
                  f"under {report['logs_dir']} / {report['trace_dir']}")

    show(one_pass())
    while args.watch:
        _time.sleep(args.watch)
        print()
        show(one_pass())
    return 0


def cmd_obs_goodput(args) -> int:
    """The goodput ledger report (ISSUE 5 tentpole): wall-clock
    decomposed into productive step / compile / data_wait / ckpt / idle
    / lost_work / restart_downtime buckets that SUM to wall time, per
    host and fleet-averaged, with incident attribution from the ft
    plane's events.jsonl — the answer to "what fraction of paid
    TPU-seconds trained the model, and who stole the rest"."""
    import json as _json
    import time as _time

    from tpucfn.obs.aggregate import JsonlTailer
    from tpucfn.obs.goodput import (LEDGER_GLOB, host_id_from_path,
                                    merge_goodput, render_goodput)

    # --run-dir only derives the defaults, so explicit --goodput-dir
    # (relocated/copied ledgers) stands on its own.
    if not args.run_dir and not args.goodput_dir:
        print("error: --run-dir or --goodput-dir required",
              file=sys.stderr)
        return 2
    run_dir = Path(args.run_dir).expanduser() if args.run_dir else None
    goodput_dir = (Path(args.goodput_dir) if args.goodput_dir
                   else run_dir / "goodput")
    ft_events = (Path(args.ft_events) if args.ft_events
                 else run_dir / "ft" / "events.jsonl" if run_dir
                 else None)

    # Same incremental-tail discipline as cmd_obs (ISSUE 5 satellite):
    # --watch appends only NEW complete lines per tick instead of
    # re-parsing O(run-length) ledger history; one-shot mode is simply
    # the first poll.
    tailer = JsonlTailer()
    by_host: dict[int, list[dict]] = {}
    ev_store: dict[str, list[dict]] = {}
    # Idle-tick cache, same discipline as cmd_obs: a tick that tailed
    # nothing new must not re-merge O(run-length) ledger history.
    cache: dict = {"report": None}

    def one_pass() -> dict:
        dirty = cache["report"] is None
        if goodput_dir.is_dir():
            dirty |= tailer.poll_into(
                sorted(goodput_dir.glob(LEDGER_GLOB)), by_host,
                key_fn=host_id_from_path)
        if ft_events is not None and ft_events.is_file():
            dirty |= tailer.poll_into([ft_events], ev_store,
                                      key_fn=lambda p: "ft")
        if dirty:
            cache["report"] = merge_goodput(
                by_host, ev_store.get("ft", ()),
                skipped_lines=tailer.skipped)
        return cache["report"]

    def show(report: dict) -> None:
        if args.json:
            print(_json.dumps(report))
        elif report["num_hosts"] == 0:
            print(f"no goodput ledgers under {goodput_dir} "
                  "(runs write them via examples/common.py; see README "
                  "Observability → Goodput)")
            # ft incidents can exist without any ledger (older worker,
            # misplaced goodput dir) — exactly the broken-run case the
            # operator is diagnosing; don't hide them.
            if report["incidents"]:
                print(f"{len(report['incidents'])} ft incident(s) in "
                      f"{ft_events} (downtime "
                      f"{report['incident_downtime_s']:.2f}s) — "
                      "run --json for detail")
            if report["skipped_lines"]:
                print(f"skipped {report['skipped_lines']} "
                      "undecodable line(s)")
        else:
            print(render_goodput(report))

    show(one_pass())
    if getattr(args, "ledger", None):
        # Cross-run regression ledger (ISSUE 6 satellite): one BENCH-
        # row-style line per invocation; `tpucfn obs diff` compares the
        # last two.  Refused under --watch — a watch starts while the
        # run is LIVE, so the row would freeze the opening seconds'
        # compile-dominated shares and poison every later diff; append
        # from a one-shot invocation after the run.  An EMPTY report is
        # never appended either: a mistyped --run-dir writing
        # {wall_s: 0} would make the next diff compare a real run
        # against nothing and mask a real regression.
        if args.watch:
            print("not appending to the goodput ledger under --watch "
                  "(the run is still in progress — append with a "
                  "one-shot `tpucfn obs goodput --ledger` after it "
                  "ends)", file=sys.stderr)
        elif cache["report"]["num_hosts"] == 0:
            print("not appending to the goodput ledger: no ledgers "
                  "found (wrong --run-dir?)", file=sys.stderr)
        else:
            from tpucfn.obs.goodput import append_goodput_ledger

            path = append_goodput_ledger(
                args.ledger, cache["report"],
                run_dir=str(run_dir if run_dir else goodput_dir))
            print(f"appended goodput row to {path}", file=sys.stderr)
    while args.watch:
        _time.sleep(args.watch)
        print()
        show(one_pass())
    return 0


def cmd_obs_postmortem(args) -> int:
    """Assemble one incident's forensic bundle (ISSUE 6 tentpole): the
    enriched incident row, the skew-corrected timeline windowed around
    detection, the window's goodput buckets, every host's flight-
    recorder tail, and the last heartbeat per host — as a bundle
    directory + rendered report."""
    import json as _json

    from tpucfn.obs.postmortem import (build_postmortem, render_postmortem,
                                       write_bundle)

    if not args.run_dir:
        print("error: --run-dir required", file=sys.stderr)
        return 2
    run_dir = Path(args.run_dir).expanduser()
    try:
        report = build_postmortem(
            run_dir, incident_id=args.incident, window_s=args.window,
            ft_dir=args.ft_dir)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    inc = report["incident"]["incident"]
    out = (Path(args.out) if args.out
           else run_dir / "postmortem" / f"incident-{inc:03d}")
    bundle = write_bundle(report, out)
    if args.json:
        print(_json.dumps({**report, "bundle": str(bundle)}))
    else:
        print(render_postmortem(report))
        print(f"\nbundle: {bundle}")
    return 0


def cmd_obs_profile(args) -> int:
    """Client for the on-demand profiler capture (ISSUE 6): POST
    /profile?seconds=S against a host's obs endpoint; prints the JSON
    body naming the artifact directory (an XProf/TensorBoard trace on
    that host)."""
    import urllib.error
    import urllib.request

    host = args.host
    if ":" not in host:
        if not args.port:
            print("error: --port required when --host has no :port",
                  file=sys.stderr)
            return 2
        host = f"{host}:{args.port}"
    url = f"http://{host}/profile?seconds={args.seconds:g}"
    req = urllib.request.Request(url, data=b"", method="POST")
    timeout = args.timeout or args.seconds + 120.0
    try:
        # The server blocks for the capture duration; pad the client
        # timeout generously — profiler session setup alone can take
        # tens of seconds on a busy host (a timed-out client does NOT
        # cancel the server-side capture; it completes and the artifact
        # still lands in the profile dir).
        with urllib.request.urlopen(req, timeout=timeout) as r:
            body = r.read().decode()
    except urllib.error.HTTPError as e:
        detail = e.read().decode(errors="replace").strip()
        print(f"error: {url} -> {e.code}: {detail}", file=sys.stderr)
        return 1
    except (urllib.error.URLError, OSError) as e:
        print(f"error: {url} unreachable: {e}", file=sys.stderr)
        return 1
    print(body.strip())
    return 0


def cmd_obs_diff(args) -> int:
    """Compare the last two rows of the cross-run goodput ledger
    (ISSUE 6 satellite): goodput_ratio and per-bucket share deltas —
    the regression check MFU alone cannot do."""
    import json as _json

    from tpucfn.obs.aggregate import render_table
    from tpucfn.obs.goodput import diff_goodput_rows, read_goodput_ledger

    rows, skipped = read_goodput_ledger(args.ledger)
    if len(rows) < 2:
        print(f"error: need at least 2 goodput_run rows in {args.ledger} "
              f"(have {len(rows)}; append with `tpucfn obs goodput "
              "--run-dir R --ledger`)", file=sys.stderr)
        return 1
    diff = diff_goodput_rows(rows[-2], rows[-1])
    if args.json:
        print(_json.dumps({**diff, "skipped_lines": skipped}))
        return 0
    print(f"# goodput diff  {args.ledger}  (last two of {len(rows)} rows)")
    print(f"prev: {diff['prev']['run_dir']}  "
          f"ratio={diff['prev']['goodput_ratio']}")
    print(f"last: {diff['last']['run_dir']}  "
          f"ratio={diff['last']['goodput_ratio']}")
    d = diff["goodput_ratio_delta"]
    print("goodput_ratio delta: "
          + (f"{d:+.4f}" if d is not None else "n/a"))
    print()
    print(render_table(diff["buckets"],
                       ["bucket", "prev_share", "last_share", "delta"]))
    return 0


def _trace_merge(args):
    """Shared load for the trace subcommands: merge the run's per-host
    span files onto the fleet clock, preferring the coordinator's
    measured /clock probes when the run has them."""
    from tpucfn.obs.timeline import merge_timeline

    run_dir = Path(args.run_dir).expanduser()
    trace_dir = Path(args.trace_dir) if args.trace_dir \
        else run_dir / "trace"
    if not trace_dir.is_dir():
        print(f"error: no trace dir at {trace_dir} (run with tracing "
              "enabled, or pass --trace-dir)", file=sys.stderr)
        return None, None
    offsets = Path(args.offsets) if args.offsets \
        else run_dir / "ft" / "clock-offsets.jsonl"
    merged = merge_timeline(
        trace_dir, offsets_path=offsets if offsets.is_file() else None)
    if not merged["events"]:
        print(f"error: no span events under {trace_dir}", file=sys.stderr)
        return None, None
    return merged, run_dir


def cmd_trace_export(args) -> int:
    """Merge a run's per-host span files into one clock-aligned
    Chrome/Perfetto trace (ISSUE 20 tentpole): process lanes per
    (host, role), flow arrows on every resolved cross-host link —
    load the output in https://ui.perfetto.dev or chrome://tracing."""
    import json as _json

    from tpucfn.obs.timeline import write_chrome_trace

    merged, run_dir = _trace_merge(args)
    if merged is None:
        return 1
    out = Path(args.out) if args.out else run_dir / "trace" / "timeline.json"
    write_chrome_trace(merged, out)
    stats = merged["link_stats"]
    summary = {
        "out": str(out), "events": len(merged["events"]),
        "links_resolved": stats["resolved"],
        "link_carriers": stats["carriers"],
        "by_name": stats["by_name"],
        "hosts_probed": sorted(merged["offsets"]),
    }
    if args.json:
        print(_json.dumps(summary))
    else:
        print(f"wrote {out}: {summary['events']} events, "
              f"{stats['resolved']}/{stats['carriers']} cross-host links "
              f"resolved ({len(merged['offsets'])} host(s) on measured "
              "clock offsets)")
    return 0


def cmd_trace_critpath(args) -> int:
    """Per-step critical-path attribution (ISSUE 20 tentpole): walk
    each trainer step's merged span tree, attribute wall time to planes
    (compute / remote-serve / input-local / artifact-fetch / ckpt /
    coordinator), print per-step "bounded by" verdicts — and cross-check
    the aggregate shares against the goodput ledger when the run has
    one."""
    import json as _json

    from tpucfn.obs.timeline import (critical_path, crosscheck_goodput,
                                     render_critpath)

    merged, run_dir = _trace_merge(args)
    if merged is None:
        return 1
    cp = critical_path(merged)
    if not cp["steps"]:
        print("error: no trainer step spans in the merged timeline — "
              "nothing to attribute", file=sys.stderr)
        return 1
    crosscheck = None
    gp_dir = Path(args.goodput) if args.goodput else run_dir / "goodput"
    if gp_dir.is_dir():
        from tpucfn.obs.goodput import goodput_report

        ev = run_dir / "ft" / "events.jsonl"
        report = goodput_report(gp_dir, ev if ev.is_file() else None)
        if report.get("num_hosts"):
            crosscheck = crosscheck_goodput(cp, report)
    if args.json:
        print(_json.dumps({**cp, "crosscheck": crosscheck}))
    else:
        print(render_critpath(cp, crosscheck), end="")
    return 0


def cmd_trace_advise(args) -> int:
    """Per-plane deadline autotune ADVISORY (ISSUE 20 satellite):
    observed frame-time percentiles from the merged span timeline →
    suggested deadline values, report-only — the operator changes the
    flag, nothing auto-applies."""
    import json as _json

    from tpucfn.net.autotune import render_advice, suggest_deadlines

    merged, _run_dir = _trace_merge(args)
    if merged is None:
        return 1
    rows = suggest_deadlines(merged["events"], headroom=args.headroom,
                             min_samples=args.min_samples)
    if args.json:
        print(_json.dumps(rows))
    else:
        print(render_advice(rows), end="")
    return 0


def cmd_forensics_diff(args) -> int:
    """Diff two postmortem bundles of the same incident class
    (ISSUE 20 satellite): same-window goodput bucket shares, per-host
    heartbeat-age and span-count deltas — what did the second incident
    do differently?"""
    import json as _json

    from tpucfn.obs.postmortem import diff_bundles, render_bundle_diff

    for d in (args.bundle_a, args.bundle_b):
        if not (Path(d) / "incident.json").is_file():
            print(f"error: {d} is not a postmortem bundle (no "
                  "incident.json — make one with `tpucfn obs "
                  "postmortem`)", file=sys.stderr)
            return 2
    diff = diff_bundles(args.bundle_a, args.bundle_b)
    if args.json:
        print(_json.dumps(diff))
    else:
        print(render_bundle_diff(diff))
    return 0


def cmd_check(args) -> int:
    """Static analysis (ISSUE 10): run the concurrency/fleet-invariant
    rule pack over the package — jax-free, seconds, rc 1 on findings —
    so the bug classes the repo has already shipped (signal-handler
    deadlocks, joins under locks, unregistered metrics, vocabulary
    drift) are machine-checked before every PR instead of rediscovered
    by reviewers.  Exit codes: 0 clean, 1 findings, 2 usage error."""
    import json as _json

    from tpucfn.analysis import (apply_baseline, changed_files,
                                 load_baseline, resolve_rules, run_check,
                                 write_baseline)

    if args.path:
        package_root = Path(args.path).resolve()
        if not package_root.is_dir():
            print(f"error: {package_root} is not a directory",
                  file=sys.stderr)
            return 2
    else:
        import tpucfn

        package_root = Path(tpucfn.__file__).resolve().parent
    repo_root = package_root.parent

    rules = None
    if args.rules:
        rules = [r for chunk in args.rules for r in chunk.split(",") if r]
        try:
            resolve_rules(rules)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    # every pure usage error is decided BEFORE the (~2s) package scan
    if args.update_baseline:
        # a --diff or --rules run sees only a SUBSET of findings;
        # rewriting the baseline from that partial view would silently
        # drop every suppression the subset didn't reproduce
        if args.diff is not None:
            print("error: --update-baseline cannot run with --diff "
                  "(a partial view would drop unrelated suppressions)",
                  file=sys.stderr)
            return 2
        if rules is not None:
            print("error: --update-baseline cannot run with --rules "
                  "(the unselected rules' suppressions would be "
                  "dropped)", file=sys.stderr)
            return 2

    baseline_path = Path(args.baseline) if args.baseline \
        else repo_root / "runs" / "analysis_baseline.json"
    baseline: dict = {}
    if baseline_path.is_file():
        try:
            baseline = load_baseline(baseline_path)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    elif args.baseline and not args.update_baseline:
        # an explicit baseline that doesn't exist is a typo'd path, not
        # a clean slate — unless we're about to create it
        print(f"error: baseline {baseline_path} not found", file=sys.stderr)
        return 2

    only = None
    if args.diff is not None:
        try:
            only = changed_files(repo_root, args.diff)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    findings = run_check(package_root, rules=rules, repo_root=repo_root,
                         only=only)

    if args.update_baseline:
        p = write_baseline(baseline_path, findings, baseline)
        print(f"baseline updated: {p} ({len(findings)} suppression(s); "
              "fill in any TODO justifications before committing)")
        return 0

    active, suppressed, stale = apply_baseline(findings, baseline)
    if args.json:
        for f in active:
            print(_json.dumps(f.to_json()))
    else:
        for f in active:
            print(f"{f.path}:{f.line}: [{f.rule}] {f.message}  "
                  f"(fingerprint {f.fingerprint})")
        scope = f"{len(only)} changed file(s)" if only is not None \
            else str(package_root)
        print(f"tpucfn check: {len(active)} finding(s), "
              f"{len(suppressed)} baselined, over {scope}",
              file=sys.stderr)
    # under --rules (or --diff) the unselected rules' suppressions look
    # stale without being stale — and the prune hint would point at a
    # command this partial view refuses
    if stale and only is None and rules is None:
        print(f"note: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} no longer match any "
              "finding — prune with --update-baseline",
              file=sys.stderr)
    return 1 if active else 0


def cmd_ft_status(args) -> int:
    """Render the fault-tolerance plane's fleet view: per-host heartbeat
    verdicts (LIVE/STRAGGLER/SUSPECT/DEAD), the supervisor's ft_*
    metrics (restarts, failures detected, MTTR), and the recent
    detect→decide→act→recovered event tail — the read side of
    ``tpucfn launch --ft`` (ISSUE 4)."""
    import json as _json

    from tpucfn.ft import HeartbeatMonitor, MonitorConfig
    from tpucfn.obs.aggregate import render_table

    if not args.dir and not args.name:
        print("error: ft status needs --name (cluster) or --dir "
              "(heartbeat dir)", file=sys.stderr)
        return 2
    ft_dir = Path(args.dir) if args.dir else _run_dir(args, args.name) / "ft"
    if not ft_dir.is_dir():
        print(f"error: no ft dir at {ft_dir} (launch with --ft first, "
              "or pass --dir)", file=sys.stderr)
        return 1

    sup: dict = {}
    sup_path = ft_dir / "supervisor.json"
    if sup_path.is_file():
        try:
            sup = _json.loads(sup_path.read_text())
        except (OSError, _json.JSONDecodeError):
            sup = {}
    interval = args.heartbeat_interval
    if interval is None:
        interval = sup.get("heartbeat_interval_s") or 1.0
    monitor = HeartbeatMonitor(
        ft_dir, expected_hosts=sup.get("gang_hosts"),
        config=MonitorConfig(interval_s=float(interval)))
    view = monitor.observe()
    healthy, health_detail = view.healthy()

    events: list[dict] = []
    ev_path = ft_dir / "events.jsonl"
    if ev_path.is_file():
        for line in ev_path.read_text().splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                events.append(_json.loads(line))
            except _json.JSONDecodeError:
                continue  # torn tail while the supervisor appends

    rows = [{"host": v.host_id, "state": v.state.value,
             "age_s": v.age_s, "step": v.step, "pid": v.pid,
             "reason": v.reason} for v in view.hosts]
    report = {
        "ft_dir": str(ft_dir),
        "healthy": healthy,
        "fleet": health_detail["fleet"],
        "max_step": health_detail["max_step"],
        "hosts": rows,
        "policy": sup.get("policy"),
        "budget": sup.get("budget"),
        "metrics": sup.get("metrics", {}),
        "events": events[-args.events:] if args.events else events,
    }
    if args.json:
        print(_json.dumps(report))
        return 0
    print(f"# ft fleet view  {ft_dir}  "
          f"{'HEALTHY' if healthy else 'UNHEALTHY'}")
    if rows:
        print(render_table(rows, ["host", "state", "age_s", "step", "pid",
                                  "reason"], float_fmt="{:.2f}"))
    else:
        print("no heartbeats yet")
    m = report["metrics"]
    if m:
        mttr = m.get("ft_mttr_seconds") or {}
        print(f"\nrestarts={m.get('ft_restarts_total', 0)} "
              f"(gang={m.get('ft_gang_restarts_total', 0)} "
              f"solo={m.get('ft_solo_restarts_total', 0)}) "
              f"failures_detected={m.get('ft_failures_detected_total', 0)} "
              f"mttr_p50={(mttr.get('p50') if isinstance(mttr, dict) else None)}")
        # The graceful-degradation surface (ISSUE 7): only when any of
        # the four paths actually fired — a quiet fleet stays terse.
        degrade = {"planned_drains": m.get("ft_preempt_drains_total", 0),
                   "shrinks": m.get("ft_shrinks_total", 0),
                   "ckpt_retries": m.get("ft_ckpt_retries_total", 0),
                   "evictions": m.get("ft_straggler_evictions_total", 0)}
        if any(degrade.values()):
            pm = m.get("ft_planned_mttr_seconds") or {}
            planned_p50 = (pm.get("p50")
                           if isinstance(pm, dict) else None)
            print("degradation: "
                  + " ".join(f"{k}={v}" for k, v in degrade.items())
                  + (f" planned_mttr_p50={planned_p50}"
                     if degrade["planned_drains"] else ""))
        if report["budget"]:
            b = report["budget"]
            print(f"policy={report['policy']} budget "
                  f"{b.get('used', 0)}/{b.get('max_restarts', 0)} used")
    if report["events"]:
        print("\n== recent events ==")
        for e in report["events"]:
            extra = {k: v for k, v in e.items() if k not in ("ts", "kind")}
            # Lead with the story, not the raw dict, for the new kinds:
            # a drained preemption / shrink / ckpt retry must be
            # recognizable at a glance, not read as a generic restart.
            kind = e.get("kind", "?")
            tag = ""
            if kind == "recovered" and e.get("planned"):
                tag = " [planned]"
            elif kind == "shrink":
                tag = (f" [{e.get('from_hosts')}->{e.get('to_hosts')} "
                       f"gen {e.get('generation')}]")
            elif kind == "ckpt_retry":
                tag = (f" [bad step {e.get('bad_step')} -> retry from "
                       f"{e.get('retry_from')}]")
            print(f"  {e.get('ts', 0):.3f} {kind:12s}{tag} {extra}")
    return 0


def cmd_rl_train(args) -> int:
    """Run one host's Podracer RL loop (tpucfn.rl): co-located jitted
    actors + a Trainer-backed A2C learner on ONE mesh, trajectories
    through the on-device replay queue, param refresh as a device-to-
    device copy.  The third workload class next to ``launch`` (training)
    and ``serve`` — and like them it is fan-out-ready: run it as the
    command under ``tpucfn launch`` and every rank gets heartbeats
    (``TPUCFN_FT_DIR``), fleet warm start (``TPUCFN_COMPILE_CACHE_*``),
    goodput ledgers with the ``act``/``learn``/``refresh`` buckets, and
    chaos-coherent resume from the latest checkpoint."""
    from tpucfn.rl.loop import RLConfig, run_rl_loop

    cfg = RLConfig(
        run_dir=args.run_dir, env=args.env, num_envs=args.num_envs,
        unroll=args.unroll, iters=args.iters, hidden=args.hidden,
        lr=args.lr, gamma=args.gamma, entropy_coef=args.entropy_coef,
        seed=args.seed, ckpt_every=args.ckpt_every,
        log_every=args.log_every, queue_capacity=args.queue_capacity,
        stop_after=args.stop_after, fresh=args.fresh,
        iter_sleep_s=args.iter_sleep_s)
    run_rl_loop(cfg)
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpucfn", description=__doc__)
    p.add_argument("--state-dir", default=os.environ.get("TPUCFN_STATE_DIR", "~/.tpucfn"))
    env_backend = os.environ.get("TPUCFN_BACKEND", "fake").lower()
    if env_backend not in ("fake", "gcp"):
        # argparse never validates defaults — a typo'd env var must not
        # silently fall back to the fake backend.
        raise SystemExit(
            f"error: TPUCFN_BACKEND={env_backend!r} is not one of fake, gcp")
    p.add_argument("--backend", choices=["fake", "gcp"],
                   default=env_backend,
                   help="control plane: 'fake' (local state file; CI and "
                        "single-host) or 'gcp' (TPU queued resources via "
                        "gcloud; needs TPUCFN_GCP_PROJECT/_ZONE)")
    sub = p.add_subparsers(dest="command", required=True)

    c = sub.add_parser("create-stack", help="provision a cluster (≈ CFN create-stack)")
    c.add_argument("--name")
    c.add_argument("--spec", help="cluster spec JSON file (≈ the template)")
    c.add_argument("--accelerator", default="v5e-8")
    c.add_argument("--storage", help="shared storage root (≈ EFS)")
    c.set_defaults(fn=cmd_create_stack)

    s = sub.add_parser("status", help="describe a cluster")
    s.add_argument("--name", required=True)
    s.set_defaults(fn=cmd_status)

    d = sub.add_parser("delete", help="delete a cluster")
    d.add_argument("--name", required=True)
    d.set_defaults(fn=cmd_delete)

    r = sub.add_parser("resize", help="re-acquire at a new topology (≈ update-stack)")
    r.add_argument("--name", required=True)
    r.add_argument("--accelerator", required=True)
    r.set_defaults(fn=cmd_resize)

    e = sub.add_parser("env", help="print the cluster env contract (eval-able)")
    e.add_argument("--name", required=True)
    e.set_defaults(fn=cmd_env)

    l = sub.add_parser("launch", help="fan a command out across all hosts")
    l.add_argument("--name", required=True)
    l.add_argument("--transport", choices=["local", "ssh"], default="local")
    l.add_argument("--restarts", type=int, default=0,
                   help="auto-relaunch the gang up to N times on failure "
                        "(jobs resume from their latest checkpoint)")
    l.add_argument("--kill-host-after", metavar="HOST:SECONDS",
                   help="fault injection: SIGKILL host's rank after N "
                        "seconds on the first attempt (recovery drill)")
    l.add_argument("--obs-port", type=int, default=0, metavar="BASE",
                   help="observability plane: supervisor /metrics on BASE, "
                        "each host's process on BASE+1+host_id via "
                        "TPUCFN_OBS_PORT (0 = off)")
    l.add_argument("--ft", action="store_true",
                   help="fault-tolerance plane: per-host heartbeats "
                        "(TPUCFN_FT_DIR fan-out), failure detection, and "
                        "gang-coordinated recovery via tpucfn.ft")
    l.add_argument("--ft-heartbeat-interval", type=float, default=1.0,
                   metavar="SECONDS",
                   help="heartbeat write interval; detection thresholds "
                        "scale off it (suspect 3x, dead 6x)")
    l.add_argument("--ft-restart-budget", type=int, default=None,
                   metavar="N",
                   help="recoveries allowed before giving up "
                        "(default: --restarts)")
    l.add_argument("--ft-startup-grace", type=float, default=120.0,
                   metavar="SECONDS",
                   help="no-heartbeat-yet window after every (re)launch "
                        "before a silent host counts as hung — must cover "
                        "runtime boot (jax import + first compile); crash "
                        "detection is unaffected")
    l.add_argument("--ft-policy", choices=["gang", "solo"], default="gang",
                   help="recovery shape: gang = kill all + relaunch all + "
                        "resume from latest checkpoint (the SPMD-safe "
                        "default); solo = restart only the dead host into "
                        "the same gang")
    l.add_argument("--ft-backoff", type=float, default=1.0, metavar="SECONDS",
                   help="base restart backoff; doubles per restart with "
                        "seeded jitter (--ft-seed)")
    l.add_argument("--ft-seed", type=int, default=0,
                   help="seed for backoff jitter (determinism: same seed "
                        "replays the same delays)")
    l.add_argument("--ft-drain-grace", type=float, default=30.0,
                   metavar="SECONDS",
                   help="preemption drain: how long to wait for clean "
                        "exits when the notice carries no lead time (a "
                        "shorter notice lead wins)")
    l.add_argument("--ft-no-shrink", action="store_true",
                   help="disable elastic N-1 shrink: a host the control "
                        "plane lost gives up instead of re-converging "
                        "the contract at fewer hosts")
    l.add_argument("--ft-straggler-hysteresis", type=float, default=30.0,
                   metavar="SECONDS",
                   help="sustained step-lag required before a straggler "
                        "is evicted (solo-restarted)")
    l.add_argument("--ft-straggler-flap-budget", type=int, default=3,
                   metavar="N",
                   help="brief lag episodes tolerated per host before a "
                        "chronic flapper is evicted without waiting out "
                        "the hysteresis window")
    l.add_argument("--ft-max-ckpt-retries", type=int, default=3,
                   metavar="N",
                   help="checkpoint-corruption retries (each blacklists "
                        "one bad step and resumes from the previous) "
                        "before the normal restart policy decides")
    l.add_argument("--input-hosts", type=int, default=0, metavar="N",
                   help="disaggregated input plane: the LAST N hosts of "
                        "the slice stream batches (`tpucfn data serve` or "
                        "--input-cmd) instead of training; trainers get "
                        "TPUCFN_INPUT_ADDRS and the rendezvous shrinks to "
                        "the trainer count")
    l.add_argument("--input-port", type=int, default=0, metavar="BASE",
                   help="input service base port (input host h binds "
                        "BASE + h; 0 = the default base)")
    l.add_argument("--input-cmd", metavar="CMD",
                   help="command input hosts run (shlex-split; usually "
                        "`python -m tpucfn.cli data serve ...`); required "
                        "with --input-hosts")
    l.add_argument("--ft-restart-input-hosts", action="store_true",
                   help="solo-relaunch a dead input host (bounded, budget "
                        "untouched); default: trainers just degrade to "
                        "local loading")
    adopt_group = l.add_mutually_exclusive_group()
    adopt_group.add_argument(
        "--adopt", action="store_true",
        help="crash-safety: replay the write-ahead journal and "
             "adopt the running fleet instead of launching a "
             "new one (the default whenever an unfinished "
             "journal exists under the ft dir)")
    adopt_group.add_argument(
        "--no-adopt", action="store_true",
        help="always launch fresh, even over an unfinished "
             "journal (the previous run's journal is rotated "
             "aside, its fleet is NOT stopped)")
    l.add_argument("--compile-cache", action="store_true",
                   help="fleet warm start: run the jax-free compiled-"
                        "artifact server in this process and fan its "
                        "address out (TPUCFN_COMPILE_CACHE_ADDRS) — one "
                        "host compiles each program, the rest fetch the "
                        "serialized executable; relaunches skip the "
                        "compile entirely")
    l.add_argument("--compile-cache-dir", metavar="DIR",
                   help="artifact store directory (default: the "
                        "cluster's state dir compilecache/)")
    l.add_argument("--compile-cache-port", type=int, default=0,
                   metavar="PORT",
                   help="artifact server bind port (default 7741)")
    l.add_argument("--compile-cache-advertise", metavar="HOST",
                   help="address the fleet dials for the artifact server "
                        "(default: 127.0.0.1 for --transport local, else "
                        "the coordinator host — correct when tpucfn "
                        "launch runs ON host 0; set this when launching "
                        "from elsewhere, the server runs in THIS process)")
    l.add_argument("--provision-policy", choices=["goodput"],
                   help="goodput-driven provisioner loop (needs --ft and "
                        "--input-hosts): the coordinator reads the fleet "
                        "goodput ledgers each interval and actuates — "
                        "data_wait share over threshold grows the input "
                        "plane (planned drain-relaunch), chronic "
                        "starvation at ceiling is flagged, a starved-"
                        "free fleet shrinks it back")
    l.add_argument("--provision-interval", type=float, default=5.0,
                   metavar="SECONDS",
                   help="how often the provisioner policy observes the "
                        "goodput ledgers")
    l.add_argument("--provision-grow-threshold", type=float, default=0.25,
                   metavar="SHARE",
                   help="data_wait share of wall above which the policy "
                        "grows the input plane")
    l.add_argument("--provision-shrink-threshold", type=float, default=0.02,
                   metavar="SHARE",
                   help="data_wait share below which a served fleet "
                        "releases its input hosts")
    l.add_argument("--provision-cooldown", type=float, default=30.0,
                   metavar="SECONDS",
                   help="minimum time between provisioner actuations")
    l.add_argument("--provision-goodput-dir", metavar="DIR",
                   help="where the fleet's goodput ledgers land (must "
                        "match the trainers' run dir goodput/; default: "
                        "the cluster state dir goodput/)")
    l.add_argument("--defer-input-plane", action="store_true",
                   help="reserve the --input-hosts slots instead of "
                        "spawning them at launch: trainers start on "
                        "local loading and the provisioner activates the "
                        "input plane when goodput says it pays")
    l.add_argument("--chaos", metavar="SPEC",
                   help="deterministic fault injection (needs --ft): a "
                        "ChaosSpec JSON file (or inline JSON) replayed "
                        "against the coordinator — kill/hang/... plus the "
                        "net_* gray-failure ops, which land on the "
                        "--chaos-proxy instances")
    l.add_argument("--chaos-proxy", metavar="LISTEN:HOST:PORT",
                   action="append",
                   help="run a fault-injection TCP proxy in this process: "
                        "listen on LISTEN, forward to HOST:PORT "
                        "(repeatable; the targets of net_* chaos ops, "
                        "indexed by flag order)")
    l.add_argument("--supervise", action="store_true",
                   help="wrap the coordinator in a jax-free re-exec loop: "
                        "a crashed coordinator is relaunched and adopts "
                        "the running fleet via the journal; orphaned rank "
                        "exit codes are reaped into <ft>/rc/ (needs --ft)")
    l.add_argument("--supervise-restarts", type=int, default=3, metavar="N",
                   help="coordinator relaunches allowed before the "
                        "supervise loop gives up and propagates the rc")
    l.add_argument("cmd", nargs=argparse.REMAINDER)
    l.set_defaults(fn=cmd_launch)

    ft = sub.add_parser(
        "ft", help="fault-tolerance plane (heartbeats, recovery, chaos)")
    ftsub = ft.add_subparsers(dest="ft_command", required=True)
    fs = ftsub.add_parser(
        "status",
        help="render the fleet's heartbeat verdicts, recovery metrics "
             "(restarts, MTTR), and recent incident events")
    fs.add_argument("--name", help="cluster name (heartbeats under its "
                                   "state dir ft/)")
    fs.add_argument("--dir", help="explicit heartbeat dir (overrides --name)")
    fs.add_argument("--heartbeat-interval", type=float, default=None,
                    metavar="SECONDS",
                    help="classification interval override (default: the "
                         "supervisor snapshot's value, else 1.0)")
    fs.add_argument("--events", type=int, default=10,
                    help="incident-event tail length (0 = all)")
    fs.add_argument("--json", action="store_true",
                    help="emit the full fleet report as one JSON object")
    fs.set_defaults(fn=cmd_ft_status)

    rl = sub.add_parser(
        "rl", help="RL plane (Podracer: co-located actors + learner on "
                   "one mesh, on-device replay, chaos-coherent resume)")
    rlsub = rl.add_subparsers(dest="rl_command", required=True)
    rt = rlsub.add_parser(
        "train",
        help="run one host's actor/learner/refresh loop (fan out with "
             "`tpucfn launch -- tpucfn rl train ...` for the full drill)")
    rt.add_argument("--run-dir", default="/tmp/tpucfn-rl",
                    help="per-run state: ckpt/, rl-host*.jsonl rows")
    rt.add_argument("--env", choices=["bandit", "gridworld"],
                    default="bandit",
                    help="built-in pure-jax vectorized env (the whole "
                         "rollout stays one device program)")
    rt.add_argument("--num-envs", type=int, default=8,
                    help="vectorized env copies = learner batch size "
                         "(must divide the mesh's data-parallel degree)")
    rt.add_argument("--unroll", type=int, default=16,
                    help="env steps per jitted rollout (lax.scan length)")
    rt.add_argument("--iters", type=int, default=100,
                    help="act→learn→refresh iterations to run")
    rt.add_argument("--hidden", type=int, default=64,
                    help="policy/value MLP hidden width")
    rt.add_argument("--lr", type=float, default=1e-2)
    rt.add_argument("--gamma", type=float, default=0.99)
    rt.add_argument("--entropy-coef", type=float, default=0.01)
    rt.add_argument("--seed", type=int, default=0,
                    help="root PRNG seed; every per-iteration choice is "
                         "fold_in(root, iteration), so same seed = "
                         "bit-identical run, including across restores")
    rt.add_argument("--ckpt-every", type=int, default=25,
                    help="whole-stack snapshot interval (learner state + "
                         "env state + queue ring + iteration)")
    rt.add_argument("--log-every", type=int, default=10)
    rt.add_argument("--queue-capacity", type=int, default=4,
                    help="on-device replay ring slots (host spill is the "
                         "overflow fallback)")
    rt.add_argument("--stop-after", type=int, default=0,
                    help="halt after this iteration (0 = run to --iters); "
                         "the planned-interruption hook drills use")
    rt.add_argument("--fresh", action="store_true",
                    help="ignore existing checkpoints (default: resume "
                         "from latest)")
    rt.add_argument("--iter-sleep-s", type=float, default=0.0,
                    help="host-side pacing between iterations (chaos "
                         "drills use it to land mid-episode kills)")
    rt.set_defaults(fn=cmd_rl_train)

    ch = sub.add_parser(
        "chaos",
        help="network fault injection (gray failures: latency, trickle, "
             "stall, partition, tear, RST)")
    chsub = ch.add_subparsers(dest="chaos_command", required=True)
    cp = chsub.add_parser(
        "proxy",
        help="run a deterministic fault-injection TCP proxy in front of "
             "any fleet plane's port")
    cp.add_argument("--listen", type=int, default=0, metavar="PORT",
                    help="port to listen on (0 = ephemeral, printed)")
    cp.add_argument("--upstream", required=True, metavar="HOST:PORT",
                    help="where healthy traffic forwards to")
    cp.add_argument("--host", default="0.0.0.0",
                    help="bind address (default 0.0.0.0)")
    cp.add_argument("--spec", metavar="FILE|JSON",
                    help="NetFaultSchedule JSON: {\"seed\": N, \"faults\": "
                         "[{\"kind\": \"throttle\", \"at_s\": 5, "
                         "\"rate_bps\": 1024, \"duration_s\": 30}, ...]}")
    cp.add_argument("--seed", type=int, default=None,
                    help="override the schedule's seed (determinism: same "
                         "seed, same fault timeline)")
    cp.add_argument("--serve-for", type=float, default=0.0,
                    metavar="SECONDS",
                    help="exit after this long (0 = until SIGTERM)")
    cp.set_defaults(fn=cmd_chaos_proxy)

    k = sub.add_parser("kill-host", help="fault injection: mark a host dead")
    k.add_argument("--name", required=True)
    k.add_argument("--host", type=int, required=True)
    k.set_defaults(fn=cmd_kill_host)

    h = sub.add_parser("heal", help="health check; re-acquire if hosts died")
    h.add_argument("--name", required=True)
    h.set_defaults(fn=cmd_heal)

    cv = sub.add_parser(
        "convert-dataset",
        help="pack an image tree / CIFAR binary / MXNet RecordIO / "
             "tokenized jsonl corpus into tpurecord shards")
    cv.add_argument("--kind",
                    choices=["image-tree", "cifar10", "recordio",
                             "token-jsonl"],
                    required=True)
    cv.add_argument("--src", required=True,
                    help="dataset root directory (or .jsonl file for "
                         "token-jsonl; .rec file or directory of them "
                         "for recordio)")
    cv.add_argument("--out", required=True, help="output shard directory")
    cv.add_argument("--num-shards", type=int, default=16)
    cv.add_argument("--test-split", action="store_true",
                    help="cifar10: convert test_batch.bin instead of train")
    cv.add_argument("--seq-len", type=int, default=2048,
                    help="token-jsonl: packed row length")
    cv.add_argument("--publish", metavar="URL",
                    help="also upload shards to gs://, s3://, or file:// URL")
    cv.set_defaults(fn=cmd_convert_dataset)

    st = sub.add_parser("stage-data",
                        help="sync dataset shards from a store URL to local cache")
    st.add_argument("--url", required=True, help="gs://, s3://, file://, or path")
    st.add_argument("--dest", required=True)
    st.set_defaults(fn=cmd_stage_data)

    da = sub.add_parser(
        "data", help="input-plane commands (disaggregated batch service)")
    dasub = da.add_subparsers(dest="data_command", required=True)
    dsv = dasub.add_parser(
        "serve",
        help="stream ready batches to trainer hosts: the input-host "
             "role of `tpucfn launch --input-hosts N` (jax-free)")
    dsv.add_argument("--shards", required=True, metavar="DIR",
                     help="directory of *.tpurec shards (must match the "
                          "trainers' local fallback dataset)")
    dsv.add_argument("--batch-size", type=int, required=True,
                     help="per-trainer batch size (handshake-validated)")
    dsv.add_argument("--num-trainers", type=int, default=None, metavar="T",
                     help="trainer fleet size (default: "
                          "TPUCFN_WORKERS_COUNT from the launch fan-out)")
    dsv.add_argument("--seed", type=int, default=0)
    dsv.add_argument("--num-epochs", type=int, default=None,
                     help="epochs per trainer stream (default: unbounded)")
    dsv.add_argument("--host", default="0.0.0.0",
                     help="bind address (default all interfaces)")
    dsv.add_argument("--port", type=int, default=None,
                     help="bind port (default: TPUCFN_INPUT_PORT from the "
                          "launch fan-out, else ephemeral)")
    dsv.add_argument("--queue-batches", type=int, default=4,
                     help="encoded batches buffered per trainer stream "
                          "(the memory bound; TCP backpressure beyond it)")
    dsv.add_argument("--sndbuf-kb", type=int, default=0, metavar="KB",
                     help="cap the kernel send buffer per stream (makes "
                          "the per-trainer memory bound exact; 0 = OS "
                          "auto-tuning, right for high-bandwidth links)")
    dsv.add_argument("--mp-workers", type=int, default=0, metavar="W",
                     help="decode across W worker PROCESSES per stream "
                          "(MultiProcessLoader; 0 = in-process)")
    dsv.add_argument("--workers", type=int, default=0,
                     help="transform thread pool per stream "
                          "(ShardedDataset num_workers; 0 = inline)")
    dsv.add_argument("--no-shuffle", action="store_true")
    dsv.add_argument("--stream", action="store_true",
                     help="constant-memory shard streaming instead of "
                          "caching decoded examples in RAM")
    dsv.add_argument("--idle-exit", type=float, default=0.0,
                     metavar="SECONDS",
                     help="exit rc 0 after this long with no connected "
                          "trainer (0 = serve until SIGTERM); the launch "
                          "fan-out needs this so the supervisor can end "
                          "the run")
    dsv.add_argument("--obs-port", type=int, default=None, metavar="PORT",
                     help="serve /metrics /healthz /varz (default: "
                          "TPUCFN_OBS_PORT from the launch fan-out)")
    dsv.add_argument("--send-deadline", type=float, default=120.0,
                     metavar="SECONDS",
                     help="end-to-end deadline per sent frame: a stalled/"
                          "blackholed trainer is dropped (and its producer "
                          "freed) after this long instead of pinning the "
                          "stream; must exceed the trainers' worst-case "
                          "step time (0 = disabled)")
    dsv.add_argument("--trace-dir", default=None, metavar="DIR",
                     help="write input_serve trace spans here (default: "
                          "TPUCFN_TRACE_DIR; unset = tracing off) — the "
                          "input-host half of the fleet timeline")
    dsv.set_defaults(fn=cmd_data_serve)

    cc = sub.add_parser(
        "compilecache",
        help="fleet warm-start plane (compiled-artifact store/server)")
    ccsub = cc.add_subparsers(dest="compilecache_command", required=True)
    ccs = ccsub.add_parser(
        "serve",
        help="run the jax-free compiled-artifact server standalone "
             "(host 0 / input-role host); `tpucfn launch "
             "--compile-cache` is the coordinator-hosted form")
    ccs.add_argument("--dir", metavar="DIR",
                     help="artifact store directory (default "
                          "$TPUCFN_COMPILE_CACHE_DIR or the XLA cache's "
                          "_artifacts sibling)")
    ccs.add_argument("--host", default="0.0.0.0")
    ccs.add_argument("--port", type=int, default=None,
                     help="bind port (default 7741)")
    ccs.add_argument("--device-kind", default="",
                     help="pin the fleet device identity (default: the "
                          "first client's handshake pins it)")
    ccs.add_argument("--jax-version", default="",
                     help="pin the fleet jax/jaxlib identity")
    ccs.add_argument("--serve-for", type=float, default=0.0,
                     metavar="SECONDS",
                     help="exit cleanly after this long (0 = until "
                          "SIGTERM)")
    ccs.add_argument("--trace-dir", default=None, metavar="DIR",
                     help="write artifact_serve trace spans here "
                          "(default: TPUCFN_TRACE_DIR; unset = off)")
    ccs.set_defaults(fn=cmd_compilecache_serve)
    ccg = ccsub.add_parser(
        "gc",
        help="cap a store dir at --max-bytes: LRU eviction by meta "
             "atime, claimed keys kept, orphan payloads swept")
    ccg.add_argument("--dir", metavar="DIR",
                     help="store dir (default: TPUCFN_COMPILE_CACHE_DIR "
                          "or the persistent-XLA-cache sibling)")
    ccg.add_argument("--max-bytes", required=True, metavar="N[KMG]",
                     help="live-entry byte cap (0 = evict everything "
                          "unclaimed)")
    ccg.add_argument("--orphan-age", type=float, default=3600.0,
                     metavar="SECONDS",
                     help="age before unreferenced payloads / tmp files "
                          "are swept (younger may be an in-flight "
                          "publish)")
    ccg.set_defaults(fn=cmd_compilecache_gc)
    cct = ccsub.add_parser(
        "stats", help="query a running artifact server's stats")
    cct.add_argument("--addr", required=True, metavar="HOST:PORT")
    cct.set_defaults(fn=cmd_compilecache_stats)

    sv = sub.add_parser(
        "serve",
        help="continuous-batching inference over a prompt workload "
             "(paged KV cache, bucketed prefills, admission control)")
    sv.add_argument("--preset",
                    choices=["nano", "tiny", "llama3-1b", "llama3-8b"],
                    default="tiny")
    sv.add_argument("--prompts",
                    help='JSONL file of {"tokens": [ids...]} prompts')
    sv.add_argument("--synthetic", type=int, default=8,
                    help="generate N random prompts instead of --prompts")
    sv.add_argument("--prompt-len", metavar="LO:HI",
                    help="synthetic prompt length range (default 4:32)")
    sv.add_argument("--max-new", type=int, default=16)
    sv.add_argument("--temperature", type=float, default=0.0)
    sv.add_argument("--max-batch", type=int, default=8,
                    help="decode slots (the fixed decode batch shape)")
    sv.add_argument("--cache-len", type=int, default=None,
                    help="per-slot KV capacity in tokens (default: model "
                         "max_seq)")
    sv.add_argument("--num-blocks", type=int, default=256)
    sv.add_argument("--block-size", type=int, default=16)
    sv.add_argument("--max-queued-tokens", type=int, default=1 << 16,
                    help="backpressure cap: outstanding prompt+budget "
                         "tokens before 429")
    sv.add_argument("--prefix-cache", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="block-level prompt-prefix caching: shared "
                         "prefixes are copied device-side instead of "
                         "re-prefilled (--no-prefix-cache disables)")
    sv.add_argument("--max-prefill-batch", type=int, default=4,
                    help="same-bucket prefills fused into one jitted call "
                         "(the engine's fixed lane count; 1 disables)")
    sv.add_argument("--deadline-s", type=float, default=None)
    sv.add_argument("--slo-ttft", type=float, default=0.5, metavar="SECONDS",
                    help="TTFT SLO target; burn rate exported as "
                         "serve_slo_ttft_burn_rate")
    sv.add_argument("--slo-tpot", type=float, default=0.05,
                    metavar="SECONDS",
                    help="per-output-token SLO target")
    sv.add_argument("--slo-objective", type=_slo_objective, default=0.99,
                    help="fraction of requests that must meet each target "
                         "(exclusive (0, 1))")
    sv.add_argument("--slo-shed", action="store_true",
                    help="SLO-aware early shedding: 429 new requests while "
                         "the rolling-window burn rate is sustained above "
                         "1 (sheds counted in serve_slo_shed_total)")
    sv.add_argument("--replicas", type=int, default=1, metavar="N",
                    help="engine replicas behind a resilient router "
                         "(health-driven failover, deadline-budgeted "
                         "retry, hedging, graceful drain); 1 = classic "
                         "single server")
    sv.add_argument("--retry-budget", type=int, default=2, metavar="K",
                    help="max resubmissions per request after replica "
                         "failure (bounded by the deadline budget "
                         "either way)")
    sv.add_argument("--hedge-ms", type=float, default=0.0, metavar="MS",
                    help="enable hedging: duplicate a straggling request "
                         "to a second replica after the p99-derived "
                         "delay, floored at MS (0 disables; first "
                         "completion wins, the loser is cancelled)")
    sv.add_argument("--drain-grace", type=float, default=30.0,
                    metavar="SECONDS",
                    help="SIGTERM drain window: admission closes and "
                         "accepted work gets this long to finish before "
                         "being failed/requeued")
    sv.add_argument("--spec-draft", metavar="PRESET",
                    choices=["self", "nano", "tiny", "llama3-1b",
                             "llama3-8b"],
                    help="speculative decoding: pair each engine with a "
                         "DRAFT engine of this preset at the same slot "
                         "layout ('self' = same preset and weights — the "
                         "acceptance-rate drill).  Greedy output is "
                         "bit-identical to plain decode; unset = the "
                         "plain engine path, byte-identical")
    sv.add_argument("--spec-k", type=int, default=4, metavar="K",
                    help="draft tokens proposed per slot per round (the "
                         "adaptive controller's ceiling)")
    sv.add_argument("--spec-adaptive", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="acceptance-driven k controller: shrink toward 1 "
                         "when the measured acceptance rate drops, turn "
                         "speculation off (with periodic probes) below "
                         "that (--no-spec-adaptive pins k)")
    sv.add_argument("--spec-draft-seed", type=int, default=None,
                    help="draft init seed for random-init draft presets "
                         "(default: --seed, which for the same preset "
                         "means identical weights)")
    sv.add_argument("--spec-replicas", metavar="I,J,...",
                    help="with --replicas N: comma-separated replica "
                         "indices that decode speculatively (default all) "
                         "— the router mixes spec and plain replicas "
                         "freely because greedy output is identical")
    sv.add_argument("--seed", type=int, default=0)
    sv.add_argument("--obs-port", type=int, default=None, metavar="PORT",
                    help="serve /metrics, /healthz, /varz on PORT while the "
                         "workload runs (0 = ephemeral port, printed)")
    sv.add_argument("--trace-dir", metavar="DIR",
                    help="write request-lifecycle trace spans (queue_wait/"
                         "prefill/decode_round/request_done JSONL) to DIR")
    sv.set_defaults(fn=cmd_serve)

    ck = sub.add_parser(
        "check",
        help="static analysis: concurrency/fleet-invariant rule pack "
             "(signal safety, locks, metric hygiene, jax hazards, "
             "vocabulary drift) — jax-free, rc 1 on findings")
    ck.add_argument("path", nargs="?", default=None,
                    help="package root to analyze (default: the "
                         "installed tpucfn package)")
    ck.add_argument("--json", action="store_true",
                    help="one machine-readable JSON line per finding "
                         "(file, line, rule, fingerprint, message)")
    ck.add_argument("--rules", action="append", metavar="ID[,ID...]",
                    help="run only these rules (repeatable / comma-"
                         "separated); unknown ids are a usage error")
    ck.add_argument("--baseline", metavar="PATH",
                    help="suppression file (default runs/"
                         "analysis_baseline.json next to the package); "
                         "every entry needs a one-line justification")
    ck.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to cover exactly the "
                         "current findings (existing justifications are "
                         "preserved; new entries get a TODO)")
    ck.add_argument("--diff", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="report findings only in files changed vs the "
                         "git ref (default HEAD); the whole package is "
                         "still parsed so cross-module rules keep "
                         "context")
    ck.set_defaults(fn=cmd_check)

    ob = sub.add_parser(
        "obs",
        help="aggregate per-host metrics/trace JSONL into one fleet view "
             "(merged step timeline, stragglers, request latency breakdown)")
    # not argparse-required: `tpucfn obs goodput` is a subcommand with
    # its own --run-dir; cmd_obs validates for the fleet view itself.
    ob.add_argument("--run-dir",
                    help="the training/serving --run-dir (expects logs/ "
                         "and trace/ beneath unless overridden)")
    ob.add_argument("--logs-dir", help="metrics JSONL dir (default RUN/logs)")
    ob.add_argument("--trace-dir", help="trace JSONL dir (default RUN/trace)")
    ob.add_argument("--steps", type=int, default=20,
                    help="timeline rows to show (most recent N steps)")
    ob.add_argument("--json", action="store_true",
                    help="emit the full report as one JSON object")
    ob.add_argument("--watch", type=float, default=0, metavar="SECONDS",
                    help="re-render every N seconds, tailing files "
                         "incrementally from their last offset")
    ob.set_defaults(fn=cmd_obs)
    obsub = ob.add_subparsers(dest="obs_command")
    og = obsub.add_parser(
        "goodput",
        help="per-run wall-clock ledger: productive/compile/data_wait/"
             "ckpt/idle/lost_work/restart_downtime buckets that sum to "
             "wall time, plus ft incident attribution")
    # SUPPRESS defaults on the flags the parent `obs` parser also owns:
    # argparse applies subparser defaults AFTER the parent's values are
    # parsed, so a plain default here would silently clobber
    # `tpucfn obs --json --run-dir X goodput` back to json=False.
    og.add_argument("--run-dir", default=argparse.SUPPRESS,
                    help="the training --run-dir (expects goodput/ and "
                         "optionally ft/events.jsonl beneath)")
    og.add_argument("--goodput-dir",
                    help="explicit ledger dir (default RUN/goodput)")
    og.add_argument("--ft-events",
                    help="ft incident log (default RUN/ft/events.jsonl)")
    og.add_argument("--json", action="store_true", default=argparse.SUPPRESS,
                    help="emit the full report as one JSON object")
    og.add_argument("--watch", type=float, default=argparse.SUPPRESS,
                    metavar="SECONDS",
                    help="re-read and re-render every N seconds")
    og.add_argument("--ledger", nargs="?", metavar="PATH",
                    const="runs/goodput_ledger.jsonl", default=None,
                    help="also append this run's report as one JSON row "
                         "to the cross-run regression ledger (default "
                         "runs/goodput_ledger.jsonl); diff with "
                         "`tpucfn obs diff`")
    og.set_defaults(fn=cmd_obs_goodput)

    pm = obsub.add_parser(
        "postmortem",
        help="assemble one incident's forensic bundle: incident row, "
             "skew-corrected timeline window, goodput span, per-host "
             "flight-recorder tails, last heartbeats")
    pm.add_argument("--run-dir", default=argparse.SUPPRESS,
                    help="the training --run-dir (expects ft/, trace/, "
                         "goodput/, flight/ beneath)")
    pm.add_argument("--ft-dir", default=None,
                    help="explicit ft dir (default RUN/ft)")
    which = pm.add_mutually_exclusive_group()
    which.add_argument("--incident", type=int, default=None,
                       help="incident number (from events.jsonl / "
                            "`tpucfn ft status`)")
    which.add_argument("--latest", action="store_true",
                       help="the newest incident (the default)")
    pm.add_argument("--window", type=float, default=15.0, metavar="SECONDS",
                    help="timeline/goodput window padding around "
                         "detection..recovery")
    pm.add_argument("--out", metavar="DIR",
                    help="bundle directory (default "
                         "RUN/postmortem/incident-NNN)")
    pm.add_argument("--json", action="store_true", default=argparse.SUPPRESS,
                    help="emit the full report (+ bundle path) as JSON")
    pm.set_defaults(fn=cmd_obs_postmortem)

    pf = obsub.add_parser(
        "profile",
        help="trigger an on-demand jax.profiler capture on a host via "
             "its obs endpoint (POST /profile)")
    pf.add_argument("--host", required=True, metavar="HOST[:PORT]",
                    help="obs endpoint address (the launch banner prints "
                         "each host's port)")
    pf.add_argument("--port", type=int, default=0,
                    help="port when --host has none")
    pf.add_argument("--seconds", type=float, default=2.0,
                    help="capture duration")
    pf.add_argument("--timeout", type=float, default=0.0,
                    help="client timeout (default: seconds + 120 — "
                         "profiler session setup can take tens of "
                         "seconds on a busy host)")
    pf.set_defaults(fn=cmd_obs_profile)

    df = obsub.add_parser(
        "diff",
        help="compare goodput_ratio + bucket shares between the last "
             "two rows of the cross-run goodput ledger")
    df.add_argument("--ledger", default="runs/goodput_ledger.jsonl",
                    help="ledger path (written by `tpucfn obs goodput "
                         "--ledger`)")
    df.add_argument("--json", action="store_true", default=argparse.SUPPRESS,
                    help="emit the diff as one JSON object")
    df.set_defaults(fn=cmd_obs_diff)

    tr = sub.add_parser(
        "trace",
        help="fleet timeline plane: clock-aligned Perfetto export, "
             "per-step critical-path attribution, deadline advice")
    trsub = tr.add_subparsers(dest="trace_command", required=True)

    def _trace_common(tp):
        tp.add_argument("--run-dir", required=True, metavar="DIR",
                        help="the training run directory (traces under "
                             "DIR/trace, clock probes under "
                             "DIR/ft/clock-offsets.jsonl)")
        tp.add_argument("--trace-dir", default=None, metavar="DIR",
                        help="span-file directory (default: "
                             "<run-dir>/trace)")
        tp.add_argument("--offsets", default=None, metavar="FILE",
                        help="coordinator clock-offsets.jsonl (default: "
                             "<run-dir>/ft/clock-offsets.jsonl when "
                             "present; absent = step-anchored estimate "
                             "only)")
        tp.add_argument("--json", action="store_true",
                        help="emit machine-readable JSON")

    te = trsub.add_parser(
        "export",
        help="merge per-host span files into one Chrome/Perfetto "
             "trace-event JSON with cross-host flow arrows")
    _trace_common(te)
    te.add_argument("--out", default=None, metavar="FILE",
                    help="output path (default: "
                         "<run-dir>/trace/timeline.json)")
    te.set_defaults(fn=cmd_trace_export)
    tc = trsub.add_parser(
        "critpath",
        help="per-step critical-path attribution: which plane bounded "
             "each step, with a goodput-ledger cross-check")
    _trace_common(tc)
    tc.add_argument("--goodput", default=None, metavar="DIR",
                    help="goodput ledger dir for the aggregate "
                         "cross-check (default: <run-dir>/goodput when "
                         "present)")
    tc.set_defaults(fn=cmd_trace_critpath)
    ta = trsub.add_parser(
        "advise",
        help="deadline autotune ADVISORY from observed frame-time "
             "percentiles (report-only)")
    _trace_common(ta)
    ta.add_argument("--headroom", type=float, default=8.0,
                    help="suggested = clamp(p99 * headroom, 1s, "
                         "current default)")
    ta.add_argument("--min-samples", type=int, default=8,
                    help="suggest nothing below this many observed "
                         "frames")
    ta.set_defaults(fn=cmd_trace_advise)

    fo = sub.add_parser(
        "forensics",
        help="postmortem bundle tooling (diff two incidents)")
    fosub = fo.add_subparsers(dest="forensics_command", required=True)
    fd = fosub.add_parser(
        "diff",
        help="diff two postmortem bundles of the same incident class: "
             "goodput-share and per-host deltas over each bundle's "
             "window")
    fd.add_argument("bundle_a", metavar="BUNDLE_A",
                    help="earlier bundle dir (from `tpucfn obs "
                         "postmortem`)")
    fd.add_argument("bundle_b", metavar="BUNDLE_B",
                    help="later bundle dir")
    fd.add_argument("--json", action="store_true",
                    help="emit the diff as one JSON object")
    fd.set_defaults(fn=cmd_forensics_diff)

    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
