"""span-balance: every emitted trace-span family is balanced and read.

The trace plane's analogue of the lost-Summary rule (ROADMAP
correctness follow-on, landed with ISSUE 13 — which adds the
``compile_fetch`` span and is exactly the kind of change that could
ship a write-only span).  Two rots, both silent at runtime:

* **unbalanced span** — a ``tracer.record(name, start=...)`` call that
  passes neither ``end=`` nor ``dur_s=`` writes a zero-duration span:
  the start was observed, the end never was, and every downstream
  percentile over that family reads 0.  (``queue_wait``'s retroactive
  record is the sanctioned *pattern* — start observed on another
  thread — and it is balanced: it passes ``end=``.  Point events go
  through ``.event()`` / ``kind="event"`` and are exempt: zero
  duration is their contract.)
* **write-only span** — a literal span name emitted somewhere but
  consumed by no reader in the package (``obs.aggregate``'s views, the
  postmortem, anything matching on the record's ``name``): the span
  costs a JSONL line per occurrence and tells nobody anything.
* **unpinned cross-host span** (ISSUE 20) — an emission passing
  ``remote_parent=`` (a cross-host causal link) whose name is not in
  the package's ``CROSS_HOST_SPAN_NAMES`` tuple: the merged timeline's
  link stats and the trace-smoke gate select carriers by that
  vocabulary, so an unpinned carrier's flow arrows silently vanish
  from the coverage accounting.  The reverse drifts too: a name pinned
  in the tuple that no emission site carries is a stale vocabulary
  entry — same contract as event kinds.

Emitters are ``X.record("lit", ..., start=...)`` and ``X.span("lit",
...)`` call sites (the ``start=`` keyword is what distinguishes a
trace-span record from the flight ring's same-named method).
Consumers are string literals compared (``==``/``in``/...) against a
``name`` field lookup — ``e.get("name")``, ``e["name"]``, a variable
bound from one — including comparisons against a module-level string
tuple (``CONTROL_SPAN_NAMES``), whose elements then all count as
consumed.  A package emitting no literal spans gets no findings.
"""

from __future__ import annotations

import ast

from tpucfn.analysis.core import Analysis, Finding
from tpucfn.analysis.rules.vocab import (
    _compared_literals,
    _is_field_lookup,
    _lookup_bound_names,
    _scope_walk,
)

RULE_ID = "span-balance"


def _literal_str(node: ast.expr) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _kw(call: ast.Call, name: str) -> ast.expr | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _span_emissions(analysis: Analysis):
    """``(mod, call, name, balanced, is_event, is_carrier)`` for every
    literal-named trace-span emission in the package (``is_carrier``:
    the call passes ``remote_parent=`` — a cross-host link)."""
    for mod in analysis.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute) \
                    or not node.args:
                continue
            name = _literal_str(node.args[0])
            if name is None:
                continue
            carrier = _kw(node, "remote_parent") is not None
            if node.func.attr == "record":
                if _kw(node, "start") is None:
                    continue  # flight-ring / SLO record, not a trace span
                kind = _kw(node, "kind")
                is_event = (_literal_str(kind) == "event"
                            if kind is not None else False)
                balanced = (_kw(node, "end") is not None
                            or _kw(node, "dur_s") is not None)
                yield mod, node, name, balanced, is_event, carrier
            elif node.func.attr == "span":
                # context-managed spans time their own end
                yield mod, node, name, True, False, carrier


def _module_str_tuples(analysis: Analysis) -> dict[str, list[str]]:
    """Module-level ``NAME = ("a", "b", ...)`` string tuples,
    package-wide — comparison sides naming one consume its elements."""
    out: dict[str, list[str]] = {}
    for mod in analysis.modules:
        for stmt in mod.tree.body:
            if not isinstance(stmt, ast.Assign):
                continue
            if not isinstance(stmt.value, (ast.Tuple, ast.List)):
                continue
            vals = []
            ok = True
            for e in stmt.value.elts:
                s = _literal_str(e)
                if s is None:
                    ok = False
                    break
                vals.append(s)
            if not ok or not vals:
                continue
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = vals
    return out


def _consumed_names(analysis: Analysis) -> set[str]:
    """Every span name some reader in the package matches on."""
    tuples = _module_str_tuples(analysis)
    consumed: set[str] = set()
    for mod in analysis.modules:
        scopes = [mod.tree.body]
        for _qual, info in analysis.functions(mod).items():
            if not isinstance(info.node, ast.Lambda):
                scopes.append(info.node.body)
        for body in scopes:
            name_vars = _lookup_bound_names(body, "name")

            def is_name(e: ast.expr) -> bool:
                if _is_field_lookup(e, "name"):
                    return True
                return isinstance(e, ast.Name) and e.id in name_vars

            for node in _scope_walk(body):
                if not isinstance(node, ast.Compare):
                    continue
                sides = [node.left, *node.comparators]
                if not any(is_name(s) for s in sides):
                    continue
                consumed.update(_compared_literals(node, is_name))
                for s in sides:
                    if isinstance(s, ast.Name) and s.id in tuples:
                        consumed.update(tuples[s.id])
    return consumed


def check(analysis: Analysis):
    findings: list[Finding] = []
    emissions = list(_span_emissions(analysis))
    if not emissions:
        return findings
    consumed = _consumed_names(analysis)
    pinned = _module_str_tuples(analysis).get("CROSS_HOST_SPAN_NAMES", [])
    flagged_unconsumed: set[str] = set()
    flagged_unpinned: set[str] = set()
    carried: set[str] = set()
    for mod, call, name, balanced, is_event, carrier in emissions:
        if carrier:
            carried.add(name)
            if pinned and name not in pinned \
                    and name not in flagged_unpinned:
                flagged_unpinned.add(name)
                findings.append(Finding(
                    RULE_ID, mod.rel, call.lineno,
                    f"span {name!r} carries remote_parent= (a cross-host "
                    "causal link) but is not pinned in "
                    "CROSS_HOST_SPAN_NAMES — the merged timeline's link "
                    "stats count carriers by that vocabulary, so this "
                    "span's flow arrows silently vanish from coverage "
                    "accounting (add the name to the tuple)",
                    key=f"unpinned-crosshost:{name}"))
        if not is_event and not balanced:
            findings.append(Finding(
                RULE_ID, mod.rel, call.lineno,
                f"span {name!r} records a start but neither end= nor "
                "dur_s= — the end path was never observed, so every "
                "duration percentile over this family reads 0 (pass the "
                "measured end/duration, or make it an explicit "
                "kind=\"event\" point marker)",
                key=f"unbalanced:{name}"))
        if is_event:
            continue  # point events are an open vocabulary by contract
        if name not in consumed and name not in flagged_unconsumed:
            flagged_unconsumed.add(name)
            findings.append(Finding(
                RULE_ID, mod.rel, call.lineno,
                f"span {name!r} is emitted here but no reader in the "
                "package ever matches on it — a write-only span costs a "
                "JSONL line per occurrence and tells nobody anything "
                "(consume it in an obs.aggregate view, or stop emitting "
                "it)",
                key=f"unconsumed:{name}"))
    # Reverse drift: a name pinned in CROSS_HOST_SPAN_NAMES that no
    # emission site in the package carries or even emits is a stale
    # vocabulary entry (the forward check above keeps carriers pinned;
    # this keeps the pin honest).  Emitted-but-not-carrying is fine —
    # e.g. data_wait carries remote_parent only on remote batches.
    emitted = {name for _m, _c, name, _b, _e, _cr in emissions}
    for stale in pinned:
        if stale in emitted:
            continue
        for mod in analysis.modules:
            loc = None
            for stmt in mod.tree.body:
                if isinstance(stmt, ast.Assign) and any(
                        isinstance(t, ast.Name)
                        and t.id == "CROSS_HOST_SPAN_NAMES"
                        for t in stmt.targets):
                    loc = stmt.lineno
                    break
            if loc is not None:
                findings.append(Finding(
                    RULE_ID, mod.rel, loc,
                    f"CROSS_HOST_SPAN_NAMES pins {stale!r} but no "
                    "emission site in the package records a span by "
                    "that name — stale vocabulary entry (drop it, or "
                    "restore the emitter)",
                    key=f"stale-pin:{stale}"))
                break
    return findings
