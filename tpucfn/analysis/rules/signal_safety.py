"""signal-safety: no non-reentrant lock acquisition reachable from a
signal handler.

The incidents this encodes (CHANGES.md): PR 6 shipped a flight-dump
SIGTERM handler that self-deadlocked at ``stop_all`` time because the
handler ran on the main thread *inside* a ``record()`` critical section
guarded by a plain ``threading.Lock`` (fixed by making it an RLock);
PR 8's first ``Server.drain(wait=False)`` — the SIGTERM drain hook —
acquired the non-reentrant server lock the interrupted frame already
held, deadlocking the process at the exact moment it tried to die
gracefully (fixed by making the arm-only path lock-free).

The rule finds every handler installed via ``signal.signal(sig, h)``
(including handlers defined inside ``install_dump_handlers``-style
installers) and walks the call graph out of it.  Any function reachable
from the handler that acquires a ``threading.Lock`` (``with self._lock``
or ``.acquire()``) is a finding; ``RLock`` and conditions over RLocks
are exempt — reentrancy is precisely the property that makes them
signal-safe here.  Constant keyword arguments prune branches: a call
like ``drain(wait=False)`` analyzes only the early-return arm-only path
(the fixed shape), not the lock-taking ``wait=True`` body it never
reaches.
"""

from __future__ import annotations

import ast

from tpucfn.analysis.core import (
    Analysis,
    Finding,
    FuncInfo,
    call_consts,
    calls_in,
    live_statements,
)

RULE_ID = "signal-safety"
_MAX_DEPTH = 8


def _is_signal_install(call: ast.Call, aliases: tuple[frozenset[str],
                                                      frozenset[str]]) -> bool:
    """``signal.signal(sig, h)`` where the receiver is an import alias
    of the :mod:`signal` module (``import signal`` / ``import signal as
    _signal``), or the bare-name form from ``from signal import
    signal``.  Requiring the receiver to resolve keeps event-bus-style
    ``obj.signal(name, cb)`` APIs out of the rule."""
    if len(call.args) < 2:
        return False
    module_aliases, name_aliases = aliases
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "signal" \
            and isinstance(f.value, ast.Name):
        return f.value.id in module_aliases
    return isinstance(f, ast.Name) and f.id in name_aliases


def _signal_aliases(mod) -> tuple[frozenset[str], frozenset[str]]:
    """``(module_aliases, name_aliases)`` under which this module can
    reach ``signal.signal``."""
    mods, names = set(), set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "signal":
                    mods.add(a.asname or a.name)
        elif isinstance(node, ast.ImportFrom) and node.module == "signal":
            for a in node.names:
                if a.name == "signal":
                    names.add(a.asname or a.name)
    return frozenset(mods), frozenset(names)


def _handler_info(analysis: Analysis, mod, caller: FuncInfo,
                  arg: ast.expr) -> FuncInfo | None:
    funcs = analysis.functions(mod)
    if isinstance(arg, ast.Name):
        nested = f"{caller.qualname}.{arg.id}"
        return funcs.get(nested) or funcs.get(arg.id)
    if isinstance(arg, ast.Attribute) and isinstance(arg.value, ast.Name):
        if arg.value.id == "self" and caller.class_name is not None:
            return analysis._method(mod, caller.class_name, arg.attr)
    if isinstance(arg, ast.Lambda):
        return FuncInfo(f"{caller.qualname}.<lambda>", arg, mod,
                        caller.class_name)
    return None


def _body(info: FuncInfo) -> list[ast.stmt]:
    if isinstance(info.node, ast.Lambda):
        e = ast.Expr(value=info.node.body)
        ast.copy_location(e, info.node.body)
        return [e]
    return info.node.body


def check(analysis: Analysis):
    findings: list[Finding] = []
    for mod in analysis.modules:
        aliases = _signal_aliases(mod)
        if not any(aliases):
            continue  # module cannot install a signal handler
        # module-scope installs included: a top-level
        # ``signal.signal(...)`` arms a handler just as surely as one
        # inside a function
        scopes: list[FuncInfo] = [
            FuncInfo("<module>", mod.tree, mod)]  # type: ignore[arg-type]
        for qual, info in analysis.functions(mod).items():
            if not isinstance(info.node, ast.Lambda):
                scopes.append(info)
        for info in scopes:
            for stmt in live_statements(info.node.body):
                for call in calls_in(stmt):
                    if not _is_signal_install(call, aliases):
                        continue
                    handler = _handler_info(analysis, mod, info,
                                            call.args[1])
                    if handler is not None:
                        findings.extend(
                            _walk_handler(analysis, handler))
    return findings


def _walk_handler(analysis: Analysis, handler: FuncInfo):
    findings: list[Finding] = []
    seen: set[tuple] = set()
    work: list[tuple[FuncInfo, dict, int]] = [(handler, {}, 0)]
    flagged: set[tuple[str, str]] = set()
    while work:
        info, consts, depth = work.pop()
        key = (info.module.rel, info.qualname,
               tuple(sorted(consts.items())))
        if key in seen or depth > _MAX_DEPTH:
            continue
        seen.add(key)
        mod = info.module
        for stmt in live_statements(_body(info), consts):
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    kind, name = analysis.lock_kind(
                        mod, info.class_name, item.context_expr)
                    if kind == "lock" and (info.qualname, name) \
                            not in flagged:
                        flagged.add((info.qualname, name))
                        findings.append(Finding(
                            RULE_ID, mod.rel, stmt.lineno,
                            f"{info.qualname} acquires non-reentrant "
                            f"lock {name} and is reachable from signal "
                            f"handler {handler.qualname} — if the signal "
                            "interrupts a frame already holding it, the "
                            "process deadlocks while trying to die; use "
                            "an RLock, a lock-free arm-only path, or "
                            "defer to the main loop",
                            key=f"{handler.qualname}->{info.qualname}"
                                f":{name}"))
            for call in calls_in(stmt):
                f = call.func
                if isinstance(f, ast.Attribute) and f.attr == "acquire":
                    kind, name = analysis.lock_kind(
                        mod, info.class_name, f.value)
                    if kind == "lock" and (info.qualname, name) \
                            not in flagged:
                        flagged.add((info.qualname, name))
                        findings.append(Finding(
                            RULE_ID, mod.rel, call.lineno,
                            f"{info.qualname} calls .acquire() on "
                            f"non-reentrant lock {name} and is reachable "
                            f"from signal handler {handler.qualname}",
                            key=f"{handler.qualname}->{info.qualname}"
                                f":{name}"))
                callee = analysis.resolve_call(mod, info, call)
                if callee is not None and not isinstance(callee.node,
                                                         ast.Lambda):
                    work.append((callee, call_consts(call, callee),
                                 depth + 1))
    return findings
