"""Llama-3-family decoder — the flagship model (BASELINE config 4:
"Llama-3 8B FSDP-style param sharding on v5p-64").

Architecture (public Llama-3 recipe): RMSNorm pre-norm, GQA attention with
RoPE (theta 500k), SwiGLU MLP, untied LM head. TPU-first choices:

* layers run under ``nn.scan`` + ``nn.remat`` — one compiled block body
  regardless of depth (compile time O(1) in layers) and activation
  rematerialization to trade MXU flops for HBM (the standard TPU memory
  recipe). Scanned params carry a leading layer axis; ``sharding_rules``
  accounts for it.
* bf16 activations, fp32 params/optimizer, fp32 logits for the softmax.
* attention inner op is pluggable (dense XLA / Pallas flash / ring SP).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tpucfn.mesh import AXIS_EXPERT, AXIS_FSDP, AXIS_TENSOR
from tpucfn.models.layers import (
    AttentionFn,
    CausalSelfAttention,
    RMSNorm,
    SwiGLUMLP,
)
from tpucfn.models.moe import MoEConfig, MoEMLP
from tpucfn.ops.attention import dot_product_attention
from tpucfn.parallel.sharding import ShardingRules


def remat_policy(remat: bool | str):
    """(do_remat, jax.checkpoint policy) for a ``LlamaConfig.remat``
    value — shared by the scanned model and the pipeline stage body so
    both paths honor the same policy vocabulary."""
    if remat in (True, "full"):
        return True, None
    if remat in (False, "none"):
        return False, None
    if remat == "dots":
        return True, jax.checkpoint_policies.checkpoint_dots
    if remat == "dots_no_batch":
        return True, jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    raise ValueError(
        f"remat={remat!r} — expected True/'full', 'dots', "
        "'dots_no_batch', or False/'none'")


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    max_seq: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    # Rematerialization policy for the block stack (numerics-identical
    # across all choices — only the flops/HBM schedule differs):
    #   True / "full": checkpoint everything (max memory savings, ~1/3
    #     extra recompute flops) — the fits-anywhere default.
    #   "dots": jax.checkpoint_policies.checkpoint_dots — keep matmul
    #     (MXU) outputs, recompute only cheap elementwise ops; the
    #     standard TPU middle ground when activations almost fit.
    #   "dots_no_batch": dots_with_no_batch_dims_saveable — save only
    #     weight-stationary matmuls (Megatron-style selective remat).
    #   False / "none": no remat (pure MFU when the model fits).
    remat: bool | str = True
    moe: MoEConfig | None = None  # None = dense SwiGLU MLP

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def __post_init__(self):
        remat_policy(self.remat)  # validate early, not at first apply

    @classmethod
    def llama3_8b(cls) -> "LlamaConfig":
        return cls()  # the defaults above are the 8B shape

    @classmethod
    def llama3_1b(cls) -> "LlamaConfig":
        # ~1B proxy for single-chip benchmarking.
        return cls(dim=2048, n_layers=16, n_heads=32, n_kv_heads=8, ffn_dim=8192)

    @classmethod
    def tiny(cls, vocab: int = 256) -> "LlamaConfig":
        return cls(vocab_size=vocab, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
                   ffn_dim=128, max_seq=512, dtype=jnp.float32)


class LlamaBlock(nn.Module):
    """One decoder block. ``__call__`` uses scan's (carry, _) -> (carry, None)
    shape so the same body works unrolled and under ``nn.scan``; q_offset
    rides in the carry because it can be a traced value (ring/SP shards
    derive it from ``lax.axis_index``)."""

    cfg: LlamaConfig
    attention_fn: AttentionFn = dot_product_attention
    decode: bool = False
    # Mesh for the MoE explicit expert-parallel dispatch (models/moe.py);
    # None keeps MoE single-device. Static module metadata, like
    # attention_fn.
    ep_mesh: Any = None
    # True when this block runs inside a shard_map whose manual axes
    # include `expert` (the pipeline stage body): MoE runs its EP body
    # inline with locally-declared expert params (models/moe.py).
    ep_manual: bool = False

    @nn.compact
    def __call__(self, carry, _=None):
        x, q_offset = carry
        cfg = self.cfg
        h = CausalSelfAttention(
            n_heads=cfg.n_heads, n_kv_heads=cfg.n_kv_heads, head_dim=cfg.head_dim,
            rope_theta=cfg.rope_theta, max_seq=cfg.max_seq, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, attention_fn=self.attention_fn,
            decode=self.decode, name="attn",
        )(RMSNorm(cfg.norm_eps, cfg.dtype, name="input_norm")(x), q_offset=q_offset)
        x = x + h
        normed = RMSNorm(cfg.norm_eps, cfg.dtype, name="post_attn_norm")(x)
        if cfg.moe is not None:
            h = MoEMLP(cfg.ffn_dim, cfg.moe, cfg.dtype, cfg.param_dtype,
                       ep_mesh=self.ep_mesh, ep_manual=self.ep_manual,
                       name="mlp")(normed)
        else:
            h = SwiGLUMLP(cfg.ffn_dim, cfg.dtype, cfg.param_dtype, name="mlp")(normed)
        return (x + h, q_offset), None


class Llama(nn.Module):
    cfg: LlamaConfig
    # None = automatic dense↔flash dispatch (tpucfn.kernels.auto): the
    # Pallas flash kernel on TPU at S >= TPUCFN_FLASH_MIN_S, XLA dense
    # everywhere else. Pass an explicit fn (dense, ring, flash) to pin.
    attention_fn: AttentionFn | None = None
    decode: bool = False  # KV-cache autoregressive mode (generation)
    # Mesh enabling the MoE explicit expert-parallel all-to-all dispatch
    # when its `expert` axis is >1 (tpucfn/models/moe.py). Pass the
    # training mesh; None (default) keeps MoE on the single-device path.
    ep_mesh: Any = None

    @nn.compact
    def __call__(self, tokens, *, q_offset=0, return_hidden=False,
                 segment_ids=None):
        """tokens: (B, S) int32 → logits (B, S, vocab) fp32.

        ``q_offset`` is the global position of tokens[:, 0] — nonzero when
        the sequence axis is sharded (ring attention / SP).

        ``return_hidden=True`` stops after the final norm and returns the
        (B, S, dim) hidden states instead of logits — pair it with
        :func:`chunked_causal_lm_loss`, which applies the LM head
        chunk-by-chunk so the fp32 (B, S, vocab) logits tensor is never
        materialized (at B=8, S=2k, V=128k that tensor alone is ~8 GB —
        more than half a v5e's HBM; observed OOM on chip).  Init with the
        default ``False`` so the head params are created.

        ``segment_ids`` (B, S) enables packed-sequence training:
        attention is masked across document boundaries (the flash
        kernel's native segment path on TPU, an explicit mask on dense)
        — pair with ``packed_causal_lm_loss``.  Overrides
        ``attention_fn``; incompatible with decode/SP.
        """
        if self.decode and not (isinstance(q_offset, int) and q_offset == 0):
            raise ValueError("decode mode is incompatible with q_offset/SP sharding")
        if segment_ids is not None:
            if self.decode:
                raise ValueError("segment_ids is incompatible with decode mode")
            if not (isinstance(q_offset, int) and q_offset == 0):
                raise ValueError(
                    "segment_ids is incompatible with q_offset/SP sharding")
            from tpucfn.data.packing import packed_attention_fn

            attention_fn = packed_attention_fn(segment_ids)
        else:
            attention_fn = self.attention_fn
        if attention_fn is None:
            from tpucfn.kernels.auto import auto_attention_static_zero

            # Flash-eligible only when offsets are the static zero of the
            # unsharded path (decode and SP keep the dense/ring ops).
            if not self.decode and isinstance(q_offset, int) and q_offset == 0:
                attention_fn = auto_attention_static_zero
            else:
                attention_fn = dot_product_attention
        cfg = self.cfg
        x = nn.Embed(
            cfg.vocab_size, cfg.dim, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            name="embed_tokens",
        )(tokens)

        block = LlamaBlock
        do_remat, policy = remat_policy(cfg.remat)
        if do_remat and not self.decode:
            block = nn.remat(block, prevent_cse=False, policy=policy)
        carry = (x, jnp.asarray(q_offset))
        if cfg.scan_layers:
            carry, _ = nn.scan(
                block,
                variable_axes={"params": 0, "losses": 0, "metrics": 0, "cache": 0},
                split_rngs={"params": True},
                length=cfg.n_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, attention_fn, self.decode, self.ep_mesh,
              name="layers")(carry)
        else:
            for i in range(cfg.n_layers):
                carry, _ = block(cfg, attention_fn, self.decode, self.ep_mesh,
                                 name=f"layers_{i}")(carry)
        x = carry[0]

        x = RMSNorm(cfg.norm_eps, cfg.dtype, name="final_norm")(x)
        if return_hidden:
            return x
        logits = nn.DenseGeneral(
            cfg.vocab_size, use_bias=False, dtype=jnp.float32,
            param_dtype=cfg.param_dtype, name="lm_head",
        )(x.astype(jnp.float32))
        return logits


def sharding_rules(cfg: LlamaConfig, *, fsdp: bool = True, tensor: bool = True,
                   layer_lead_axis: str | None = None) -> ShardingRules:
    """Megatron TP × FSDP rules for the Llama param tree.

    Scanned layers stack params with a leading ``layers`` axis; every
    spec under ``layers/`` starts with ``layer_lead_axis`` there —
    None (unsharded depth) normally, the pipeline axis for PP stage
    sharding (llama_pp.pp_sharding_rules).  The ``spec()`` helper below
    is used by exactly the per-layer rules, so this composes without
    any pattern-matching on rule strings.
    """
    t = AXIS_TENSOR if tensor else None
    f = AXIS_FSDP if fsdp else None
    lead = (layer_lead_axis,) if cfg.scan_layers else ()

    def spec(*axes):
        full = lead + axes
        while full and full[-1] is None:  # canonical: no trailing Nones
            full = full[:-1]
        return P(*full)

    e = AXIS_EXPERT
    return ShardingRules((
        # MoE experts first (more specific than the dense MLP rules).
        (r"experts/(gate_proj|up_proj)/kernel$", spec(e, f, t)),
        (r"experts/down_proj/kernel$", spec(e, t, f)),
        (r"router/kernel$", spec(f)),
        (r"(q_proj|k_proj|v_proj)/kernel$", spec(f, t)),
        (r"o_proj/kernel$", spec(t, f)),
        (r"(gate_proj|up_proj)/kernel$", spec(f, t)),
        (r"down_proj/kernel$", spec(t, f)),
        (r"(input_norm|post_attn_norm)/scale$", spec()),
        (r"embed_tokens/embedding$", P(t, f)),
        (r"lm_head/kernel$", P(f, t)),
        (r".*", P()),
    ))


def chunked_causal_lm_loss(
    hidden: jax.Array,          # (B, S, D) — Llama(...)(…, return_hidden=True)
    lm_head_kernel: jax.Array,  # (D, V)
    tokens: jax.Array,          # (B, S) int32
    *,
    chunk_size: int = 512,
    z_loss: float = 0.0,
) -> tuple[jax.Array, jax.Array]:
    """Next-token CE + accuracy WITHOUT materializing (B, S, V) logits.

    Numerically equal to ``causal_lm_loss(hidden @ W, tokens)`` (tests
    assert values and grads): a ``lax.scan`` over sequence chunks
    computes each chunk's fp32 logits, reduces them to a CE sum and a
    correct-count, and drops them; ``jax.checkpoint`` on the chunk body
    makes reverse-mode recompute logits chunkwise instead of stashing
    them.  Peak logits memory is (B, chunk, V) instead of (B, S, V) —
    the difference between fitting and the observed on-chip OOM for
    Llama-1B (V=128k) on one 16 GB chip, and a hard requirement at the
    long-context end (S=32k never fits materialized).
    """
    import optax

    b, s, _ = hidden.shape
    n = s - 1
    pred = hidden[:, :-1]
    targets = tokens[:, 1:]
    c = max(1, min(chunk_size, n))
    pad = (-n) % c
    if pad:
        pred = jnp.pad(pred, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)), constant_values=-1)
    k = (n + pad) // c
    pred = pred.reshape(b, k, c, -1).swapaxes(0, 1)     # (k, B, c, D)
    targets = targets.reshape(b, k, c).swapaxes(0, 1)   # (k, B, c)

    @jax.checkpoint
    def chunk_sums(w, h_c, t_c):
        logits = h_c.astype(jnp.float32) @ w.astype(jnp.float32)
        per_tok = optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.maximum(t_c, 0))
        if z_loss:
            per_tok = per_tok + z_loss * jax.nn.logsumexp(logits, axis=-1) ** 2
        valid = t_c >= 0
        ce = jnp.sum(jnp.where(valid, per_tok, 0.0))
        correct = jnp.sum(jnp.where(valid, jnp.argmax(logits, -1) == t_c,
                                    False).astype(jnp.float32))
        return ce, correct

    def body(carry, xs):
        ce_acc, cor_acc = carry
        h_c, t_c = xs
        ce, cor = chunk_sums(lm_head_kernel, h_c, t_c)
        return (ce_acc + ce, cor_acc + cor), None

    (ce, cor), _ = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (pred, targets))
    denom = b * n
    return ce / denom, cor / denom


def causal_lm_loss(logits: jax.Array, tokens: jax.Array,
                   *, z_loss: float = 0.0) -> tuple[jax.Array, jax.Array]:
    """Next-token cross entropy (mean over B, S-1) + optional z-loss.

    Returns (loss, accuracy)."""
    import optax

    targets = tokens[:, 1:]
    pred = logits[:, :-1]
    ce = optax.softmax_cross_entropy_with_integer_labels(pred, targets).mean()
    if z_loss:
        ce = ce + z_loss * jnp.mean(jax.nn.logsumexp(pred, axis=-1) ** 2)
    acc = jnp.mean(jnp.argmax(pred, -1) == targets)
    return ce, acc
