"""BERT-base encoder + MLM pretraining head (BASELINE config 3:
"BERT-base pretraining, Horovod→JAX launcher, all-reduce over ICI").

Classic post-LayerNorm BERT architecture (learned positions, GELU MLP,
tied-shape untied-weight MLM head). Param naming (q_proj/…/o_proj,
fc1/fc2) matches the transformer sharding presets, so the same TP×FSDP
rules drive it. bf16 compute / fp32 params; fp32 softmax and LayerNorm.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpucfn.ops.attention import dot_product_attention


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    ffn_dim: int = 3072
    max_positions: int = 512
    type_vocab: int = 2
    dropout: float = 0.1
    norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @classmethod
    def base(cls) -> "BertConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "BertConfig":
        return cls(vocab_size=128, dim=32, n_layers=2, n_heads=2, ffn_dim=64,
                   max_positions=64, dropout=0.0, dtype=jnp.float32)


class BertLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, attn_mask, *, train: bool):
        cfg = self.cfg
        b, s, _ = x.shape
        dense = lambda feat, name: nn.DenseGeneral(  # noqa: E731
            feat, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name=name
        )
        ln = lambda name: nn.LayerNorm(  # noqa: E731
            epsilon=cfg.norm_eps, dtype=jnp.float32, param_dtype=cfg.param_dtype,
            name=name,
        )
        drop = nn.Dropout(cfg.dropout, deterministic=not train)

        q = dense(cfg.dim, "q_proj")(x).reshape(b, s, cfg.n_heads, cfg.head_dim)
        k = dense(cfg.dim, "k_proj")(x).reshape(b, s, cfg.n_heads, cfg.head_dim)
        v = dense(cfg.dim, "v_proj")(x).reshape(b, s, cfg.n_heads, cfg.head_dim)
        attn = dot_product_attention(q, k, v, causal=False,
                                     mask=attn_mask[:, None, None, :])
        attn = attn.reshape(b, s, cfg.dim)
        x = ln("attn_norm")((x + drop(dense(cfg.dim, "o_proj")(attn))).astype(jnp.float32))
        x = x.astype(cfg.dtype)

        h = nn.gelu(dense(cfg.ffn_dim, "fc1")(x))
        x = ln("mlp_norm")((x + drop(dense(cfg.dim, "fc2")(h))).astype(jnp.float32))
        return x.astype(cfg.dtype)


class Bert(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, tokens, *, token_types=None, attn_mask=None, train: bool = False):
        """tokens: (B, S) → MLM logits (B, S, vocab) fp32."""
        cfg = self.cfg
        b, s = tokens.shape
        if attn_mask is None:
            attn_mask = jnp.ones((b, s), bool)
        if token_types is None:
            token_types = jnp.zeros((b, s), jnp.int32)

        embed = nn.Embed(cfg.vocab_size, cfg.dim, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="embed_tokens")
        x = embed(tokens)
        x = x + nn.Embed(cfg.max_positions, cfg.dim, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="embed_positions")(
            jnp.arange(s)[None, :]
        )
        x = x + nn.Embed(cfg.type_vocab, cfg.dim, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="embed_types")(token_types)
        x = nn.LayerNorm(epsilon=cfg.norm_eps, dtype=jnp.float32,
                         param_dtype=cfg.param_dtype, name="embed_norm")(
            x.astype(jnp.float32)
        ).astype(cfg.dtype)
        x = nn.Dropout(cfg.dropout, deterministic=not train)(x)

        for i in range(cfg.n_layers):
            x = BertLayer(cfg, name=f"layers_{i}")(x, attn_mask, train=train)

        # MLM head: transform + vocab projection.
        h = nn.gelu(nn.DenseGeneral(cfg.dim, dtype=cfg.dtype,
                                    param_dtype=cfg.param_dtype, name="mlm_transform")(x))
        h = nn.LayerNorm(epsilon=cfg.norm_eps, dtype=jnp.float32,
                         param_dtype=cfg.param_dtype, name="mlm_norm")(
            h.astype(jnp.float32)
        )
        logits = nn.DenseGeneral(cfg.vocab_size, dtype=jnp.float32,
                                 param_dtype=cfg.param_dtype, name="lm_head")(h)
        return logits


def mlm_loss(logits: jax.Array, labels: jax.Array, mask: jax.Array):
    """Masked-LM loss over positions where ``mask`` is True.

    labels: (B, S) original token ids; mask: (B, S) bool of masked slots."""
    import optax

    per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    denom = jnp.maximum(mask.sum(), 1)
    loss = jnp.where(mask, per_tok, 0.0).sum() / denom
    acc = jnp.where(mask, jnp.argmax(logits, -1) == labels, False).sum() / denom
    return loss, acc
