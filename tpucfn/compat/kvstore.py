"""MXNet-KVStore-shaped compat surface.

The reference's training scripts selected their distribution mode with
``--kv-store dist_sync`` and programmatically via
``mx.kvstore.create("dist_sync")`` (SURVEY.md §3.2); under it, ps-lite
servers held weights and every batch did push(grad)/pull(weights) over
TCP. tpucfn has no parameter server — synchronous DP is one SPMD program
with a compiler-emitted gradient psum over ICI (SURVEY.md §2.3 row 1) —
but scripts keep working: this shim accepts the same mode strings and
returns an object describing the equivalent tpucfn configuration (and
raises with a pointed message for modes whose *semantics* don't exist on
TPU, i.e. async PS).
"""

from __future__ import annotations

import dataclasses

from jax.sharding import PartitionSpec as P

from tpucfn.parallel.sharding import ShardingRules

_SYNC_MODES = {"local", "device", "dist_sync", "dist_sync_device"}
_ASYNC_MODES = {"dist_async"}


@dataclasses.dataclass(frozen=True)
class KVStoreShim:
    """What a kv-store mode means here: a sharding-rule choice, not a
    server fleet. ``rank``/``num_workers`` mirror the KVStore attributes
    scripts read for epoch math."""

    type: str

    @property
    def rank(self) -> int:
        import jax

        return jax.process_index()

    @property
    def num_workers(self) -> int:
        import jax

        return jax.process_count()

    def rules(self) -> ShardingRules:
        """Replicated params; gradient reduction is implicit in the SPMD
        step — exactly dist_sync's convergence semantics at none of its
        wire cost."""
        return ShardingRules(((r".*", P()),))


def create(mode: str = "local") -> KVStoreShim:
    if mode in _SYNC_MODES:
        return KVStoreShim(type=mode)
    if mode in _ASYNC_MODES:
        raise NotImplementedError(
            "dist_async was a ps-lite artifact (stale-gradient updates to a "
            "server copy). The TPU path is synchronous SPMD; use dist_sync "
            "(same convergence contract the reference's examples used)."
        )
    raise ValueError(f"unknown kv-store mode {mode!r}")
