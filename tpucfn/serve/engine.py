"""ServeEngine — jitted prefill/decode steps over a slot-resident KV cache.

The engine owns ``max_batch`` physical decode slots.  Each slot carries
its own flax decode cache (the same ``cache`` collection
``models/generate.py`` uses), batched on a leading slot axis, so decode
is ONE jitted program over all slots via ``jax.vmap`` of the
single-sequence apply — per-slot ``cache_index`` scalars fall out of the
vmap for free, which is exactly what continuous batching needs (every
slot sits at a different sequence position) and what the training-style
shared-scalar cache cannot express.

Two compiled entry points, both with the slot cache DONATED (the
multi-hundred-MB buffer is updated in place, never double-buffered):

* ``prefill``: one sequence, padded to its length bucket, run through
  the decode-mode model in a single pass; its per-layer ``cache_index``
  is then rewound to the TRUE prefix length, so the pad garbage beyond
  it is overwritten by the next decode step before causality could ever
  expose it; the fresh cache row is scattered into the donated slot
  cache and the first token is sampled from the last REAL position's
  logits.  Compiles once per (bucket) — the scheduler's pow-2 buckets
  keep that set small.
* ``decode``: one token for EVERY slot (fixed shape, compiles once).
  Vacant slots compute garbage lanes that are never read — the standard
  static-shape trade.

Greedy decode here is token-identical to ``models/generate.py`` (the
parity test in ``tests/test_serve_engine.py`` pins it): same model code,
same cache math, same argmax.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from tpucfn.parallel.sharding import _path_str


def _sample(logits: jax.Array, temps: jax.Array, key: jax.Array) -> jax.Array:
    """(N, V) fp32 logits -> (N,) int32 tokens.  temp<=0 is greedy;
    otherwise categorical over logits/temp (the ``models/generate.py``
    convention — temperature scaling first)."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temps > 0.0, sampled, greedy)


def _rewind_cache_index(cache, true_len):
    """Post-prefill surgery: every ``cache_index`` leaf (shape (L,) under
    nn.scan, () unrolled) is set to the TRUE prefix length, un-counting
    the bucket padding.  Pad K/V beyond ``true_len`` stays in the buffer
    but is dead: the next decode step overwrites position ``true_len``
    before attending, and causality masks everything past the query."""

    def fix(path, leaf):
        if _path_str(path).endswith("cache_index"):
            return jnp.full(leaf.shape, true_len, leaf.dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, cache)


class ServeEngine:
    """Wraps any decode-protocol flax model (init/apply with a ``cache``
    collection, ``(B, S) int32 -> (B, S, V)`` logits) behind the two
    jitted serving steps.  Use :meth:`from_llama` for the model zoo's
    decoder (optionally LoRA-merged via ``train/lora.py``)."""

    def __init__(self, model: Any, params: Any, *, max_batch: int,
                 cache_len: int, rng: jax.Array | None = None):
        self.model = model
        self.params = params
        self.max_batch = max_batch
        self.cache_len = cache_len
        self._base_key = jax.random.key(0) if rng is None else rng
        self._step_count = 0

        # Single-sequence cache template (b=1) — the per-slot unit.
        row_shapes = jax.eval_shape(
            lambda: model.init(jax.random.key(0),
                               jnp.zeros((1, 1), jnp.int32)))["cache"]
        self._row_shapes = row_shapes
        # Slot-batched cache: every leaf gains a leading (max_batch,) axis.
        self.cache = jax.tree.map(
            lambda s: jnp.zeros((max_batch,) + s.shape, s.dtype), row_shapes)
        # Host-side per-slot sampling temperature (set at prefill time).
        self._temps = np.zeros((max_batch,), np.float32)

        self._prefill_jit = jax.jit(self._prefill_impl, donate_argnums=(0,))
        self._decode_jit = jax.jit(self._decode_impl, donate_argnums=(0,))

    @classmethod
    def from_llama(cls, cfg, params, *, max_batch: int = 8,
                   cache_len: int | None = None, lora_adapters=None,
                   lora_scale: float = 1.0, rng: jax.Array | None = None):
        """Engine over the flagship decoder.  ``cache_len`` sizes every
        slot's KV buffer (default ``cfg.max_seq``); ``lora_adapters``
        (from ``train.lora.lora_init``-shaped trees) are merged into the
        weights once, host-side — serving then runs the plain decoder,
        no per-step merge cost."""
        from tpucfn.kernels.auto import serve_decode_attention_fn
        from tpucfn.models.llama import Llama

        cache_len = cache_len or cfg.max_seq
        dcfg = dataclasses.replace(cfg, max_seq=cache_len)
        if lora_adapters is not None:
            from tpucfn.train.lora import lora_materialize

            params = jax.tree.map(np.asarray, lora_materialize(
                params, lora_adapters, scale=lora_scale))
        model = Llama(dcfg, decode=True,
                      attention_fn=serve_decode_attention_fn(cache_len))
        return cls(model, params, max_batch=max_batch, cache_len=cache_len,
                   rng=rng)

    # -- jitted bodies -----------------------------------------------------
    def _apply_one(self, params, cache_row, tokens_row):
        """One slot's apply: tokens (1, S) against its own cache row."""
        logits, muts = self.model.apply(
            {"params": params, "cache": cache_row}, tokens_row,
            mutable=["cache"])
        return logits, muts["cache"]

    def _prefill_impl(self, cache, params, prompt, true_len, slot, temp, key):
        """prompt (bucket,) int32, true_len/slot () int32, temp () f32."""
        row0 = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                            self._row_shapes)
        logits, row = self._apply_one(params, row0, prompt[None])
        row = _rewind_cache_index(row, true_len)
        last = jax.lax.dynamic_index_in_dim(
            logits[0], true_len - 1, axis=0, keepdims=False)  # (V,)
        tok = _sample(last[None], temp[None], key)[0]
        new_cache = jax.tree.map(lambda full, r: full.at[slot].set(r),
                                 cache, row)
        return tok, new_cache

    def _decode_impl(self, cache, params, tokens, temps, key):
        """tokens (B,) int32 -> (next (B,), cache).  Every slot steps."""

        def one(cache_row, tok):
            logits, row = self._apply_one(params, cache_row, tok[None, None])
            return logits[0, -1], row

        logits, new_cache = jax.vmap(one)(cache, tokens)
        return _sample(logits.astype(jnp.float32), temps, key), new_cache

    # -- host API (the scheduler loop calls these) -------------------------
    def _next_key(self) -> jax.Array:
        self._step_count += 1
        return jax.random.fold_in(self._base_key, self._step_count)

    def prefill(self, slot: int, prefix: list[int], bucket: int,
                temperature: float = 0.0) -> int:
        """Run one bucketed prefill into ``slot``; returns the sequence's
        first sampled token."""
        n = len(prefix)
        if not 1 <= n <= bucket <= self.cache_len:
            raise ValueError(
                f"prefix len {n} / bucket {bucket} / cache_len "
                f"{self.cache_len} violate 1 <= len <= bucket <= cache_len")
        padded = np.zeros((bucket,), np.int32)
        padded[:n] = np.asarray(prefix, np.int32)
        self._temps[slot] = temperature
        tok, self.cache = self._prefill_jit(
            self.cache, self.params, jnp.asarray(padded),
            jnp.int32(n), jnp.int32(slot), jnp.float32(temperature),
            self._next_key())
        return int(tok)

    def decode(self, tokens_by_slot: dict[int, int]) -> dict[int, int]:
        """One decode iteration.  ``tokens_by_slot`` maps ACTIVE slots to
        their last emitted token; vacant slots run dead lanes.  Returns
        the next token per active slot."""
        toks = np.zeros((self.max_batch,), np.int32)
        for slot, tok in tokens_by_slot.items():
            toks[slot] = tok
        nxt, self.cache = self._decode_jit(
            self.cache, self.params, jnp.asarray(toks),
            jnp.asarray(self._temps), self._next_key())
        nxt = np.asarray(nxt)
        return {slot: int(nxt[slot]) for slot in tokens_by_slot}


# Named Llama configs for the demo/bench surfaces (one source of truth
# for `tpucfn serve --preset` and `benches/serve_bench.py`).
LLAMA_PRESETS = ("tiny", "llama3-1b", "llama3-8b")


def demo_llama_engine(preset: str, *, seed: int = 0, max_batch: int = 8,
                      cache_len: int | None = None):
    """(cfg, ServeEngine) over a RANDOM-init Llama preset — the shared
    bring-up for the CLI demo workload and the serving bench (real
    deployments construct the engine from checkpointed params
    themselves)."""
    import jax

    from tpucfn.models.llama import Llama, LlamaConfig

    ctors = {"tiny": LlamaConfig.tiny, "llama3-1b": LlamaConfig.llama3_1b,
             "llama3-8b": LlamaConfig.llama3_8b}
    cfg = ctors[preset]()
    params = Llama(cfg).init(jax.random.key(seed),
                             jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, ServeEngine.from_llama(cfg, params, max_batch=max_batch,
                                       cache_len=cache_len)
