#!/usr/bin/env python
"""Anakin-style RL on one mesh (the Podracer layout, arXiv:2104.06272).

The tpucfn RL plane end-to-end in one script: jitted actors roll out a
pure-jax vectorized env (bandit or gridworld) on the SAME mesh as the
Trainer-backed A2C learner, trajectory slabs flow through the on-device
replay queue, and the actors pick up new params as a device-to-device
copy every iteration.  Checkpoints snapshot the whole stack (learner
state + env state + queue ring + iteration), so an interrupted run —
``--stop-after``, a preemption drain, or a chaos kill under ``tpucfn
launch`` — resumes bit-identically.

Flags mirror the training examples (``--steps`` is the iteration
budget); the same loop also ships as ``tpucfn rl train``.
"""

from __future__ import annotations

import argparse


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--run-dir", default="/tmp/tpucfn-rl",
                   help="checkpoints and per-iteration rows land here")
    p.add_argument("--steps", type=int, default=100,
                   help="act→learn→refresh iterations to run")
    p.add_argument("--env", choices=["bandit", "gridworld"],
                   default="bandit")
    p.add_argument("--num-envs", type=int, default=8,
                   help="vectorized env copies = learner batch size")
    p.add_argument("--unroll", type=int, default=16,
                   help="env steps per jitted rollout")
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--lr", type=float, default=1e-2)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--ckpt-every", type=int, default=25)
    p.add_argument("--stop-after", type=int, default=0,
                   help="simulated interruption: halt at iteration N "
                        "without changing the budget; rerunning resumes")
    p.add_argument("--fresh", action="store_true",
                   help="ignore existing checkpoints in --run-dir")
    args = p.parse_args()

    from tpucfn.launch import initialize_runtime

    initialize_runtime()

    from tpucfn.rl.loop import RLConfig, run_rl_loop

    run_rl_loop(RLConfig(
        run_dir=args.run_dir, env=args.env, num_envs=args.num_envs,
        unroll=args.unroll, iters=args.steps, hidden=args.hidden,
        lr=args.lr, seed=args.seed, ckpt_every=args.ckpt_every,
        log_every=args.log_every, stop_after=args.stop_after,
        fresh=args.fresh))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
