"""Device telemetry gauges (ISSUE 6): device_hbm_* exposition pins via
a fake ``memory_stats`` device, the CPU absent-not-crashing path, live
read-time values, and the jit_cache_programs source."""

import urllib.request

from tpucfn.obs import (MetricRegistry, ObsServer, device_memory_stats,
                        register_device_gauges)


class FakeDev:
    """A device whose memory_stats the tests control (the TPU shape of
    the dict: bytes_in_use / peak_bytes_in_use / bytes_limit)."""

    def __init__(self, used=1024, peak=2048, limit=16 * 2**30):
        self.stats = {"bytes_in_use": used, "peak_bytes_in_use": peak,
                      "bytes_limit": limit}

    def memory_stats(self):
        return self.stats


def test_device_memory_stats_none_safe():
    # real first device on this image is CPU: stats are None, no raise
    assert device_memory_stats() is None

    class Raises:
        def memory_stats(self):
            raise RuntimeError("backend gone")

    assert device_memory_stats(Raises()) is None

    class NotADict:
        def memory_stats(self):
            return 42

    assert device_memory_stats(NotADict()) is None


def test_cpu_path_registers_nothing_and_metrics_still_serves():
    reg = MetricRegistry(labels={"host": "0"})
    reg.counter("alive_total").add()
    assert register_device_gauges(reg) == []
    srv = ObsServer(reg, port=0, host="127.0.0.1")
    try:
        with urllib.request.urlopen(srv.url("/metrics")) as r:
            body = r.read().decode()
    finally:
        srv.close()
    # absent, not zero: a dashboard must see "no HBM", not "empty HBM"
    assert "device_hbm" not in body
    assert "alive_total" in body


def test_fake_device_gauges_pinned_in_exposition():
    dev = FakeDev(used=111, peak=222, limit=333)
    reg = MetricRegistry(labels={"host": "1", "role": "trainer"})
    names = register_device_gauges(reg, device=dev)
    assert names == ["device_hbm_used_bytes", "device_hbm_peak_bytes",
                     "device_hbm_limit_bytes"]
    body = reg.to_prometheus()
    assert ('device_hbm_used_bytes{host="1",role="trainer"} 111.0'
            in body.splitlines())
    assert ('device_hbm_peak_bytes{host="1",role="trainer"} 222.0'
            in body.splitlines())
    assert ('device_hbm_limit_bytes{host="1",role="trainer"} 333.0'
            in body.splitlines())
    assert "# TYPE device_hbm_used_bytes gauge" in body


def test_gauges_read_live_values_at_scrape_time():
    dev = FakeDev(used=10)
    reg = MetricRegistry()
    register_device_gauges(reg, device=dev)
    assert "device_hbm_used_bytes 10.0" in reg.to_prometheus()
    dev.stats["bytes_in_use"] = 99  # the allocator grew between scrapes
    assert "device_hbm_used_bytes 99.0" in reg.to_prometheus()
    # a device that stops reporting mid-run degrades to 0, not a crash
    dev.stats = None
    dev.memory_stats = lambda: None
    assert "device_hbm_used_bytes 0.0" in reg.to_prometheus()


def test_partial_stats_register_only_present_keys():
    class PartialDev:
        def memory_stats(self):
            return {"bytes_in_use": 5}  # no peak/limit on this backend

    reg = MetricRegistry()
    assert register_device_gauges(reg, device=PartialDev()) == [
        "device_hbm_used_bytes"]
    body = reg.to_prometheus()
    assert "device_hbm_used_bytes 5.0" in body
    assert "device_hbm_peak_bytes" not in body


def test_jit_cache_programs_sums_sources_and_tolerates_unbuilt():
    class FakeJit:
        def __init__(self, n):
            self.n = n

        def _cache_size(self):
            return self.n

    reg = MetricRegistry()
    built = {"step": FakeJit(3), "eval": None}  # eval not compiled yet
    names = register_device_gauges(
        reg, device=FakeDev(),
        jit_sources=(lambda: built["step"], lambda: built["eval"]))
    assert "jit_cache_programs" in names
    assert "jit_cache_programs 3.0" in reg.to_prometheus()
    built["eval"] = FakeJit(2)  # lazily compiled later
    assert "jit_cache_programs 5.0" in reg.to_prometheus()

    class Broken:
        def _cache_size(self):
            raise AttributeError("jax internals moved")

    built["step"] = Broken()  # best-effort: broken source contributes 0
    assert "jit_cache_programs 2.0" in reg.to_prometheus()


def test_reregistration_rebinds_to_the_live_device():
    # a rebuilt loop registering against the shared registry must leave
    # the LIVE device backing the series (computed_gauge rebind rule)
    old, new = FakeDev(used=1), FakeDev(used=7)
    reg = MetricRegistry()
    register_device_gauges(reg, device=old)
    register_device_gauges(reg, device=new)
    assert "device_hbm_used_bytes 7.0" in reg.to_prometheus()
