"""End-to-end smoke of the minimum slice (SURVEY.md §7.3): the bundled
CIFAR-10 example trains on 8 fake devices in a subprocess, checkpoints,
and resumes — the convergence-smoke analogue of the reference's "stack
reaches CREATE_COMPLETE and the CIFAR-10 example converges" manual test.
"""

import json
import os
import re
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _run_example(run_dir, steps, resume=False, extra=()):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    )
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [
        sys.executable, str(REPO / "examples" / "cifar10_resnet20.py"),
        "--run-dir", str(run_dir),
        "--batch-size", "64",
        "--steps", str(steps),
        "--num-examples", "256",
        "--ckpt-every", "5",
        "--log-every", "5",
    ] + (["--resume"] if resume else []) + list(extra)
    return subprocess.run(cmd, env=env, capture_output=True, text=True, timeout=600)


def test_cifar10_example_end_to_end(tmp_path):
    r = _run_example(tmp_path, steps=10)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "items/sec" in r.stdout

    # metrics were logged as JSONL with loss/accuracy/step_time, plus the
    # one-shot time_to_first_step record (SURVEY.md §7.4 item 6)
    logs = list((tmp_path / "logs").glob("*.jsonl"))
    assert logs, r.stdout
    records = [json.loads(line) for line in logs[0].read_text().splitlines()]
    assert any(rec["step"] == 10 for rec in records)
    assert any("time_to_first_step" in rec for rec in records)
    loss_recs = [rec for rec in records if "time_to_first_step" not in rec]
    assert loss_recs and all("loss" in rec for rec in loss_recs)

    # checkpoints exist
    assert (tmp_path / "ckpt").exists()

    # restart implies resume: a plain relaunch (no --resume) continues
    # from step 10 rather than retraining from 0 (SURVEY.md §5 failure row)
    r2 = _run_example(tmp_path, steps=14)
    assert r2.returncode == 0, f"stdout:\n{r2.stdout}\nstderr:\n{r2.stderr}"
    assert "resumed from step 10" in r2.stdout
    m = re.search(r"final: step=(\d+)", r2.stdout)
    assert m and int(m.group(1)) == 14

    # --fresh opts out and retrains from step 0
    r3 = _run_example(tmp_path, steps=3, extra=("--fresh",))
    assert r3.returncode == 0, f"stdout:\n{r3.stdout}\nstderr:\n{r3.stderr}"
    assert "resumed" not in r3.stdout


def test_cifar10_example_stop_after_keeps_budget(tmp_path):
    """--stop-after halts execution without redefining the budget: the
    first leg stops at 4 of a 12-step budget, the relaunch resumes at 4
    and runs to the SAME 12-step budget (an interruption must not change
    the LR schedule — using --steps as the cap would anneal a --cosine
    schedule to zero by the interruption point; observed degrading eval
    on the full accuracy run)."""
    r = _run_example(tmp_path, steps=12, extra=("--stop-after", "4", "--cosine"))
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    m = re.search(r"final: step=(\d+)", r.stdout)
    assert m and int(m.group(1)) == 4

    r2 = _run_example(tmp_path, steps=12, extra=("--cosine",))
    assert r2.returncode == 0, f"stdout:\n{r2.stdout}\nstderr:\n{r2.stderr}"
    assert "resumed from step 4" in r2.stdout
    m = re.search(r"final: step=(\d+)", r2.stdout)
    assert m and int(m.group(1)) == 12


def test_cifar10_example_fsdp_mode(tmp_path):
    r = _run_example(tmp_path, steps=4, extra=("--fsdp", "2"))
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"


def test_cifar10_example_eval_split(tmp_path):
    r = _run_example(tmp_path, steps=6, extra=("--eval-every", "3"))
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    logs = list((tmp_path / "logs").glob("*.jsonl"))
    records = [json.loads(line) for line in logs[0].read_text().splitlines()]
    eval_recs = [rec for rec in records if "eval_accuracy" in rec]
    assert eval_recs, records
    assert all("eval_loss" in rec for rec in eval_recs)
