"""Fleet aggregation over per-host JSONL metrics and trace files.

The write side (MetricLogger, Tracer) produces one file per host; this
is the read side ``tpucfn obs`` uses to answer the three questions you
otherwise tail 64 files for:

* **merged step timeline** — for each global step, every host's wall
  time fused into min/median/max + which host was slowest;
* **per-host straggler report** — mean step/data-wait time per host
  relative to the fleet median (the Podracer-style per-actor timing
  decomposition: a 1.3x host is a hardware or input-pipeline problem,
  not a model problem);
* **request latency breakdown** — per-request queue-wait / prefill /
  decode reconstructed from serve trace spans, with fleet aggregates.

Everything here is pure functions over parsed dicts so the CLI, tests,
and notebooks share one implementation.
"""

from __future__ import annotations

import json
import statistics
from pathlib import Path
from typing import Iterable

from tpucfn.obs.goodput import parse_jsonl_line
from tpucfn.obs.trace import read_trace_file


def read_metrics_dir(d: str | Path) -> dict[str, list[dict]]:
    """``host label -> [records]`` for every ``*.jsonl`` under ``d``
    (one file per host by MetricLogger convention; torn lines skipped —
    same still-being-appended tolerance as the trace reader)."""
    return {p.stem: read_trace_file(p)
            for p in sorted(Path(d).glob("*.jsonl"))}


def merge_step_timeline(by_host: dict[str, list[dict]],
                        key: str = "step_time",
                        last: int | None = None) -> list[dict]:
    """One row per global step seen on any host: per-step fleet spread
    of ``key`` plus the slowest host — the merged timeline view."""
    per_step: dict[int, dict[str, float]] = {}
    for host, rows in by_host.items():
        for r in rows:
            if key in r and "step" in r:
                per_step.setdefault(int(r["step"]), {})[host] = float(r[key])
    steps = sorted(per_step)
    if last is not None:
        steps = steps[-last:]
    out = []
    for s in steps:
        vals = per_step[s]
        straggler = max(vals, key=vals.get)
        out.append({
            "step": s,
            "hosts": len(vals),
            "min": min(vals.values()),
            "median": statistics.median(vals.values()),
            "max": vals[straggler],
            "straggler": straggler,
        })
    return out


def host_straggler_report(by_host: dict[str, list[dict]],
                          keys: tuple[str, ...] = ("step_time",),
                          slow_factor: float = 1.2) -> list[dict]:
    """Per-host means of ``keys`` with each host's ratio to the fleet
    median of the first key; ``slow`` flags ratios above
    ``slow_factor`` (the "go look at that host" bit)."""
    rows = []
    for host, recs in sorted(by_host.items()):
        row: dict = {"host": host, "records": len(recs)}
        for k in keys:
            vals = [float(r[k]) for r in recs if k in r]
            row[f"mean_{k}"] = statistics.fmean(vals) if vals else None
            row[f"n_{k}"] = len(vals)
        rows.append(row)
    primary = f"mean_{keys[0]}"
    meds = [r[primary] for r in rows if r[primary] is not None]
    fleet_median = statistics.median(meds) if meds else None
    for r in rows:
        if fleet_median and r[primary] is not None:
            r["vs_fleet_median"] = r[primary] / fleet_median
            r["slow"] = r["vs_fleet_median"] > slow_factor
        else:
            r["vs_fleet_median"], r["slow"] = None, False
    return rows


def request_breakdown(events: Iterable[dict]) -> tuple[list[dict], dict]:
    """Per-request latency decomposition from serve trace events.

    Returns ``(rows, aggregate)``: one row per request with queue_wait /
    prefill (first, non-resumed) / decode (sum of the decode rounds
    whose batch contained this sequence) / ttft / total and the
    outcome; aggregate carries fleet percentiles of each part.

    Requests are keyed by ``(host, trace_id)``: each server process
    numbers its requests from 0, so in a multi-host serve gang the same
    trace_id appears once per host and keying on it alone would fuse
    different hosts' requests into one wrong row.
    """
    per_req: dict = {}
    decode_rounds: list[dict] = []

    def req(host, tid):
        return per_req.setdefault((host, tid), {
            "host": host, "request": tid,
            "queue_wait_s": None, "prefill_s": None,
            "re_prefill_s": 0.0, "decode_s": 0.0, "decode_rounds": 0,
            "spec_propose_s": 0.0, "spec_verify_s": 0.0,
            "ttft_s": None, "total_s": None, "generated": None,
            "outcome": None})

    for e in events:
        name, tid, host = e.get("name"), e.get("trace_id"), e.get("host")
        attrs = e.get("attrs", {})
        if name == "queue_wait" and tid is not None:
            req(host, tid)["queue_wait_s"] = e["dur_s"]
        elif name == "prefill" and tid is not None:
            if attrs.get("resumed"):
                req(host, tid)["re_prefill_s"] += e["dur_s"]
            else:
                req(host, tid)["prefill_s"] = e["dur_s"]
        elif name in ("decode_round", "spec_propose", "spec_verify"):
            # Round-level spans (one per decode batch, fanned out to
            # each member request below).  spec_propose/spec_verify are
            # the propose-verify halves of a speculative round (ISSUE
            # 14): per request, decode_s splits into draft time and
            # target-verify time, so a TPOT regression names its layer.
            decode_rounds.append(e)
        elif name == "request_done" and tid is not None:
            r = req(host, tid)
            r["outcome"] = attrs.get("outcome")
            r["total_s"] = attrs.get("latency_s")
            r["ttft_s"] = attrs.get("ttft_s")
            r["generated"] = attrs.get("generated")
    for e in decode_rounds:
        for sid in e.get("attrs", {}).get("seqs", ()):
            key = (e.get("host"), sid)
            if key not in per_req:
                continue
            if e.get("name") == "spec_propose":
                per_req[key]["spec_propose_s"] += e["dur_s"]
            elif e.get("name") == "spec_verify":
                per_req[key]["spec_verify_s"] += e["dur_s"]
            else:
                per_req[key]["decode_s"] += e["dur_s"]
                per_req[key]["decode_rounds"] += 1
    rows = [per_req[k] for k in sorted(per_req,
                                       key=lambda k: (str(k[0]), str(k[1])))]

    from tpucfn.obs.metrics import nearest_rank

    agg: dict = {"requests": len(rows),
                 "completed": sum(1 for r in rows if r["outcome"] == "ok")}
    for part in ("queue_wait_s", "prefill_s", "decode_s", "ttft_s", "total_s"):
        xs = sorted(r[part] for r in rows if r[part] is not None)
        agg[part] = {"p50": nearest_rank(xs, 50), "p95": nearest_rank(xs, 95),
                     "max": xs[-1] if xs else None}
    for part in ("spec_propose_s", "spec_verify_s"):
        # only when speculation ran — a plain run's aggregate is
        # byte-identical to the pre-spec shape
        xs = sorted(r[part] for r in rows if r[part])
        if xs:
            agg[part] = {"p50": nearest_rank(xs, 50),
                         "p95": nearest_rank(xs, 95), "max": xs[-1]}
    return rows, agg


# Control-plane span families the fleet view surfaces (one canonical
# tuple — the span-balance rule of `tpucfn check` reads consumers by
# ast, and a scattered literal here would be exactly the drift it
# exists to catch): recovery spans from the gang coordinator, on-demand
# profiler captures, and the compile-artifact fetch leg of the fleet
# warm start (ISSUE 13).
CONTROL_SPAN_NAMES = ("ft_recover", "ft_give_up", "profile_capture",
                      "compile_fetch")


def control_timeline(events: Iterable[dict]) -> list[dict]:
    """One row per control-plane span, fleet-ordered: when a recovery,
    profiler capture, or compile-artifact fetch ran relative to the
    steps around it — the read side that makes those spans part of the
    merged story instead of write-only trace lines."""
    rows = []
    for e in events:
        if e.get("kind") != "span" or e.get("name") not in \
                CONTROL_SPAN_NAMES:
            continue
        attrs = e.get("attrs") or {}
        detail = {k: attrs[k] for k in ("action", "hosts", "rc", "key",
                                        "label", "addr", "bytes",
                                        "artifact", "seconds")
                  if k in attrs}
        rows.append({
            "ts": e.get("ts_adj", e.get("ts")),
            "host": e.get("host"),
            "role": e.get("role"),
            "span": e.get("name"),
            "dur_s": e.get("dur_s"),
            "trace_id": e.get("trace_id"),
            "detail": json.dumps(detail, sort_keys=True) if detail else "",
        })
    rows.sort(key=lambda r: (r["ts"] is None, r["ts"] or 0.0))
    return rows


def step_spans_by_host(events: Iterable[dict]) -> dict[str, list[dict]]:
    """Regroup trainer trace spans into the by-host record shape the
    timeline/straggler views consume (span name -> ``<name>_time``
    column, trace_id -> step) — so traces alone, without the metrics
    JSONL, still feed the fleet views."""
    by_host: dict[str, list[dict]] = {}
    for e in events:
        if e.get("kind") != "span" or e.get("name") not in (
                "data_wait", "step", "ckpt"):
            continue
        host = f"host{e.get('host')}" if e.get("host") is not None else "host?"
        rec: dict = {f"{e['name']}_time": e["dur_s"]}
        if e.get("trace_id") is not None:
            rec["step"] = e["trace_id"]
        by_host.setdefault(host, []).append(rec)
    return by_host


def select_skew_reference_beats(
        recs: Iterable[dict],
        state: tuple = (None, None)) -> tuple[list[dict], tuple]:
    """The heartbeats usable as clock-skew reference points: the first
    beat at each ``step`` value, plus any ``seq`` reset (incarnation
    boundary — the reset beat must survive so downstream incarnation
    counting still sees the boundary).  Single source of truth shared
    by :func:`estimate_clock_skew` and the watch-mode compaction in
    ``tpucfn obs`` — if the two drifted apart, compaction would discard
    beats the estimator needs and silently bias the skew.

    Returns ``(kept, new_state)``; thread ``new_state`` back in for
    incremental (tailing) use.  Selection is idempotent: running it
    over an already-selected stream keeps every beat.
    """
    prev_seq, prev_step = state
    kept = []
    for r in recs:
        seq = r.get("seq")
        if not isinstance(seq, int) or "t" not in r:
            continue
        reset = prev_seq is not None and seq <= prev_seq
        step = r.get("step")
        if reset or (step is not None and step != prev_step):
            kept.append(r)
            prev_step = step
        prev_seq = seq
    return kept, (prev_seq, prev_step)


def estimate_clock_skew(events: Iterable[dict],
                        heartbeats_by_host: dict[int, list[dict]]
                        | None = None) -> dict[str, float]:
    """Per-host wall-clock skew estimate (seconds; positive = that
    host's clock runs ahead of the fleet median).

    Cross-host span ordering rides on each host's wall ``ts``; hosts'
    clocks drift, so raw ``ts`` ordering lies.  The reference points
    must be events that truly happen fleet-simultaneously, and the only
    such anchor in the record streams is the **global training step**:
    an SPMD gang executes step N in lockstep (the collectives force
    it).  Two sources carry it, preferred in order:

    * **Heartbeats** — each beat stamps the loop's current ``step``;
      the first beat observing step N lands within one heartbeat
      interval of the host reaching N.  (Pairing beats by ``seq``
      instead would conflate writer *start stagger* — a host whose jax
      import ran seconds longer — with clock skew and mis-order events
      whose raw timestamps were correct, so beats without a step, e.g.
      a serve host's, contribute nothing.)
    * **Step spans** — the per-step trace spans' wall times, same
      lockstep argument without the beat-interval quantization.

    Returns ``{host_label: skew_s}``; subtract the skew from a host's
    ``ts`` to place its events on the fleet's median clock
    (:func:`apply_clock_skew`).
    """
    # reference_points: host -> {key: wall_t}
    points: dict[str, dict] = {}
    if heartbeats_by_host:
        for host, recs in heartbeats_by_host.items():
            # HeartbeatWriter restarts seq from 1 per incarnation while
            # appending to the SAME file, and a restarted trainer REWINDS
            # to the checkpoint step — so key by (incarnation, step):
            # a restarted host's post-downtime re-run of step N must not
            # overwrite its first-incarnation reference point (it would
            # read as tens of seconds of phantom skew), and its second
            # incarnation only matches peers that restarted with it
            # (gang restart) — a solo restart's unmatched points are
            # simply dropped by the >=2-hosts filter below.
            pts = {}
            incarnation, prev_seq, prev_step = 0, None, None
            kept, _ = select_skew_reference_beats(recs)
            for r in kept:
                if prev_seq is not None and r["seq"] <= prev_seq:
                    incarnation += 1
                    prev_step = None
                prev_seq = r["seq"]
                step = r.get("step")
                if step is not None and step != prev_step:
                    pts[(incarnation, step)] = float(r["t"])
                    prev_step = step
            if pts:
                points[f"host{host}"] = pts
    if len(points) < 2:
        # Fewer than two hosts have usable heartbeats (one hb file
        # missing/torn still means NO cross-host reference) — fall back
        # to step spans wholesale rather than mixing point sources.
        points = {}
        for e in events:
            if (e.get("kind") == "span" and e.get("name") == "step"
                    and e.get("trace_id") is not None
                    and e.get("ts") is not None):
                host = (f"host{e['host']}" if e.get("host") is not None
                        else "host?")
                points.setdefault(host, {})[e["trace_id"]] = float(e["ts"])
    if len(points) < 2:
        return {h: 0.0 for h in points}
    # per shared key, the fleet median; per host, median offset from it
    all_keys: dict = {}
    for pts in points.values():
        for k, t in pts.items():
            all_keys.setdefault(k, []).append(t)
    medians = {k: statistics.median(ts) for k, ts in all_keys.items()
               if len(ts) >= 2}
    skew = {}
    for host, pts in sorted(points.items()):
        offsets = [t - medians[k] for k, t in pts.items() if k in medians]
        skew[host] = statistics.median(offsets) if offsets else 0.0
    return skew


def apply_clock_skew(events: list[dict],
                     skew: dict[str, float]) -> list[dict]:
    """Events sorted on the skew-corrected fleet clock, each annotated
    with ``ts_adj`` — the cross-host-comparable timestamp the merged
    timeline orders by (original dicts are not mutated).

    Each event's ``mono`` (the write instant on its host's monotonic
    clock) breaks same-instant ties within a host: wall ``ts`` is
    reconstructed from two clock reads and can collide or invert for
    back-to-back writes (retroactively recorded spans, a stepping NTP
    clock), while ``mono`` strictly orders one process's writes.
    Monotonic origins are per-process, so ``mono`` is only consulted
    when the corrected wall times actually tie."""
    out = []
    for e in events:
        host = f"host{e['host']}" if e.get("host") is not None else "host?"
        ts = e.get("ts")
        adj = (ts - skew.get(host, 0.0)) if ts is not None else None
        out.append({**e, "ts_adj": adj})
    out.sort(key=lambda e: (e["ts_adj"] is None, e["ts_adj"] or 0.0,
                            e.get("mono") is None, e.get("mono") or 0.0))
    return out


def window_events(events: Iterable[dict], start: float,
                  end: float) -> list[dict]:
    """The skew-corrected events inside ``[start, end]`` — the slice a
    postmortem renders around an incident.  Operates on ``ts_adj`` (the
    :func:`apply_clock_skew` annotation) so the window means the same
    instant on every host; events without one (no wall clock recorded)
    cannot be placed and are excluded."""
    return [e for e in events
            if e.get("ts_adj") is not None and start <= e["ts_adj"] <= end]


class JsonlTailer:
    """Incremental multi-file JSONL reader for ``--watch`` mode.

    ``tpucfn obs --watch`` used to re-read every metrics/trace file from
    byte 0 on each tick — O(run length) per refresh.  This keeps a byte
    offset per file and yields only complete NEW lines each poll:

    * a torn tail (writer mid-append) is left in place — the offset
      only advances past the last ``\\n``, so the partial line is
      re-read whole on a later tick (same tolerance as the heartbeat
      reader);
    * an undecodable complete line is skipped and counted
      (:attr:`skipped`), never raised on;
    * a file that SHRANK (rotated/truncated) resets to byte 0 — stale
      offsets must not silently hide a restarted writer;
    * a file whose first bytes CHANGED resets too: a restarted writer
      (Tracer opens with truncate) that regrows PAST the stored offset
      between two polls never shrinks from the tailer's point of view,
      so the size check alone would resume mid-stream inside the new
      file — the head signature betrays the swap.
    """

    _HEAD_SIG_LEN = 64

    def __init__(self):
        self._offsets: dict[Path, int] = {}
        self._heads: dict[Path, bytes] = {}  # first-bytes signature
        self.skipped = 0
        # files whose size shrank on the LAST poll: the re-read restarts
        # from byte 0, so a caller holding accumulated records for the
        # file must drop them first or every old record double-counts.
        self.truncated: set[Path] = set()

    def poll(self, paths: Iterable[str | Path]) -> dict[Path, list[dict]]:
        """New records per file since the last poll (files appear in the
        result only when they produced records).  Check
        :attr:`truncated` after each call for files that restarted."""
        out: dict[Path, list[dict]] = {}
        self.truncated = set()
        for p in paths:
            p = Path(p)
            try:
                size = p.stat().st_size
            except OSError:
                continue
            off = self._offsets.get(p, 0)
            if size < off:  # truncated/rotated: start over
                # Persist the reset NOW: if the regrown file has no
                # complete line yet this poll, the stale offset would
                # otherwise survive, and a file that later regrows PAST
                # it would resume mid-stream — silently dropping the new
                # file's head (and starting mid-line).
                off = self._offsets[p] = 0
                self.truncated.add(p)
                self._heads.pop(p, None)
            head = self._heads.get(p) if off else None
            if size == off and not head:
                continue
            try:
                with open(p, "rb") as f:
                    if head and f.read(len(head)) != head:
                        # Truncate-then-regrow past the stored offset:
                        # size never dipped below `off`, only the first
                        # bytes changed.  Restart from byte 0.
                        off = self._offsets[p] = 0
                        self.truncated.add(p)
                        self._heads.pop(p, None)
                    if size == off:
                        continue
                    f.seek(off)
                    chunk = f.read(size - off)
            except OSError:
                continue
            # only consume up to the last complete line
            nl = chunk.rfind(b"\n")
            if nl < 0:
                continue  # torn tail only; retry next tick
            if off == 0:  # consumed bytes only — immutable once written
                self._heads[p] = chunk[: min(self._HEAD_SIG_LEN, nl + 1)]
            self._offsets[p] = off + nl + 1
            recs = []
            for raw in chunk[: nl + 1].splitlines():
                raw = raw.strip()
                if not raw:
                    continue
                rec = parse_jsonl_line(raw)
                if rec is None:
                    self.skipped += 1
                else:
                    recs.append(rec)
            if recs:
                out[p] = recs
        return out

    def poll_into(self, paths: Iterable[str | Path], store: dict,
                  key_fn=None, extend=None, on_drop=None) -> bool:
        """:meth:`poll` plus the accumulate discipline every ``--watch``
        domain repeats: a truncated file's accumulated records are
        dropped BEFORE its re-read records are appended (the other
        order double-counts history), and the caller learns whether
        anything actually changed (the idle-tick recompute caches key
        off it).

        ``key_fn(path)`` maps a file to its ``store`` key (return None
        to skip the file); ``extend(key, lst, recs)`` appends and
        returns how many records it kept (default keeps all — a
        compacting extend that kept nothing does not dirty the store);
        ``on_drop(key)`` clears caller state beyond the store entry.
        """
        key_fn = key_fn or (lambda p: p)
        dirty = False
        new = self.poll(paths)
        for p in self.truncated:
            k = key_fn(p)
            if k is None:
                continue
            store.pop(k, None)
            if on_drop is not None:
                on_drop(k)
            dirty = True
        for p, recs in new.items():
            k = key_fn(p)
            if k is None:
                continue
            lst = store.setdefault(k, [])
            if extend is not None:
                if extend(k, lst, recs):
                    dirty = True
            else:
                lst.extend(recs)
                dirty = True
        return dirty


def render_table(rows: list[dict], columns: list[str],
                 float_fmt: str = "{:.4f}") -> str:
    """Minimal fixed-width table (no external deps on the hosts)."""
    def cell(v):
        if isinstance(v, bool):
            return "YES" if v else ""
        if isinstance(v, float):
            return float_fmt.format(v)
        return "" if v is None else str(v)

    grid = [columns] + [[cell(r.get(c)) for c in columns] for r in rows]
    widths = [max(len(row[i]) for row in grid) for i in range(len(columns))]
    lines = ["  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip()
             for row in grid]
    lines.insert(1, "  ".join("-" * w for w in widths))
    return "\n".join(lines)
