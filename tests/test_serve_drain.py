"""Server-level resilience (ISSUE 9 satellites): terminal ``status``
field, graceful ``drain()`` (admission-off + bounded in-flight
completion), step-boundary ``cancel``/``evict_queued``, the structured
serve-loop failure path, and loop-driven heartbeats."""

import threading
import time

import pytest

from tpucfn.ft.heartbeat import HeartbeatWriter, read_heartbeat_file
from tpucfn.serve import (
    AdmissionError,
    Cancelled,
    DeadlineExceeded,
    ReplicaFailed,
    Requeued,
    Server,
)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class FakeEngine:
    """Deterministic tokens (prefill = hash of prefix, decode = next in
    a fixed chain) so retried/rerouted outputs are comparable."""

    def __init__(self, max_batch=4, cache_len=64, fail_on=None, clock=None,
                 step_cost=0.0):
        self.max_batch = max_batch
        self.cache_len = cache_len
        self.fail_on = fail_on  # "prefill" | "decode" | None
        self.clock = clock      # FakeClock advanced per engine call
        self.step_cost = step_cost
        self.calls = 0

    def _tick(self):
        self.calls += 1
        if self.clock is not None:
            self.clock.advance(self.step_cost)

    def prefill(self, slot, prefix, bucket, temperature=0.0):
        self._tick()
        if self.fail_on == "prefill":
            raise RuntimeError("engine prefill boom")
        return sum(prefix) % 97

    def decode(self, tokens_by_slot):
        self._tick()
        if self.fail_on == "decode":
            raise RuntimeError("engine decode boom")
        return {s: (t * 7 + 1) % 97 for s, t in tokens_by_slot.items()}


# ---- terminal status field (ISSUE 9 satellite) ----------------------------

def test_status_ok_and_expired():
    server = Server(FakeEngine(), num_blocks=64, block_size=8)
    ok = server.submit([1, 2, 3], max_new_tokens=2)
    dead = server.submit([4, 5, 6], max_new_tokens=2, deadline_s=-1.0)
    server.run_until_idle()
    assert ok.status == "ok" and ok.error is None
    assert dead.status == "expired"
    assert isinstance(dead.error, DeadlineExceeded)
    snap = server.metrics.snapshot()
    assert snap["expired"] == 1 and snap["replica_failed"] == 0


def test_status_replica_failed_and_counted_separately_from_expired():
    server = Server(FakeEngine(), num_blocks=64, block_size=8)
    req = server.submit([1, 2, 3], max_new_tokens=4)
    server.fail(ReplicaFailed("chaos kill"))
    assert req.status == "replica_failed"
    assert isinstance(req.error, ReplicaFailed)
    snap = server.metrics.snapshot()
    assert snap["replica_failed"] == 1 and snap["expired"] == 0
    # the registry series exists too
    assert "serve_replica_failed_requests_total 1.0" \
        in server.metrics.registry.to_prometheus()
    # a failed replica refuses new work with the 503 retry-elsewhere code
    with pytest.raises(AdmissionError) as e:
        server.submit([7], max_new_tokens=1)
    assert e.value.status == 503


def test_status_retried_on_evict_queued():
    server = Server(FakeEngine(), num_blocks=64, block_size=8)
    req = server.submit([1, 2, 3], max_new_tokens=4)
    server.evict_queued()
    server.step()  # processed at the step boundary
    assert req.status == "retried"
    assert isinstance(req.error, Requeued)
    assert isinstance(req.error, ReplicaFailed)  # routers catch one class
    # not counted as a replica failure — it is a handoff, not a death
    assert server.metrics.snapshot()["replica_failed"] == 0


def test_status_cancelled_via_cancel():
    server = Server(FakeEngine(), num_blocks=64, block_size=8)
    queued = server.submit([1, 2, 3], max_new_tokens=4)
    server.cancel(queued.req_id)
    server.step()
    assert queued.status == "cancelled"
    assert isinstance(queued.error, Cancelled)
    # cancel of a RUNNING sequence releases its slot and blocks
    running = server.submit([4, 5, 6], max_new_tokens=8)
    server.step()  # prefill: now running
    server.cancel(running.req_id)
    server.run_until_idle()
    assert running.status == "cancelled"
    assert server.kv.allocator.num_used == 0
    # cancelling a finished/unknown id is a no-op
    server.cancel(queued.req_id)
    server.cancel(12345)
    server.run_until_idle()


def test_on_done_callback_fires_once_with_terminal_state():
    server = Server(FakeEngine(), num_blocks=64, block_size=8)
    seen = []
    server.submit([1, 2, 3], max_new_tokens=2,
                  on_done=lambda r: seen.append((r.status, r.tokens)))
    server.run_until_idle()
    assert len(seen) == 1
    assert seen[0][0] == "ok" and len(seen[0][1]) == 2


# ---- serve-loop failure path ----------------------------------------------

def test_engine_crash_completes_inflight_with_structured_error():
    """The old behavior silently killed the serve thread and left every
    in-flight request hanging forever."""
    server = Server(FakeEngine(fail_on="decode"), num_blocks=64,
                    block_size=8)
    reqs = [server.submit([i, i + 1], max_new_tokens=4) for i in range(3)]
    server.start()
    for r in reqs:
        assert r.done.wait(5.0), "request hung after engine crash"
        assert r.status == "replica_failed"
    assert isinstance(server.failed, ReplicaFailed)
    server.stop()


def test_run_until_idle_reraises_engine_crash_after_failing_inflight():
    server = Server(FakeEngine(fail_on="prefill"), num_blocks=64,
                    block_size=8)
    req = server.submit([1, 2], max_new_tokens=2)
    with pytest.raises(ReplicaFailed):
        server.run_until_idle()
    assert req.status == "replica_failed"


# ---- drain (admission-off + bounded in-flight completion) -----------------

def test_drain_completes_queued_and_inflight_then_rejects_503():
    server = Server(FakeEngine(), num_blocks=64, block_size=8)
    reqs = [server.submit([i, i + 1, i + 2], max_new_tokens=3)
            for i in range(4)]
    assert server.drain(grace_s=30.0) is True
    assert all(r.status == "ok" for r in reqs)
    with pytest.raises(AdmissionError) as e:
        server.submit([9], max_new_tokens=1)
    assert e.value.status == 503
    assert "draining" in str(e.value)


def test_drain_grace_expiry_fails_leftovers():
    clk = FakeClock()
    # every engine call advances the fake clock 1s; grace 5s cannot
    # cover 4 requests x 4 tokens of work
    eng = FakeEngine(clock=clk, step_cost=1.0)
    server = Server(eng, num_blocks=64, block_size=8, clock=clk)
    reqs = [server.submit([i, i + 1], max_new_tokens=4) for i in range(4)]
    assert server.drain(grace_s=5.0) is False
    assert server.outstanding() == 0  # nothing left hanging
    assert all(r.done.is_set() for r in reqs)
    leftovers = [r for r in reqs if r.status == "replica_failed"]
    assert leftovers, "grace expiry must fail whatever missed the window"
    assert all(r.status in ("ok", "replica_failed") for r in reqs)


def test_drain_wait_false_arms_only_and_loop_enforces():
    clk = FakeClock()
    eng = FakeEngine(clock=clk, step_cost=1.0)
    server = Server(eng, num_blocks=64, block_size=8, clock=clk)
    req = server.submit([1, 2], max_new_tokens=10)
    # the SIGTERM-handler form: returns immediately, admission closed
    server.drain(grace_s=3.0, wait=False)
    with pytest.raises(AdmissionError):
        server.submit([3], max_new_tokens=1)
    # the (still running) loop enforces the deadline
    while server.outstanding():
        try:
            if not server.step():
                break
        except ReplicaFailed:
            break
    assert req.done.is_set()


def test_drain_threaded_completes_and_stops():
    server = Server(FakeEngine(), num_blocks=64, block_size=8)
    server.start()
    reqs = [server.submit([i, i + 1], max_new_tokens=2) for i in range(3)]
    assert server.drain(grace_s=10.0) is True
    assert all(r.status == "ok" for r in reqs)
    assert server._thread is None  # drained to a stop


# ---- chaos hooks: freeze / slow -------------------------------------------

def test_freeze_stalls_loop_and_heartbeats_then_recovers(tmp_path):
    hb = HeartbeatWriter(tmp_path, 0, interval_s=0.02, role="replica")
    server = Server(FakeEngine(), num_blocks=64, block_size=8,
                    heartbeat=hb)
    server.start()
    r = server.submit([1, 2, 3], max_new_tokens=2)
    assert r.done.wait(5.0)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        rec = read_heartbeat_file(hb.path)
        if rec is not None:
            break
        time.sleep(0.01)
    assert rec is not None, "serve loop never beat"
    server.freeze(10.0)
    time.sleep(0.15)  # let the loop hit the freeze gate
    seq0 = (read_heartbeat_file(hb.path) or {}).get("seq")
    time.sleep(0.2)
    assert (read_heartbeat_file(hb.path) or {}).get("seq") == seq0, \
        "a frozen serve loop must stop beating (that's the detector)"
    server.unfreeze()
    r2 = server.submit([4, 5], max_new_tokens=2)
    assert r2.done.wait(5.0) and r2.status == "ok"
    server.stop()
    hb.stop()


def test_kill_beats_freeze():
    server = Server(FakeEngine(), num_blocks=64, block_size=8)
    server.start()
    req = server.submit([1, 2], max_new_tokens=8)
    server.freeze(60.0)
    time.sleep(0.05)
    server.fail(ReplicaFailed("kill while frozen"))
    assert req.done.wait(5.0), "kill must break through a frozen loop"
    assert req.status == "replica_failed"
    server.stop()


def test_slow_injects_per_step_latency():
    clk = FakeClock()
    eng = FakeEngine()
    server = Server(eng, num_blocks=64, block_size=8)
    t0 = time.monotonic()
    server.submit([1, 2], max_new_tokens=2)
    server.run_until_idle()
    base = time.monotonic() - t0
    server2 = Server(FakeEngine(), num_blocks=64, block_size=8)
    server2.slow(0.05)
    server2.submit([1, 2], max_new_tokens=2)
    t0 = time.monotonic()
    server2.run_until_idle()
    assert time.monotonic() - t0 >= 0.05  # at least one injected delay
    assert base < 0.05 or True  # sanity only; no strict timing on CI


def test_drain_arm_only_takes_no_lock():
    """The SIGTERM handler runs on a thread that may have interrupted a
    frame already HOLDING the server lock — drain(wait=False) must not
    acquire it or the process deadlocks at shutdown (review pin)."""
    server = Server(FakeEngine(), num_blocks=64, block_size=8)
    server.submit([1, 2], max_new_tokens=2)
    acquired = server._lock.acquire()  # simulate the interrupted frame
    try:
        assert acquired
        done = []
        t = threading.Thread(
            target=lambda: done.append(server.drain(5.0, wait=False)))
        t.start()
        t.join(2.0)
        assert not t.is_alive(), \
            "drain(wait=False) blocked on the server lock"
        assert done == [False]  # one request outstanding
    finally:
        server._lock.release()
    assert server._draining and server._drain_deadline is not None


def test_threaded_drain_grace_expiry_reports_not_clean():
    """The threaded join path must not report a clean drain when the
    serve thread force-failed the leftovers itself on its way out
    (review pin — the sync path was already pinned above)."""
    eng = FakeEngine()
    orig_decode = eng.decode
    eng.decode = lambda toks: (time.sleep(0.02), orig_decode(toks))[1]
    server = Server(eng, num_blocks=64, block_size=8)
    server.start()
    reqs = [server.submit([i, i + 1], max_new_tokens=8) for i in range(4)]
    assert server.drain(grace_s=0.05) is False
    assert server.outstanding() == 0
    assert any(r.status == "replica_failed" for r in reqs)
