"""Test harness: 8 fake CPU devices, per SURVEY.md §4.

The reference had no test suite at all (its only "integration test" was a
CloudFormation stack reaching CREATE_COMPLETE); we test every parallelism
path on a virtual 8-device CPU mesh so multi-chip behavior is exercised in
CI without TPU hardware.

Env must be adjusted before the first JAX backend initialization. The image
ships an `axon` TPU plugin that force-registers itself via sitecustomize
when PALLAS_AXON_POOL_IPS is set, so we both scrub the env and pin
jax_platforms to cpu explicitly.
"""

import os

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _assert_fake_devices():
    assert jax.devices()[0].platform == "cpu"
    assert len(jax.devices()) == 8, (
        "tests need 8 fake CPU devices; got "
        f"{len(jax.devices())} — check XLA_FLAGS handling in conftest"
    )
    yield


@pytest.fixture()
def mesh8():
    """A full 6-axis mesh over the 8 fake devices: 2 data × 2 fsdp × 2 tensor."""
    from tpucfn.mesh import MeshSpec, build_mesh

    return build_mesh(MeshSpec(data=2, fsdp=2, tensor=2))


@pytest.fixture()
def mesh_dp8():
    """Pure-DP mesh (data=8) — the reference-equivalent topology."""
    from tpucfn.mesh import MeshSpec, build_mesh

    return build_mesh(MeshSpec(data=8))
