from tpucfn.obs.metrics import (  # noqa: F401
    Counter,
    Gauge,
    MetricLogger,
    StepTimer,
    Summary,
    device_memory_stats,
    register_device_gauges,
)
from tpucfn.obs.flight import (  # noqa: F401
    FlightRecorder,
    hbm_watermark,
    read_flight_dir,
    read_flight_file,
)
from tpucfn.obs.goodput import (  # noqa: F401
    GoodputLedger,
    goodput_report,
    merge_goodput,
    read_goodput_dir,
)
from tpucfn.obs.profiler import (  # noqa: F401
    CompileCacheProbe,
    ProfileCapture,
    ProfilerBusy,
    enable_compile_cache,
    profile_steps,
    start_profiler_server,
)
from tpucfn.obs.registry import (  # noqa: F401
    Histogram,
    MetricRegistry,
    default_registry,
    set_default_labels,
)
from tpucfn.obs.server import (  # noqa: F401
    ObsServer,
    obs_port_from_env,
    start_obs_server,
)
from tpucfn.obs.trace import (  # noqa: F401
    Tracer,
    read_trace_dir,
    read_trace_file,
)
