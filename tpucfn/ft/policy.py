"""Recovery policies: what to do about a detected failure, and at what
cost.

The decision layer between detection (ft/heartbeat.py, process exit
codes) and action (ft/coordinator.py).  Three pieces:

* :class:`RestartBudget` — how many recoveries a run is allowed, and the
  exponential-backoff-with-jitter delay before each one.  Jitter comes
  from a ``random.Random`` the caller seeds (no wall-clock randomness:
  the same seed replays the same delays, which is what makes the chaos
  harness deterministic).
* A **decision table** — failure class → action, overridable per policy
  (the per-failure-class table from ISSUE 4: a crash is not a hang is
  not a straggler).
* :class:`GangRestart` / :class:`SoloRestart` — the two recovery shapes
  for a TPU gang.  A TPU slice runs one SPMD program, so the safe
  default is gang restart: kill all, relaunch all, resume from the
  latest checkpoint.  Solo restart (restart only the dead host into the
  same gang) is the cheaper path for harnesses whose ranks are loosely
  coupled (data-parallel CPU rigs, serving fleets) — it falls back to a
  gang restart when multiple hosts fail at once.
"""

from __future__ import annotations

import dataclasses
import enum
import random


class FailureKind(enum.Enum):
    CLEAN_EXIT = "clean_exit"  # rc == 0 — not a failure; never burns budget
    CRASH = "crash"            # process exited nonzero (or was killed)
    HANG = "hang"              # process alive but heartbeats went DEAD
    STRAGGLER = "straggler"    # alive, beating, but step-lagging the fleet


class Action(enum.Enum):
    NONE = "none"
    SOLO_RESTART = "solo_restart"
    GANG_RESTART = "gang_restart"
    GIVE_UP = "give_up"


@dataclasses.dataclass(frozen=True)
class Failure:
    host_id: int
    kind: FailureKind
    rc: int | None = None      # exit code for CRASH/CLEAN_EXIT
    step: int | None = None    # last heartbeat step, when known
    detail: str = ""


@dataclasses.dataclass(frozen=True)
class Decision:
    action: Action
    hosts: tuple[int, ...] = ()  # SOLO_RESTART victims; empty = whole gang
    delay_s: float = 0.0
    reason: str = ""


# action each failure class earns by default; CLEAN_EXIT and STRAGGLER
# are observe-only (a straggler is a scheduling/obs problem first — see
# ROADMAP ft follow-ons for eviction policies).
DEFAULT_DECISION_TABLE: dict[FailureKind, Action] = {
    FailureKind.CLEAN_EXIT: Action.NONE,
    FailureKind.CRASH: Action.GANG_RESTART,
    FailureKind.HANG: Action.GANG_RESTART,
    FailureKind.STRAGGLER: Action.NONE,
}


class RestartBudget:
    """``max_restarts`` recoveries, exponential backoff + jitter between.

    Delay before restart ``k`` (0-based over *consumed* restarts)::

        min(backoff_s * multiplier**k, max_backoff_s) * (1 + U(-j, +j))

    ``backoff_s=0`` disables delays entirely (the unit-test path).  The
    budget is only consumed for actual recoveries — a clean exit after
    prior restarts must not burn a slot (ISSUE 4 satellite: exit-cause
    accounting).
    """

    def __init__(self, max_restarts: int, *, backoff_s: float = 0.0,
                 multiplier: float = 2.0, max_backoff_s: float = 60.0,
                 jitter: float = 0.1, rng: random.Random | None = None):
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
        if not 0.0 <= jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {jitter}")
        self.max_restarts = max_restarts
        self.backoff_s = float(backoff_s)
        self.multiplier = float(multiplier)
        self.max_backoff_s = float(max_backoff_s)
        self.jitter = float(jitter)
        self.rng = rng if rng is not None else random.Random(0)
        self.used = 0

    @property
    def remaining(self) -> int:
        return max(0, self.max_restarts - self.used)

    def next_delay(self) -> float:
        """The delay the *next* restart would wait (no state change)."""
        if self.backoff_s <= 0.0:
            return 0.0
        base = min(self.backoff_s * self.multiplier ** self.used,
                   self.max_backoff_s)
        if self.jitter:
            base *= 1.0 + self.rng.uniform(-self.jitter, self.jitter)
        return base

    def consume(self) -> bool:
        """Take one restart slot; False when the budget is exhausted."""
        if self.used >= self.max_restarts:
            return False
        self.used += 1
        return True


class RecoveryPolicy:
    """decide(failures) → Decision; owns the budget and the table."""

    name = "base"

    def __init__(self, budget: RestartBudget,
                 table: dict[FailureKind, Action] | None = None):
        self.budget = budget
        self.table = dict(DEFAULT_DECISION_TABLE)
        if table:
            self.table.update(table)

    def _restart_shape(self, actionable: list[Failure]) -> Action:
        raise NotImplementedError

    def decide(self, failures: list[Failure]) -> Decision:
        actionable = [f for f in failures
                      if self.table.get(f.kind, Action.NONE) is not Action.NONE]
        if not actionable:
            kinds = ",".join(sorted({f.kind.value for f in failures})) or "none"
            return Decision(Action.NONE, reason=f"table: no action for {kinds}")
        shape = self._restart_shape(actionable)
        # Delay is drawn before consume so it reflects the restart being
        # paid for (restart k waits multiplier**k), and only when the
        # budget actually has a slot (a drawn-then-refused delay would
        # desync the seeded jitter stream between runs that exhaust at
        # different points).
        if self.budget.remaining == 0:
            return Decision(
                Action.GIVE_UP,
                reason=f"restart budget exhausted "
                       f"({self.budget.max_restarts} used)")
        delay = self.budget.next_delay()
        self.budget.consume()
        hosts = tuple(sorted(f.host_id for f in actionable))
        if shape is Action.SOLO_RESTART:
            return Decision(Action.SOLO_RESTART, hosts=hosts, delay_s=delay,
                            reason=f"solo restart of host(s) {hosts} "
                                   f"({self.budget.used}/"
                                   f"{self.budget.max_restarts})")
        return Decision(Action.GANG_RESTART, delay_s=delay,
                        reason=f"gang restart for host(s) {hosts} "
                               f"({self.budget.used}/"
                               f"{self.budget.max_restarts})")


class GangRestart(RecoveryPolicy):
    """Kill all, relaunch all, resume from the latest checkpoint — the
    only safe shape when the ranks form one SPMD program (a TPU slice's
    collectives wedge the moment one participant is gone)."""

    name = "gang"

    def _restart_shape(self, actionable: list[Failure]) -> Action:
        return Action.GANG_RESTART


class SoloRestart(RecoveryPolicy):
    """Restart only the dead host back into the same gang (same host_id,
    same env: obs port, heartbeat file).  Correct only for loosely
    coupled ranks; multiple simultaneous failures escalate to a gang
    restart (correlated death usually means the gang state is gone)."""

    name = "solo"

    def _restart_shape(self, actionable: list[Failure]) -> Action:
        if len(actionable) == 1:
            return Action.SOLO_RESTART
        return Action.GANG_RESTART


POLICIES = {GangRestart.name: GangRestart, SoloRestart.name: SoloRestart}


def policy_from_name(name: str, budget: RestartBudget,
                     table: dict[FailureKind, Action] | None = None
                     ) -> RecoveryPolicy:
    try:
        cls = POLICIES[name]
    except KeyError:
        raise ValueError(
            f"unknown ft policy {name!r}; choose from {sorted(POLICIES)}"
        ) from None
    return cls(budget, table)
