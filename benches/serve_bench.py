#!/usr/bin/env python
"""Continuous-batching serving benchmark (tpucfn.serve).

Two workloads through the full Server → scheduler → engine path, ONE
JSON line out in the standard BENCH row schema:

* **Mixed** (the headline): Zipf-ish spread of prompt lengths,
  Poisson-ish arrival jitter deliberately OMITTED (open-loop arrivals
  would measure the queue, not the engine; every request is submitted
  up front so the scheduler stays saturated).  Produces
  ``serve_tokens_per_sec``.
* **Shared-prefix** (ISSUE 3 acceptance): every request opens with the
  same ``--shared-prefix-len`` system prompt.  Run once with the prefix
  cache OFF (and prefill batching at 1) and once ON (batching at
  ``--max-prefill-batch``), same engine, same prompts — the
  ``detail.shared_prefix`` block reports prefix hit rate, prefill calls
  per request, prefilled tokens per request, and TTFT for both, plus
  ``prefilled_tokens_reduction`` (the >= 2x acceptance number) and the
  ``ceil(requests / K)`` call ceiling batching is held to.

Compile warmup is excluded from every timed window: each phase's
buckets (and the copy_prefix program) are compiled by throwaway servers
on the SAME engine first, mirroring bench.py's warmup-exclusion rule
for training steps.

``vs_baseline`` is 0.0: the reference repo was a training-only harness
with no serving number to compare against (detail.baseline_note says
so).  Meaningful throughput needs the real chip; on CPU this is a
correctness and scheduling-overhead bench.

* **Availability** (``--availability``, ISSUE 9): the serve-side
  analogue of ``ft_bench``'s MTTR split — a deterministic open-loop run
  (seeded exponential arrival trace) against TWO replicas behind the
  :class:`~tpucfn.serve.router.ReplicaRouter`, with replica 0 killed at
  the trace midpoint.  Emits its own BENCH row
  (``metric: serve_availability``) whose ``detail`` carries
  ``availability`` (fraction of ACCEPTED requests completing within
  deadline), the retry success rate, and the hedge win rate.

* **Speculative decoding** (``--spec``, ISSUE 14): three legs over one
  prompt set — plain decode, a self-draft (identical weights ⇒ the
  synthetic high-acceptance workload), and an adversarial nano draft
  with divergent weights (⇒ zero acceptance, the controller's worst
  case).  Every leg's outputs are asserted bit-identical (greedy spec
  decode's correctness contract), then two rc gates: the high-
  acceptance leg must reach >= 1.5x ``tokens_per_target_step`` vs
  plain, and the adversarial leg's measured TPOT must stay within
  1.3x of plain — the acceptance-driven controller shrinking k and
  then turning speculation off (amortized probes only) is what makes
  that bound real rather than hoped.

Usage: python benches/serve_bench.py [--preset tiny --requests 32 ...]
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _run_workload(engine, args, prompts, *, prefix_cache, max_prefill_batch,
                  max_new):
    """One timed pass over ``prompts`` through a fresh Server (fresh
    metrics + KV pool; jit caches ride on the shared engine)."""
    from tpucfn.serve import Server

    server = Server(engine, num_blocks=args.num_blocks,
                    block_size=args.block_size, prefix_cache=prefix_cache,
                    max_prefill_batch=max_prefill_batch)
    t0 = time.perf_counter()
    reqs = [server.submit(q, max_new_tokens=max_new) for q in prompts]
    server.run_until_idle()
    wall = time.perf_counter() - t0
    snap = server.metrics.snapshot()
    n = len(prompts)
    return {
        "wall_s": round(wall, 3),
        "failed": sum(1 for r in reqs if r.error is not None),
        "kv_blocks_leaked": server.kv.allocator.num_used,
        "kv_blocks_high_water": server.kv.allocator.high_water,
        "prefill_calls": int(snap["prefill_calls"]),
        "prefill_calls_per_request": round(snap["prefill_calls"] / n, 3),
        "prefilled_tokens_per_request": round(snap["prefilled_tokens"] / n, 3),
        "prefix_hit_rate": round(snap["prefix_hit_requests"] / n, 3),
        "prefix_hit_tokens_per_request": round(
            snap["prefix_hit_tokens"] / n, 3),
        "ttft_p50_s": snap["ttft_s"]["p50"],
        "ttft_p95_s": snap["ttft_s"]["p95"],
        "tokens_per_sec": round(snap["generated_tokens"] / wall, 3),
        "snapshot": snap,
        "slo": server.slo.snapshot(),
    }


def run_availability(args) -> int:
    """Open-loop availability drill: 2 replicas, seeded arrival trace,
    replica 0 killed after half the trace has been submitted.  Every
    count in the row is over ACCEPTED requests — admission rejections
    are the router doing its job, not lost availability."""
    import jax
    import numpy as np

    from tpucfn.serve import AdmissionError, ReplicaRouter, Server
    from tpucfn.serve.engine import ServeEngine, demo_llama_engine

    print(f"# backend={jax.default_backend()} availability drill "
          f"requests={args.avail_requests}", file=sys.stderr)
    cfg, engine = demo_llama_engine(args.preset, seed=args.seed,
                                    max_batch=args.max_batch,
                                    cache_len=args.cache_len,
                                    prefill_width=args.max_prefill_batch)
    engines = [engine,
               ServeEngine.from_llama(cfg, engine.params,
                                      max_batch=args.max_batch,
                                      cache_len=args.cache_len,
                                      prefill_width=args.max_prefill_batch)]

    def factory(i: int) -> Server:
        return Server(engines[i], num_blocks=args.num_blocks,
                      block_size=args.block_size, prefix_cache=True,
                      max_prefill_batch=args.max_prefill_batch)

    rs = np.random.RandomState(args.seed)
    prompts = [rs.randint(0, cfg.vocab_size,
                          rs.randint(args.prompt_len_lo,
                                     args.prompt_len_hi + 1)).tolist()
               for _ in range(args.avail_requests)]
    # Seeded open-loop arrival trace: exponential inter-arrivals, fixed
    # by --seed, so two runs submit the same prompts at the same
    # offsets — the arrival process is part of the drill's identity.
    gaps = rs.exponential(args.avail_interarrival_ms / 1000.0,
                          size=args.avail_requests)
    arrivals = np.cumsum(gaps)

    # Compile warmup outside the timed/measured window: both replicas'
    # buckets (each engine owns its own jit caches).
    from tpucfn.serve.scheduler import prefill_bucket
    for eng in engines:
        warm = Server(eng, num_blocks=args.num_blocks,
                      block_size=args.block_size, prefix_cache=False,
                      max_prefill_batch=args.max_prefill_batch)
        for b in sorted({prefill_bucket(len(q), args.cache_len)
                         for q in prompts}):
            warm.submit([1] * min(b, args.cache_len - 2), max_new_tokens=2)
        warm.run_until_idle()

    router = ReplicaRouter(factory, 2, retry_budget=args.retry_budget,
                           hedge_ms=args.hedge_ms,
                           breaker_cooldown_s=1.0)
    router.start()
    kill_at = args.avail_requests // 2
    reqs, rejected = [], 0
    t0 = time.perf_counter()
    killed_at_s = None
    for k, q in enumerate(prompts):
        if k == kill_at:
            killed_at_s = time.perf_counter() - t0
            router.kill_replica(0)
        lag = arrivals[k] - (time.perf_counter() - t0)
        if lag > 0:
            time.sleep(lag)
        try:
            reqs.append(router.submit(q, max_new_tokens=args.max_new,
                                      deadline_s=args.avail_deadline_s))
        except AdmissionError:
            # ONLY admission rejections are tolerable here; a router
            # bug raising anything else must crash the bench, not be
            # tallied into a plausible-looking row
            rejected += 1
    for r in reqs:
        r.done.wait(args.avail_deadline_s + 30.0)
    wall = time.perf_counter() - t0
    router.stop()

    accepted = len(reqs)
    ok = sum(1 for r in reqs if r.status == "ok")
    dropped = sum(1 for r in reqs if r.status == "pending")
    retried = [r for r in reqs if r.retries > 0]
    retried_ok = sum(1 for r in retried if r.status == "ok")
    snap = router.snapshot()
    availability = ok / accepted if accepted else 0.0
    row = {
        "metric": "serve_availability",
        "value": round(availability, 4),
        "unit": "fraction of accepted requests completing within deadline",
        "vs_baseline": 0.0,
        "detail": {
            "baseline_note": "reference harness was training-only; no "
                             "published serving availability exists",
            "backend": jax.default_backend(),
            "preset": args.preset,
            "replicas": 2,
            "requests": args.avail_requests,
            "accepted": accepted,
            "rejected_at_submit": rejected,
            "availability": round(availability, 4),
            "dropped": dropped,
            "completed_ok": ok,
            "retried": len(retried),
            "retry_success_rate": (round(retried_ok / len(retried), 4)
                                   if retried else None),
            "hedges": snap["hedges"],
            "hedge_win_rate": (round(snap["hedges_won"] / snap["hedges"], 4)
                               if snap["hedges"] else None),
            "failovers": snap["failovers"],
            "kill_at_request": kill_at,
            "killed_at_s": (round(killed_at_s, 3)
                            if killed_at_s is not None else None),
            "deadline_s": args.avail_deadline_s,
            "interarrival_ms": args.avail_interarrival_ms,
            "retry_budget": args.retry_budget,
            "hedge_ms": args.hedge_ms,
            "wall_s": round(wall, 3),
            "seed": args.seed,
            "router": snap,
        },
    }
    print(json.dumps(row))
    # A dropped request (accepted, never reached a terminal status) is
    # the one unacceptable outcome — the row reports availability, the
    # exit code guards delivery.
    return 0 if dropped == 0 else 1


def run_spec(args) -> int:
    """Plain vs speculative decode on one prompt set (see module
    docstring).  The worst-case leg runs the ADAPTIVE controller with a
    short window so the run demonstrates the bound it gates on: shrink
    to k=1, then speculation OFF with amortized probes."""
    import jax
    import numpy as np

    from tpucfn.serve import Server
    from tpucfn.serve.engine import ServeEngine, demo_llama_engine
    from tpucfn.serve.scheduler import prefill_bucket
    from tpucfn.serve.spec import SpecDecoder, SpecKController

    print(f"# backend={jax.default_backend()} spec drill "
          f"preset={args.preset} k={args.spec_k} "
          f"requests={args.spec_requests} max_new={args.spec_max_new}",
          file=sys.stderr)
    cfg, target_plain = demo_llama_engine(
        args.preset, seed=args.seed, max_batch=args.max_batch,
        cache_len=args.cache_len, prefill_width=args.max_prefill_batch)
    params = target_plain.params

    def eng(p=None, seed=None):
        if p is not None:
            return ServeEngine.from_llama(
                cfg, p, max_batch=args.max_batch, cache_len=args.cache_len,
                prefill_width=args.max_prefill_batch)
        _, e = demo_llama_engine(
            "nano", seed=seed, max_batch=args.max_batch,
            cache_len=args.cache_len, prefill_width=args.max_prefill_batch)
        return e

    # High-acceptance leg: self-draft (identical weights — the draft
    # always agrees, the synthetic upper bound real distilled drafts
    # approach).  Worst-case leg: a nano draft with DIVERGENT weights
    # (different init seed) — acceptance ~0 on random-init models.
    spec_hi = SpecDecoder(eng(params), eng(params), k=args.spec_k)
    spec_lo = SpecDecoder(
        eng(params), eng(seed=args.seed + 1),
        controller=SpecKController(k=args.spec_k, window=4,
                                   probe_every=64))

    rs = np.random.RandomState(args.seed)
    prompts = [rs.randint(0, cfg.vocab_size,
                          rs.randint(args.prompt_len_lo,
                                     args.prompt_len_hi + 1)).tolist()
               for _ in range(args.spec_requests)]

    def leg(engine, fresh_controller=None):
        # compile warmup on the engine pair (buckets, decode, verify
        # widths, rollback), excluded from the timed pass — bench.py's
        # warmup-exclusion rule.
        warm = Server(engine, num_blocks=args.num_blocks,
                      block_size=args.block_size, prefix_cache=False,
                      max_prefill_batch=args.max_prefill_batch)
        for b in sorted({prefill_bucket(len(q), args.cache_len)
                         for q in prompts}):
            warm.submit([1] * min(b, args.cache_len - args.spec_max_new),
                        max_new_tokens=min(args.spec_max_new, 24))
        warm.run_until_idle()
        if fresh_controller is not None:
            # The warmup also ADAPTED the controller (an adversarial
            # warmup leaves it already off).  Reset it so the timed
            # pass pays the full shrink-to-off transient — the gate
            # bounds the controller's whole trajectory, not just its
            # steady state.
            engine.controller = fresh_controller()
        server = Server(engine, num_blocks=args.num_blocks,
                        block_size=args.block_size, prefix_cache=False,
                        max_prefill_batch=args.max_prefill_batch)
        t0 = time.perf_counter()
        reqs = [server.submit(q, max_new_tokens=args.spec_max_new)
                for q in prompts]
        server.run_until_idle()
        wall = time.perf_counter() - t0
        outs = [r.result(timeout=0) for r in reqs]
        tpots = [(r.t_done - r.t_first_token) / (len(r.tokens) - 1)
                 for r in reqs if r.tokens and len(r.tokens) > 1]
        snap = server.metrics.snapshot()
        assert server.kv.allocator.num_used == 0, "KV blocks leaked"
        return outs, {
            "wall_s": round(wall, 3),
            "tokens_per_target_step": snap["tokens_per_target_step"],
            "acceptance_rate": snap["spec_acceptance_rate"],
            "spec_proposed": snap["spec_proposed"],
            "spec_accepted": snap["spec_accepted"],
            "decode_rounds": snap["decode_rounds"],
            "spec_rounds": snap["spec_rounds"],
            "tpot_mean_s": (round(sum(tpots) / len(tpots), 6)
                            if tpots else None),
            "tokens_per_sec": round(snap["generated_tokens"] / wall, 3),
        }

    ref, plain = leg(target_plain)
    out_hi, hi = leg(spec_hi)
    out_lo, lo = leg(
        spec_lo,
        fresh_controller=lambda: SpecKController(
            k=args.spec_k, window=4, probe_every=64))
    hi["controller_k_final"] = spec_hi.controller.k
    lo["controller_k_final"] = spec_lo.controller.k

    identical = (out_hi == ref) and (out_lo == ref)
    tps_gain = (hi["tokens_per_target_step"] or 0.0) \
        / max(plain["tokens_per_target_step"] or 1.0, 1e-9)
    tpot_ratio = (lo["tpot_mean_s"] / plain["tpot_mean_s"]
                  if lo["tpot_mean_s"] and plain["tpot_mean_s"] else None)
    gates = {
        "bit_identical": identical,
        "tokens_per_target_step_gain": round(tps_gain, 3),
        "tokens_per_target_step_gate": tps_gain >= 1.5,
        "worst_case_tpot_ratio": (round(tpot_ratio, 3)
                                  if tpot_ratio is not None else None),
        "worst_case_tpot_gate": (tpot_ratio is not None
                                 and tpot_ratio <= 1.3),
    }
    row = {
        "metric": "serve_spec_tokens_per_target_step",
        "value": hi["tokens_per_target_step"],
        "unit": "decode tokens per target dispatch per slot "
                "(high-acceptance self-draft leg)",
        "vs_baseline": 0.0,
        "detail": {
            "baseline_note": "reference harness was training-only; no "
                             "published speculative-decode number exists",
            "backend": jax.default_backend(),
            "preset": args.preset,
            "draft": {"high_acceptance": "self",
                      "worst_case": "nano (divergent init)"},
            "spec_k": args.spec_k,
            "requests": args.spec_requests,
            "max_new": args.spec_max_new,
            "max_batch": args.max_batch,
            "plain": plain,
            "spec_high_acceptance": hi,
            "spec_worst_case": lo,
            "gates": gates,
            "seed": args.seed,
        },
    }
    print(json.dumps(row))
    ok = (identical and gates["tokens_per_target_step_gate"]
          and gates["worst_case_tpot_gate"])
    return 0 if ok else 1


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--preset", choices=["tiny", "llama3-1b", "llama3-8b"],
                   default="tiny")
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--prompt-len-lo", type=int, default=8)
    p.add_argument("--prompt-len-hi", type=int, default=96)
    p.add_argument("--max-new", type=int, default=32)
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--cache-len", type=int, default=256)
    p.add_argument("--num-blocks", type=int, default=512)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--shared-prefix-len", type=int, default=64,
                   help="common system-prompt length of the shared-prefix "
                        "workload")
    p.add_argument("--max-prefill-batch", type=int, default=4)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--availability", action="store_true",
                   help="run the 2-replica open-loop availability drill "
                        "(replica killed mid-trace) instead of the "
                        "throughput workloads")
    p.add_argument("--avail-requests", type=int, default=24)
    p.add_argument("--avail-deadline-s", type=float, default=15.0)
    p.add_argument("--avail-interarrival-ms", type=float, default=30.0,
                   help="mean of the seeded exponential inter-arrival "
                        "trace")
    p.add_argument("--retry-budget", type=int, default=2)
    p.add_argument("--hedge-ms", type=float, default=250.0,
                   help="hedge delay floor for the availability drill "
                        "(0 disables hedging)")
    p.add_argument("--spec", action="store_true",
                   help="run the speculative-decoding drill (plain vs "
                        "self-draft vs adversarial nano draft) instead "
                        "of the throughput workloads")
    p.add_argument("--spec-k", type=int, default=4)
    p.add_argument("--spec-requests", type=int, default=8)
    p.add_argument("--spec-max-new", type=int, default=96,
                   help="decode length of the spec drill (long enough "
                        "for the adaptive controller to reach its "
                        "steady state on the adversarial leg)")
    args = p.parse_args()

    if args.availability:
        return run_availability(args)
    if args.spec:
        return run_spec(args)

    import jax
    import numpy as np

    from tpucfn.serve import Server
    from tpucfn.serve.engine import demo_llama_engine
    from tpucfn.serve.scheduler import prefill_bucket

    print(f"# backend={jax.default_backend()} preset={args.preset} "
          f"requests={args.requests}", file=sys.stderr)
    cfg, engine = demo_llama_engine(args.preset, seed=args.seed,
                                    max_batch=args.max_batch,
                                    cache_len=args.cache_len,
                                    prefill_width=args.max_prefill_batch)

    rs = np.random.RandomState(args.seed)
    mixed = [rs.randint(0, cfg.vocab_size,
                        rs.randint(args.prompt_len_lo,
                                   args.prompt_len_hi + 1)).tolist()
             for _ in range(args.requests)]
    # Shared-prefix workload: one system prompt, per-request tails sized
    # to land in ONE suffix bucket (tail in (block_size, 2*block_size])
    # so batched-prefill call counts are deterministic.
    sys_prompt = rs.randint(0, cfg.vocab_size,
                            args.shared_prefix_len).tolist()
    shared = [sys_prompt + rs.randint(
        0, cfg.vocab_size,
        rs.randint(args.block_size + 1, 2 * args.block_size + 1)).tolist()
        for _ in range(args.requests)]

    # -- compile warmup (excluded from every timed window) -----------------
    # prefix_cache OFF here: the warm prompts all share a [1]*n prefix,
    # and a hit would prefill a short suffix in a SMALLER bucket —
    # leaving the large buckets uncompiled for the timed phases.
    warm = Server(engine, num_blocks=args.num_blocks,
                  block_size=args.block_size, prefix_cache=False,
                  max_prefill_batch=args.max_prefill_batch)
    for b in sorted({prefill_bucket(len(q), args.cache_len)
                     for q in mixed}):
        warm.submit([1] * min(b, args.cache_len - 2), max_new_tokens=2)
    warm.run_until_idle()
    # the shared-prefix phase's programs: full bucket, suffix bucket,
    # copy_prefix (two identical-prefix requests back to back).
    _run_workload(engine, args, shared[: 2 * args.max_prefill_batch],
                  prefix_cache=True,
                  max_prefill_batch=args.max_prefill_batch, max_new=2)

    # -- timed: mixed headline ---------------------------------------------
    head = _run_workload(engine, args, mixed, prefix_cache=True,
                         max_prefill_batch=args.max_prefill_batch,
                         max_new=args.max_new)
    # -- timed: shared-prefix, cache off vs on, same run -------------------
    off = _run_workload(engine, args, shared, prefix_cache=False,
                        max_prefill_batch=1, max_new=args.max_new)
    on = _run_workload(engine, args, shared, prefix_cache=True,
                       max_prefill_batch=args.max_prefill_batch,
                       max_new=args.max_new)
    reduction = (off["prefilled_tokens_per_request"]
                 / max(on["prefilled_tokens_per_request"], 1e-9))

    strip = lambda d: {k: v for k, v in d.items() if k != "snapshot"}  # noqa: E731
    row = {
        "metric": "serve_tokens_per_sec",
        "value": head["tokens_per_sec"],
        "unit": "generated tokens/sec",
        "vs_baseline": 0.0,
        "detail": {
            "baseline_note": "reference harness was training-only; no "
                             "published serving number exists",
            "backend": jax.default_backend(),
            "preset": args.preset,
            "requests": args.requests,
            "failed": head["failed"],
            "wall_s": head["wall_s"],
            "max_batch": args.max_batch,
            "cache_len": args.cache_len,
            "block_size": args.block_size,
            "num_blocks": args.num_blocks,
            "max_new": args.max_new,
            "max_prefill_batch": args.max_prefill_batch,
            "ttft_s": head["snapshot"]["ttft_s"],
            "request_latency_s": head["snapshot"]["request_latency_s"],
            "preemptions": head["snapshot"]["preemptions"],
            "kv_blocks_high_water": head["kv_blocks_high_water"],
            "kv_blocks_leaked": head["kv_blocks_leaked"],
            # The full ServingMetrics snapshot rides on every row so a
            # perf regression carries its own latency decomposition
            # (queue depth, occupancy, token counts) instead of just the
            # headline number (ISSUE 2 satellite).
            "serving_metrics": head["snapshot"],
            # The serve_slo_* snapshot (ISSUE 5): TTFT/TPOT objective
            # targets, violation counts, and rolling-window burn rates
            # for the headline workload.
            "serve_slo": head["slo"],
            # ISSUE 3 acceptance: prefix caching's prefilled-token
            # reduction and batched prefill's call ceiling, cache off vs
            # on over identical prompts in the same run.
            "shared_prefix": {
                "prefix_len": args.shared_prefix_len,
                "requests": args.requests,
                "max_prefill_batch": args.max_prefill_batch,
                "prefill_calls_ceiling": math.ceil(
                    args.requests / args.max_prefill_batch),
                "off": strip(off),
                "on": strip(on),
                "prefilled_tokens_reduction": round(reduction, 3),
            },
        },
    }
    print(json.dumps(row))
    leaked = (head["kv_blocks_leaked"] or off["kv_blocks_leaked"]
              or on["kv_blocks_leaked"])
    failed = head["failed"] or off["failed"] or on["failed"]
    return 0 if not failed and not leaked else 1


if __name__ == "__main__":
    sys.exit(main())
