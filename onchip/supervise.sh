#!/bin/bash
# Retry megabench until it completes. rc 42 = client creation failed
# (tunnel wedged): sleep on the recovery timescale and retry. rc 43 =
# per-phase watchdog fired with phases checkpointed: retry immediately
# (the next attempt skips completed phases). Any other nonzero rc is a
# deterministic failure: give up rather than stall. Never kills a
# running attempt (killed clients extend the wedge).
cd /root/repo
log=onchip/megabench.log
for attempt in $(seq 1 14); do
  echo "=== attempt $attempt $(date -u +%FT%TZ) ===" >> "$log"
  python onchip/megabench.py >> "$log" 2>&1
  rc=$?
  echo "=== attempt $attempt rc=$rc $(date -u +%FT%TZ) ===" >> "$log"
  case "$rc" in
    0)  exit 0 ;;
    42) sleep 420 ;;
    43) ;;
    *)  echo "=== fatal rc=$rc, giving up $(date -u +%FT%TZ) ===" >> "$log"
        exit "$rc" ;;
  esac
done
echo "=== supervisor exhausted $(date -u +%FT%TZ) ===" >> "$log"
exit 1
