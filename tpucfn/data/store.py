"""Object-store staging — the "S3 → local → HBM" half of the data path.

The reference's workflow staged datasets from S3 onto the cluster's
shared volume before training (SURVEY.md §2.1 "S3 data staging", §3.1:
``aws s3 sync s3://bucket/dataset /efs/dataset``).  tpucfn models that
with a small :class:`Store` interface — list/read/write/download by key —
with three implementations:

* :class:`LocalStore` — a directory tree; the CI-testable default, and
  also the "shared filesystem" case (NFS/Filestore mounts).
* :class:`CliObjectStore` — gs:// and s3:// URLs via the corresponding
  CLI (``gsutil`` / ``aws s3``) in a subprocess.  The build environment
  has zero egress and no cloud CLIs, so this class takes an injectable
  ``runner`` and the test suite drives it with recorded argv fixtures;
  on a real pod the default runner shells out.

:func:`stage` is the ``s3 sync`` analogue: download every shard under a
prefix into a local cache directory (idempotent — existing files with
matching sizes are kept), returning the local paths that
``ShardedDataset`` consumes.  Training never reads the remote store on
the hot path; steps stream from local disk/page cache.
"""

from __future__ import annotations

import os
import subprocess
from pathlib import Path
from typing import Callable, Sequence

# runner(argv) -> stdout str; raises CalledProcessError on failure.
CliRunner = Callable[[Sequence[str]], str]


def _default_runner(argv: Sequence[str]) -> str:
    return subprocess.run(
        list(argv), check=True, capture_output=True, text=True
    ).stdout


class Store:
    """Key-addressed blob store; keys are '/'-separated relative paths."""

    def list(self, prefix: str = "") -> list[str]:
        raise NotImplementedError

    def read_bytes(self, key: str) -> bytes:
        raise NotImplementedError

    def write_bytes(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def download(self, key: str, dest: str | Path) -> Path:
        """Fetch ``key`` to the local path ``dest`` (parent dirs created)."""
        dest = Path(dest)
        dest.parent.mkdir(parents=True, exist_ok=True)
        dest.write_bytes(self.read_bytes(key))
        return dest

    def size(self, key: str) -> int | None:
        """Object size in bytes, or None if unknown/cheaply unavailable."""
        return None

    def upload(self, src: str | Path, key: str) -> None:
        """Publish a local file to ``key``. Default round-trips through
        memory; path-capable backends override to stream from disk."""
        self.write_bytes(key, Path(src).read_bytes())


class LocalStore(Store):
    def __init__(self, root: str | Path):
        self.root = Path(root)

    def _p(self, key: str) -> Path:
        p = (self.root / key).resolve()
        if not p.is_relative_to(self.root.resolve()):
            raise ValueError(f"key {key!r} escapes store root")
        return p

    def list(self, prefix: str = "") -> list[str]:
        # Directory semantics, matching CliObjectStore: prefix 'imagenet'
        # must not match a sibling 'imagenet2012/...'.
        base = self.root
        out = []
        if not base.exists():
            return out
        pfx = prefix.strip("/")
        for p in sorted(base.rglob("*")):
            if p.is_file():
                key = p.relative_to(base).as_posix()
                if not pfx or key == pfx or key.startswith(pfx + "/"):
                    out.append(key)
        return out

    def read_bytes(self, key: str) -> bytes:
        return self._p(key).read_bytes()

    def write_bytes(self, key: str, data: bytes) -> None:
        p = self._p(key)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_bytes(data)

    def size(self, key: str) -> int | None:
        p = self._p(key)
        return p.stat().st_size if p.exists() else None

    def upload(self, src: str | Path, key: str) -> None:
        import shutil

        dest = self._p(key)
        if dest.exists() and os.path.samefile(src, dest):
            return  # publishing a file onto itself is a no-op
        dest.parent.mkdir(parents=True, exist_ok=True)
        shutil.copyfile(src, dest)


class CliObjectStore(Store):
    """gs:// / s3:// objects via the cloud CLI in a subprocess.

    Commands used (stable, scriptable surfaces):
        gsutil ls gs://b/prefix**      |  aws s3 ls --recursive b/prefix
        gsutil cp gs://b/key dest      |  aws s3 cp s3://b/key dest
        gsutil cp src gs://b/key       |  aws s3 cp src s3://b/key

    ``runner`` is injectable so CI (zero egress, no CLIs installed)
    exercises the full argv surface against recorded fixtures.
    """

    def __init__(self, base_url: str, runner: CliRunner | None = None):
        if not base_url.startswith(("gs://", "s3://")):
            raise ValueError(f"unsupported object-store url {base_url!r}")
        self.base_url = base_url.rstrip("/")
        self.scheme = base_url.split("://", 1)[0]
        self.runner = runner or _default_runner

    def _url(self, key: str) -> str:
        return f"{self.base_url}/{key}" if key else self.base_url

    def list(self, prefix: str = "") -> list[str]:
        # ``prefix`` has directory semantics (like `s3 sync`): an explicit
        # '/' separator is appended so 'datasets/imagenet' never matches a
        # sibling 'datasets/imagenet2012'.
        if self.scheme == "gs":
            base = self._url(prefix.strip("/"))
            out = self.runner(["gsutil", "ls", base.rstrip("/") + "/**"])
        else:
            bucket_and_path = self.base_url[len("s3://"):]
            bucket = bucket_and_path.split("/", 1)[0]
            base_key = (bucket_and_path.split("/", 1)[1].strip("/") + "/"
                        if "/" in bucket_and_path else "")
            list_prefix = base_key + prefix.strip("/")
            if prefix.strip("/"):
                list_prefix += "/"
            out = self.runner(["aws", "s3api", "list-objects-v2", "--bucket",
                               bucket, "--prefix", list_prefix,
                               "--query", "Contents[].Key", "--output", "text"])
            keys = []
            for tok in out.split():
                if tok != "None":
                    keys.append(tok[len(base_key):] if base_key and
                                tok.startswith(base_key) else tok)
            return sorted(keys)
        keys = []
        root = self.base_url + "/"
        for line in out.splitlines():
            line = line.strip()
            if line.startswith(root) and not line.endswith("/"):
                keys.append(line[len(root):])
        return sorted(keys)

    def read_bytes(self, key: str) -> bytes:
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            dest = Path(td) / "obj"
            self.download(key, dest)
            return dest.read_bytes()

    def download(self, key: str, dest: str | Path) -> Path:
        dest = Path(dest)
        dest.parent.mkdir(parents=True, exist_ok=True)
        cli = ["gsutil", "cp"] if self.scheme == "gs" else ["aws", "s3", "cp"]
        self.runner(cli + [self._url(key), str(dest)])
        return dest

    def write_bytes(self, key: str, data: bytes) -> None:
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            src = Path(td) / "obj"
            src.write_bytes(data)
            self.upload(src, key)

    def upload(self, src: str | Path, key: str) -> None:
        # Stream straight from disk: no RAM pass, no temp copy.
        cli = ["gsutil", "cp"] if self.scheme == "gs" else ["aws", "s3", "cp"]
        self.runner(cli + [str(src), self._url(key)])

    def size(self, key: str) -> int | None:
        try:
            if self.scheme == "gs":
                out = self.runner(["gsutil", "stat", self._url(key)])
                for line in out.splitlines():
                    if "Content-Length" in line:
                        return int(line.split(":", 1)[1].strip())
                return None
            bucket_and_path = self.base_url[len("s3://"):]
            bucket = bucket_and_path.split("/", 1)[0]
            base_key = (bucket_and_path.split("/", 1)[1].strip("/") + "/"
                        if "/" in bucket_and_path else "")
            out = self.runner(["aws", "s3api", "head-object", "--bucket", bucket,
                               "--key", base_key + key,
                               "--query", "ContentLength", "--output", "text"])
            return int(out.strip())
        except (subprocess.CalledProcessError, ValueError):
            return None  # treat as unknown: stage() re-downloads


def store_for_url(url: str, runner: CliRunner | None = None) -> tuple[Store, str]:
    """(store, prefix) for a dataset URL.

    ``gs://bucket/path`` and ``s3://bucket/path`` → CliObjectStore rooted
    at the bucket with ``path`` as the prefix; ``file:///dir`` or a plain
    path → LocalStore rooted at the dir with empty prefix.
    """
    if url.startswith(("gs://", "s3://")):
        scheme, rest = url.split("://", 1)
        bucket, _, prefix = rest.partition("/")
        return CliObjectStore(f"{scheme}://{bucket}", runner=runner), prefix
    if url.startswith("file://"):
        url = url[len("file://"):]
    return LocalStore(url), ""


def stage(
    store: Store,
    prefix: str,
    cache_dir: str | Path,
    *,
    suffix: str = ".tpurec",
    owner_slice: tuple[int, int] | None = None,
) -> list[Path]:
    """Sync-down every ``suffix`` object under ``prefix`` into
    ``cache_dir`` (the ``aws s3 sync`` analogue).

    * Idempotent: a local file whose size matches the remote object is
      not re-fetched, so restarts only pay the transfer once.
    * Atomic: downloads land in a temp name and rename into place, so a
      concurrent reader never sees a torn shard.
    * Collision-free: keys keep their path relative to ``prefix`` under
      ``cache_dir`` (train/x.tpurec and val/x.tpurec stay distinct).
    * ``owner_slice=(i, n)`` downloads only shards ``i::n`` of the
      sorted list (the ShardedDataset ownership rule) but returns ALL
      local paths in sorted order, so every process computes the same
      shard list while fetching only what it will read — the multi-host
      bandwidth contract of the reference's per-worker `s3 cp` loop.
    """
    import os as _os
    import uuid

    cache = Path(cache_dir)
    cache.mkdir(parents=True, exist_ok=True)
    keys = sorted(k for k in store.list(prefix) if k.endswith(suffix))
    pfx = prefix.strip("/")
    out = []
    for i, key in enumerate(keys):
        rel = key[len(pfx):].lstrip("/") if pfx and key.startswith(pfx) else key
        dest = cache / rel
        out.append(dest)
        if owner_slice is not None and i % owner_slice[1] != owner_slice[0]:
            continue
        # Check the cheap local condition first: a cold cache skips the
        # per-shard remote stat entirely.
        if dest.exists():
            remote_size = store.size(key)
            if remote_size is not None and dest.stat().st_size == remote_size:
                continue
        tmp = dest.with_name(f".{dest.name}.{uuid.uuid4().hex[:8]}.tmp")
        store.download(key, tmp)
        _os.replace(tmp, dest)
    if not out:
        raise FileNotFoundError(
            f"no {suffix} objects under prefix {prefix!r} in {store!r}")
    return out


def stage_url(url: str, cache_dir: str | Path,
              runner: CliRunner | None = None,
              owner_slice: tuple[int, int] | None = None) -> list[Path]:
    """One-call staging: resolve ``url`` to a store and sync its shards
    down to ``cache_dir``."""
    store, prefix = store_for_url(url, runner=runner)
    return stage(store, prefix, cache_dir, owner_slice=owner_slice)
