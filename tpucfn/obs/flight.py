"""Flight recorder: the fleet's last-N-seconds, always in memory.

When the ft plane declares an incident, the operator's evidence so far
was one ``events.jsonl`` row — nothing about what each host was *doing*
in its final seconds (ISSUE 6).  The :class:`FlightRecorder` is the
black box that fixes that: a bounded in-memory ring of high-frequency
samples (step durations, data-wait, HBM bytes-in-use/peak, serve queue
depth, scheduler decisions) that costs O(capacity) memory forever and
is materialized only when someone asks:

* **on signal / atexit** — :meth:`install_dump_handlers` writes the
  ring to ``<dir>/flight-host{NNN}.jsonl`` when the process ends (the
  gang coordinator's SIGTERM included), so even a host that dies keeps
  its last seconds on disk;
* **on demand** — the obs HTTP server's ``GET /flightrecorder`` route
  returns :meth:`snapshot` as JSON (tpucfn/obs/server.py);
* **at detect time** — :class:`~tpucfn.ft.coordinator.GangCoordinator`
  fetches every surviving host's ring over that route *before* it kills
  the gang, writing ``<ft_dir>/flight/incident{NNN}-host{HHH}.jsonl``
  so every incident carries the fleet's final seconds (the postmortem
  bundle's per-host tails).

Sample schema (one JSON object per ring entry; ``seq`` is a monotonic
per-recorder counter so a reader can tell how much history the ring
overwrote)::

    {"kind": "step",  "t": <wall>, "seq": 17, "step": 120, "dur_s": 0.2}
    {"kind": "hbm",   "t": <wall>, "seq": 18, "used": ..., "peak": ...,
     "limit": ...}
    {"kind": "serve", "t": <wall>, "seq": 19, "queue": 3, "running": 8,
     "occupancy": 0.8}
    {"kind": "sched", "t": <wall>, "seq": 20, "work": "prefill",
     "batch": 4, "bucket": 32}

Dump file layout: a ``{"kind": "flight_dump", ...}`` header line
(host/role/capacity/recorded/dropped/samples) followed by one line per
sample.  The read side (:func:`read_flight_file`) is torn-tolerant and
counting, like every other JSONL reader in the repo — a dump cut short
by SIGKILL mid-write yields whatever complete lines landed.
"""

from __future__ import annotations

import collections
import json
import os
import signal as _signal
import threading
import time
from pathlib import Path

from tpucfn.obs.goodput import host_id_from_path, read_jsonl_counting

FLIGHT_GLOB = "flight-host*.jsonl"

# Canonical kinds of the flight FILE format (ISSUE 10): "flight" is a
# live snapshot body, "flight_dump" the on-disk header line.  Ring
# SAMPLE kinds stay an open vocabulary (each instrumentation point
# names its own); only the file-level kinds are matched by readers.
FLIGHT_FILE_KINDS = ("flight", "flight_dump")


def flight_path(d: str | Path, host_id: int) -> Path:
    return Path(d) / f"flight-host{host_id:03d}.jsonl"


def incident_flight_path(d: str | Path, incident: int, host_id: int) -> Path:
    """Where the coordinator lands a host's ring captured at detect time
    (``<ft_dir>/flight/``); ``host_id_from_path`` still parses the host."""
    return Path(d) / f"incident{incident:03d}-host{host_id:03d}.jsonl"


class FlightRecorder:
    """Bounded ring of high-frequency samples for one process.

    ``record()`` is cheap (one dict build + deque append under a lock)
    so instrumentation points can call it every step / serve iteration;
    the ring overwrites oldest-first and counts what it dropped.  All
    sampling is pull-free — nothing leaves the process until a dump or
    an HTTP snapshot asks.
    """

    def __init__(self, capacity: int = 4096, host_id: int = 0, *,
                 role: str = "", clock=time.time):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.host_id = host_id
        self.role = role
        self.clock = clock
        self._ring: collections.deque[dict] = collections.deque(
            maxlen=capacity)
        # REENTRANT on purpose: the SIGTERM dump handler runs ON the
        # main thread, possibly interrupting a record() that already
        # holds this lock — a plain Lock would self-deadlock exactly at
        # the moment the dump exists for (the coordinator's stop_all),
        # and the process would hang until the SIGKILL escalation.
        self._lock = threading.RLock()
        self._seq = 0
        self._dropped = 0
        # device handle resolved once; None-result memoized so a CPU
        # host does not re-resolve jax.devices() every step for nothing.
        self._device = None
        self._device_probed = False

    def record(self, kind: str, **fields) -> dict:
        rec = {"kind": kind, "t": self.clock(), **fields}
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            if len(self._ring) == self.capacity:
                self._dropped += 1
            self._ring.append(rec)
        return rec

    def sample_device(self, device=None) -> dict | None:
        """One ``hbm`` sample from ``device.memory_stats()`` — None-safe:
        CPU backends report no stats, so the call is a memoized no-op
        there (no sample, no error)."""
        from tpucfn.obs.metrics import device_memory_stats

        if device is None:
            if self._device_probed and self._device is None:
                return None  # known stats-less backend
            device = self._device
        stats = device_memory_stats(device)
        if device is None and not self._device_probed:
            # first resolve: remember the device (or that there is none)
            self._device_probed = True
            if stats is not None:
                try:
                    import jax

                    self._device = jax.devices()[0]
                except Exception:
                    pass
        if stats is None:
            return None
        return self.record(
            "hbm",
            used=stats.get("bytes_in_use"),
            peak=stats.get("peak_bytes_in_use"),
            limit=stats.get("bytes_limit"))

    def snapshot(self) -> dict:
        """The ring's current contents plus its own accounting — the
        ``GET /flightrecorder`` body and the dump's source of truth."""
        with self._lock:
            samples = list(self._ring)
            seq, dropped = self._seq, self._dropped
        return {"kind": "flight", "host": self.host_id, "role": self.role,
                "t": self.clock(), "capacity": self.capacity,
                "recorded": seq, "dropped": dropped, "samples": samples}

    # -- materialization ---------------------------------------------------

    def dump(self, path: str | Path) -> Path:
        """Write the ring to ``path`` (a dir derives the standard
        per-host file name).  Truncate-write on purpose: the latest ring
        IS the forensic record; repeated dumps (signal then atexit) must
        not concatenate two overlapping rings into one fused timeline."""
        p = Path(path)
        if p.suffix != ".jsonl":
            p.mkdir(parents=True, exist_ok=True)
            p = flight_path(p, self.host_id)
        else:
            p.parent.mkdir(parents=True, exist_ok=True)
        write_flight_dump(p, self.snapshot())
        return p

    def install_dump_handlers(self, d: str | Path,
                              signals=(_signal.SIGTERM,)) -> None:
        """Dump to ``d`` on process exit: atexit for clean ends, and the
        given signals (default SIGTERM — what the coordinator's
        ``stop_all`` sends first) for killed ones.  After dumping, the
        signal's default disposition is restored and the signal
        re-raised so the process still dies with the right status.
        Signal installation needs the main thread; elsewhere only the
        atexit hook is armed."""
        import atexit

        d = Path(d)
        atexit.register(self._dump_quietly, d)
        for sig in signals:
            try:
                prev = _signal.getsignal(sig)

                def _handler(signum, frame, _prev=prev):
                    self._dump_quietly(d)
                    if _prev is _signal.SIG_IGN:
                        # the process was configured to survive this
                        # signal (inherited ignore); dump, keep living
                        return
                    if callable(_prev) and _prev is not _signal.SIG_DFL:
                        _prev(signum, frame)
                    else:
                        _signal.signal(signum, _signal.SIG_DFL)
                        os.kill(os.getpid(), signum)

                _signal.signal(sig, _handler)
            except ValueError:  # not the main thread: atexit still holds
                break

    def _dump_quietly(self, d: Path) -> None:
        try:
            self.dump(d)
        except OSError:
            pass  # a full/vanished disk must not mask the real exit


# HBM watermark defaults (ISSUE 12 satellite, ROADMAP forensics
# follow-on): "used/limit sustained over a threshold" — the burn-rate
# shape the serve SLO plane uses, applied to device memory so an OOM
# becomes a /healthz prediction instead of a postmortem.
HBM_WATERMARK_THRESHOLD = 0.92
HBM_WATERMARK_SUSTAIN_S = 30.0


def hbm_watermark(samples, *, threshold: float = HBM_WATERMARK_THRESHOLD,
                  sustain_s: float = HBM_WATERMARK_SUSTAIN_S,
                  now: float | None = None) -> dict:
    """Burn-rate-style watermark over a ring's ``hbm`` samples.

    Walks the contiguous tail of samples whose ``used/limit`` ratio is
    at or above ``threshold``; the alert fires only when that tail has
    *sustained* for ``sustain_s`` seconds — one transient allocation
    spike (a compile's scratch, a fused temp) must not page anyone.

    Returns ``{"level": "ok"|"alert"|"no_data", "ratio", "peak_ratio",
    "sustained_s", "threshold", "sustain_s"}`` — merged into /healthz
    detail by the obs server whenever a flight recorder is attached.
    ``level`` never flips the probe's HTTP status: a watermark is a
    prediction for operators and autoscalers, not a liveness verdict.
    """
    pts: list[tuple[float, float]] = []
    for s in samples:
        # tpucfn: allow[vocab-drift] ring SAMPLE kinds are open (module doc)
        if s.get("kind") != "hbm":
            continue
        used, limit = s.get("used"), s.get("limit")
        if not isinstance(used, (int, float)) \
                or not isinstance(limit, (int, float)) or limit <= 0:
            continue
        pts.append((float(s.get("t", 0.0)), used / limit))
    base = {"threshold": threshold, "sustain_s": sustain_s}
    if not pts:
        return {"level": "no_data", "ratio": None, "peak_ratio": None,
                "sustained_s": 0.0, **base}
    ratio = pts[-1][1]
    peak = max(r for _, r in pts)
    over_since = None
    for t, r in reversed(pts):
        if r < threshold:
            break
        over_since = t
    sustained = 0.0
    if over_since is not None and ratio >= threshold:
        end = pts[-1][0] if now is None else now
        sustained = max(0.0, end - over_since)
    level = "alert" if sustained >= sustain_s else "ok"
    return {"level": level, "ratio": round(ratio, 4),
            "peak_ratio": round(peak, 4),
            "sustained_s": round(sustained, 3), **base}


def write_flight_dump(path: str | Path, snapshot: dict) -> Path:
    """One dump file from a :meth:`FlightRecorder.snapshot`-shaped dict:
    header line (``samples`` becomes a count) then one line per sample.
    Shared by the in-process dump and the coordinator's HTTP capture so
    the two artifacts are read by the same :func:`read_flight_file`."""
    p = Path(path)
    samples = snapshot.get("samples") or []
    header = {**snapshot, "kind": "flight_dump", "samples": len(samples)}
    with open(p, "w", buffering=1) as f:
        f.write(json.dumps(header) + "\n")
        for s in samples:
            f.write(json.dumps(s) + "\n")
    return p


def read_flight_file(path: str | Path) -> tuple[dict | None, list[dict], int]:
    """``(header, samples, skipped)`` for one dump file.  Torn/undecodable
    lines are skipped and counted (a SIGKILL mid-dump leaves a partial
    tail); a dump missing its header (torn head) still yields samples."""
    recs, skipped = read_jsonl_counting(path)
    header = None
    samples: list[dict] = []
    for r in recs:
        if r.get("kind") == "flight_dump" and header is None:
            header = r
        else:
            samples.append(r)
    return header, samples, skipped


def read_flight_dir(d: str | Path,
                    glob: str = FLIGHT_GLOB) -> dict[int, dict]:
    """``host_id -> {header, samples, skipped, path}`` for every dump
    matching ``glob`` under ``d`` (missing dir -> ``{}``).  When several
    files name the same host, the lexicographically last wins (incident
    captures are numbered, so later incidents shadow earlier ones)."""
    out: dict[int, dict] = {}
    dd = Path(d)
    if not dd.is_dir():
        return out
    for p in sorted(dd.glob(glob)):
        host = host_id_from_path(p)
        if host is None:
            continue
        header, samples, skipped = read_flight_file(p)
        if header is None and not samples:
            continue
        out[host] = {"header": header, "samples": samples,
                     "skipped": skipped, "path": str(p)}
    return out
