#!/usr/bin/env python
"""Podracer RL plane benchmark (tpucfn.rl), ONE JSON line out in the
standard BENCH row schema — rc-gated.

Two legs over the identical workload (same env, same policy net, same
number of updates), which isolates exactly what co-location buys:

* **co-located** (the headline): the real plane — rollout is ONE jitted
  ``lax.scan`` program on the mesh, the slab goes through the on-device
  replay ring, and param refresh is a device-to-device copy.  Produces
  ``rl_env_steps_per_sec``.
* **host-roundtrip reference**: the layout Anakin replaced — a host
  loop drives the env one step at a time (separate jit dispatches for
  policy and env step, reward synced to host every step), assembles the
  trajectory slab host-side, feeds the learner via host transfer, and
  refreshes actor params through a device→host→device bounce.

Gates (rc 1 on violation):

* co-located env-steps/s >= ``--min-ratio`` x the host-roundtrip
  reference (the co-location floor; default 1.5x holds easily on the
  8-fake-device CPU mesh because dispatch+sync overhead dominates).
* mean device-to-device refresh latency <= ``--refresh-budget-ms``
  (regression alarm for the copy program growing a host bounce or a
  recompile; steady-state is sub-millisecond for the bench policy).

Compile warmup is excluded from every timed window (bench.py's rule):
each leg's programs run once on their exact shapes before timing.

``vs_baseline`` is 0.0: the reference repo was a supervised-training
harness with no RL number to compare against.

Usage: python benches/rl_bench.py [--quick] [--iters 30 ...]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def _build(args):
    from tpucfn.mesh import MeshSpec, build_mesh
    from tpucfn.rl import Actor, ReplayQueue, RLLearner, make_env

    mesh = build_mesh(MeshSpec.for_devices(jax.device_count()))
    env = make_env(args.env, args.num_envs)
    learner = RLLearner(mesh, env, hidden=args.hidden)
    actor = Actor(env, learner.apply_fn, unroll=args.unroll)
    queue = ReplayQueue(capacity=2)
    return mesh, env, learner, actor, queue


def _colocated_leg(args, mesh, env, learner, actor, queue):
    """The real plane: scan rollout -> device ring -> learner -> d2d
    refresh, in the loop's exact mesh layout (actor plane pinned via
    ``actor_plane_shardings`` — un-pinned inputs would make GSPMD
    re-shard around every rollout and wreck the number).
    Returns (env_steps_per_s, refresh_latencies_s)."""
    from tpucfn.rl.loop import actor_plane_shardings

    env_sh, slot_sh, repl = actor_plane_shardings(mesh, env.num_envs)
    root = jax.random.key(args.seed)
    state = learner.init(jax.random.fold_in(root, 0))
    es, obs = actor.reset(jax.random.fold_in(root, 1))
    es, obs = jax.device_put((es, obs), env_sh)
    params = learner.refresh(state)
    # warmup: compile every program on its exact shapes + shardings
    es_w, obs_w, traj = actor.rollout(params, es, obs,
                                      jax.random.fold_in(root, 2))
    qs = queue.init_state(traj)
    qs = {k: jax.device_put(v, slot_sh if k == "slots" else repl)
          for k, v in qs.items()}
    qs = queue.push(qs, traj)
    qs, slab = queue.pop(qs)
    state, _ = learner.step(state, slab)
    params = learner.refresh(state)
    jax.block_until_ready(params)

    refresh_lat = []
    t0 = time.perf_counter()
    for it in range(args.iters):
        es, obs, traj = actor.rollout(params, es, obs,
                                      jax.random.fold_in(root, 3 + it))
        qs = queue.push(qs, traj)
        qs, slab = queue.pop(qs)
        state, metrics = learner.step(state, slab)
        r0 = time.perf_counter()
        params = learner.refresh(state)
        jax.block_until_ready(params)
        refresh_lat.append(time.perf_counter() - r0)
        jax.block_until_ready(metrics["loss"])
    wall = time.perf_counter() - t0
    steps = args.iters * actor.steps_per_rollout
    return steps / wall, refresh_lat


def _host_roundtrip_leg(args, mesh, env, learner):
    """The pre-Anakin layout: host drives every env step, the slab and
    the refreshed params both bounce through host memory.  Everything
    still lives on the SAME mesh in the same (replicated) layout as the
    co-located leg — on a real pod the host-driven actor doesn't get a
    smaller device footprint, it gets per-step dispatch and sync on the
    same one — so the legs differ only in orchestration."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpucfn.rl.learner import mlp_apply

    repl = NamedSharding(mesh, P())
    apply_j = jax.jit(mlp_apply)
    step_j = jax.jit(env.step)
    sample_j = jax.jit(
        lambda k, logits: jax.random.categorical(k, logits))
    root = jax.random.key(args.seed)
    state = learner.init(jax.random.fold_in(root, 0))

    def host_refresh(state):
        # device -> host -> device: what refresh() exists to avoid
        return jax.device_put(jax.tree.map(np.asarray, state.params), repl)

    params = host_refresh(state)
    es, obs = jax.jit(env.reset)(jax.random.fold_in(root, 1))
    es, obs = jax.device_put((es, obs), repl)

    def host_rollout(params, es, obs, key):
        cols = {k: [] for k in ("obs", "action", "reward", "done", "value")}
        for t in range(args.unroll):
            logits, value = apply_j(params, obs)
            k_act, k_env = jax.random.split(jax.random.fold_in(key, t))
            action = sample_j(k_act, logits)
            es2, obs2, reward, done = step_j(es, action, k_env)
            # the host loop inspects progress every step: a forced sync
            cols["obs"].append(np.asarray(obs))
            cols["action"].append(np.asarray(action))
            cols["reward"].append(np.asarray(reward))
            cols["done"].append(np.asarray(done))
            cols["value"].append(np.asarray(value))
            es, obs = es2, obs2
        traj = {k: np.stack(v, axis=1) for k, v in cols.items()}
        _, bootstrap = apply_j(params, obs)
        traj["bootstrap"] = np.asarray(bootstrap)
        return es, obs, traj

    # warmup (same programs, exact shapes)
    es_w, obs_w, traj = host_rollout(params, es, obs,
                                     jax.random.fold_in(root, 2))
    state, _ = learner.step(state, jax.device_put(traj))
    params = host_refresh(state)

    t0 = time.perf_counter()
    for it in range(args.iters):
        es, obs, traj = host_rollout(params, es, obs,
                                     jax.random.fold_in(root, 3 + it))
        state, metrics = learner.step(state, jax.device_put(traj))
        params = host_refresh(state)
        jax.block_until_ready(metrics["loss"])
    wall = time.perf_counter() - t0
    steps = args.iters * args.unroll * args.num_envs
    return steps / wall


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--env", choices=["bandit", "gridworld"],
                   default="gridworld")
    p.add_argument("--num-envs", type=int, default=8)
    p.add_argument("--unroll", type=int, default=32)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--min-ratio", type=float, default=1.5,
                   help="rc gate: co-located steps/s must beat the "
                        "host-roundtrip reference by this factor")
    p.add_argument("--refresh-budget-ms", type=float, default=50.0,
                   help="rc gate: mean d2d refresh latency bound")
    p.add_argument("--quick", action="store_true",
                   help="CI sizing (fewer iterations, same gates)")
    args = p.parse_args()
    if args.quick:
        args.iters = min(args.iters, 10)

    mesh, env, learner, actor, queue = _build(args)
    colocated_sps, refresh_lat = _colocated_leg(args, mesh, env, learner,
                                                actor, queue)
    host_sps = _host_roundtrip_leg(args, mesh, env, learner)

    ratio = colocated_sps / host_sps if host_sps > 0 else float("inf")
    refresh_mean_ms = 1e3 * float(np.mean(refresh_lat))
    refresh_p50_ms = 1e3 * float(np.percentile(refresh_lat, 50))
    ratio_ok = ratio >= args.min_ratio
    refresh_ok = refresh_mean_ms <= args.refresh_budget_ms
    ok = ratio_ok and refresh_ok

    print(f"# rl_bench colocated={colocated_sps:.0f} steps/s "
          f"host_roundtrip={host_sps:.0f} steps/s ratio={ratio:.2f} "
          f"(floor {args.min_ratio}) refresh_mean={refresh_mean_ms:.3f}ms "
          f"(budget {args.refresh_budget_ms}ms) ok={ok}", file=sys.stderr)
    row = {
        "metric": "rl_env_steps_per_sec",
        "value": round(colocated_sps, 1),
        "unit": "steps/s",
        "vs_baseline": 0.0,
        "detail": {
            "baseline_note": "reference harness was supervised-training "
                             "only; no RL throughput number exists",
            "ok": ok,
            "env": args.env,
            "num_envs": args.num_envs,
            "unroll": args.unroll,
            "iters": args.iters,
            "devices": jax.device_count(),
            "colocated_steps_per_s": round(colocated_sps, 1),
            "host_roundtrip_steps_per_s": round(host_sps, 1),
            "colocation_ratio": round(ratio, 3),
            "min_ratio": args.min_ratio,
            "ratio_ok": ratio_ok,
            "refresh_mean_ms": round(refresh_mean_ms, 4),
            "refresh_p50_ms": round(refresh_p50_ms, 4),
            "refresh_budget_ms": args.refresh_budget_ms,
            "refresh_ok": refresh_ok,
        },
    }
    print(json.dumps(row))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
