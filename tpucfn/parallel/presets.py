"""Sharding-rule presets for the supported parallelism strategies.

SURVEY.md §2.3 is the contract: the reference shipped synchronous DP in two
flavors (parameter-server ``dist_sync`` and Horovod ring all-reduce); the
rebuild must additionally provide FSDP, TP, PP, SP and EP as first-class
axes. DP/FSDP/TP/EP are pure sharding-rule presets (this module); PP and SP
need program structure too and live in :mod:`tpucfn.parallel.pipeline` /
:mod:`tpucfn.kernels.ring_attention`.

Conventions the rules match against (models in :mod:`tpucfn.models` follow
them):

* dense / conv kernels: ``.../kernel`` with shape ``(..., in, out)``
* attention projections: ``qkv`` or ``q_proj|k_proj|v_proj`` (out = heads),
  ``o_proj`` (in = heads)
* MLP: ``up_proj|gate_proj`` (out = ffn), ``down_proj`` (in = ffn)
* embeddings: ``embedding`` with shape ``(vocab, model)``
* MoE experts: ``experts/...`` with a leading expert dim
"""

from __future__ import annotations

from jax.sharding import PartitionSpec as P

from tpucfn.mesh import AXIS_EXPERT, AXIS_FSDP, AXIS_TENSOR
from tpucfn.parallel.sharding import ShardingRules

_REPLICATED_TAIL = ((r".*", P()),)


def dense_rules(fsdp: bool = False) -> ShardingRules:
    """Rules for conv/dense vision models (ResNet family).

    Pure DP replicates everything — the TPU equivalent of the reference's
    ``dist_sync``/Horovod placement (SURVEY.md §2.3 rows 1-2). With
    ``fsdp=True``, the largest dim of each kernel shards over the fsdp axis
    (ZeRO-3 style); XLA all-gathers per layer and reduce-scatters grads.
    """
    if not fsdp:
        return ShardingRules(_REPLICATED_TAIL)
    return ShardingRules(
        (
            # conv kernels (H, W, Cin, Cout): shard Cout.
            (r"conv.*/kernel$", P(None, None, None, AXIS_FSDP)),
            (r"(dense|head|fc).*/kernel$", P(None, AXIS_FSDP)),
        )
        + _REPLICATED_TAIL
    )


def transformer_rules(
    fsdp: bool = True,
    tensor: bool = True,
    expert: bool = True,
) -> ShardingRules:
    """Megatron-style TP composed with FSDP for transformer families
    (BERT, Llama, and the UNet's attention blocks).

    TP: column-parallel qkv/up projections (shard out-features over
    ``tensor``), row-parallel o/down projections (shard in-features) so the
    only TP collective per block is the psum XLA inserts after the
    row-parallel matmul. FSDP shards the *other* kernel dim, composing
    orthogonally. Embedding shards vocab over tensor (XLA handles the
    masked gather + psum that Megatron hand-codes).
    """
    t = AXIS_TENSOR if tensor else None
    f = AXIS_FSDP if fsdp else None
    e = AXIS_EXPERT if expert else None
    return ShardingRules(
        (
            # MoE experts: leading expert dim over the expert axis, then the
            # usual TP split on the trailing matmul dims.
            (r"experts/.*(up|gate)_proj/kernel$", P(e, f, t)),
            (r"experts/.*down_proj/kernel$", P(e, t, f)),
            (r"router/kernel$", P(f, None)),
            # Attention: qkv column-parallel (heads on tensor), o row-parallel.
            (r"(qkv|q_proj|k_proj|v_proj)/kernel$", P(f, t)),
            (r"o_proj/kernel$", P(t, f)),
            # MLP: up/gate column-parallel, down row-parallel.
            (r"(up_proj|gate_proj|fc1|wi(_\d+)?)/kernel$", P(f, t)),
            (r"(down_proj|fc2|wo)/kernel$", P(t, f)),
            # Embedding + unembed: vocab over tensor, model dim over fsdp.
            (r"(embedding|embed_tokens).*/embedding$", P(t, f)),
            (r"(lm_head|unembed)/kernel$", P(f, t)),
            # Biases / norm scales attached to a TP-sharded output.
            (r"(qkv|q_proj|k_proj|v_proj|up_proj|gate_proj|fc1|wi(_\d+)?)/bias$", P(t)),
            # Everything else (norm scales, small biases): replicated.
        )
        + _REPLICATED_TAIL
    )


def zero1_rules(model_rules: ShardingRules | None = None) -> ShardingRules:
    """ZeRO-1: replicated params, optimizer state sharded over ``fsdp``.

    The "dist_sync compat at scale" preset (SURVEY.md §2.3 row 1: PS-style
    API maps onto sharded-optimizer DP): forward/backward see replicated
    params (no per-layer all-gathers like full FSDP), but the optimizer
    moments — 2× param memory under Adam — shard over the fsdp axis.

    Mechanism: optax state mirrors the param tree under ``mu``/``nu``/
    ``trace``, so each sharded rule of ``model_rules`` (default: the
    fsdp dense preset) is re-scoped to those subtrees; bare param paths
    fall through to the replicated tail.
    """
    model_rules = model_rules or dense_rules(fsdp=True)
    opt_scoped = tuple(
        ((r"(^|/)(mu|nu|trace)/.*" + pat.lstrip("^")), spec)
        for pat, spec in model_rules.rules
        if tuple(spec) != ()
    )
    return ShardingRules(opt_scoped + _REPLICATED_TAIL)


PRESETS = {
    "dp": lambda: dense_rules(fsdp=False),
    "fsdp_dense": lambda: dense_rules(fsdp=True),
    "zero1": lambda: zero1_rules(),
    "transformer": lambda: transformer_rules(),
    "transformer_tp_only": lambda: transformer_rules(fsdp=False),
    "transformer_fsdp_only": lambda: transformer_rules(tensor=False),
}
