"""Worker for the fleet warm-start ft drill (ISSUE 13 acceptance).

Each incarnation: configure the compile-cache client from the launcher
env (with a per-process-unique LOCAL store, so a relaunch cannot
store-hit and must go through the FLEET server), run one warm-jitted
step under TrainerObs + GoodputLedger, append the computed value to a
results file, and crash (rc 1) on the first attempt so the coordinator
gang-restarts.  The test then asserts the relaunched incarnation's
ledger window charged ``compile_fetched`` (not ``compile``) and the
two attempts' values are bit-identical.
"""

import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    work = Path(os.environ["CC_DRILL_DIR"])
    host = int(os.environ.get("TPUCFN_HOST_ID", "0") or 0)
    # per-incarnation local store: a relaunch must FETCH from the fleet
    # server, never shortcut through the shared local artifact dir
    os.environ["TPUCFN_COMPILE_CACHE_DIR"] = str(
        work / f"store-{os.getpid()}")

    import numpy as np

    import jax
    import jax.numpy as jnp

    from tpucfn.compilecache import configure_from_env
    from tpucfn.compilecache.jit import maybe_warm
    from tpucfn.obs.goodput import GoodputLedger
    from tpucfn.obs.profiler import CompileCacheProbe
    from tpucfn.obs.registry import MetricRegistry
    from tpucfn.train.trainer import TrainerObs

    probe = CompileCacheProbe(work / "xla-cache")
    client = configure_from_env(probe=probe)
    assert client is not None, "drill env must carry the cache fan-out"

    def fn(x):
        h = x
        for _ in range(8):
            h = jnp.tanh(h @ h.T) @ h
        return h.sum()

    step = maybe_warm(jax.jit(fn), label="ft_drill")
    ledger = GoodputLedger(work / "goodput", host)
    obs = TrainerObs(MetricRegistry(), ledger=ledger, compile_probe=probe)
    x = np.full((16, 16), 0.01, np.float32)
    with obs.step(1):
        out = float(step(x))
    ledger.close()

    with open(work / f"results-host{host}.jsonl", "a") as f:
        f.write(json.dumps({"pid": os.getpid(), "value": out,
                            "outcome": client.last_outcome}) + "\n")

    flag = work / f"crashed-{host}"
    if not flag.exists():
        flag.write_text(str(os.getpid()))
        return 1  # first incarnation crashes: the coordinator restarts
    return 0


if __name__ == "__main__":
    sys.exit(main())
