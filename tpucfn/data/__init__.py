from tpucfn.data.records import RecordShardWriter, read_record_shard, write_dataset_shards  # noqa: F401
from tpucfn.data.pipeline import ShardedDataset, prefetch_to_mesh  # noqa: F401
from tpucfn.data.synthetic import (  # noqa: F401
    synthetic_cifar10,
    synthetic_imagenet,
    synthetic_latents,
    synthetic_tokens,
)
