#!/usr/bin/env python
"""BERT-base MLM pretraining (BASELINE config 3: "Horovod→JAX launcher
path, all-reduce over ICI").

The reference ran BERT through Horovod's ``mpirun`` + NCCL all-reduce
(SURVEY.md §3.3); here the same one-command launch produces a single SPMD
program whose gradient all-reduce XLA emits over ICI. Masking is applied
on the fly per step (15% positions, 80/10/10 mask/random/keep).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))
from common import (  # noqa: E402
    add_cluster_args,
    build_example_mesh,
    per_process_batch,
    run_train_loop,
    stage_synthetic,
)


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    add_cluster_args(p)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--num-examples", type=int, default=512)
    p.add_argument("--mask-prob", type=float, default=0.15)
    p.add_argument("--tiny", action="store_true", help="tiny config (CI)")
    args = p.parse_args()

    from tpucfn.launch import initialize_runtime

    initialize_runtime()

    import jax
    import jax.numpy as jnp
    import optax

    from tpucfn.data import ShardedDataset
    from tpucfn.models import Bert, BertConfig, mlm_loss
    from tpucfn.parallel import transformer_rules
    from tpucfn.train import Trainer

    cfg = BertConfig.tiny() if args.tiny else BertConfig.base()
    run_dir = Path(args.run_dir)
    shards = stage_synthetic(
        "tokens", run_dir / "data", n=args.num_examples,
        num_shards=max(8, jax.process_count()), seed=args.seed,
        seq_len=args.seq_len, vocab=cfg.vocab_size,
    )

    mesh = build_example_mesh(args)
    model = Bert(cfg)
    sample = jnp.zeros((1, args.seq_len), jnp.int32)
    MASK_ID = 3

    def init_fn(rng):
        return model.init(rng, sample)["params"], {}

    def loss_fn(params, mstate, batch, rng):
        tokens = batch["tokens"]
        r1, r2, r3 = jax.random.split(rng, 3)
        mask = jax.random.uniform(r1, tokens.shape) < args.mask_prob
        swap = jax.random.uniform(r2, tokens.shape)
        randoms = jax.random.randint(r3, tokens.shape, 0, cfg.vocab_size)
        masked = jnp.where(mask & (swap < 0.8), MASK_ID, tokens)
        masked = jnp.where(mask & (swap >= 0.8) & (swap < 0.9), randoms, masked)
        logits = model.apply({"params": params}, masked, train=True,
                             rngs={"dropout": rng})
        loss, acc = mlm_loss(logits, tokens, mask)
        return loss, ({"accuracy": acc}, mstate)

    total = args.steps or 1000
    tx = optax.adamw(
        optax.warmup_cosine_decay_schedule(0.0, 1e-4, max(1, min(100, total // 10)),
                                           total),
        weight_decay=0.01,
    )
    trainer = Trainer(
        mesh, transformer_rules(tensor=args.tensor > 1), loss_fn, tx, init_fn
    )
    ds = ShardedDataset(shards, batch_size_per_process=per_process_batch(args),
                        seed=args.seed)
    run_train_loop(trainer, ds, mesh, args, items_per_step=args.batch_size)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
