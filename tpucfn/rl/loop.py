"""The fleet-integrated Podracer loop: act → push → pop → learn → refresh.

This is the RL analogue of ``examples/common.run_train_loop`` — one
host's whole life, wired into every plane the harness has:

* **obs** — registry metrics (``rl_*`` on the per-host ``/metrics``
  endpoint), trace spans per phase, a flight-recorder ring, and the
  goodput ledger with the RL phases first-class: ``act`` / ``learn`` /
  ``refresh`` buckets next to ``compile`` and ``ckpt``, so
  ``tpucfn obs goodput`` decomposes an RL run's wall clock the same way
  it does a supervised one.
* **ft** — heartbeats (``TPUCFN_FT_DIR`` fan-out), resume-from-latest
  on startup, ``RESTORE_FAILED_RC`` on a corrupt checkpoint (the
  coordinator's blacklist-and-retry path), drain-request honoring, and
  ``rl_run_start`` / ``rl_resumed`` event rows.
* **chaos coherence** — everything the next iteration depends on
  (learner TrainState, env state + obs, queue ring, iteration counter)
  is ONE checkpointed pytree, and every per-iteration random choice is
  derived from ``fold_in(root, iteration)``; a gang-killed host that
  restores at iteration k replays iterations k+1..N bit-for-bit.

Per-iteration results append to ``rl-host{NNN}.jsonl`` (loss, return,
queue counters, pid) — the pinned trajectory the recovery drill diffs
against an uninterrupted reference.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import time
from pathlib import Path

import jax


@dataclasses.dataclass(frozen=True)
class RLConfig:
    """One RL run, as the CLI / examples / benches configure it."""

    run_dir: str = "/tmp/tpucfn-rl"
    env: str = "bandit"          # tpucfn.rl.env.ENVS name
    num_envs: int = 8            # must divide the mesh's dp degree
    unroll: int = 16             # env steps per rollout slab
    iters: int = 100             # learner updates (the run budget)
    hidden: int = 64
    lr: float = 1e-2
    gamma: float = 0.99
    entropy_coef: float = 0.01
    seed: int = 0
    ckpt_every: int = 25
    log_every: int = 10
    queue_capacity: int = 4
    stop_after: int = 0          # halt at iter N without changing budget
    fresh: bool = False
    iter_sleep_s: float = 0.0    # drill pacing: sleep per iter (idle time)


class RLObs:
    """TrainerObs's phase discipline for the RL loop's phases.

    ``act`` / ``learn`` / ``refresh`` each land a registry metric, a
    trace span, a goodput-ledger row, and a flight sample.  The first
    iteration's act+learn wall time is compile-dominated and charged to
    the ``compile`` bucket (the StepTimer warmup-exclusion rule), so
    steady-state ``act``/``learn`` shares stay honest.
    """

    def __init__(self, registry=None, tracer=None, *, ledger=None,
                 flight=None, clock=time.monotonic):
        from tpucfn.obs.goodput import GoodputLedger
        from tpucfn.obs.registry import default_registry
        from tpucfn.obs.trace import Tracer

        r = self.registry = (registry if registry is not None
                             else default_registry())
        self.tracer = tracer if tracer is not None else Tracer(None)
        self.ledger = ledger if ledger is not None else GoodputLedger(None)
        self.flight = flight
        self.clock = clock
        self.act_time = r.histogram(
            "rl_act_seconds", "actor rollout wall time (one slab)")
        self.learn_time = r.histogram(
            "rl_learn_seconds", "learner update wall time (one slab)")
        self.refresh_time = r.summary(
            "rl_refresh_seconds",
            "actor param refresh wall time (device-to-device copy)")
        self.iters_total = r.counter(
            "rl_iterations_total", "completed act+learn+refresh iterations")
        self.env_steps_total = r.counter(
            "rl_env_steps_total", "env steps advanced across all envs")
        self.spilled_total = r.counter(
            "rl_spilled_total",
            "trajectory slabs spilled to host memory (queue overflow)")
        self.return_g = r.gauge(
            "rl_episode_return", "mean per-step reward of the last slab")
        self.last_iter_g = r.gauge("rl_last_iter", "most recent iteration")
        self.queue_depth_g = r.gauge(
            "rl_queue_depth", "slabs queued (device ring + host spill)")
        self._iters_seen = 0
        self._compile_s = 0.0

    @contextlib.contextmanager
    def phase(self, name: str, metric, it: int | None):
        t0 = self.clock()
        try:
            yield
        finally:
            dt = self.clock() - t0
            metric.observe(dt)
            self.tracer.record(name, start=t0, dur_s=dt, trace_id=it)
            if self._iters_seen == 0 and name in ("act", "learn", "refresh"):
                # first iteration: compile-dominated, charged as compile
                self.ledger.account("compile", dt, step=it)
            else:
                self.ledger.account(name, dt, step=it)
            if self.flight is not None:
                self.flight.record(name, step=it, dur_s=dt)

    def act(self, it):
        return self.phase("act", self.act_time, it)

    def learn(self, it):
        return self.phase("learn", self.learn_time, it)

    def refresh(self, it):
        return self.phase("refresh", self.refresh_time, it)

    def ckpt(self, it):
        return self.phase("ckpt", self.ckpt_time, it)

    @property
    def ckpt_time(self):
        return self.registry.summary(
            "rl_ckpt_seconds", "checkpoint save-call time")

    def iteration_done(self, it: int, env_steps: int) -> None:
        self._iters_seen += 1
        self.iters_total.add()
        self.env_steps_total.add(env_steps)
        self.last_iter_g.set(it)


def _host_id() -> int:
    """Rank inside a launch fan-out: the launcher's env contract wins
    (each fanned-out process runs its own jax runtime on CPU drills, so
    ``jax.process_index()`` alone cannot tell ranks apart there)."""
    env = os.environ.get("TPUCFN_HOST_ID", "").strip()
    if env:
        try:
            return int(env)
        except ValueError:
            pass
    return jax.process_index()


def _abstract_like(tree):
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(
            x.shape, x.dtype, sharding=getattr(x, "sharding", None)),
        tree)


def actor_plane_shardings(mesh, num_envs):
    """Placement of the actor plane on the mesh, Anakin layout.

    Returns ``(env_sh, slot_sh, repl)``: env state/obs SHARDED over the
    batch axes (each device acts its own env slice; params stay
    replicated, so the rollout has no cross-device traffic) and queue
    ring slots sharded the same way on their post-capacity axis — which
    also makes the popped slab already match the trainer's batch
    sharding.  Falls back to replicated when ``num_envs`` doesn't divide
    the data-parallel degree.  Pinning these is not just layout hygiene:
    un-pinned (uncommitted, single-device) inputs make GSPMD re-shard
    the rollout around every call, and the checkpoint manager
    rematerializes the saved tree in one jit, which rejects mixed
    single-device/mesh trees.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    from tpucfn.mesh import BATCH_AXES

    repl = NamedSharding(mesh, PartitionSpec())
    dp = 1
    for ax in BATCH_AXES:
        dp *= mesh.shape[ax]
    if num_envs % dp == 0:
        env_sh = NamedSharding(mesh, PartitionSpec(BATCH_AXES))
        slot_sh = NamedSharding(mesh, PartitionSpec(None, BATCH_AXES))
    else:
        env_sh = slot_sh = repl
    return env_sh, slot_sh, repl


def run_rl_loop(cfg: RLConfig):
    """Run one host's Podracer loop to completion; returns final stats."""
    import jax.numpy as jnp

    from tpucfn.compilecache import configure_from_env
    from tpucfn.mesh import MeshSpec, build_mesh
    from tpucfn.obs import (FlightRecorder, Tracer, set_default_labels,
                            start_obs_server)
    from tpucfn.obs.goodput import GoodputLedger
    from tpucfn.rl.actor import Actor
    from tpucfn.rl.env import make_env
    from tpucfn.rl.learner import RLLearner
    from tpucfn.rl.replay import ReplayQueue

    host = _host_id()
    run_dir = Path(cfg.run_dir)
    run_dir.mkdir(parents=True, exist_ok=True)
    ft_dir = os.environ.get("TPUCFN_FT_DIR", "").strip()

    mesh = build_mesh(MeshSpec.for_devices(jax.device_count()))
    env = make_env(cfg.env, cfg.num_envs)
    learner = RLLearner(mesh, env, hidden=cfg.hidden, lr=cfg.lr,
                        gamma=cfg.gamma, entropy_coef=cfg.entropy_coef)
    actor = Actor(env, learner.apply_fn, unroll=cfg.unroll)
    queue = ReplayQueue(cfg.queue_capacity)

    tracer = obs_srv = hb = ledger = None
    registry = set_default_labels(host=str(host), role="rl")
    try:
        tracer = Tracer(run_dir / "trace", host_id=host, role="rl")
        ledger = GoodputLedger(run_dir / "goodput", host_id=host, role="rl")
        flight = FlightRecorder(host_id=host, role="rl")
        flight.install_dump_handlers(run_dir / "flight")
        configure_from_env(tracer=tracer, registry=registry)
        obs = RLObs(registry, tracer, ledger=ledger, flight=flight)
        obs_srv = start_obs_server(
            registry, role="rl", host_id=host,
            health_fn=lambda: (True, {"iter": obs.last_iter_g.value}),
            flight=flight)
        if ft_dir:
            from tpucfn.ft import HeartbeatWriter

            try:
                hb_s = float(os.environ.get("TPUCFN_FT_HEARTBEAT_S", "")
                             or 1.0)
            except ValueError:
                hb_s = 1.0
            hb = HeartbeatWriter(ft_dir, host_id=host, interval_s=hb_s,
                                 role="rl").start()
        return _rl_loop_body(cfg, host, run_dir, ft_dir, mesh, env, learner,
                             actor, queue, obs, hb, jnp)
    finally:
        if hb is not None:
            hb.stop()
        if tracer is not None:
            tracer.close()
        if ledger is not None:
            ledger.close()
        if obs_srv is not None:
            obs_srv.close()


def _rl_loop_body(cfg, host, run_dir, ft_dir, mesh, env, learner, actor,
                  queue, obs, hb, jnp):
    from tpucfn.ckpt import CheckpointManager
    from tpucfn.ft import RESTORE_FAILED_RC, drain_requested

    root = jax.random.key(cfg.seed)
    # One checkpointable pytree per host: learner TrainState + actor-side
    # env state/obs + queue ring + the iteration counter.  Saves are
    # synchronous (tiny states) so a finalized step on disk is always a
    # coherent whole-stack snapshot — the chaos drill's resume anchor.
    with CheckpointManager(run_dir / "ckpt", async_save=False,
                           save_interval_steps=cfg.ckpt_every) as ckpt:
        state = learner.init(jax.random.fold_in(root, 0))
        env_state, env_obs = actor.reset(jax.random.fold_in(root, 1))
        env_sh, slot_sh, repl = actor_plane_shardings(mesh, env.num_envs)
        env_state, env_obs = jax.device_put((env_state, env_obs), env_sh)
        qstate = queue.init_state(_example_slab(actor, learner, state,
                                                env_state, env_obs, root))
        qstate = jax.device_put(qstate, {
            k: (jax.tree.map(lambda _: slot_sh, v) if k == "slots" else repl)
            for k, v in qstate.items()})
        full = {"train": state, "env": env_state, "obs": env_obs,
                "queue": qstate,
                "iter": jax.device_put(jnp.zeros((), jnp.int32), repl)}
        latest = None if cfg.fresh else ckpt.latest_step()
        resumed = None
        if latest is not None:
            try:
                full = ckpt.restore(_abstract_like(full))
            except Exception as e:  # noqa: BLE001 — corrupt artifact
                # Distinguishable rc: the coordinator blacklists the bad
                # step and relaunches to retry from the previous one.
                print(f"rl checkpoint restore of step {latest} failed: {e}",
                      flush=True)
                raise SystemExit(RESTORE_FAILED_RC)
            resumed = latest
            print(f"rl resumed from iteration {int(full['iter'])}",
                  flush=True)
        if ft_dir and host == 0:
            from tpucfn.ft.events import append_event

            if resumed is None:
                append_event(ft_dir, "rl_run_start", env=cfg.env,
                             iters=cfg.iters, num_envs=cfg.num_envs,
                             unroll=cfg.unroll)
            else:
                append_event(ft_dir, "rl_resumed",
                             iteration=int(full["iter"]), ckpt_step=resumed)

        state, env_state, env_obs, qstate = (
            full["train"], full["env"], full["obs"], full["queue"])
        it = int(full["iter"])
        halt = min(cfg.iters, cfg.stop_after) if cfg.stop_after else cfg.iters
        rows = run_dir / f"rl-host{host:03d}.jsonl"
        metrics = {}
        # actors start from the current learner params — on a resumed run
        # that is the RESTORED policy, not a fresh one (refresh is the
        # only path params ever take to the actor plane)
        actor_params = learner.refresh(state)
        with open(rows, "a") as rows_f:
            while it < halt:
                it += 1
                # -- act: one on-device rollout slab -----------------------
                with obs.act(it):
                    env_state, env_obs, traj = actor.rollout(
                        actor_params, env_state, env_obs,
                        jax.random.fold_in(root, 2 + it))
                    jax.block_until_ready(traj["reward"])
                qstate = queue.push(qstate, traj)
                obs.queue_depth_g.set(queue.size(qstate))
                if queue.spilled_total > obs.spilled_total.value:
                    obs.spilled_total.add(queue.spilled_total
                                          - obs.spilled_total.value)
                # -- learn: pop oldest slab, one A2C update ----------------
                qstate, slab = queue.pop(qstate)
                with obs.learn(it):
                    state, metrics = learner.step(state, slab)
                    jax.block_until_ready(metrics["loss"])
                # -- refresh: device-to-device param copy to the actors ----
                with obs.refresh(it):
                    actor_params = learner.refresh(state)
                    jax.block_until_ready(actor_params)
                obs.return_g.set(float(metrics["reward_mean"]))
                obs.iteration_done(it, actor.steps_per_rollout)
                if hb is not None:
                    hb.update_step(it)
                rows_f.write(json.dumps({
                    "iter": it, "pid": os.getpid(),
                    "loss": float(metrics["loss"]),
                    "reward_mean": float(metrics["reward_mean"]),
                    "entropy": float(metrics["entropy"]),
                    "pushed": int(qstate["pushed"]),
                    "popped": int(qstate["popped"])}) + "\n")
                rows_f.flush()
                if it % cfg.log_every == 0 or it == halt:
                    print(f"iter={it} loss={float(metrics['loss']):.4f} "
                          f"reward={float(metrics['reward_mean']):.4f}",
                          flush=True)
                # -- checkpoint: whole-stack snapshot at queue quiescence --
                if host == 0:
                    queue.assert_quiescent()
                    full = {"train": state, "env": env_state,
                            "obs": env_obs, "queue": qstate,
                            "iter": jax.device_put(
                                jnp.asarray(it, jnp.int32), repl)}
                    t0 = time.monotonic()
                    if ckpt.save(it, full):
                        obs.ckpt_time.observe(time.monotonic() - t0)
                        obs.tracer.record("ckpt", start=t0,
                                          dur_s=time.monotonic() - t0,
                                          trace_id=it)
                        obs.ledger.account("ckpt", time.monotonic() - t0,
                                           step=it)
                if cfg.iter_sleep_s:
                    time.sleep(cfg.iter_sleep_s)
                if ft_dir and drain_requested(ft_dir, it):
                    print(f"preemption drain: stopping cleanly at "
                          f"iteration {it}", flush=True)
                    break
            if host == 0:
                queue.assert_quiescent()
                full = {"train": state, "env": env_state, "obs": env_obs,
                        "queue": qstate,
                        "iter": jax.device_put(
                            jnp.asarray(it, jnp.int32), repl)}
                ckpt.save(it, full, force=True)

    loss = float(metrics.get("loss", float("nan"))) if metrics else \
        float("nan")
    reward = float(metrics.get("reward_mean", float("nan"))) if metrics \
        else float("nan")
    print(f"final: step={it} loss={loss:.4f} reward={reward:.4f}",
          flush=True)
    return {"iter": it, "loss": loss, "reward_mean": reward,
            "spilled": queue.spilled_total}


def _example_slab(actor, learner, state, env_state, env_obs, root):
    """Shape template for the queue ring — one abstract rollout, no
    device work (eval_shape), materialized as zeros by the queue."""
    import jax.numpy as jnp

    params = jax.eval_shape(lambda s: s.params, state)
    out = jax.eval_shape(actor._rollout_fn, params, env_state, env_obs,
                         jax.random.fold_in(root, 2))
    traj = out[2]
    return jax.tree.map(lambda a: jnp.zeros(a.shape, a.dtype), traj)
