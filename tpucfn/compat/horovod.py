"""Horovod-shaped compat surface.

The reference's TF path wrapped everything in Horovod (SURVEY.md §3.3):
``hvd.init()``, rank/size queries, ``DistributedOptimizer`` hooking a
tensor-fusion NCCL all-reduce behind the optimizer. On tpucfn the SPMD
program *is* the distribution, so these become thin queries/no-ops with
the same signatures — a port of a Horovod-era script keeps its structure
and loses the wrapper cost.

    import tpucfn.compat.horovod as hvd
    hvd.init()                      # jax.distributed via the env contract
    hvd.rank(), hvd.size()          # process index / count
    hvd.local_rank()                # host-local index (always 0: one
                                    # process drives all local chips)
    tx = hvd.DistributedOptimizer(optax.adam(1e-3))  # identity: psum is
                                    # already in the compiled step
"""

from __future__ import annotations

import optax


def init() -> None:
    from tpucfn.launch import initialize_runtime

    initialize_runtime()


def rank() -> int:
    import jax

    return jax.process_index()


def size() -> int:
    import jax

    return jax.process_count()


def local_rank() -> int:
    # One tpucfn process drives every local chip (vs Horovod's
    # process-per-GPU), so the local rank is always 0.
    return 0


def DistributedOptimizer(tx: optax.GradientTransformation, **_ignored) -> optax.GradientTransformation:
    """Identity: gradient averaging is part of the jit-compiled step (the
    batch is sharded, so XLA emits the psum Horovod's hook existed to
    provide)."""
    return tx


def broadcast_parameters(*args, **kwargs) -> None:
    """No-op: Trainer.init creates params *born sharded/replicated* on
    their target devices; there is no rank-0 copy to broadcast."""
