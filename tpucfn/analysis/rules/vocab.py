"""vocab-drift: stringly-typed vocabularies stay on their canonical set.

The repo's control planes speak in string literals: ft incident events
(``events.jsonl`` rows with a ``kind``), the goodput ledger's record
kinds, flight-dump headers, and ``ServeRequest.status`` terminal values.
Before ISSUE 10 these were scattered literals across coordinator /
router / frontend / postmortem — one typo'd emitter or consumer and an
event silently never matches (the same drift ``HB_GLOB`` was introduced
to stop for heartbeat file names in PR 5).

Ground truth is read from the package itself, by ast — no imports:
module-level tuples of strings whose name ends in ``_KINDS`` (e.g.
``EVENT_KINDS`` in ``ft/events.py``, ``LEDGER_KINDS`` in
``obs/goodput.py``) and the ``REQUEST_STATUSES`` tuple in
``serve/frontend.py``.  The rule then flags:

* ``x._event("lit", ...)`` emitters whose literal is outside
  ``EVENT_KINDS`` — a kind nothing will ever match;
* comparisons of ``rec.get("kind")`` / ``rec["kind"]`` / a bare ``kind``
  variable against a literal outside the union of every ``*_KINDS``
  vocabulary — a consumer waiting for an event that never comes;
* ``.status`` assignments/comparisons (and ``status="lit"`` keywords)
  whose literal is outside ``REQUEST_STATUSES``.

A package that defines no canonical tuples gets no findings — the rule
activates the moment the vocabulary is centralized.
"""

from __future__ import annotations

import ast

from tpucfn.analysis.core import Analysis, Finding

RULE_ID = "vocab-drift"


def _collect_vocab(analysis: Analysis):
    kinds_union: set[str] = set()
    event_kinds: set[str] | None = None
    statuses: set[str] | None = None
    for mod in analysis.modules:
        for node in mod.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if not isinstance(t, ast.Name):
                    continue
                vals = _str_tuple(node.value)
                if vals is None:
                    continue
                if t.id.endswith("_KINDS"):
                    kinds_union.update(vals)
                    if t.id == "EVENT_KINDS":
                        event_kinds = set(vals)
                elif t.id == "REQUEST_STATUSES":
                    statuses = set(vals)
    return event_kinds, kinds_union or None, statuses


def _str_tuple(node: ast.expr) -> list[str] | None:
    if not isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return None
    out = []
    for e in node.elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            out.append(e.value)
        else:
            return None
    return out


def _is_field_lookup(e: ast.expr, field: str) -> bool:
    """``x.get("<field>")`` / ``x["<field>"]``."""
    if isinstance(e, ast.Call) and isinstance(e.func, ast.Attribute) \
            and e.func.attr == "get" and e.args \
            and isinstance(e.args[0], ast.Constant) \
            and e.args[0].value == field:
        return True
    return (isinstance(e, ast.Subscript)
            and isinstance(e.slice, ast.Constant)
            and e.slice.value == field)


def _lookup_bound_names(scope_stmts, field: str) -> set[str]:
    """Variable names assigned from a ``["<field>"]`` lookup inside this
    scope — ``kind = e.get("kind")`` binds ``kind`` as a kind variable,
    while an unrelated local that happens to be called ``kind`` (a lock
    kind, a dataset kind) stays out of the vocabulary check."""
    out: set[str] = set()
    for stmt in scope_stmts:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Assign):
                continue
            values = [node.value]
            if isinstance(node.value, ast.Tuple):
                values = list(node.value.elts)
            srcs = [any(_is_field_lookup(v, field)
                        for v in ast.walk(val) if isinstance(v, ast.expr))
                    for val in values]
            targets = node.targets
            if len(targets) == 1 and isinstance(targets[0], ast.Tuple):
                targets = targets[0].elts
            for t in targets:
                if isinstance(t, ast.Name) and any(srcs):
                    if t.id == field:
                        out.add(t.id)
    return out


def _compared_literals(node: ast.Compare, match) -> list[str]:
    """String literals compared (==, !=, in, not in) against a matching
    lookup expression."""
    sides = [node.left, *node.comparators]
    if not any(match(s) for s in sides):
        return []
    out: list[str] = []
    for s in sides:
        if isinstance(s, ast.Constant) and isinstance(s.value, str):
            out.append(s.value)
        elif isinstance(s, (ast.Tuple, ast.List, ast.Set)):
            vals = _str_tuple(s)
            if vals:
                out.extend(vals)
    return out


def _scope_walk(body):
    """All nodes of a scope's statements, without descending into
    nested function/class definitions (those are their own scopes)."""
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        stack: list[ast.AST] = [stmt]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            yield n
            stack.extend(ast.iter_child_nodes(n))


def check(analysis: Analysis):
    event_kinds, kinds_union, statuses = _collect_vocab(analysis)
    findings: list[Finding] = []

    def bad(mod, line, msg, key):
        findings.append(Finding(RULE_ID, mod.rel, line, msg, key=key))

    for mod in analysis.modules:
        # ServeRequest.status is the serve plane's vocabulary; other
        # planes have their own status-shaped fields (GCP op states).
        check_status = statuses is not None and "serve/" in mod.rel
        scopes = [mod.tree.body]
        for qual, info in analysis.functions(mod).items():
            if not isinstance(info.node, ast.Lambda):
                scopes.append(info.node.body)
        for body in scopes:
            kind_vars = (_lookup_bound_names(body, "kind")
                         if kinds_union is not None else set())
            status_vars = (_lookup_bound_names(body, "status")
                           if check_status else set())

            def is_kind(e: ast.expr) -> bool:
                if _is_field_lookup(e, "kind"):
                    return True
                return isinstance(e, ast.Name) and e.id in kind_vars

            def is_status(e: ast.expr) -> bool:
                if isinstance(e, ast.Attribute) and e.attr == "status":
                    return True
                if _is_field_lookup(e, "status"):
                    return True
                return isinstance(e, ast.Name) and e.id in status_vars

            for node in _scope_walk(body):
                if event_kinds is not None and isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "_event" and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    lit = node.args[0].value
                    if lit not in event_kinds:
                        bad(mod, node.lineno,
                            f"event kind {lit!r} is not in the canonical "
                            "EVENT_KINDS tuple — consumers matching on "
                            "kind will never see it (add it to "
                            "EVENT_KINDS or fix the typo)",
                            f"event:{lit}")
                if isinstance(node, ast.Compare):
                    if kinds_union is not None:
                        for lit in _compared_literals(node, is_kind):
                            if lit not in kinds_union:
                                bad(mod, node.lineno,
                                    f"kind literal {lit!r} is outside "
                                    "every canonical *_KINDS vocabulary "
                                    "— this comparison can never match "
                                    "an emitted record",
                                    f"kind:{lit}")
                    if check_status:
                        for lit in _compared_literals(node, is_status):
                            if lit not in statuses:
                                bad(mod, node.lineno,
                                    f"status literal {lit!r} is outside "
                                    "the canonical REQUEST_STATUSES "
                                    "tuple",
                                    f"status:{lit}")
                if not check_status:
                    continue
                lit = None
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Attribute) \
                        and node.targets[0].attr == "status" \
                        and isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, str):
                    lit = node.value.value
                elif isinstance(node, ast.keyword) \
                        and node.arg == "status" \
                        and isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, str):
                    lit = node.value.value
                if lit is not None and lit not in statuses:
                    bad(mod, node.value.lineno,
                        f"status literal {lit!r} is outside the "
                        "canonical REQUEST_STATUSES tuple — routers and "
                        "tests branching on status will never match it",
                        f"status:{lit}")
    return findings
