"""Learner glue: a Trainer-backed A2C policy-gradient update.

The learner IS a :class:`tpucfn.train.Trainer` — same sharding rules
engine, same jit/donation discipline, same checkpoint layout, same
``maybe_warm`` fleet warm-start hook — bound to an actor-critic loss
over the trajectory slabs the replay queue hands over.  Nothing about
the train plane had to change to host an RL workload; that is the
point of the exercise.

Parameter refresh to the actors is a **device-to-device copy** (one
jitted identity program), never a checkpoint round-trip.  The copy is
not an optimization nicety — it is required for correctness: the
trainer's step donates the state buffers, so actors holding the raw
``state.params`` references would read freed memory one update later.
``refresh`` gives the actor plane its own buffers in the actor-side
(replicated) sharding.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from tpucfn.parallel.sharding import ShardingRules
from tpucfn.train.trainer import Trainer, TrainerConfig

from tpucfn.rl.actor import _maybe_warm


# -- policy/value network (pure-jax MLP; no framework dependency) ----------

def mlp_init(key: jax.Array, obs_dim: int, num_actions: int,
             hidden: int = 64):
    """Two-layer torso with separate policy and value heads."""
    k1, k2, k3 = jax.random.split(key, 3)

    def dense(k, n_in, n_out):
        scale = jnp.sqrt(2.0 / n_in)
        return {"kernel": jax.random.normal(k, (n_in, n_out),
                                            jnp.float32) * scale,
                "bias": jnp.zeros((n_out,), jnp.float32)}

    return {"torso": dense(k1, obs_dim, hidden),
            "pi": dense(k2, hidden, num_actions),
            "v": dense(k3, hidden, 1)}


def mlp_apply(params, obs):
    """``obs [..., obs_dim] -> (logits [..., A], value [...])``."""
    h = jnp.tanh(obs @ params["torso"]["kernel"] + params["torso"]["bias"])
    logits = h @ params["pi"]["kernel"] + params["pi"]["bias"]
    value = (h @ params["v"]["kernel"] + params["v"]["bias"])[..., 0]
    return logits, value


# -- A2C loss over [B, T] trajectory slabs ---------------------------------

def make_a2c_loss(gamma: float = 0.99, value_coef: float = 0.5,
                  entropy_coef: float = 0.01):
    """Loss in the Trainer's ``(params, model_state, batch, rng)``
    signature.  ``batch`` is one replay slab: ``obs [B,T,obs_dim]``,
    ``action/reward/done [B,T]``, ``bootstrap [B]``.  Returns are
    n-step discounted-to-go with the bootstrap value closing the
    truncated tail; ``done`` cuts the discount chain at episode ends.
    """

    def loss_fn(params, model_state, batch, rng):
        del rng  # the update is deterministic given the slab
        obs, action = batch["obs"], batch["action"]
        reward, done = batch["reward"], batch["done"]
        logits, values = mlp_apply(params, obs)  # [B,T,A], [B,T]

        def disc(carry, xs):
            r, d = xs
            ret = r + gamma * jnp.where(d, 0.0, carry)
            return ret, ret

        # reverse-time scan per env: time axis to front, flip, scan
        r_t = jnp.swapaxes(reward, 0, 1)[::-1]  # [T,B]
        d_t = jnp.swapaxes(done, 0, 1)[::-1]
        _, rets = jax.lax.scan(disc, batch["bootstrap"], (r_t, d_t))
        returns = jnp.swapaxes(rets[::-1], 0, 1)  # [B,T]

        logp = jax.nn.log_softmax(logits)
        logp_a = jnp.take_along_axis(logp, action[..., None],
                                     axis=-1)[..., 0]
        adv = jax.lax.stop_gradient(returns - values)
        pg_loss = -jnp.mean(logp_a * adv)
        v_loss = jnp.mean((returns - values) ** 2)
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp) * logp, axis=-1))
        loss = pg_loss + value_coef * v_loss - entropy_coef * entropy
        aux = {"pg_loss": pg_loss, "v_loss": v_loss, "entropy": entropy,
               "reward_mean": jnp.mean(reward)}
        return loss, (aux, model_state)

    return loss_fn


class RLLearner:
    """Binds env shape + A2C loss into a Trainer, plus the refresh copy.

    The tiny policy net replicates across the mesh (catch-all ``P()``
    rule); the trajectory batch shards over the batch axes exactly like
    a supervised batch — ``num_envs`` must divide the mesh's
    data-parallel degree.
    """

    def __init__(self, mesh, env, *, hidden: int = 64, lr: float = 1e-2,
                 gamma: float = 0.99, value_coef: float = 0.5,
                 entropy_coef: float = 0.01, seed_split: int = 0):
        del seed_split  # reserved for multi-learner variants
        self.mesh = mesh
        self.env = env
        self.apply_fn = mlp_apply

        def init_fn(rng):
            return mlp_init(rng, env.obs_dim, env.num_actions, hidden), {}

        self.trainer = Trainer(
            mesh, ShardingRules(((r".*", P()),)),
            make_a2c_loss(gamma, value_coef, entropy_coef),
            optax.adam(lr), init_fn, TrainerConfig(donate_state=True))
        self._jit_refresh = None

    # -- Trainer pass-throughs --------------------------------------------

    def init(self, rng: jax.Array):
        return self.trainer.init(rng)

    def step(self, state, slab):
        """One A2C update on a replay slab; Trainer's jitted/donating/
        warm-startable step underneath.  The slab leaves the replay ring
        with the actor-side layout; resharding onto the trainer's batch
        spec is a device-to-device move, never a host bounce."""
        slab = jax.device_put(slab, self.trainer.batch_sharding())
        return self.trainer.step(state, slab)

    def abstract_state(self) -> Any:
        return self.trainer.abstract_state()

    # -- actor param refresh ----------------------------------------------

    def refresh(self, state):
        """Actor-side copy of the current policy params.

        One jitted elementwise copy, device to device, output pinned to
        the replicated actor sharding — fresh XLA buffers, so the
        trainer's donation of ``state`` cannot invalidate what the
        actors hold, and no checkpoint (or host) round-trip happens on
        the refresh path."""
        if self._jit_refresh is None:
            repl = NamedSharding(self.mesh, P())
            self._jit_refresh = _maybe_warm(jax.jit(
                lambda p: jax.tree.map(jnp.copy, p),
                out_shardings=repl), "rl_refresh")
        return self._jit_refresh(state.params)
