"""Automatic dense↔flash attention dispatch (VERDICT r2 item 3/weak 5).

The Pallas flash kernel is the right default above a sequence-length
threshold on TPU; XLA dense attention is the right default everywhere
else (short S, CPU tests, masked/bidirectional shapes the kernel does
not support). This module owns that policy so models and ring hops
share one rule:

* ``should_use_flash(s)`` — True iff the backend is TPU and
  ``s >= flash_threshold()``.
* ``flash_threshold()`` — ``TPUCFN_FLASH_MIN_S`` (default 2048, now
  MEASURED, r3 on a v5e with the shipped autotuned block table
  (kernels/flash_tune_builtin.json): fwd+bwd vs XLA dense 1.16x at
  S=2k, 2.19x/1.65x at 4k, 38.6x/2.9x at 8k, flash-only at 32k (dense
  OOMs). On device kinds without a tuned table entry the 128/128
  default blocks lose the backward at 2k (0.64x) — run
  ``flash_autotune.tune`` once per device generation, or raise the env
  var to 4096 where tuning isn't an option).

Dispatch sites:
* :class:`tpucfn.models.llama.Llama` with ``attention_fn=None`` (the
  default) resolves here per call — flash only when the call's
  ``q_offset`` is the static 0 of the non-sequence-parallel path (the
  kernel takes static offsets; SP shards use ring attention instead).
* :func:`tpucfn.kernels.ring_attention.ring_attention` with
  ``hop_attention="auto"`` (the default) routes each hop through the
  flash kernel by the same rule on the LOCAL shard length.
"""

from __future__ import annotations

import os


def flash_threshold() -> int:
    return int(os.environ.get("TPUCFN_FLASH_MIN_S", "2048"))


def _backend() -> str:
    import jax

    try:
        return jax.default_backend()
    except Exception:  # noqa: BLE001 — backend init failure → be safe
        return "cpu"


def should_use_flash(s: int, *, causal: bool = True, mask=None) -> bool:
    """One policy for every dispatch site. ``s`` must be a static int
    (trace-time shape)."""
    if mask is not None or not causal:
        return False  # kernel supports causal/segment masking only
    return _backend() == "tpu" and int(s) >= flash_threshold()


def should_use_flash_full(s_q: int, s_kv: int, *, mask=None) -> bool:
    """Non-causal (full) attention policy: the dense path materializes a
    (B, H, s_q, s_kv) score tensor, so flash pays when BOTH sides are
    long (a 77-key cross-attention's scores are tiny — dense wins).
    Observed on chip: SD-UNet's 64x64 spatial self-attention (s=4096)
    OOMs dense at batch 8 via 4G fp32 score temps."""
    if mask is not None:
        return False
    t = flash_threshold()
    return _backend() == "tpu" and int(s_q) >= t and int(s_kv) >= t


def full_attention_auto(q, k, v, *, mask=None):
    """Dense↔flash dispatch for non-causal attention call sites (UNet
    spatial/cross attention). Layout (B, S, H, D) like every AttentionFn."""
    if should_use_flash_full(q.shape[1], k.shape[1], mask=mask):
        from tpucfn.kernels.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=False)
    from tpucfn.ops.attention import dot_product_attention

    return dot_product_attention(q, k, v, causal=False, mask=mask)


def auto_attention_static_zero(q, k, v, *, causal=True, mask=None,
                               q_offset=0, k_offset=0):
    """AttentionFn for call sites whose offsets are STATICALLY zero but
    arrive as traced zeros (Llama's scan carry, the PP stage body):
    dispatches on the local (trace-time) sequence length and DROPS the
    traced zero offsets when taking the flash path — the kernel takes
    static offsets. The caller is responsible for only installing this
    where q_offset/k_offset are provably zero."""
    if mask is None and should_use_flash(q.shape[1], causal=causal):
        from tpucfn.kernels.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal)
    from tpucfn.ops.attention import dot_product_attention

    return dot_product_attention(q, k, v, causal=causal, mask=mask,
                                 q_offset=q_offset, k_offset=k_offset)


def auto_attention(q, k, v, *, causal=True, mask=None, q_offset=0,
                   k_offset=0, segment_ids=None):
    """AttentionFn-shaped dispatcher for call sites whose offsets are
    static Python ints (bench harnesses, direct use). Model integration
    goes through Llama's attention_fn=None resolution instead, because
    scan carries make in-model offsets traced."""
    from tpucfn.kernels.flash_attention import flash_attention
    from tpucfn.ops.attention import dot_product_attention

    static_offsets = isinstance(q_offset, int) and isinstance(k_offset, int)
    if static_offsets and should_use_flash(q.shape[1], causal=causal,
                                           mask=mask):
        return flash_attention(q, k, v, causal=causal, q_offset=q_offset,
                               k_offset=k_offset, segment_ids=segment_ids)
    if segment_ids is not None:
        raise NotImplementedError(
            "segment_ids on the dense fallback path is not wired; pass an "
            "explicit mask or use flash_attention directly")
    return dot_product_attention(q, k, v, causal=causal, mask=mask,
                                 q_offset=q_offset, k_offset=k_offset)
