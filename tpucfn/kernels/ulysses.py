"""Ulysses-style sequence parallelism: all-to-all head scatter.

The alternative SP mode (SURVEY.md §2.3 "Ulysses"): instead of rotating
KV around a ring, one all-to-all swaps the sharded axis — sequence-sharded
activations become head-sharded just for the attention op, each device
computes *full-sequence* attention for its subset of heads, and a second
all-to-all swaps back. Two collectives per attention total; on the ICI
torus an all-to-all is cheap, and the attention math itself needs no
modification (any inner impl works on the gathered sequence).

Constraint: kv heads must be divisible by the context-axis size (heads
are the unit being scattered).
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpucfn.mesh import AXIS_CONTEXT, AXIS_TENSOR, BATCH_AXES


def make_ulysses_attention(
    mesh: Mesh,
    *,
    seq_axis: str = AXIS_CONTEXT,
    heads_axis: str | None = AXIS_TENSOR,
    batch_axes: Sequence[str] = BATCH_AXES,
    inner: Callable | None = None,
):
    """``inner=None`` uses the shared dense↔flash auto policy
    (tpucfn.kernels.auto) on the GATHERED sequence length — the
    all-to-all hands each device the full sequence for its head subset,
    which is exactly the long-S regime the flash kernel exists for."""
    if inner is None:
        from tpucfn.kernels.auto import auto_attention_static_zero

        inner = auto_attention_static_zero
    spec = P(tuple(batch_axes), seq_axis, heads_axis)

    def attention_fn(q, k, v, *, causal=True, mask=None, q_offset=0, k_offset=0):
        if mask is not None:
            raise NotImplementedError("ulysses attention is causal-only here")

        def body(q_, k_, v_):
            n = lax.axis_size(seq_axis)
            if q_.shape[2] % n or k_.shape[2] % n:
                raise ValueError(
                    f"heads {q_.shape[2]}/{k_.shape[2]} not divisible by "
                    f"context axis {n} — use ring attention instead"
                )
            # (B, S/n, H, D) -> (B, S, H/n, D): scatter heads, gather seq
            a2a = lambda x: lax.all_to_all(  # noqa: E731
                x, seq_axis, split_axis=2, concat_axis=1, tiled=True
            )
            out = inner(a2a(q_), a2a(k_), a2a(v_), causal=causal)
            # (B, S, H/n, D) -> (B, S/n, H, D)
            return lax.all_to_all(out, seq_axis, split_axis=1, concat_axis=2,
                                  tiled=True)

        fn = jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                           out_specs=spec, check_vma=False)
        return fn(q, k, v)

    return attention_fn
