"""Typed, named-axis collective wrappers.

One comm stack instead of the reference's three (ps-lite ZMQ for PS traffic,
OpenMPI for rendezvous, NCCL for the collective data path — SURVEY.md §2.4):
everything here lowers to XLA collectives that ride ICI inside a slice and
DCN across slices. These wrappers only run inside ``shard_map``/``pmap``
axis contexts; under plain ``jit`` + ``NamedSharding``, XLA inserts the
equivalent collectives automatically and user code never calls these.

They exist because raw ``lax`` collectives have sharp edges we want checked
once (tuple axes, tiled vs stacked all_gather, ppermute's pair format), and
so the parallelism layers (ring attention, pipeline, MoE dispatch) read as
intent rather than lax incantations.
"""

from __future__ import annotations

from typing import Sequence

import jax
from jax import lax

AxisName = str | Sequence[str]


def axis_index(axis: str) -> jax.Array:
    """This shard's coordinate along ``axis`` (0-based)."""
    return lax.axis_index(axis)


def axis_size(axis: str) -> int:
    """Static size of a mesh axis from inside a mapped computation."""
    return lax.axis_size(axis)


def psum(x, axis: AxisName):
    """Sum across ``axis``. The gradient all-reduce that replaces both of
    the reference's DP flavors: ps-lite push/pull and NCCL ring all-reduce
    (SURVEY.md §3.2/§3.3 hot loops)."""
    return lax.psum(x, axis)


def pmean(x, axis: AxisName):
    """Mean across ``axis`` — gradient averaging, metric reduction."""
    return lax.pmean(x, axis)


def pmax(x, axis: AxisName):
    return lax.pmax(x, axis)


def all_gather(x, axis: str, *, tiled: bool = True, gather_axis: int = 0):
    """Gather shards along ``axis``; ``tiled=True`` concatenates on
    ``gather_axis`` (FSDP param gather), ``tiled=False`` stacks a new
    leading axis."""
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reduce_scatter(x, axis: str, *, scatter_axis: int = 0):
    """Sum across ``axis`` then keep this shard's slice of ``scatter_axis``
    — FSDP gradient reduction at 1/N the bytes of a full psum."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)


def ring_permute(x, axis: str, *, shift: int = 1):
    """Rotate shards around ``axis`` as a ring: shard i's value goes to
    shard (i + shift) % N. The building block of ring attention's KV
    rotation and pipeline stage hand-off; maps to neighbor ICI hops on the
    torus."""
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def all_to_all(x, axis: str, *, split_axis: int, concat_axis: int):
    """Scatter ``split_axis`` across ``axis`` while gathering the axis into
    ``concat_axis`` — Ulysses head-scatter and MoE expert dispatch."""
    return lax.all_to_all(x, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=True)
