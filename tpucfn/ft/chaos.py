"""Deterministic fault injection — the chaos harness the recovery plane
is tested with.

A :class:`ChaosSpec` is a declarative schedule ("at second T / at fleet
step N: kill host k, hang host k for D seconds, delay heartbeats, corrupt
the latest checkpoint").  A :class:`ChaosEngine` replays it against a
:class:`ChaosTarget`:

* the gang coordinator's real subprocesses (SIGKILL / SIGSTOP+SIGCONT)
  — ft/coordinator.py implements the target over its process table;
* :class:`~tpucfn.provision.control_plane.FakeControlPlane` via
  :class:`ControlPlaneChaosTarget` (``kill_host`` flips the host record
  unhealthy, exercising the provisioning-side monitor/heal path).

Every random choice (unpinned victim host, corruption byte offsets)
comes from a ``random.Random`` seeded by the spec — no wall-clock
randomness anywhere, so a chaos run replays bit-for-bit (ISSUE 4
tentpole).  Time itself is injectable: the engine never reads a clock,
it is *told* the elapsed time and fleet step on each ``tick``.
"""

from __future__ import annotations

import dataclasses
import json
import random
import re
from pathlib import Path
from typing import Any

ACTIONS = ("kill", "hang", "delay_heartbeats", "corrupt_ckpt",
           "preempt_notice", "lose_host",
           # serve-tier ops (ISSUE 9): fired against a ReplicaRouter —
           # `host` addresses the replica index on serve targets
           "kill_replica", "freeze_replica", "slow_replica",
           # crash-safety op (ISSUE 12): SIGKILL the supervisor itself —
           # the fleet must survive its watchman dying (`host` unused)
           "kill_coordinator",
           # network gray-failure ops (ISSUE 15): injected through
           # tpucfn.net.proxy.ChaosProxy instances registered on the
           # target — `host` (optional) is a PROXY index, not a fleet
           # member; unpinned means every registered proxy
           "net_latency", "net_throttle", "net_stall", "net_partition",
           "net_tear", "net_rst", "net_clear")

# Actions that do not target a fleet member: an unpinned `host` must
# NOT draw a victim from the seeded RNG for them, or the spec's other
# events would resolve different victims depending on whether one of
# these precedes them.
_HOSTLESS_ACTIONS = ("corrupt_ckpt", "kill_coordinator",
                     "net_latency", "net_throttle", "net_stall",
                     "net_partition", "net_tear", "net_rst", "net_clear")


@dataclasses.dataclass(frozen=True)
class ChaosEvent:
    """One scheduled fault.  Fires when EITHER trigger is reached:
    ``at_s`` (seconds since the engine's first tick) or ``at_step``
    (fleet max step).  ``host=None`` lets the seeded RNG pick a victim
    at fire time.

    Graceful-degradation ops (ISSUE 7): ``preempt_notice`` raises an
    advance preemption notice for the host (``duration_s`` doubles as
    the notice's lead seconds); ``lose_host`` kills the host AND marks
    it un-reacquirable, so the coordinator's next relaunch must shrink
    to N-1 instead of bringing it back; ``corrupt_ckpt`` with ``step``
    set corrupts that specific step instead of the latest.

    Serve-tier ops (ISSUE 9, fired against a
    :class:`~tpucfn.serve.router.ReplicaRouter`): ``kill_replica``
    fails the replica's serve loop (its in-flight requests complete
    with ReplicaFailed and the router fails over); ``freeze_replica``
    stalls the serve loop — and its heartbeats — for ``duration_s``
    (0 = until unfrozen); ``slow_replica`` adds ``delay_s`` of latency
    to every step for ``duration_s``."""

    action: str
    at_s: float | None = None
    at_step: int | None = None
    host: int | None = None
    duration_s: float = 0.0  # hang / delay_heartbeats / preempt lead / freeze
    step: int | None = None  # corrupt_ckpt: target step (None = latest)
    delay_s: float = 0.0     # slow_replica / net_latency: injected latency
    rate_bps: float = 0.0    # net_throttle: forwarding rate (trickle)
    direction: str = "both"  # net_*: "up" | "down" | "both"
    after_bytes: int | None = None  # net_tear/net_stall arming offset

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(
                f"unknown chaos action {self.action!r}; one of {ACTIONS}")
        if self.at_s is None and self.at_step is None:
            raise ValueError("chaos event needs at_s and/or at_step")
        if self.direction not in ("up", "down", "both"):
            raise ValueError(
                f"bad direction {self.direction!r}; one of up/down/both")
        # net_* parameter validation happens HERE, at spec construction
        # — a bad launch-level spec must fail at parse time (rc 2), not
        # unwind the live coordinator's supervision loop (and kill the
        # gang) when the event fires minutes into the run.
        if self.action == "net_latency" and self.delay_s <= 0:
            raise ValueError("net_latency needs delay_s > 0")
        if self.action == "net_throttle" and self.rate_bps <= 0:
            raise ValueError("net_throttle needs rate_bps > 0")

    def to_json(self) -> dict:
        return {k: v for k, v in dataclasses.asdict(self).items()
                if v is not None
                and not (k in ("duration_s", "delay_s", "rate_bps")
                         and v == 0.0)
                and not (k == "direction" and v == "both")}


@dataclasses.dataclass(frozen=True)
class ChaosSpec:
    events: tuple[ChaosEvent, ...]
    seed: int = 0

    @classmethod
    def from_json(cls, obj: str | dict) -> "ChaosSpec":
        if isinstance(obj, str):
            obj = json.loads(obj)
        return cls(events=tuple(ChaosEvent(**e) for e in obj.get("events", ())),
                   seed=int(obj.get("seed", 0)))

    def to_json(self) -> dict:
        return {"seed": self.seed,
                "events": [e.to_json() for e in self.events]}


class ChaosTarget:
    """What the engine acts on.  Implementations: the coordinator's
    subprocess table, ControlPlaneChaosTarget, and test recorders."""

    def num_hosts(self) -> int:
        raise NotImplementedError

    def kill_host(self, host_id: int) -> None:
        raise NotImplementedError

    def hang_host(self, host_id: int) -> None:
        """Freeze the host (SIGSTOP for subprocesses) — heartbeats stop
        but the process stays alive, the HANG failure class."""
        raise NotImplementedError

    def resume_host(self, host_id: int) -> None:
        """Undo hang_host (SIGCONT) once the event's duration elapsed."""
        raise NotImplementedError

    def delay_heartbeats(self, host_id: int, duration_s: float) -> None:
        """Make the monitor see this host's heartbeats as stale without
        touching the process (detector-side fault)."""
        raise NotImplementedError

    def preempt_notice(self, host_id: int, lead_s: float) -> None:
        """Raise an advance preemption notice for the host — the
        graceful path: the coordinator should drain, not die."""
        raise NotImplementedError

    def lose_host(self, host_id: int) -> None:
        """Kill the host AND refuse to ever give it back (a permanently
        revoked machine) — the elastic-shrink trigger."""
        raise NotImplementedError

    def corrupt_latest_checkpoint(self, rng: random.Random,
                                  step: int | None = None) -> None:
        raise NotImplementedError

    # -- serve-tier ops (ISSUE 9: tpucfn.serve.router.ReplicaRouter) -------

    def kill_replica(self, replica: int) -> None:
        """Fail the replica's serve loop: in-flight requests complete
        with ReplicaFailed and the router's failover path takes over."""
        raise NotImplementedError

    def freeze_replica(self, replica: int, duration_s: float) -> None:
        """Stall the replica's serve loop (and its loop-driven
        heartbeats) for ``duration_s`` seconds (0 = indefinitely) —
        the serve-side HANG class."""
        raise NotImplementedError

    def slow_replica(self, replica: int, delay_s: float,
                     duration_s: float) -> None:
        """Add ``delay_s`` of latency to every serve step for
        ``duration_s`` seconds (0 = indefinitely) — the straggler
        class, the hedge path's reason to exist."""
        raise NotImplementedError

    # -- network gray-failure ops (ISSUE 15) --------------------------------

    def net_fault(self, proxy: int | None, kind: str, *,
                  duration_s: float, delay_s: float, rate_bps: float,
                  direction: str, after_bytes: int | None) -> None:
        """Inject one network fault (``kind`` is the short fault name —
        ``latency``/``throttle``/``stall``/``partition``/``tear``/
        ``rst``/``clear``) into the :class:`~tpucfn.net.proxy.
        ChaosProxy` at index ``proxy`` — or into EVERY registered proxy
        when unpinned.  Network faults are hostless by design: they
        target a transport plane, not a fleet member."""
        raise NotImplementedError

    # -- crash-safety op (ISSUE 12) -----------------------------------------

    def kill_coordinator(self) -> None:
        """SIGKILL the supervisor process itself, mid-supervision —
        the chaos op behind the kill-the-watchman drills: the fleet
        must keep training, and a ``--supervise`` relaunch must adopt
        it rather than restart it."""
        raise NotImplementedError


class ControlPlaneChaosTarget(ChaosTarget):
    """Replays kill events against the provisioning fake — the chaos
    path for the ``tpucfn heal`` / Provisioner.ensure_healthy state
    machine rather than live processes."""

    def __init__(self, control_plane, cluster_name: str):
        self.cp = control_plane
        self.name = cluster_name

    def num_hosts(self) -> int:
        return len(self.cp.describe(self.name).hosts)

    def kill_host(self, host_id: int) -> None:
        self.cp.kill_host(self.name, host_id)

    def lose_host(self, host_id: int) -> None:
        # On the control plane a kill IS a loss: the record flips
        # unhealthy and stays so until a re-acquire replaces the slice.
        self.cp.kill_host(self.name, host_id)


@dataclasses.dataclass
class FiredEvent:
    event: ChaosEvent
    host: int | None
    elapsed_s: float
    fleet_step: int | None


class ChaosEngine:
    """Replays one spec against one target.

    Call :meth:`tick` from the supervision loop with the elapsed wall
    seconds (since the run started) and the current fleet max step; the
    engine fires every due, not-yet-fired event in schedule order and
    schedules hang resumes.  Events and their resolved victims land in
    :attr:`fired` — the audit trail tests and benches assert on.
    """

    def __init__(self, spec: ChaosSpec, target: ChaosTarget, *,
                 rng: random.Random | None = None,
                 on_fire=None):
        self.spec = spec
        self.target = target
        self.rng = rng if rng is not None else random.Random(spec.seed)
        self._pending = list(spec.events)
        # spec index by identity (events may compare equal): the stable
        # name a durable journal can record a firing under, so a
        # restarted supervisor replays the spec minus what already fired
        # (ISSUE 12 — without this, an adopted run re-fires every event,
        # and a kill_coordinator spec would kill every incarnation).
        self._index = {id(e): i for i, e in enumerate(spec.events)}
        # on_fire(index, event, host) runs BEFORE the action is applied
        # — the write-ahead hook (a kill_coordinator must be journaled
        # before it kills the journaler).
        self.on_fire = on_fire
        self._resumes: list[tuple[float, int]] = []  # (due_elapsed_s, host)
        self.fired: list[FiredEvent] = []

    def skip_fired(self, indices) -> None:
        """Drop the pending events at these spec indices — they fired
        in a previous coordinator incarnation (journal-replayed)."""
        drop = set(indices)
        self._pending = [e for e in self._pending
                         if self._index[id(e)] not in drop]

    def done(self) -> bool:
        return not self._pending and not self._resumes

    def _due(self, ev: ChaosEvent, elapsed_s: float,
             fleet_step: int | None) -> bool:
        if ev.at_s is not None and elapsed_s >= ev.at_s:
            return True
        return (ev.at_step is not None and fleet_step is not None
                and fleet_step >= ev.at_step)

    def tick(self, elapsed_s: float, fleet_step: int | None = None) -> list[FiredEvent]:
        fired_now: list[FiredEvent] = []
        still = []
        for ev in self._pending:
            if not self._due(ev, elapsed_s, fleet_step):
                still.append(ev)
                continue
            host = ev.host
            if host is None and ev.action not in _HOSTLESS_ACTIONS:
                host = self.rng.randrange(self.target.num_hosts())
            rec = FiredEvent(ev, host, elapsed_s, fleet_step)
            if self.on_fire is not None:
                self.on_fire(self._index[id(ev)], ev, host)
            if ev.action == "kill":
                self.target.kill_host(host)
            elif ev.action == "hang":
                self.target.hang_host(host)
                if ev.duration_s > 0:
                    self._resumes.append((elapsed_s + ev.duration_s, host))
            elif ev.action == "delay_heartbeats":
                self.target.delay_heartbeats(host, ev.duration_s)
            elif ev.action == "preempt_notice":
                self.target.preempt_notice(host, ev.duration_s)
            elif ev.action == "lose_host":
                self.target.lose_host(host)
            elif ev.action == "kill_replica":
                self.target.kill_replica(host)
            elif ev.action == "freeze_replica":
                self.target.freeze_replica(host, ev.duration_s)
            elif ev.action == "slow_replica":
                self.target.slow_replica(host, ev.delay_s, ev.duration_s)
            elif ev.action == "kill_coordinator":
                self.target.kill_coordinator()
            elif ev.action.startswith("net_"):
                self.target.net_fault(
                    host, ev.action[len("net_"):],
                    duration_s=ev.duration_s, delay_s=ev.delay_s,
                    rate_bps=ev.rate_bps, direction=ev.direction,
                    after_bytes=ev.after_bytes)
            elif ev.action == "corrupt_ckpt":
                self.target.corrupt_latest_checkpoint(self.rng, step=ev.step)
            self.fired.append(rec)
            fired_now.append(rec)
        self._pending = still
        ripe = [r for r in self._resumes if elapsed_s >= r[0]]
        if ripe:
            self._resumes = [r for r in self._resumes if elapsed_s < r[0]]
            for _, host in ripe:
                self.target.resume_host(host)
        return fired_now


_STEP_DIR = re.compile(r"^\d+$")


def corrupt_latest_checkpoint(ckpt_dir: str | Path, rng: random.Random,
                              *, garbage_bytes: int = 256,
                              step: int | None = None) -> Path | None:
    """Overwrite the head of the largest file under the latest step's
    checkpoint directory with RNG garbage (and truncate there), so a
    subsequent restore fails loudly instead of resuming from silently
    wrong state.  ``step`` targets a specific finalized step instead of
    the latest (ISSUE 7: deterministic drills need to hit the exact
    checkpoint the retry path will blacklist).  Returns the corrupted
    path, or None when there is no matching checkpoint.

    Works on the Orbax layout (``<dir>/<step>/...``) but only assumes
    "numeric step subdirectories containing files".
    """
    d = Path(ckpt_dir)
    if not d.is_dir():
        return None
    steps = sorted((int(p.name), p) for p in d.iterdir()
                   if p.is_dir() and _STEP_DIR.match(p.name))
    if step is not None:
        steps = [(s, p) for s, p in steps if s == step]
    if not steps:
        return None
    _, latest = steps[-1]
    files = sorted(p for p in latest.rglob("*") if p.is_file())
    if not files:
        return None
    victim = max(files, key=lambda p: (p.stat().st_size, str(p)))
    junk = bytes(rng.randrange(256) for _ in range(garbage_bytes))
    with open(victim, "r+b") as f:
        f.write(junk)
        f.truncate(max(garbage_bytes, 1))
    return victim
