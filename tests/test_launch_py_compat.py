"""The reference's `launch.py -n N -H hostfile cmd` line works verbatim
through the compat entry point."""

import sys

from tpucfn.compat.launch_py import main


def test_launch_py_shape_fans_out(tmp_path):
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("127.0.0.1\n127.0.0.1\n127.0.0.1\n")
    marker = tmp_path / "out"
    marker.mkdir()
    rc = main([
        "-n", "2", "-H", str(hostfile), "--local", "--",
        sys.executable, "-c",
        "import os,pathlib;pathlib.Path("
        f"r'{marker}'"
        ").joinpath(os.environ['TPUCFN_HOST_ID']).write_text("
        "os.environ['DEEPLEARNING_WORKERS_COUNT'])",
    ])
    assert rc == 0
    # -n 2 launches exactly two ranks even though the hostfile lists 3
    assert sorted(p.name for p in marker.iterdir()) == ["0", "1"]
    assert (marker / "0").read_text() == "2"  # legacy env var exported


def test_launch_py_too_few_hosts(tmp_path, capsys):
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("127.0.0.1\n")
    rc = main(["-n", "4", "-H", str(hostfile), "--local", "--", "true"])
    assert rc == 2
    assert "hostfile has 1 hosts" in capsys.readouterr().err


def test_launch_py_no_command(tmp_path, capsys):
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("127.0.0.1\n")
    rc = main(["-n", "1", "-H", str(hostfile), "--local"])
    assert rc == 2
