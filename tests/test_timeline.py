"""Fleet timeline plane (ISSUE 20): clock-offset estimation with
injectable clocks, cross-host link resolution, Perfetto export and
critical-path determinism (same span files ⇒ byte-identical report),
the goodput cross-check, and the deadline-autotune advisory.

Everything is synthetic span dicts / JSONL in tmp dirs — no sockets,
no jax, milliseconds per test."""

import json
import struct

import pytest

from tpucfn.net.autotune import suggest_deadlines
from tpucfn.obs.timeline import (
    CROSS_HOST_SPAN_NAMES,
    PLANES,
    ClockProbe,
    critical_path,
    crosscheck_goodput,
    export_chrome_trace,
    fleet_skew,
    merge_timeline,
    probe_clock,
    read_clock_offsets,
    render_critpath,
    resolve_links,
    write_chrome_trace,
)
from tpucfn.obs.trace import Tracer, origin_id, read_trace_file


# -- clock probes (injectable clocks, zero sockets) -------------------------

def _fake_clocks(mono_seq, wall_t):
    """mono() pops from mono_seq; wall() returns the fixed wall_t."""
    seq = list(mono_seq)
    return (lambda: seq.pop(0)), (lambda: wall_t)


def test_probe_clock_offset_and_uncertainty():
    mono, wall = _fake_clocks([10.0, 10.2], 1000.0)
    pr = probe_clock("http://x/clock",
                     fetch=lambda u: {"wall": 1005.0, "host_id": 3,
                                      "role": "trainer"},
                     mono=mono, wall=wall)
    # local midpoint 1000.1; server 1005.0 -> offset 4.9, unc = rtt/2
    assert pr.offset_s == pytest.approx(4.9)
    assert pr.unc_s == pytest.approx(0.1)
    assert pr.rtt_s == pytest.approx(0.2)
    assert (pr.host, pr.role) == (3, "trainer")


def test_probe_clock_error_bounded_by_uncertainty():
    """Worst-case asymmetric halves: the estimate may be wrong, but by
    no more than the reported unc_s — the bound is the contract."""
    true_offset = 3.0
    for req_s, rsp_s in [(0.08, 0.02), (0.01, 0.09), (0.05, 0.05)]:
        rtt = req_s + rsp_s
        mono, wall = _fake_clocks([0.0, rtt], 100.0)
        # the server's wall read happens req_s after the local send
        server_wall = 100.0 + req_s + true_offset
        pr = probe_clock("http://x/clock",
                         fetch=lambda u, w=server_wall: {"wall": w},
                         mono=mono, wall=wall)
        assert abs(pr.offset_s - true_offset) <= pr.unc_s + 1e-12
        assert pr.unc_s == pytest.approx(rtt / 2)


def test_read_clock_offsets_min_uncertainty_wins(tmp_path):
    p = tmp_path / "clock-offsets.jsonl"
    rows = [
        {"kind": "clock_probe", "host": 0, "role": "trainer",
         "offset_s": 1.5, "unc_s": 0.20, "rtt_s": 0.4, "t": 1.0},
        {"kind": "clock_probe", "host": 0, "role": "trainer",
         "offset_s": 1.1, "unc_s": 0.05, "rtt_s": 0.1, "t": 2.0},
        {"kind": "other_record", "host": 0, "offset_s": 9.9, "unc_s": 0.0},
        {"kind": "clock_probe", "host": 9, "role": "input",
         "offset_s": -0.3, "unc_s": 0.02, "rtt_s": 0.04, "t": 2.0},
    ]
    p.write_text("".join(json.dumps(r) + "\n" for r in rows)
                 + "torn{line\n")
    offs = read_clock_offsets(p)
    assert offs["host0"]["offset_s"] == pytest.approx(1.1)  # tighter probe
    assert offs["host0"]["probes"] == 2
    assert offs["host9"]["offset_s"] == pytest.approx(-0.3)
    assert read_clock_offsets(tmp_path / "missing.jsonl") == {}


def _span(host, role, name, trace_id, span_id, ts, dur, rp=None, **attrs):
    e = {"kind": "span", "name": name, "trace_id": trace_id,
         "span_id": span_id, "parent_id": None, "start": ts, "dur_s": dur,
         "ts": ts, "mono": ts, "host": host, "role": role, "attrs": attrs}
    if rp is not None:
        e["rp"] = rp
    return e


def _step_spans(host, base, steps=3, shift=0.0):
    out = []
    for k in range(1, steps + 1):
        out.append(_span(host, "trainer", "step", k, 20 + k,
                         base + k + 0.2 + shift, 0.7))
    return out


def test_fleet_skew_probe_overrides_and_rebases():
    """Probes are relative to the prober's clock, the estimator to the
    fleet median — mixing must preserve relative drift while adopting
    the probes' reference."""
    events = _step_spans(0, 1000.0) + _step_spans(1, 1000.0, shift=0.5)
    est = fleet_skew(events)
    assert est["host1"] - est["host0"] == pytest.approx(0.5)
    probed = fleet_skew(events, {"host0": {"offset_s": 1.0, "unc_s": 0.01,
                                           "role": "trainer"}})
    assert probed["host0"] == pytest.approx(1.0)  # the measurement wins
    # the unprobed host keeps its relative drift, re-based to the probe
    assert probed["host1"] - probed["host0"] == pytest.approx(0.5)


# -- cross-host link resolution ---------------------------------------------

def _fleet_events(base=1000.0, steps=3, input_host=9):
    """One trainer (host 0) + one input host: per step a data_wait with
    an rp naming the input host's input_serve span, then step + ckpt —
    contiguous, so each step's attributed time equals its wall."""
    org = origin_id("input", input_host)
    ev = []
    for k in range(1, steps + 1):
        t0 = base + k
        ev.append(_span(input_host, "input", "input_serve", k - 1, 100 + k,
                        t0 - 0.05, 0.04, trainer=0))
        ev.append(_span(0, "trainer", "data_wait", k, 10 + k, t0, 0.2,
                        rp={"trace_id": k - 1, "span_id": 100 + k,
                            "origin": org}))
        ev.append(_span(0, "trainer", "step", k, 20 + k, t0 + 0.2, 0.7))
        ev.append(_span(0, "trainer", "ckpt", k, 30 + k, t0 + 0.9, 0.1))
    return ev


def _write_trace_dir(d, events):
    d.mkdir(parents=True, exist_ok=True)
    by = {}
    for e in events:
        by.setdefault((e["role"], e["host"]), []).append(e)
    for (role, host), evs in by.items():
        p = d / f"trace-{role}-host{host:03d}.jsonl"
        p.write_text("".join(json.dumps(e) + "\n" for e in evs))


def test_resolve_links_matches_rp_against_origin_index():
    events = _fleet_events(steps=3)
    links, stats = resolve_links(events)
    assert stats["carriers"] == 3 and stats["resolved"] == 3
    assert stats["unpinned"] == 0
    assert stats["by_name"]["data_wait"] == {"carriers": 3, "resolved": 3}
    for pi, ci in links:
        assert events[pi]["name"] == "input_serve"
        assert events[ci]["name"] == "data_wait"
        assert events[ci]["rp"]["span_id"] == events[pi]["span_id"]


def test_resolve_links_counts_unresolved_and_unpinned():
    events = _fleet_events(steps=2)
    events[1]["rp"]["span_id"] = 999  # dangling parent
    events.append(_span(0, "trainer", "mystery", 1, 77, 2000.0, 0.1,
                        rp={"trace_id": 1, "span_id": 101,
                            "origin": origin_id("input", 9)}))
    assert "mystery" not in CROSS_HOST_SPAN_NAMES
    links, stats = resolve_links(events)
    assert stats["carriers"] == 3
    assert stats["resolved"] == 2  # the dangler stays a carrier only
    assert stats["unpinned"] == 1


# -- critical path ----------------------------------------------------------

def test_critpath_attribution_planes_and_coverage(tmp_path):
    _write_trace_dir(tmp_path / "trace", _fleet_events(steps=3))
    merged = merge_timeline(tmp_path / "trace")
    cp = critical_path(merged)
    assert len(cp["steps"]) == 3
    for row in cp["steps"]:
        assert row["remote-serve"] == pytest.approx(0.2)
        assert row["input-local"] == 0.0
        assert row["compute"] == pytest.approx(0.7)
        assert row["ckpt"] == pytest.approx(0.1)
        assert row["bounded_by"] == "compute"
        # attributed within 10% of measured step wall (the rc gate)
        assert abs(row["coverage"] - 1.0) <= 0.10
    assert abs(cp["coverage_median"] - 1.0) <= 0.10
    # shares: 0.2/0.7/0.1 of each step
    assert cp["shares"]["compute"] == pytest.approx(0.7, abs=1e-3)
    assert cp["shares"]["remote-serve"] == pytest.approx(0.2, abs=1e-3)


def test_critpath_local_wait_without_link(tmp_path):
    events = [e for e in _fleet_events(steps=2) if e["host"] == 0]
    for e in events:
        e.pop("rp", None)  # local loader: no wire context
    _write_trace_dir(tmp_path / "trace", events)
    cp = critical_path(merge_timeline(tmp_path / "trace"))
    for row in cp["steps"]:
        assert row["input-local"] == pytest.approx(0.2)
        assert row["remote-serve"] == 0.0


def test_critpath_report_is_byte_identical(tmp_path):
    """Satellite 4's pin: same span files ⇒ byte-identical report and
    byte-identical Chrome trace (two directories, two invocations)."""
    events = _fleet_events(steps=3)
    outs = []
    for name in ("a", "b"):
        d = tmp_path / name
        _write_trace_dir(d / "trace", events)
        merged = merge_timeline(d / "trace")
        cp = critical_path(merged)
        text = render_critpath(cp, crosscheck_goodput(
            cp, {"buckets": {"productive_step": 7.0, "data_wait": 2.0,
                             "ckpt": 1.0, "compile_fetched": 0.0}}))
        trace_path = write_chrome_trace(merged, d / "timeline.json")
        outs.append((text, trace_path.read_bytes()))
    assert outs[0][0] == outs[1][0]
    assert outs[0][1] == outs[1][1]
    # and a re-run over the SAME dir reproduces itself
    merged2 = merge_timeline(tmp_path / "a" / "trace")
    assert render_critpath(critical_path(merged2)) == \
        render_critpath(critical_path(merge_timeline(tmp_path / "a" / "trace")))


def test_export_chrome_trace_flow_arrows_and_lanes(tmp_path):
    _write_trace_dir(tmp_path / "trace", _fleet_events(steps=3))
    merged = merge_timeline(tmp_path / "trace")
    doc = export_chrome_trace(merged)
    evs = doc["traceEvents"]
    lanes = {(e["pid"], e["args"]["name"]) for e in evs
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert lanes == {(0, "host0 (trainer)"), (9, "host9 (input)")}
    starts = [e for e in evs if e["ph"] == "s"]
    finishes = [e for e in evs if e["ph"] == "f"]
    assert len(starts) == len(finishes) == 3
    assert {e["id"] for e in starts} == {e["id"] for e in finishes}
    for f in finishes:
        assert f["bp"] == "e" and f["pid"] == 0  # arrowhead on the trainer
    assert doc["otherData"]["link_stats"]["resolved"] == 3


def test_crosscheck_goodput_agrees_on_matching_shares(tmp_path):
    _write_trace_dir(tmp_path / "trace", _fleet_events(steps=3))
    cp = critical_path(merge_timeline(tmp_path / "trace"))
    # ledger with the same 0.7/0.2/0.1 proportions -> near-zero deltas
    rows = crosscheck_goodput(cp, {"buckets": {
        "productive_step": 70.0, "data_wait": 20.0, "ckpt": 10.0,
        "compile_fetched": 0.0, "restart_downtime": 55.0}})
    assert {r["bucket"] for r in rows} == {"productive_step", "data_wait",
                                           "ckpt", "compile_fetched"}
    for r in rows:
        assert abs(r["delta"]) < 0.01  # renormalized over shared buckets


# -- deadline autotune advisory ---------------------------------------------

def test_autotune_suggests_below_default_never_above():
    events = [_span(9, "input", "input_serve", k, k, 0.0, 0.010 + k * 1e-4)
              for k in range(40)]
    rows = {r["spans"]: r for r in suggest_deadlines(events)}
    r = rows["input_serve"]
    assert r["n"] == 40
    # p99*8 << default 120 but below the 1s floor -> floor wins
    assert r["suggested_s"] == pytest.approx(1.0)
    assert r["suggested_s"] <= r["current_default_s"]
    # a plane with huge observed frames never suggests above default
    slow = [_span(9, "input", "input_serve", k, k, 0.0, 60.0)
            for k in range(40)]
    r2 = {x["spans"]: x for x in suggest_deadlines(slow)}["input_serve"]
    assert r2["suggested_s"] == r2["current_default_s"]


def test_autotune_withholds_verdict_below_min_samples():
    events = [_span(9, "input", "input_serve", k, k, 0.0, 0.01)
              for k in range(3)]
    r = {x["spans"]: x for x in suggest_deadlines(events)}["input_serve"]
    assert r["n"] == 3 and r["suggested_s"] is None


# -- wire contract ----------------------------------------------------------

def test_frame_header_carries_trace_context():
    import socket

    from tpucfn.data.service import (FRAME_BATCH, MAGIC, PROTOCOL_VERSION,
                                     recv_frame_ctx, send_frame)

    assert PROTOCOL_VERSION == 2
    a, b = socket.socketpair()
    try:
        send_frame(a, FRAME_BATCH, b"payload", ctx=(7, 42, 0xDEAD))
        kind, payload, ctx = recv_frame_ctx(b, magic=MAGIC)
        assert (kind, payload) == (FRAME_BATCH, b"payload")
        assert ctx == (7, 42, 0xDEAD)
        send_frame(a, FRAME_BATCH, b"bare")  # no context -> zeros -> None
        _, _, ctx2 = recv_frame_ctx(b, magic=MAGIC)
        assert ctx2 is None
    finally:
        a.close()
        b.close()


def test_frame_header_layout_is_the_documented_contract():
    """The wire contract pinned as bytes: magic(4s) kind(c) len(I) then
    trace_id/span_id/origin as little-endian u64s, zeros = no context."""
    from tpucfn.data.service import _HEADER, MAGIC

    assert _HEADER.format == "<4scIQQQ"
    raw = _HEADER.pack(MAGIC, b"B", 5, 7, 42, 0xDEAD)
    assert len(raw) == _HEADER.size == 4 + 1 + 4 + 8 * 3
    assert struct.unpack("<4scIQQQ", raw) == (MAGIC, b"B", 5, 7, 42, 0xDEAD)


def test_tracer_records_remote_parent():
    d_org = origin_id("input", 9)
    assert d_org != 0 and d_org == origin_id("input", 9)
    assert origin_id("input", 9) != origin_id("trainer", 9)


def test_tracer_rp_roundtrip(tmp_path):
    tr = Tracer(tmp_path, host_id=0, role="trainer")
    tr.record("data_wait", start=0.0, dur_s=0.1, trace_id=5,
              remote_parent=(4, 101, origin_id("input", 9)))
    tr.record("data_wait", start=0.0, dur_s=0.1, trace_id=6,
              remote_parent=(0, 0, 0))  # peer with tracing off
    tr.close()
    evs = read_trace_file(tmp_path / "trace-trainer-host000.jsonl")
    assert evs[0]["rp"] == {"trace_id": 4, "span_id": 101,
                            "origin": origin_id("input", 9)}
    assert "rp" not in evs[1]


# -- forensics diff ---------------------------------------------------------

def _bundle(d, incident, action, downtime, buckets, hb, spans_per_host):
    d.mkdir(parents=True)
    (d / "incident.json").write_text(json.dumps({
        "incident": {"incident": incident, "action": action,
                     "planned": False, "downtime_s": downtime,
                     "detection_s": 0.5, "lost_steps": 4},
        "window": {"window_s": 15.0}}))
    (d / "goodput.json").write_text(json.dumps({"buckets": buckets}))
    (d / "heartbeats.json").write_text(json.dumps(
        [{"host": h, "age_at_detect_s": age} for h, age in hb.items()]))
    with open(d / "timeline.jsonl", "w") as f:
        for h, n in spans_per_host.items():
            for i in range(n):
                f.write(json.dumps({"kind": "span", "host": h,
                                    "name": "step", "ts_adj": i}) + "\n")
    return d


def test_diff_bundles_same_class_deltas(tmp_path):
    from tpucfn.obs.postmortem import diff_bundles, render_bundle_diff

    a = _bundle(tmp_path / "a", 1, "restart", 2.0,
                {"productive_step": 8.0, "data_wait": 2.0},
                {0: 0.1, 1: 0.2}, {0: 10, 1: 10})
    b = _bundle(tmp_path / "b", 2, "restart", 3.5,
                {"productive_step": 5.0, "data_wait": 5.0},
                {0: 0.1, 1: 1.4}, {0: 10, 1: 2})
    diff = diff_bundles(a, b)
    assert diff["incident"]["class_match"] is True
    assert diff["incident"]["downtime_s"]["delta"] == pytest.approx(1.5)
    by_bucket = {r["bucket"]: r for r in diff["buckets"]}
    # shares: data_wait 0.2 -> 0.5
    assert by_bucket["data_wait"]["delta"] == pytest.approx(0.3)
    host1 = next(r for r in diff["hosts"] if r["host"] == 1)
    assert host1["hb_age_delta_s"] == pytest.approx(1.2)
    assert host1["span_delta"] == -8
    text = render_bundle_diff(diff)
    assert "WARNING" not in text and "data_wait" in text


def test_diff_bundles_flags_differing_incident_class(tmp_path):
    from tpucfn.obs.postmortem import diff_bundles, render_bundle_diff

    a = _bundle(tmp_path / "a", 1, "restart", 2.0, {}, {}, {})
    b = _bundle(tmp_path / "b", 2, "shrink", 9.0, {}, {}, {})
    diff = diff_bundles(a, b)
    assert diff["incident"]["class_match"] is False
    assert any("classes differ" in n for n in diff["notes"])
    assert "WARNING" in render_bundle_diff(diff)


# -- plane vocabulary stays closed ------------------------------------------

def test_planes_and_crosshost_vocabulary():
    assert set(CROSS_HOST_SPAN_NAMES) == {"data_wait", "input_serve",
                                          "compile_fetch", "artifact_serve"}
    assert "compute" in PLANES and "coordinator" in PLANES
    # ClockProbe is the probe_clock return contract
    pr = ClockProbe(host=0, role="x", offset_s=0.0, unc_s=0.0, rtt_s=0.0)
    assert pr.host == 0
