import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from tpucfn.parallel import ShardingRules, shard_batch
from tpucfn.train import Trainer


def _mlp_init(rng):
    k1, k2 = jax.random.split(rng)
    params = {
        "fc1": {"kernel": jax.random.normal(k1, (4, 32)) * 0.1, "bias": jnp.zeros(32)},
        "fc2": {"kernel": jax.random.normal(k2, (32, 1)) * 0.1, "bias": jnp.zeros(1)},
    }
    return params, {}


def _mlp_loss(params, model_state, batch, rng):
    h = jnp.tanh(batch["x"] @ params["fc1"]["kernel"] + params["fc1"]["bias"])
    pred = h @ params["fc2"]["kernel"] + params["fc2"]["bias"]
    loss = jnp.mean((pred[:, 0] - batch["y"]) ** 2)
    return loss, ({"mae": jnp.mean(jnp.abs(pred[:, 0] - batch["y"]))}, model_state)


def _regression_batch(n=64):
    rs = np.random.RandomState(0)
    x = rs.randn(n, 4).astype(np.float32)
    y = (x @ np.array([1.0, -2.0, 0.5, 0.0], np.float32)).astype(np.float32)
    return {"x": x, "y": y}


def _rules_fsdp():
    return ShardingRules(
        ((r"(fc1|fc2)/kernel$", P("fsdp")), (r".*", P()))
    )


def test_dp_training_learns(mesh_dp8):
    trainer = Trainer(
        mesh_dp8,
        ShardingRules(((r".*", P()),)),
        _mlp_loss,
        optax.adam(1e-2),
        _mlp_init,
    )
    state = trainer.init(jax.random.key(0))
    batch = shard_batch(mesh_dp8, _regression_batch())
    first = None
    for _ in range(50):
        state, metrics = trainer.step(state, batch)
        first = first if first is not None else float(metrics["loss"])
    assert float(metrics["loss"]) < first * 0.1
    assert int(state.step) == 50


def test_fsdp_state_is_sharded(mesh8):
    trainer = Trainer(mesh8, _rules_fsdp(), _mlp_loss, optax.adam(1e-2), _mlp_init)
    state = trainer.init(jax.random.key(0))
    k = state.params["fc1"]["kernel"]
    assert k.sharding.spec == P("fsdp")
    # optimizer first-moment follows the same sharding as the param
    mu = state.opt_state[0].mu["fc1"]["kernel"]
    assert mu.sharding.spec == P("fsdp")
    # each fsdp shard holds half the rows
    assert k.addressable_shards[0].data.shape[0] == 2


def test_fsdp_matches_replicated_training(mesh8):
    """FSDP and plain DP must be numerically the same program — sharding is
    placement, not math."""
    batch = _regression_batch()
    losses = {}
    for name, rules in [
        ("dp", ShardingRules(((r".*", P()),))),
        ("fsdp", _rules_fsdp()),
    ]:
        trainer = Trainer(mesh8, rules, _mlp_loss, optax.adam(1e-2), _mlp_init)
        state = trainer.init(jax.random.key(0))
        b = shard_batch(mesh8, batch)
        for _ in range(5):
            state, m = trainer.step(state, b)
        losses[name] = float(m["loss"])
    np.testing.assert_allclose(losses["dp"], losses["fsdp"], rtol=1e-5)


def test_eval_step_runs(mesh_dp8):
    trainer = Trainer(
        mesh_dp8, ShardingRules(((r".*", P()),)), _mlp_loss, optax.adam(1e-2), _mlp_init
    )
    state = trainer.init(jax.random.key(0))
    m = trainer.eval_step(state, shard_batch(mesh_dp8, _regression_batch()))
    assert "loss" in m and "mae" in m


def test_metrics_are_replicated_scalars(mesh_dp8):
    trainer = Trainer(
        mesh_dp8, ShardingRules(((r".*", P()),)), _mlp_loss, optax.adam(1e-2), _mlp_init
    )
    state = trainer.init(jax.random.key(0))
    state, m = trainer.step(state, shard_batch(mesh_dp8, _regression_batch()))
    assert m["loss"].shape == ()


def test_adafactor_factored_state_shards_and_trains():
    """Factored optimizer state (Adafactor v_row/v_col, rank n-1) mirrors
    the param paths, so param rules' specs are over-long for it; the
    Trainer must replicate those leaves instead of raising (observed
    on-chip: the llama bench with adafactor died in state_shardings on
    'opt_state/0/v_row/embed_tokens/embedding')."""
    import optax

    from tpucfn.mesh import MeshSpec, build_mesh
    from tpucfn.models.llama import Llama, LlamaConfig, causal_lm_loss, sharding_rules

    mesh = build_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
    cfg = LlamaConfig.tiny()
    model = Llama(cfg)
    sample = jnp.zeros((4, 16), jnp.int32)

    def init_fn(rng):
        return model.init(rng, sample)["params"], {}

    def loss_fn(params, mstate, batch, rng):
        loss, acc = causal_lm_loss(
            model.apply({"params": params}, batch["tokens"]), batch["tokens"])
        return loss, ({"accuracy": acc}, mstate)

    trainer = Trainer(mesh, sharding_rules(cfg), loss_fn,
                      optax.adafactor(3e-3), init_fn)
    state = trainer.init(jax.random.key(0))
    # params keep their rule shardings; factored vectors are replicated
    emb = state.params["embed_tokens"]["embedding"]
    assert emb.sharding.spec == P("tensor", "fsdp")

    def leaves_with_path(tree):
        return jax.tree_util.tree_flatten_with_path(tree)[0]

    factored = [(p, leaf) for p, leaf in leaves_with_path(state.opt_state)
                if "v_row" in str(p) or "v_col" in str(p)]
    assert factored, "adafactor state should contain factored vectors"
    for p, leaf in factored:
        assert leaf.sharding.spec == P(), (p, leaf.sharding.spec)

    rs = np.random.RandomState(0)
    batch = shard_batch(mesh, {"tokens": rs.randint(
        0, cfg.vocab_size, (8, 16)).astype(np.int32)})
    first = None
    for _ in range(10):
        state, m = trainer.step(state, batch)
        first = first if first is not None else float(m["loss"])
    assert float(m["loss"]) < first


def test_ema_tracks_params():
    """ema_decay keeps a post-update moving average under
    model_state['ema'], sharded/checkpointed with the state."""
    from tpucfn.mesh import MeshSpec, build_mesh
    from tpucfn.train import TrainerConfig

    mesh = build_mesh(MeshSpec(data=8))
    trainer = Trainer(mesh, ShardingRules(((r".*", P()),)), _mlp_loss,
                      optax.sgd(0.05), _mlp_init,
                      config=TrainerConfig(ema_decay=0.9))
    state = trainer.init(jax.random.key(0))
    np.testing.assert_array_equal(
        np.asarray(state.model_state["ema"]["fc1"]["kernel"]),
        np.asarray(state.params["fc1"]["kernel"]))

    rs = np.random.RandomState(0)
    batch = {"x": rs.randn(16, 4).astype(np.float32),
             "y": rs.randn(16, 1).astype(np.float32)}
    from tpucfn.parallel import shard_batch as sb

    b = sb(mesh, batch)
    # the step donates the previous state: snapshot to host numpy first
    snap = lambda t: jax.tree.map(np.asarray, t)  # noqa: E731
    ema_prev = snap(state.model_state["ema"])
    for _ in range(3):
        p_prev = snap(state.params)
        state, _ = trainer.step(state, b)
        want = jax.tree.map(lambda e, p: e * 0.9 + np.asarray(p) * 0.1,
                            ema_prev, state.params)
        np.testing.assert_allclose(
            np.asarray(state.model_state["ema"]["fc1"]["kernel"]),
            want["fc1"]["kernel"], rtol=1e-6)
        ema_prev = snap(state.model_state["ema"])
        # params moved, ema lags
        assert not np.allclose(np.asarray(state.params["fc1"]["kernel"]),
                               p_prev["fc1"]["kernel"])
    assert not np.allclose(
        np.asarray(state.model_state["ema"]["fc1"]["kernel"]),
        np.asarray(state.params["fc1"]["kernel"]))
