"""Span tracing + serve request lifecycle (ISSUE 2 tentpole).

The acceptance pin: a serve run's trace JSONL reconstructs each
request's TTFT decomposition (queue-wait + prefill + any decode-round
time before the first token) that sums to the measured TTFT within
5 ms.  Uses a duck-typed fake engine (Server only needs max_batch /
cache_len / prefill / decode) so the timing is deterministic and the
test runs in milliseconds, not compiles.
"""

import json
import threading
import time

import pytest

from tpucfn.obs import MetricRegistry, Tracer, read_trace_dir, read_trace_file
from tpucfn.obs.aggregate import request_breakdown
from tpucfn.serve import Server


class FakeEngine:
    """Deterministic delays instead of XLA programs."""

    def __init__(self, max_batch=4, cache_len=64,
                 prefill_delay=0.004, decode_delay=0.002):
        self.max_batch = max_batch
        self.cache_len = cache_len
        self.prefill_delay = prefill_delay
        self.decode_delay = decode_delay

    def prefill(self, slot, prefix, bucket, temperature=0.0):
        time.sleep(self.prefill_delay)
        return 11

    def decode(self, tokens_by_slot):
        time.sleep(self.decode_delay)
        return {s: 12 for s in tokens_by_slot}


# ---- Tracer primitives --------------------------------------------------

def test_span_nesting_and_parent_propagation(tmp_path):
    tr = Tracer(tmp_path / "t.jsonl", host_id=3, role="trainer")
    with tr.span("outer", trace_id=7, a=1) as s:
        with tr.span("inner", trace_id=7):
            time.sleep(0.001)
        s["b"] = 2
    tr.close()
    events = read_trace_file(tmp_path / "t.jsonl")
    inner = next(e for e in events if e["name"] == "inner")
    outer = next(e for e in events if e["name"] == "outer")
    assert inner["parent_id"] == outer["span_id"]
    assert outer["parent_id"] is None
    assert outer["attrs"] == {"a": 1, "b": 2}
    assert outer["dur_s"] >= inner["dur_s"] >= 0.001
    assert outer["host"] == 3 and outer["role"] == "trainer"


def test_span_error_is_recorded(tmp_path):
    tr = Tracer(tmp_path / "t.jsonl")
    with pytest.raises(ValueError):
        with tr.span("boom"):
            raise ValueError("x")
    tr.close()
    [e] = read_trace_file(tmp_path / "t.jsonl")
    assert e["attrs"]["error"] == "ValueError"


def test_noop_tracer_writes_nothing_and_never_fails():
    tr = Tracer(None)
    assert not tr.enabled
    with tr.span("x"):
        pass
    tr.event("y", trace_id=1)
    tr.record("z", start=0.0, dur_s=1.0)
    tr.close()


def test_tracer_dir_derives_per_host_filename(tmp_path):
    tr = Tracer(tmp_path, host_id=5, role="server")
    tr.event("e")
    tr.close()
    assert (tmp_path / "trace-server-host005.jsonl").exists()


def test_tracer_thread_safety(tmp_path):
    tr = Tracer(tmp_path / "t.jsonl")

    def work(i):
        for j in range(50):
            tr.event("e", trace_id=i, j=j)

    threads = [threading.Thread(target=work, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    tr.close()
    events = read_trace_file(tmp_path / "t.jsonl")
    assert len(events) == 200  # no torn/interleaved lines
    assert len({e["span_id"] for e in events}) == 200


# ---- serve lifecycle ----------------------------------------------------

def _run_traced_server(tmp_path, prompts, max_new=4, **server_kw):
    tracer = Tracer(tmp_path / "trace", host_id=0, role="server")
    server = Server(FakeEngine(), num_blocks=64, block_size=8,
                    tracer=tracer, **server_kw)
    reqs = [server.submit(p, max_new_tokens=max_new) for p in prompts]
    server.run_until_idle()
    tracer.close()
    return server, reqs, read_trace_dir(tmp_path / "trace")


def test_ttft_decomposition_sums_to_measured_ttft(tmp_path):
    """ACCEPTANCE: queue-wait + prefill + first-token-window decode time
    from the trace JSONL reconstructs each request's measured TTFT
    within 5 ms."""
    server, reqs, events = _run_traced_server(
        tmp_path, [[1] * n for n in (3, 5, 9, 17, 2)])
    rows, _ = request_breakdown(events)
    by_id = {r["request"]: r for r in rows}
    spans = [e for e in events if e["kind"] == "span"]
    assert all(r.error is None for r in reqs)
    for req in reqs:
        measured_ttft = req.t_first_token - req.t_submit
        row = by_id[req.req_id]
        # decode-round time that falls before this request's first token
        # (zero for fresh sequences — the first token IS the prefill's —
        # but summed explicitly so the reconstruction is general):
        first_tok_mono = req.t_submit + row["ttft_s"]
        decode_before = sum(
            min(e["start"] + e["dur_s"], first_tok_mono) - e["start"]
            for e in spans
            if e["name"] == "decode_round"
            and req.req_id in e["attrs"]["seqs"]
            and e["start"] < first_tok_mono)
        decomposed = row["queue_wait_s"] + row["prefill_s"] + decode_before
        assert decomposed == pytest.approx(measured_ttft, abs=0.005)
        # and the trace's own ttft matches the request object's
        assert row["ttft_s"] == pytest.approx(measured_ttft, abs=1e-6)


def test_lifecycle_events_cover_queue_prefill_decode_done(tmp_path):
    server, reqs, events = _run_traced_server(tmp_path, [[1, 2, 3]],
                                              max_new=3)
    names = [e["name"] for e in events]
    assert names.count("request_submitted") == 1
    assert names.count("queue_wait") == 1
    assert names.count("prefill") == 1
    assert names.count("decode_round") == 2  # tokens 2 and 3
    assert names.count("request_done") == 1
    done = next(e for e in events if e["name"] == "request_done")
    assert done["attrs"]["outcome"] == "ok"
    assert done["attrs"]["generated"] == 3
    pf = next(e for e in events if e["name"] == "prefill")
    assert pf["attrs"]["resumed"] is False
    assert pf["attrs"]["bucket"] == 16


def test_queue_wait_reflects_head_of_line_blocking(tmp_path):
    """With a 1-slot engine the second request's queue wait covers the
    whole first request — the 'why was it slow' answer the spans exist
    to give."""
    eng = FakeEngine(max_batch=1, prefill_delay=0.01, decode_delay=0.005)
    tracer = Tracer(tmp_path / "trace", host_id=0, role="server")
    server = Server(eng, num_blocks=64, block_size=8, tracer=tracer)
    r1 = server.submit([1, 2], max_new_tokens=3)
    r2 = server.submit([3, 4], max_new_tokens=1)
    server.run_until_idle()
    tracer.close()
    rows, _ = request_breakdown(read_trace_dir(tmp_path / "trace"))
    by_id = {r["request"]: r for r in rows}
    # r2 waited at least r1's full occupancy (prefill + 2 decode rounds)
    assert by_id[r2.req_id]["queue_wait_s"] >= 0.01 + 2 * 0.005 - 0.001
    assert by_id[r1.req_id]["queue_wait_s"] < by_id[r2.req_id]["queue_wait_s"]
    assert r1.error is None and r2.error is None


def test_expired_request_done_event_keeps_partial_generated(tmp_path):
    """A request that dies mid-decode is not zero-output work: the
    request_done event carries the tokens it generated before the
    deadline (what the error message already said)."""
    from tpucfn.serve import DeadlineExceeded

    eng = FakeEngine(max_batch=2, prefill_delay=0.0, decode_delay=0.03)
    tracer = Tracer(tmp_path / "trace", host_id=0, role="server")
    server = Server(eng, num_blocks=64, block_size=8, tracer=tracer)
    req = server.submit([1, 2, 3], max_new_tokens=50, deadline_s=0.08)
    server.run_until_idle()
    tracer.close()
    assert isinstance(req.error, DeadlineExceeded)
    done = next(e for e in read_trace_dir(tmp_path / "trace")
                if e["name"] == "request_done")
    assert done["attrs"]["outcome"] == "expired"
    # prefill gave token 1 instantly; 0.03s decode rounds against a
    # 0.08s deadline leave at least one more token behind
    assert done["attrs"]["generated"] >= 1


def test_request_breakdown_aggregate(tmp_path):
    server, reqs, events = _run_traced_server(
        tmp_path, [[1] * 4, [2] * 6, [3] * 8], max_new=2)
    rows, agg = request_breakdown(events)
    assert agg["requests"] == 3 and agg["completed"] == 3
    assert agg["ttft_s"]["p50"] is not None
    assert agg["total_s"]["max"] >= agg["total_s"]["p50"]
    for r in rows:
        assert r["decode_rounds"] == 1  # max_new=2: prefill token + 1 round
        assert r["outcome"] == "ok"


def test_trainer_obs_phases_feed_registry_and_trace(tmp_path):
    from tpucfn.train.trainer import TrainerObs

    registry = MetricRegistry()
    tracer = Tracer(tmp_path / "t.jsonl", host_id=1, role="trainer")
    obs = TrainerObs(registry, tracer)
    obs.record_data_wait(1, time.monotonic(), 0.02)
    with obs.step(1):
        time.sleep(0.001)
    with obs.ckpt(1):
        pass
    tracer.close()
    events = read_trace_file(tmp_path / "t.jsonl")
    assert {e["name"] for e in events} == {"data_wait", "step", "ckpt"}
    assert all(e["trace_id"] == 1 for e in events)
    v = registry.varz()["metrics"]
    assert v["train_steps_total"] == 1.0 and v["train_last_step"] == 1.0
    assert v["train_data_wait_seconds"]["count"] == 1
    assert v["train_step_seconds"]["count"] == 1


# ---- the /metrics acceptance scrape ------------------------------------

def test_metrics_endpoint_on_running_server_covers_serving_and_training(
        tmp_path):
    """ACCEPTANCE: GET /metrics on a serving process returns valid
    Prometheus exposition covering the serving counters (TTFT,
    tokens, preemptions, KV occupancy) AND registry-registered
    training metrics — one registry, one scrape surface per host."""
    import urllib.request

    from tpucfn.obs import ObsServer

    registry = MetricRegistry(labels={"host": "0"})
    # a training-side metric registered into the same per-process registry
    registry.histogram("train_step_seconds",
                       "host-observed step wall time").observe(0.125)
    server = Server(FakeEngine(), num_blocks=64, block_size=8,
                    registry=registry)
    for n in (3, 5):
        server.submit([1] * n, max_new_tokens=2)
    server.run_until_idle()
    srv = ObsServer(registry, port=0, host="127.0.0.1", role="server")
    try:
        with urllib.request.urlopen(srv.url("/metrics"), timeout=5) as r:
            assert r.status == 200
            body = r.read().decode()
    finally:
        srv.close()
    for needle in (
        "serve_ttft_seconds_count",          # TTFT summary
        "serve_generated_tokens_total",      # tokens/sec numerator
        "serve_preemptions_total",           # preemptions
        "serve_kv_cache_occupancy",          # KV occupancy
        "serve_request_latency_seconds_bucket",  # the new Histogram
        "train_step_seconds_bucket",         # training metric, same scrape
    ):
        assert needle in body, f"{needle} missing from exposition"
    # structural validity, line by line (same rule as test_obs_server)
    import re
    LINE_RE = re.compile(
        r"^(?:# (?:HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+"
        r"|[a-zA-Z_:][a-zA-Z0-9_:]*(?:\{[^{}]*\})? "
        r"(?:[+-]?(?:\d+\.?\d*(?:e[+-]?\d+)?|Inf)|NaN))$")
    for line in body.rstrip("\n").splitlines():
        assert LINE_RE.match(line), f"invalid exposition line: {line!r}"
    # and the snapshot dict still carries the dashboard
    snap = server.metrics.snapshot()
    assert snap["completed"] == 2 and snap["generated_tokens"] == 4
    assert json.dumps(snap)  # JSON-able end to end
