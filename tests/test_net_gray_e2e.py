"""ISSUE 15 acceptance drills: gray failures against the REAL fleet
planes under the real launch fan-out, detection latency rc-gated.

* **input plane** — 1 input host (the real ``tpucfn data serve`` CLI)
  + 2 trainer hosts, with a :class:`ChaosProxy` between the trainers
  and the input host.  Mid-run the proxy starts TRICKLING (the fault
  per-chunk timeouts can never catch); every trainer must degrade to
  local loading within the configured end-to-end deadline — ≤ 10 s in
  the drill, vs the pre-ISSUE-15 worst case of minutes — and the full
  trajectory must be bit-identical to an uninterrupted reference.
* **compile-artifact plane** — same shape: a GET stalled mid-payload
  (connection held open) must degrade to a local compile inside the op
  deadline with the same program.

The reference/served/degraded comparison discipline (and the worker)
are shared with test_input_service_e2e.py.
"""

import json
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from tpucfn.bootstrap import EnvContract
from tpucfn.data import write_dataset_shards
from tpucfn.ft import (
    GangCoordinator,
    GangRestart,
    HeartbeatMonitor,
    MonitorConfig,
    RestartBudget,
)
from tpucfn.launch import Launcher, LocalTransport
from tpucfn.net.proxy import ChaosProxy

pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parent.parent
WORKER = Path(__file__).resolve().parent / "input_e2e_worker.py"

TRAINERS = 2
BATCH = 8
SEED = 5
EPOCHS = 1
EXAMPLES, SHARDS = 480, 8
STEPS_PER_TRAINER = 30

# the drill's rc gate: fault injection -> every trainer degraded
DETECT_LATENCY_GATE_S = 10.0
OP_DEADLINE_S = 2.0


def _write_shards(tmp_path) -> Path:
    d = tmp_path / "shards"
    d.mkdir()
    rs = np.random.RandomState(1)
    write_dataset_shards(
        ({"x": rs.randn(4096).astype(np.float32)} for _ in range(EXAMPLES)),
        d, num_shards=SHARDS)
    return d


def _contract(tmp_path, n) -> EnvContract:
    hostfile = tmp_path / f"hostfile{n}"
    hostfile.write_text("".join("127.0.0.1:0\n" for _ in range(n)))
    return EnvContract(
        workers_path=str(hostfile), workers_count=n, worker_chip_count=1,
        coordinator="127.0.0.1:1234", host_id=0, storage=str(tmp_path),
        generation=1)


def _worker_env(run_dir: Path, shards: Path) -> dict[str, str]:
    return {
        "INPUT_E2E_RUN_DIR": str(run_dir),
        "INPUT_E2E_SHARDS": str(shards),
        "INPUT_E2E_BATCH": str(BATCH),
        "INPUT_E2E_SEED": str(SEED),
        "INPUT_E2E_EPOCHS": str(EPOCHS),
        "INPUT_E2E_STEP_SLEEP": "0.05",
        "INPUT_E2E_DECODE_SLEEP": "0.004",
        "TPUCFN_INPUT_RCVBUF": str(64 * 1024),
    }


def _serve_argv(shards: Path) -> list[str]:
    return [sys.executable, "-m", "tpucfn.cli", "data", "serve",
            "--shards", str(shards), "--batch-size", str(BATCH),
            "--seed", str(SEED), "--num-epochs", str(EPOCHS),
            "--host", "127.0.0.1", "--idle-exit", "2.0",
            "--queue-batches", "2", "--sndbuf-kb", "64",
            "--send-deadline", "30"]


def _run(tmp_path, shards, run_dir, *, input_plane: bool, input_port: int,
         proxy_addr: str | None = None) -> GangCoordinator:
    run_dir.mkdir(parents=True, exist_ok=True)
    n = TRAINERS + (1 if input_plane else 0)
    ft_dir = run_dir / "ft"
    extra = _worker_env(run_dir, shards)
    if proxy_addr is not None:
        # route the trainers THROUGH the proxy: extra_env is applied
        # last in host_env, overriding the launcher's computed fan-out
        extra["TPUCFN_INPUT_ADDRS"] = proxy_addr
        extra["TPUCFN_INPUT_OP_DEADLINE_S"] = str(OP_DEADLINE_S)
    launcher = Launcher(
        _contract(tmp_path, n), LocalTransport(),
        ft_dir=str(ft_dir), ft_heartbeat_s=0.2,
        input_hosts=1 if input_plane else 0,
        input_port=input_port,
        input_argv=_serve_argv(shards) if input_plane else None,
        extra_env=extra)
    monitor = HeartbeatMonitor(
        ft_dir, expected_hosts=n,
        config=MonitorConfig(interval_s=0.2, startup_grace_s=60.0))
    coord = GangCoordinator(
        launcher, [sys.executable, str(WORKER)],
        policy=GangRestart(RestartBudget(0)), monitor=monitor,
        ft_dir=ft_dir, poll_interval=0.02, term_grace_s=2.0)
    assert coord.run() == 0
    return coord


def _trajectories(run_dir: Path) -> dict[int, list[str]]:
    out = {}
    for h in range(TRAINERS):
        p = run_dir / f"losses-host{h:03d}.jsonl"
        out[h] = [ln for ln in p.read_text().splitlines() if ln.strip()]
        assert len(out[h]) == STEPS_PER_TRAINER * EPOCHS, (h, len(out[h]))
    return out


def _mode(run_dir: Path, h: int) -> dict:
    return json.loads((run_dir / f"mode-host{h:03d}.json").read_text())


def _fleet_step(run_dir: Path) -> int:
    steps = []
    for h in range(TRAINERS):
        p = run_dir / f"losses-host{h:03d}.jsonl"
        if not p.is_file():
            steps.append(0)
            continue
        lines = [s for s in p.read_text().splitlines() if s.strip()]
        steps.append(json.loads(lines[-1])["step"] if lines else 0)
    return min(steps)


def test_gray_input_trickle_degrades_within_deadline_bit_identical(tmp_path):
    shards = _write_shards(tmp_path)

    # -- reference: local loading, the bit-identical ground truth --------
    ref_dir = tmp_path / "ref"
    _run(tmp_path, shards, ref_dir, input_plane=False, input_port=9410)
    ref = _trajectories(ref_dir)
    assert not _mode(ref_dir, 0)["used_service"]

    # -- gray: served through a proxy that starts trickling mid-run ------
    gray_dir = tmp_path / "gray"
    proxy = ChaosProxy("127.0.0.1:9420", host="127.0.0.1").start()
    injected_ts = [None]

    import threading

    def injector():
        # wait for real mid-run evidence (fleet step >= 10), then make
        # the input plane TRICKLE: bytes keep flowing one dribble per
        # tick, so only the end-to-end deadline can notice
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if _fleet_step(gray_dir) >= 10:
                proxy.inject("throttle", rate_bps=128.0, duration_s=600.0)
                injected_ts[0] = time.time()
                return
            time.sleep(0.05)

    t = threading.Thread(target=injector, daemon=True)
    t.start()
    try:
        coord = _run(tmp_path, shards, gray_dir, input_plane=True,
                     input_port=9420, proxy_addr=proxy.address)
    finally:
        t.join(timeout=5)
        proxy.close()
    assert injected_ts[0] is not None, "fault never armed: drill vacuous"

    got = _trajectories(gray_dir)
    assert got == ref  # the whole point: gray degradation changed NOTHING
    for h in range(TRAINERS):
        m = _mode(gray_dir, h)
        assert m["used_service"], m
        assert m["degraded"], (h, m)
        # the rc gate: trickle onset -> this trainer degraded to local
        latency = m["degraded_ts"] - injected_ts[0]
        assert latency <= DETECT_LATENCY_GATE_S, (
            f"host {h} took {latency:.1f}s to degrade "
            f"(gate {DETECT_LATENCY_GATE_S}s, deadline {OP_DEADLINE_S}s)")
        assert latency > -1.0  # degraded BECAUSE of the fault, not before
    # a trickling host is not a dead host: no gang incident, no budget
    events = [json.loads(s) for s in
              (gray_dir / "ft" / "events.jsonl").read_text().splitlines()
              if s.strip()]
    kinds = [e["kind"] for e in events]
    assert "detect" not in kinds and "recovered" not in kinds
    assert coord.policy.budget.used == 0


def test_gray_artifact_stall_degrades_to_local_compile_in_deadline(tmp_path):
    """The compile-plane half of the acceptance: a stalled artifact
    server (payload stalls mid-stream, connection held open) degrades
    to local compile within the op deadline — same program, latency
    cost only."""
    from tpucfn.compilecache.service import ArtifactServer, CompileCacheClient
    from tpucfn.compilecache.store import ArtifactStore, cache_key

    store_dir = tmp_path / "srvstore"
    store = ArtifactStore(store_dir)
    key = cache_key({"program": "e2e-gray"})
    payload = bytes(range(256)) * 4096  # 1 MiB artifact
    store.put(key, payload, {"key": key, "label": "e2e"})
    srv = ArtifactServer(store_dir, host="127.0.0.1").start()
    proxy = ChaosProxy(srv.address, host="127.0.0.1").start()
    compiled = []
    try:
        # handshake + meta pass; the payload stalls at 128 KiB forever
        proxy.inject("stall", duration_s=3600.0, direction="down",
                     after_bytes=128 * 1024)
        client = CompileCacheClient(
            ArtifactStore(tmp_path / "local"), [proxy.address],
            op_deadline_s=OP_DEADLINE_S, wait_s=4.0)
        t0 = time.monotonic()
        result, outcome = client.get_or_compile(
            key, lambda: compiled.append(1) or b"the-program")
        wall = time.monotonic() - t0
    finally:
        proxy.close()
        srv.close()
    assert (result, outcome) == (b"the-program", "compile")
    assert compiled == [1]
    # the rc gate: the whole degrade-to-compile path inside the bound
    assert wall <= DETECT_LATENCY_GATE_S, (
        f"stalled fetch degraded in {wall:.1f}s "
        f"(gate {DETECT_LATENCY_GATE_S}s, op deadline {OP_DEADLINE_S}s)")
    v = client.registry.varz()["metrics"]
    assert v["net_compilecache_deadline_exceeded_total"] >= 1
