"""Gang coordination (tpucfn.ft.coordinator) over real subprocesses —
tiny ``python -c`` workers (no jax), sub-second timings, every incident
audited through the events JSONL and the ft_* registry metrics."""

import json
import sys
import time
from pathlib import Path

import pytest

from tpucfn.bootstrap import EnvContract
from tpucfn.ft import (
    ChaosEvent,
    ChaosSpec,
    GangCoordinator,
    GangRestart,
    HeartbeatMonitor,
    MonitorConfig,
    RestartBudget,
    SoloRestart,
)
from tpucfn.launch import Launcher, LocalTransport
from tpucfn.obs import MetricRegistry


def _contract(tmp_path, n=2) -> EnvContract:
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("".join("127.0.0.1:0\n" for _ in range(n)))
    return EnvContract(
        workers_path=str(hostfile), workers_count=n, worker_chip_count=1,
        coordinator="127.0.0.1:1234", host_id=0, storage=str(tmp_path),
        generation=1)


def _launcher(tmp_path, n=2, **kw) -> Launcher:
    return Launcher(_contract(tmp_path, n), LocalTransport(), **kw)


def _events(ft_dir) -> list[dict]:
    p = Path(ft_dir) / "events.jsonl"
    if not p.is_file():
        return []
    return [json.loads(s) for s in p.read_text().splitlines() if s.strip()]


def _kinds(ft_dir) -> list[str]:
    return [e["kind"] for e in _events(ft_dir)]


FAIL_ONCE = (
    "import pathlib,sys,os\n"
    "p = pathlib.Path(os.environ['FLAG'])\n"
    "sys.exit(0) if p.exists() else (p.write_text('x'), sys.exit(3))\n")


def test_crash_gang_restart_recovers_and_audits(tmp_path):
    ft_dir = tmp_path / "ft"
    launcher = _launcher(tmp_path, n=2)
    registry = MetricRegistry()
    import os

    os.environ["FLAG"] = str(tmp_path / "ran_once")
    try:
        coord = GangCoordinator(
            launcher, [sys.executable, "-c", FAIL_ONCE],
            policy=GangRestart(RestartBudget(2)), registry=registry,
            ft_dir=ft_dir, poll_interval=0.01, term_grace_s=0.5)
        assert coord.run() == 0
    finally:
        del os.environ["FLAG"]
    v = registry.varz()["metrics"]
    # supervisor_* compat surface (the run_with_restarts contract)
    assert v["supervisor_launch_attempts_total"] == 2
    assert v["supervisor_restarts_total"] == 1
    assert v["supervisor_failures_total"] == 1
    assert v["supervisor_last_exit_code"] == 0
    # ft_* recovery surface (ISSUE 4 acceptance metrics)
    assert v["ft_failures_detected_total"] >= 1
    assert v["ft_restarts_total"] == 1
    assert v["ft_gang_restarts_total"] == 1
    assert v["ft_mttr_seconds"]["count"] == 1
    # the audit trail: detect → decide → act(relaunch) → recovered
    kinds = _kinds(ft_dir)
    i = kinds.index("detect")
    assert kinds[:2] == ["launch", "launch"] or kinds[0] == "launch"
    assert kinds[i:i + 2] == ["detect", "decide"]
    assert "launch" in kinds[i:] and "recovered" in kinds[i:]
    assert kinds[-1] == "done"
    detect = next(e for e in _events(ft_dir) if e["kind"] == "detect")
    assert detect["failures"][0]["kind"] == "crash"
    assert detect["failures"][0]["rc"] == 3
    # supervisor.json snapshot for `tpucfn ft status`
    snap = json.loads((ft_dir / "supervisor.json").read_text())
    assert snap["policy"] == "gang"
    assert snap["metrics"]["ft_restarts_total"] == 1


def test_budget_exhaustion_gives_up_with_failing_rc(tmp_path):
    ft_dir = tmp_path / "ft"
    registry = MetricRegistry()
    coord = GangCoordinator(
        _launcher(tmp_path, n=1),
        [sys.executable, "-c", "import sys; sys.exit(7)"],
        policy=GangRestart(RestartBudget(1)), registry=registry,
        ft_dir=ft_dir, poll_interval=0.01, term_grace_s=0.5)
    assert coord.run() == 7
    v = registry.varz()["metrics"]
    assert v["supervisor_launch_attempts_total"] == 2  # first + 1 retry
    assert v["supervisor_restarts_total"] == 1
    assert v["supervisor_failures_total"] == 2
    assert v["supervisor_last_exit_code"] == 7
    assert v["ft_give_ups_total"] == 1
    assert _kinds(ft_dir)[-1] == "give_up"
    assert _events(ft_dir)[-1]["reason"].startswith("restart budget")


def test_clean_success_publishes_zero_failures(tmp_path):
    registry = MetricRegistry()
    coord = GangCoordinator(
        _launcher(tmp_path, n=2), [sys.executable, "-c", "pass"],
        registry=registry, poll_interval=0.01)
    assert coord.run() == 0
    v = registry.varz()["metrics"]
    assert v["supervisor_launch_attempts_total"] == 1
    assert v["supervisor_restarts_total"] == 0
    assert v["supervisor_failures_total"] == 0
    assert v["supervisor_last_exit_code"] == 0


def test_solo_restart_replaces_only_dead_host(tmp_path):
    """Host 1 dies once; SoloRestart relaunches ONLY host 1, host 0's
    process survives the incident (its pid never changes)."""
    ft_dir = tmp_path / "ft"
    flag = tmp_path / "h1_ran"
    ok = tmp_path / "h1_ok"
    # host0: wait for host1's second run; host1: fail once, then succeed
    worker = (
        "import os, pathlib, sys, time\n"
        f"flag = pathlib.Path(r'{flag}'); ok = pathlib.Path(r'{ok}')\n"
        "h = int(os.environ['TPUCFN_HOST_ID'])\n"
        "if h == 1:\n"
        "    if flag.exists(): ok.write_text('x'); sys.exit(0)\n"
        "    flag.write_text('x'); sys.exit(5)\n"
        "deadline = time.time() + 20\n"
        "while not ok.exists():\n"
        "    time.sleep(0.01)\n"
        "    assert time.time() < deadline\n")
    registry = MetricRegistry()
    coord = GangCoordinator(
        _launcher(tmp_path, n=2), [sys.executable, "-c", worker],
        policy=SoloRestart(RestartBudget(2)), registry=registry,
        ft_dir=ft_dir, poll_interval=0.01, term_grace_s=0.5)
    launches = []
    orig = coord.launcher.launch_host

    def spy(argv, host_id):
        launches.append(host_id)
        return orig(argv, host_id)

    coord.launcher.launch_host = spy
    assert coord.run() == 0
    assert launches == [1]
    v = registry.varz()["metrics"]
    assert v["ft_solo_restarts_total"] == 1
    assert v["ft_gang_restarts_total"] == 0
    assert v["supervisor_launch_attempts_total"] == 1  # one gang launch
    assert v["supervisor_restarts_total"] == 1
    decide = next(e for e in _events(ft_dir) if e["kind"] == "decide")
    assert decide["action"] == "solo_restart" and decide["hosts"] == [1]
    solo = next(e for e in _events(ft_dir) if e["kind"] == "solo_launch")
    assert solo["host"] == 1


@pytest.mark.slow
def test_hang_detected_via_heartbeat_monitor(tmp_path):
    """A process that stops heartbeating but stays alive is a HANG: the
    monitor condemns it, the coordinator kills + gang-restarts."""
    ft_dir = tmp_path / "ft"
    flag = tmp_path / "hung_once"
    worker = (
        "import json, os, pathlib, sys, time\n"
        f"flag = pathlib.Path(r'{flag}')\n"
        "if flag.exists(): sys.exit(0)\n"
        "flag.write_text('x')\n"
        "d = os.environ['TPUCFN_FT_DIR']; h = int(os.environ['TPUCFN_HOST_ID'])\n"
        "os.makedirs(d, exist_ok=True)\n"
        "with open(f'{d}/hb-host{h:03d}.jsonl', 'a') as f:\n"
        "    f.write(json.dumps({'host_id': h, 'pid': os.getpid(),"
        " 'step': 1, 't': time.time(), 'seq': 1}) + '\\n')\n"
        "time.sleep(60)\n")  # one beat, then silence: a hang
    # dead at 0.3s; explicit startup grace: interpreter start on a
    # loaded box can exceed the default 10x-interval window, and a
    # phantom no-heartbeat-yet incident here would burn the budget
    cfg = MonitorConfig(interval_s=0.05, startup_grace_s=3.0)
    registry = MetricRegistry()
    launcher = _launcher(tmp_path, n=1, ft_dir=str(ft_dir),
                         ft_heartbeat_s=0.05)
    coord = GangCoordinator(
        launcher, [sys.executable, "-c", worker],
        policy=GangRestart(RestartBudget(1)),
        monitor=HeartbeatMonitor(ft_dir, expected_hosts=1, config=cfg),
        registry=registry, ft_dir=ft_dir, poll_interval=0.01,
        term_grace_s=0.2)
    t0 = time.monotonic()
    assert coord.run() == 0
    assert time.monotonic() - t0 < 20
    detect = next(e for e in _events(ft_dir) if e["kind"] == "detect")
    assert detect["failures"][0]["kind"] == "hang"
    v = registry.varz()["metrics"]
    assert v["ft_gang_restarts_total"] == 1
    assert v["ft_failures_detected_total"] >= 1


@pytest.mark.slow
def test_chaos_kill_drives_detection_and_recovery(tmp_path):
    """A ChaosSpec kill against the coordinator's own process table:
    fired event audited, crash detected, gang restarted."""
    ft_dir = tmp_path / "ft"
    flag = tmp_path / "killed_once"
    # Only host 0 (the scripted victim) arms the flag and sleeps; host 1
    # exits clean immediately.  A shared flag would race: if host 1 won
    # the write, host 0 would exit before the kill ever fired.
    worker = (
        "import os, pathlib, sys, time\n"
        f"flag = pathlib.Path(r'{flag}')\n"
        "if int(os.environ['TPUCFN_HOST_ID']) != 0 or flag.exists():\n"
        "    sys.exit(0)\n"
        "flag.write_text('x')\n"
        "time.sleep(30)\n")  # first run: sit there until chaos kills us
    registry = MetricRegistry()
    coord = GangCoordinator(
        _launcher(tmp_path, n=2), [sys.executable, "-c", worker],
        policy=GangRestart(RestartBudget(1)), registry=registry,
        ft_dir=ft_dir, poll_interval=0.01, term_grace_s=0.3,
        # fire well after interpreter startup: the first-run workers
        # must have written their ran-once flag before the kill lands,
        # or the relaunched gang sleeps the full 30s
        chaos=ChaosSpec(events=(ChaosEvent(action="kill", at_s=2.0,
                                           host=0),)))
    t0 = time.monotonic()
    assert coord.run() == 0
    elapsed = time.monotonic() - t0
    assert elapsed < 20
    assert coord.chaos.done()
    assert [f.event.action for f in coord.chaos.fired] == ["kill"]
    detect = next(e for e in _events(ft_dir) if e["kind"] == "detect")
    assert detect["failures"][0]["host"] == 0
    assert detect["failures"][0]["kind"] == "crash"
    assert registry.varz()["metrics"]["ft_gang_restarts_total"] == 1


def test_observe_only_table_reaps_crash_and_returns_rc(tmp_path):
    """A decision table that declares CRASH non-actionable must still
    reap the dead rank and surface its rc — not re-detect it forever."""
    from tpucfn.ft import Action, FailureKind

    registry = MetricRegistry()
    coord = GangCoordinator(
        _launcher(tmp_path, n=1),
        [sys.executable, "-c", "import sys; sys.exit(5)"],
        policy=GangRestart(RestartBudget(3),
                           table={FailureKind.CRASH: Action.NONE}),
        registry=registry, ft_dir=tmp_path / "ft", poll_interval=0.01)
    assert coord.run() == 5
    v = registry.varz()["metrics"]
    assert v["ft_restarts_total"] == 0
    assert v["ft_incidents_total"] == 1  # detected once, not every tick


def test_at_step_chaos_without_monitor_is_rejected(tmp_path):
    """Fleet step comes from heartbeats; an at_step-only chaos event
    with no monitor would silently never fire and the drill would pass
    vacuously — constructing that coordinator must raise."""
    with pytest.raises(ValueError, match="at_step"):
        GangCoordinator(
            _launcher(tmp_path, n=1), [sys.executable, "-c", "pass"],
            chaos=ChaosSpec(events=(
                ChaosEvent(action="kill", at_step=10, host=0),)))
    # an at_s trigger needs no monitor
    GangCoordinator(
        _launcher(tmp_path, n=1), [sys.executable, "-c", "pass"],
        chaos=ChaosSpec(events=(
            ChaosEvent(action="kill", at_s=1.0, host=0),)))


@pytest.mark.slow
def test_observe_only_hang_is_one_incident(tmp_path):
    """A HANG the table declines to act on is suppressed after the
    first incident — not re-detected every poll tick for the rest of
    the run."""
    from tpucfn.ft import Action, FailureKind

    ft_dir = tmp_path / "ft"
    # one beat, then silence long past the dead threshold, then clean exit
    worker = (
        "import json, os, time\n"
        "d = os.environ['TPUCFN_FT_DIR']; h = int(os.environ['TPUCFN_HOST_ID'])\n"
        "os.makedirs(d, exist_ok=True)\n"
        "with open(f'{d}/hb-host{h:03d}.jsonl', 'a') as f:\n"
        "    f.write(json.dumps({'host_id': h, 'pid': os.getpid(),"
        " 'step': 1, 't': time.time(), 'seq': 1}) + '\\n')\n"
        "time.sleep(2.5)\n")
    registry = MetricRegistry()
    launcher = _launcher(tmp_path, n=1, ft_dir=str(ft_dir),
                         ft_heartbeat_s=0.05)
    coord = GangCoordinator(
        launcher, [sys.executable, "-c", worker],
        policy=GangRestart(RestartBudget(3),
                           table={FailureKind.HANG: Action.NONE}),
        monitor=HeartbeatMonitor(
            ft_dir, expected_hosts=1,
            config=MonitorConfig(interval_s=0.05, startup_grace_s=1.5)),
        registry=registry, ft_dir=ft_dir, poll_interval=0.01,
        term_grace_s=0.2)
    assert coord.run() == 0  # the sleeping host eventually exits clean
    v = registry.varz()["metrics"]
    assert v["ft_incidents_total"] == 1  # suppressed, not per-tick spam
    assert v["ft_restarts_total"] == 0


# -- graceful degradation (ISSUE 7): fast subprocess pins ------------------

# Stdlib drain-aware worker: first attempt beats and waits for the
# drain file (mirroring the trainer protocol: stop once `step` reaches
# the drain target, or immediately when the target is null); the
# relaunched attempt exits clean at once.
DRAIN_WORKER = (
    "import json, os, pathlib, sys, time\n"
    "d = os.environ['TPUCFN_FT_DIR']; h = int(os.environ['TPUCFN_HOST_ID'])\n"
    "os.makedirs(d, exist_ok=True)\n"
    "flag = pathlib.Path(os.environ['FLAG_DIR']) / f'second_{h}'\n"
    "if flag.exists(): sys.exit(0)\n"
    "flag.write_text('x')\n"
    "drain = pathlib.Path(d) / 'drain.json'\n"
    "seq = 0\n"
    "t_end = time.time() + 30\n"
    "while time.time() < t_end:\n"
    "    seq += 1\n"
    "    with open(f'{d}/hb-host{h:03d}.jsonl', 'a') as f:\n"
    "        f.write(json.dumps({'host_id': h, 'pid': os.getpid(),"
    " 'step': seq, 't': time.time(), 'seq': seq}) + '\\n')\n"
    "    if drain.exists():\n"
    "        try: tgt = json.loads(drain.read_text()).get('step')\n"
    "        except Exception: tgt = None\n"
    "        if tgt is None or seq >= tgt: sys.exit(0)\n"
    "    time.sleep(0.02)\n"
    "sys.exit(1)\n")


def test_preempt_notice_drains_into_planned_restart(tmp_path):
    """An external preemption notice (the preempt.json sentinel — the
    cloud-daemon hook) becomes a drain: clean exits, a relaunch, rc 0 —
    all with a budget of ZERO, because a planned restart must not need
    a restart slot."""
    import os

    from tpucfn.ft import write_notice
    from tpucfn.ft.preempt import drain_path

    ft_dir = tmp_path / "ft"
    os.environ["FLAG_DIR"] = str(tmp_path)
    try:
        import threading

        registry = MetricRegistry()
        launcher = _launcher(tmp_path, n=2, ft_dir=str(ft_dir),
                             ft_heartbeat_s=0.05)
        coord = GangCoordinator(
            launcher, [sys.executable, "-c", DRAIN_WORKER],
            policy=GangRestart(RestartBudget(0)), registry=registry,
            ft_dir=ft_dir, poll_interval=0.01, term_grace_s=0.5)
        # delivered mid-run, as a real notice daemon would: a notice
        # already on disk at startup is purged as stale (see below)
        t = threading.Timer(0.3, write_notice, args=(ft_dir,),
                            kwargs={"host": 1, "lead_s": 20.0})
        t.start()
        try:
            assert coord.run() == 0
        finally:
            t.cancel()
    finally:
        del os.environ["FLAG_DIR"]
    v = registry.varz()["metrics"]
    assert v["ft_preempt_drains_total"] == 1
    assert v["ft_planned_restarts_total"] == 1
    assert v["ft_restarts_total"] == 0  # budget untouched
    assert v["ft_planned_mttr_seconds"]["count"] == 1
    events = _events(ft_dir)
    detect = next(e for e in events if e["kind"] == "detect")
    assert detect["failures"][0]["kind"] == "preempt"
    assert detect["failures"][0]["lead_s"] == 20.0
    decide = next(e for e in events if e["kind"] == "decide")
    assert decide["action"] == "drain_restart" and decide["planned"]
    drain = next(e for e in events if e["kind"] == "drain")
    assert drain["hosts"] == [1]
    recovered = next(e for e in events if e["kind"] == "recovered")
    assert recovered["planned"] and recovered["escalated"] == 0
    gp = next(e for e in events if e["kind"] == "goodput_incident")
    assert gp["planned"] is True
    # the drain file must not survive into the relaunched gang
    assert not drain_path(ft_dir).exists()
    # the notice fired exactly once
    assert sum(1 for e in events if e["kind"] == "detect") == 1


def test_stale_drain_and_notice_purged_at_startup(tmp_path):
    """A supervisor killed mid-drain leaves drain.json/preempt.json in
    the persistent ft dir; a fresh launch must purge them — or every
    rank self-drains at its first boundary and a multi-hour job
    'finishes' rc 0 having trained nothing."""
    import os

    from tpucfn.ft import write_notice
    from tpucfn.ft.preempt import drain_path, request_drain

    ft_dir = tmp_path / "ft"
    request_drain(ft_dir, step=None)   # stale: would drain instantly
    write_notice(ft_dir, host=0, lead_s=1.0)
    os.environ["FLAG_DIR"] = str(tmp_path)
    try:
        registry = MetricRegistry()
        launcher = _launcher(tmp_path, n=1, ft_dir=str(ft_dir),
                             ft_heartbeat_s=0.05)
        # the worker EXITS 1 on drain-without-target unless it ran at
        # least 5 steps first — so a surviving stale file fails the run
        worker = (
            "import json, os, pathlib, sys, time\n"
            "d = os.environ['TPUCFN_FT_DIR']\n"
            "drain = pathlib.Path(d) / 'drain.json'\n"
            "for i in range(5):\n"
            "    if drain.exists(): sys.exit(1)\n"
            "    time.sleep(0.02)\n"
            "sys.exit(0)\n")
        coord = GangCoordinator(
            launcher, [sys.executable, "-c", worker],
            policy=GangRestart(RestartBudget(0)), registry=registry,
            ft_dir=ft_dir, poll_interval=0.01, term_grace_s=0.3)
        assert coord.run() == 0
    finally:
        del os.environ["FLAG_DIR"]
    assert not drain_path(ft_dir).exists()
    # the stale notice never became an incident
    assert registry.varz()["metrics"]["ft_incidents_total"] == 0
    assert not any(e["kind"] == "detect" for e in _events(ft_dir))


def test_ckpt_blacklist_expires_once_a_newer_step_lands(tmp_path):
    """The corruption blacklist must die once the run finalizes a step
    NEWER than everything on it — a stale blacklist would make every
    later ordinary restart skip the good re-saved checkpoint and
    silently rewind real work."""
    from tpucfn.ft import CKPT_BLACKLIST_ENV

    ckpt_dir = tmp_path / "ckpt"
    (ckpt_dir / "10").mkdir(parents=True)
    launcher = _launcher(tmp_path, n=1)
    coord = GangCoordinator(
        launcher, [sys.executable, "-c", "pass"],
        policy=GangRestart(RestartBudget(0)),
        ft_dir=tmp_path / "ft", ckpt_dir=ckpt_dir, poll_interval=0.01)
    coord._ckpt_blacklist = {20}
    coord._ckpt_retries = 1
    launcher.extra_env[CKPT_BLACKLIST_ENV] = "20"
    # nothing newer than 20 finalized yet: the blacklist stands
    coord._refresh_ckpt_blacklist()
    assert coord._ckpt_blacklist == {20}
    assert launcher.extra_env[CKPT_BLACKLIST_ENV] == "20"
    # the re-run finalized step 30: the bad artifact is history
    (ckpt_dir / "30").mkdir()
    coord._refresh_ckpt_blacklist()
    assert coord._ckpt_blacklist == set()
    assert coord._ckpt_retries == 0
    assert CKPT_BLACKLIST_ENV not in launcher.extra_env
    assert any(e["kind"] == "ckpt_blacklist_expired"
               for e in _events(tmp_path / "ft"))


def test_lose_host_shrinks_gang_to_n_minus_one(tmp_path):
    """Chaos lose_host: the killed host cannot be re-acquired, so the
    recovery re-converges the contract at N-1 (new generation) and
    relaunches the smaller gang instead of crash-looping a ghost."""
    import os

    from tpucfn.ft import ChaosEvent, ChaosSpec

    ft_dir = tmp_path / "ft"
    worker = (
        "import os, pathlib, sys, time\n"
        "flag = pathlib.Path(os.environ['FLAG_DIR']) / ("
        "'second_' + os.environ['TPUCFN_HOST_ID'])\n"
        "if flag.exists(): sys.exit(0)\n"
        "flag.write_text('x')\n"
        "time.sleep(30)\n")
    os.environ["FLAG_DIR"] = str(tmp_path)
    try:
        registry = MetricRegistry()
        launcher = _launcher(tmp_path, n=2)
        coord = GangCoordinator(
            launcher, [sys.executable, "-c", worker],
            policy=GangRestart(RestartBudget(1)), registry=registry,
            ft_dir=ft_dir, poll_interval=0.01, term_grace_s=0.3,
            chaos=ChaosSpec(events=(
                ChaosEvent(action="lose_host", at_s=0.3, host=1),)))
        assert coord.run() == 0
    finally:
        del os.environ["FLAG_DIR"]
    v = registry.varz()["metrics"]
    assert v["ft_shrinks_total"] == 1
    assert v["ft_gang_restarts_total"] == 1
    assert v["supervisor_gang_hosts"] == 1  # relaunched at N-1
    events = _events(ft_dir)
    assert any(e["kind"] == "host_lost" and e["host"] == 1 for e in events)
    shrink = next(e for e in events if e["kind"] == "shrink")
    assert shrink["from_hosts"] == 2 and shrink["to_hosts"] == 1
    assert shrink["lost"] == [1]
    assert shrink["generation"] == 2  # contract generation bumped (was 1)
    recovered = next(e for e in events if e["kind"] == "recovered")
    assert recovered["shrink"]["to_hosts"] == 1
    gp = next(e for e in events if e["kind"] == "goodput_incident")
    assert gp["shrink"]["generation"] == 2 and gp["planned"] is False
    # the launcher now holds the shrunk contract
    assert coord.launcher.contract.workers_count == 1
    assert coord.launcher.contract.generation == 2


def test_restore_failure_rc_retries_from_previous_step(tmp_path):
    """A gang exiting with RESTORE_FAILED_RC is a bad artifact, not a
    fleet failure: the coordinator blacklists + quarantines the latest
    finalized step, fans the blacklist out, and relaunches — without
    burning the restart budget."""
    from tpucfn.ft import CKPT_BLACKLIST_ENV, RESTORE_FAILED_RC

    ft_dir = tmp_path / "ft"
    ckpt_dir = tmp_path / "ckpt"
    for step in (10, 20):
        (ckpt_dir / str(step)).mkdir(parents=True)
        (ckpt_dir / str(step) / "data.bin").write_bytes(b"x" * 64)
    worker = (
        "import os, sys\n"
        "bl = os.environ.get('TPUCFN_CKPT_BLACKLIST', '')\n"
        f"sys.exit(0 if '20' in bl.split(',') else {RESTORE_FAILED_RC})\n")
    registry = MetricRegistry()
    coord = GangCoordinator(
        _launcher(tmp_path, n=2), [sys.executable, "-c", worker],
        policy=GangRestart(RestartBudget(0)),  # zero budget: none needed
        registry=registry, ft_dir=ft_dir, ckpt_dir=ckpt_dir,
        poll_interval=0.01, term_grace_s=0.3)
    assert coord.run() == 0
    v = registry.varz()["metrics"]
    assert v["ft_ckpt_retries_total"] == 1
    assert v["ft_give_ups_total"] == 0
    events = _events(ft_dir)
    retry = next(e for e in events if e["kind"] == "ckpt_retry")
    assert retry["bad_step"] == 20 and retry["retry_from"] == 10
    assert retry["blacklist"] == [20]
    recovered = next(e for e in events if e["kind"] == "recovered")
    assert recovered["action"] == "ckpt_retry"
    gp = next(e for e in events if e["kind"] == "goodput_incident")
    assert gp["ckpt"] == {"bad_step": 20, "retry_from": 10}
    # quarantined, not deleted: the bad artifact is kept for forensics
    assert not (ckpt_dir / "20").exists()
    assert (ckpt_dir / "corrupt" / "20" / "data.bin").is_file()
    assert coord.launcher.extra_env[CKPT_BLACKLIST_ENV] == "20"


def test_ckpt_retry_refused_without_a_previous_step(tmp_path):
    """Only ONE finalized checkpoint exists: quarantining it would make
    the relaunch init fresh and 'succeed' from step 0.  The coordinator
    must decline the retry and fail loudly through the normal table
    instead of silently retraining."""
    from tpucfn.ft import RESTORE_FAILED_RC

    ckpt_dir = tmp_path / "ckpt"
    (ckpt_dir / "20").mkdir(parents=True)
    (ckpt_dir / "20" / "data.bin").write_bytes(b"x")
    worker = f"import sys; sys.exit({RESTORE_FAILED_RC})\n"
    registry = MetricRegistry()
    coord = GangCoordinator(
        _launcher(tmp_path, n=1), [sys.executable, "-c", worker],
        policy=GangRestart(RestartBudget(0)), registry=registry,
        ft_dir=tmp_path / "ft", ckpt_dir=ckpt_dir, poll_interval=0.01,
        term_grace_s=0.3)
    assert coord.run() == RESTORE_FAILED_RC  # loud, not a phantom rc 0
    v = registry.varz()["metrics"]
    assert v["ft_ckpt_retries_total"] == 0
    assert v["ft_give_ups_total"] == 1
    assert (ckpt_dir / "20").is_dir()  # nothing quarantined


def test_concurrent_notice_and_crash_requeues_the_notice(tmp_path):
    """A preemption notice landing in the same detect tick as a real
    failure loses the decision to the restart — but the machine is
    still going away: the consumed notice must be re-queued so the
    relaunched gang still gets its drain."""
    from tpucfn.ft import Failure, FailureKind

    worker = "import time; time.sleep(30)\n"
    registry = MetricRegistry()
    coord = GangCoordinator(
        _launcher(tmp_path, n=2), [sys.executable, "-c", worker],
        policy=GangRestart(RestartBudget(1)), registry=registry,
        ft_dir=tmp_path / "ft", poll_interval=0.01, term_grace_s=0.3)
    try:
        coord._launch_gang(first=True)
        rc = coord._handle_incident([
            Failure(0, FailureKind.CRASH, rc=1),
            Failure(1, FailureKind.PREEMPT, lead_s=9.0)])
        assert rc is None  # gang restarted under budget
        assert [(n.host, n.lead_s) for n in coord._pending_notices] \
            == [(1, 9.0)]
        assert registry.varz()["metrics"]["ft_gang_restarts_total"] == 1
    finally:
        coord.launcher.stop_all(list(coord._procs.values()),
                                grace_s=0.3, poll_interval=0.01)


def test_ckpt_retries_exhaust_to_normal_policy(tmp_path):
    """Past max_ckpt_retries the normal table decides — a run whose
    every checkpoint is rotten must still end, with the real rc."""
    from tpucfn.ft import RESTORE_FAILED_RC

    ckpt_dir = tmp_path / "ckpt"
    for step in (10, 20):
        (ckpt_dir / str(step)).mkdir(parents=True)
        (ckpt_dir / str(step) / "data.bin").write_bytes(b"x")
    worker = f"import sys; sys.exit({RESTORE_FAILED_RC})\n"
    registry = MetricRegistry()
    coord = GangCoordinator(
        _launcher(tmp_path, n=1), [sys.executable, "-c", worker],
        policy=GangRestart(RestartBudget(0)), registry=registry,
        ft_dir=tmp_path / "ft", ckpt_dir=ckpt_dir, poll_interval=0.01,
        term_grace_s=0.3, max_ckpt_retries=1)
    assert coord.run() == RESTORE_FAILED_RC
    v = registry.varz()["metrics"]
    assert v["ft_ckpt_retries_total"] == 1  # capped
    assert v["ft_give_ups_total"] == 1      # then the budget-0 table


def test_straggler_evicted_after_hysteresis(tmp_path):
    """Sustained step lag past the hysteresis window earns a targeted
    solo restart of the straggler (the safe-by-default eviction row);
    the relaunched host catches up and the run finishes clean."""
    import os

    from tpucfn.ft import StragglerGuard

    ft_dir = tmp_path / "ft"
    worker = (
        "import json, os, pathlib, sys, time\n"
        "d = os.environ['TPUCFN_FT_DIR']; h = int(os.environ['TPUCFN_HOST_ID'])\n"
        "os.makedirs(d, exist_ok=True)\n"
        "fd = pathlib.Path(os.environ['FLAG_DIR'])\n"
        "def beat(step, seq):\n"
        "    with open(f'{d}/hb-host{h:03d}.jsonl', 'a') as f:\n"
        "        f.write(json.dumps({'host_id': h, 'pid': os.getpid(),"
        " 'step': step, 't': time.time(), 'seq': seq}) + '\\n')\n"
        "if h == 1 and (fd / 'second_1').exists():\n"
        "    beat(10**6, 1)\n"  # relaunched straggler: caught up
        "    (fd / 'done').write_text('x')\n"
        "    sys.exit(0)\n"
        "if h == 1: (fd / 'second_1').write_text('x')\n"
        "t_end = time.time() + 20\n"
        "i = 0\n"
        "while time.time() < t_end:\n"
        "    i += 1\n"
        "    beat(1 if h == 1 else 100 + i, i)\n"
        "    if h == 0 and (fd / 'done').exists(): sys.exit(0)\n"
        "    time.sleep(0.05)\n"
        "sys.exit(1)\n")
    os.environ["FLAG_DIR"] = str(tmp_path)
    try:
        registry = MetricRegistry()
        launcher = _launcher(tmp_path, n=2, ft_dir=str(ft_dir),
                             ft_heartbeat_s=0.05)
        coord = GangCoordinator(
            launcher, [sys.executable, "-c", worker],
            policy=GangRestart(RestartBudget(2)),
            monitor=HeartbeatMonitor(
                ft_dir, expected_hosts=2,
                config=MonitorConfig(interval_s=0.05, startup_grace_s=5.0,
                                     straggler_step_lag=20)),
            registry=registry, ft_dir=ft_dir, poll_interval=0.01,
            term_grace_s=0.3,
            straggler_guard=StragglerGuard(hysteresis_s=0.4,
                                           flap_budget=3))
        t0 = time.monotonic()
        assert coord.run() == 0
        assert time.monotonic() - t0 < 15
    finally:
        del os.environ["FLAG_DIR"]
    v = registry.varz()["metrics"]
    assert v["ft_straggler_evictions_total"] == 1
    assert v["ft_solo_restarts_total"] == 1
    assert v["ft_gang_restarts_total"] == 0
    events = _events(ft_dir)
    detect = next(e for e in events if e["kind"] == "detect")
    assert detect["failures"][0]["kind"] == "straggler"
    assert detect["failures"][0]["host"] == 1
    decide = next(e for e in events if e["kind"] == "decide")
    assert decide["action"] == "solo_restart" and decide["hosts"] == [1]


def test_dead_process_detection_latency(tmp_path):
    """Kill-victim path under the coordinator: the built-in fault
    injection SIGKILLs host 0 at t=0.2s and the supervision loop must
    notice within a handful of poll intervals, not seconds."""
    registry = MetricRegistry()
    coord = GangCoordinator(
        _launcher(tmp_path, n=1),
        [sys.executable, "-c", "import time; time.sleep(30)"],
        policy=GangRestart(RestartBudget(0)), registry=registry,
        ft_dir=tmp_path / "ft", poll_interval=0.01, term_grace_s=0.2,
        kill_host_after=(0, 0.2))
    t0 = time.monotonic()
    rc = coord.run()
    elapsed = time.monotonic() - t0
    assert rc == -9  # SIGKILL'd, budget 0 → give up with the real rc
    # 0.2s until the kill fires + detection + teardown; anything near a
    # second of detection latency is a polling bug
    assert elapsed < 3.0
    assert registry.varz()["metrics"]["supervisor_last_exit_code"] == -9


# -- disaggregated input plane (ISSUE 11) -----------------------------------

def _input_launcher(tmp_path, n=3, input_argv=None, **kw) -> Launcher:
    return Launcher(_contract(tmp_path, n), LocalTransport(),
                    input_hosts=1,
                    input_argv=input_argv or [
                        sys.executable, "-c", "import time; time.sleep(60)"],
                    **kw)


def test_dead_input_host_degrades_without_gang_restart(tmp_path):
    """Chaos-killing the input host records input_degraded and nothing
    else: no detect/decide incident, no relaunch, budget untouched, the
    trainers run to completion and the run exits 0."""
    from tpucfn.obs import MetricRegistry

    ft_dir = tmp_path / "ft"
    registry = MetricRegistry()
    coord = GangCoordinator(
        _input_launcher(tmp_path),
        [sys.executable, "-c", "import time; time.sleep(1.0)"],
        policy=GangRestart(RestartBudget(0)), registry=registry,
        ft_dir=ft_dir, poll_interval=0.02, term_grace_s=1.0,
        kill_host_after=(2, 0.3))
    assert coord.run() == 0
    kinds = _kinds(ft_dir)
    assert "input_degraded" in kinds
    assert "detect" not in kinds and "recovered" not in kinds
    assert "input_recovered" not in kinds  # restart off by default
    assert coord.policy.budget.used == 0
    v = registry.varz()["metrics"]
    assert v["ft_input_degradations_total"] == 1
    assert v["supervisor_restarts_total"] == 0
    degraded = next(e for e in _events(ft_dir)
                    if e["kind"] == "input_degraded")
    assert degraded["host"] == 2
    assert degraded["failure"] == "crash"


@pytest.mark.slow
def test_input_host_restart_when_enabled(tmp_path):
    """restart_input_hosts solo-relaunches the input slot (bounded) and
    records input_recovered — still zero budget, zero gang restarts."""
    from tpucfn.obs import MetricRegistry

    ft_dir = tmp_path / "ft"
    registry = MetricRegistry()
    coord = GangCoordinator(
        _input_launcher(tmp_path),
        [sys.executable, "-c", "import time; time.sleep(1.2)"],
        policy=GangRestart(RestartBudget(0)), registry=registry,
        ft_dir=ft_dir, poll_interval=0.02, term_grace_s=1.0,
        kill_host_after=(2, 0.3), restart_input_hosts=True,
        max_input_restarts=1)
    assert coord.run() == 0
    kinds = _kinds(ft_dir)
    i = kinds.index("input_degraded")
    assert "solo_launch" in kinds[i:]
    assert "input_recovered" in kinds[i:]
    v = registry.varz()["metrics"]
    assert v["ft_input_restarts_total"] == 1
    assert v["ft_gang_restarts_total"] == 0
    assert coord.policy.budget.used == 0


@pytest.mark.slow
def test_idle_input_hosts_released_when_trainers_finish(tmp_path):
    """An input service that serves until SIGTERM must not hold the run
    open after every trainer exited: the coordinator stops it and the
    run ends with the trainers' rc."""
    ft_dir = tmp_path / "ft"
    coord = GangCoordinator(
        _input_launcher(tmp_path),
        [sys.executable, "-c", "import time; time.sleep(0.3)"],
        policy=GangRestart(RestartBudget(0)),
        ft_dir=ft_dir, poll_interval=0.02, term_grace_s=1.0)
    t0 = time.monotonic()
    assert coord.run() == 0
    assert time.monotonic() - t0 < 20.0  # not the input host's sleep(60)
    exits = [e for e in _events(ft_dir) if e["kind"] == "host_exit"]
    assert any(e.get("note") for e in exits if e["host"] == 2)


@pytest.mark.slow
def test_trainer_failure_still_restarts_gang_with_input_plane(tmp_path):
    """Input-role routing must not swallow TRAINER failures: a trainer
    crash goes through the normal detect->decide->gang restart, which
    relaunches the input host too."""
    import os

    ft_dir = tmp_path / "ft"
    os.environ["FLAG"] = str(tmp_path / "ran_once")
    try:
        coord = GangCoordinator(
            _input_launcher(tmp_path),
            [sys.executable, "-c", FAIL_ONCE],
            policy=GangRestart(RestartBudget(2)),
            ft_dir=ft_dir, poll_interval=0.02, term_grace_s=1.0)
        assert coord.run() == 0
    finally:
        del os.environ["FLAG"]
    kinds = _kinds(ft_dir)
    assert "detect" in kinds and "recovered" in kinds
    assert "input_degraded" not in kinds
    # two gang launches, each covering all 3 hosts
    launches = [e for e in _events(ft_dir) if e["kind"] == "launch"]
    assert len(launches) == 2 and all(e["hosts"] == 3 for e in launches)
