import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpucfn.parallel import (
    ShardingRules,
    dense_rules,
    make_partition_spec,
    named_sharding_tree,
    shard_batch,
    transformer_rules,
)


def _specs(rules, tree):
    return make_partition_spec(rules, tree)


def test_first_match_wins():
    rules = ShardingRules(
        (
            (r"a/kernel$", P("tensor")),
            (r"kernel$", P("fsdp")),
            (r".*", P()),
        )
    )
    tree = {"a": {"kernel": jnp.zeros((4,))}, "b": {"kernel": jnp.zeros((4,))}}
    specs = _specs(rules, tree)
    assert specs["a"]["kernel"] == P("tensor")
    assert specs["b"]["kernel"] == P("fsdp")


def test_short_spec_accepted_for_higher_rank():
    rules = ShardingRules(((r"kernel$", P("fsdp", "tensor")), (r".*", P())))
    tree = {"kernel": jnp.zeros((2, 2, 2, 2))}
    assert _specs(rules, tree)["kernel"] == P("fsdp", "tensor")


def test_overlong_spec_raises():
    rules = ShardingRules(((r"kernel$", P("fsdp", "tensor")), (r".*", P())))
    with pytest.raises(ValueError, match="rank"):
        _specs(rules, {"kernel": jnp.zeros((4,))})


def test_unmatched_defaults_replicated():
    rules = ShardingRules(((r"kernel$", P("fsdp")),))
    assert _specs(rules, {"odd": jnp.zeros((4,))})["odd"] == P()


def test_transformer_preset_tp_fsdp_composition():
    rules = transformer_rules()
    tree = {
        "layers_0": {
            "attn": {
                "qkv": {"kernel": jnp.zeros((64, 192)), "bias": jnp.zeros((192,))},
                "o_proj": {"kernel": jnp.zeros((64, 64))},
            },
            "mlp": {
                "up_proj": {"kernel": jnp.zeros((64, 256))},
                "gate_proj": {"kernel": jnp.zeros((64, 256))},
                "down_proj": {"kernel": jnp.zeros((256, 64))},
            },
            "norm": {"scale": jnp.zeros((64,))},
        },
        "embed_tokens": {"embedding": jnp.zeros((1000, 64))},
    }
    specs = _specs(rules, tree)
    l0 = specs["layers_0"]
    assert l0["attn"]["qkv"]["kernel"] == P("fsdp", "tensor")
    assert l0["attn"]["qkv"]["bias"] == P("tensor")
    assert l0["attn"]["o_proj"]["kernel"] == P("tensor", "fsdp")
    assert l0["mlp"]["up_proj"]["kernel"] == P("fsdp", "tensor")
    assert l0["mlp"]["down_proj"]["kernel"] == P("tensor", "fsdp")
    assert l0["norm"]["scale"] == P()
    assert specs["embed_tokens"]["embedding"] == P("tensor", "fsdp")


def test_dense_rules_dp_replicates_all():
    specs = _specs(dense_rules(fsdp=False), {"conv1": {"kernel": jnp.zeros((3, 3, 4, 8))}})
    assert specs["conv1"]["kernel"] == P()


def test_dense_rules_fsdp_shards_cout():
    specs = _specs(dense_rules(fsdp=True), {"conv1": {"kernel": jnp.zeros((3, 3, 4, 8))}})
    assert specs["conv1"]["kernel"] == P(None, None, None, "fsdp")


def test_named_sharding_tree_binds_mesh(mesh8):
    tree = {"w": {"kernel": jnp.zeros((8, 8))}}
    sh = named_sharding_tree(mesh8, transformer_rules(), tree)
    assert isinstance(sh["w"]["kernel"], NamedSharding)
    assert sh["w"]["kernel"].mesh.axis_names == mesh8.axis_names


def test_shard_batch_places_on_batch_axes(mesh8):
    batch = {"x": np.ones((16, 4), np.float32), "y": np.ones((16,), np.int32)}
    out = shard_batch(mesh8, batch)
    assert out["x"].sharding.spec == P(("data", "fsdp", "expert"))
    # 4-way batch split (data=2 * fsdp=2): each device holds 4 rows.
    assert out["x"].addressable_shards[0].data.shape == (4, 4)
    assert isinstance(out["y"], jax.Array)


def test_shard_batch_device_layout_pins_to_copy_path(mesh8):
    """The zero-copy device-layout placement (ISSUE 18 satellite) must
    be indistinguishable downstream from shard_batch: same sharding,
    same per-device layout, bit-identical values."""
    from tpucfn.parallel.sharding import shard_batch_device_layout

    rs = np.random.RandomState(0)
    batch = {"x": rs.randn(16, 4).astype(np.float32),
             "y": rs.randint(0, 10, (16,)).astype(np.int32)}
    ref = shard_batch(mesh8, batch)
    out = shard_batch_device_layout(mesh8, batch)
    for k in batch:
        assert out[k].sharding == ref[k].sharding, k
        assert out[k].shape == ref[k].shape, k
        np.testing.assert_array_equal(np.asarray(out[k]),
                                      np.asarray(ref[k]))
        # per-device placement identical, shard for shard
        for a, b in zip(out[k].addressable_shards,
                        ref[k].addressable_shards):
            assert a.device == b.device
            np.testing.assert_array_equal(np.asarray(a.data),
                                          np.asarray(b.data))


def test_prefetch_to_mesh_device_sharded_flag(mesh_dp8, monkeypatch):
    """prefetch_to_mesh under TPUCFN_INPUT_DEVICE_SHARDED=1 yields the
    same arrays the default path does (the flag is a layout opt-in,
    never a semantic change); default-off keeps the plain path."""
    from tpucfn.data.pipeline import prefetch_to_mesh

    rs = np.random.RandomState(1)
    host_batches = [{"x": rs.randn(8, 4).astype(np.float32)}
                    for _ in range(3)]
    plain = list(prefetch_to_mesh(iter(host_batches), mesh_dp8))
    monkeypatch.setenv("TPUCFN_INPUT_DEVICE_SHARDED", "1")
    layout = list(prefetch_to_mesh(iter(host_batches), mesh_dp8))
    assert len(plain) == len(layout) == 3
    for p, q in zip(plain, layout):
        assert q["x"].sharding == p["x"].sharding
        np.testing.assert_array_equal(np.asarray(q["x"]),
                                      np.asarray(p["x"]))
