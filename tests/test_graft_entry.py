"""The driver contract file must keep working: entry() compiles, and
dryrun_multichip exercises dp/fsdp/tp/sp/ep + pipeline on fake devices."""

import sys
from pathlib import Path

import jax

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def test_dryrun_multichip_8():
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def test_entry_forward_compiles():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn).lower(*args).compile()
    assert out is not None
