"""Forensics chaos-drill acceptance (ISSUE 6): chaos-kill one host of a
two-host gang → the coordinator captures the SURVIVOR's flight ring
over its obs endpoint before restarting → after the run,
`tpucfn obs postmortem --latest` assembles a bundle whose incident
matches events.jsonl, whose flight tails cover the seconds up to
detection, and whose timeline window is skew-corrected.

Multi-second by construction (each worker pays a jax+orbax import) —
``slow``-marked, excluded from tier-1 like the other e2e drills.
"""

import json
import os
import socket
import sys
import time
from pathlib import Path

import pytest

from tpucfn.bootstrap import EnvContract
from tpucfn.ft import (
    ChaosEvent,
    ChaosSpec,
    GangCoordinator,
    GangRestart,
    HeartbeatMonitor,
    MonitorConfig,
    RestartBudget,
)
from tpucfn.launch import Launcher, LocalTransport
from tpucfn.obs import MetricRegistry
from tpucfn.obs.flight import read_flight_dir

pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parent.parent
WORKER = str(REPO / "tests" / "ft_e2e_worker.py")

TOTAL_STEPS = 40
CKPT_EVERY = 10
KILL_AT_STEP = 25  # off-boundary: the rewind definitely re-runs work


def _contract(tmp_path, n) -> EnvContract:
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("".join("127.0.0.1:0\n" for _ in range(n)))
    return EnvContract(
        workers_path=str(hostfile), workers_count=n, worker_chip_count=1,
        coordinator="127.0.0.1:1234", host_id=0, storage=str(tmp_path),
        generation=1)


def _free_port_base() -> int:
    """A base whose +1/+2 host ports are very likely free (the launcher
    hands host i base+1+i; binding base itself reserves nothing for
    them, but fresh ephemeral neighbors rarely collide on a quiet CI
    box and the drill fails loudly if they do)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_chaos_kill_postmortem_bundle(tmp_path):
    run_dir = tmp_path / "drill"
    ft_dir = run_dir / "ft"
    run_dir.mkdir()
    env = {"FT_E2E_RUN_DIR": str(run_dir),
           "FT_E2E_TOTAL_STEPS": str(TOTAL_STEPS),
           "FT_E2E_CKPT_EVERY": str(CKPT_EVERY),
           "FT_E2E_STEP_SLEEP": "0.05",
           "PYTHONPATH": str(REPO) + os.pathsep + os.environ.get(
               "PYTHONPATH", "")}
    os.environ.update(env)
    base = _free_port_base()
    launcher = Launcher(_contract(run_dir, 2), LocalTransport(),
                        obs_base_port=base,
                        ft_dir=str(ft_dir), ft_heartbeat_s=0.2)
    monitor = HeartbeatMonitor(
        ft_dir, expected_hosts=2,
        config=MonitorConfig(interval_s=0.2, startup_grace_s=120.0))
    chaos = ChaosSpec(events=(
        ChaosEvent(action="kill", at_step=KILL_AT_STEP, host=0),))
    coord = GangCoordinator(
        launcher, [sys.executable, WORKER],
        policy=GangRestart(RestartBudget(1)), monitor=monitor,
        registry=MetricRegistry(), ft_dir=ft_dir, ckpt_dir=run_dir / "ckpt",
        poll_interval=0.02, term_grace_s=1.0, chaos=chaos,
        flight_timeout_s=5.0)
    rc = coord.run()
    assert rc == 0, "gang must finish cleanly after one recovery"
    assert coord.chaos.done(), "the scripted kill must have fired"

    events = [json.loads(s) for s in
              (ft_dir / "events.jsonl").read_text().splitlines()
              if s.strip()]
    kinds = [e["kind"] for e in events]
    # -- the coordinator captured the survivor's ring at detect ----------
    assert "flight_capture" in kinds
    cap_ev = next(e for e in events if e["kind"] == "flight_capture")
    assert cap_ev["hosts"] == [1], "host 1 survived and must be captured"
    assert cap_ev["errors"] == 0
    detect = next(e for e in events if e["kind"] == "detect")
    assert detect["incident"] == cap_ev["incident"]
    captures = read_flight_dir(
        ft_dir / "flight",
        glob=f"incident{cap_ev['incident']:03d}-host*.jsonl")
    assert list(captures) == [1]
    t_last = max(s["t"] for s in captures[1]["samples"])
    # coverage up to detection: the survivor's ring reaches within a
    # couple of step periods of the detect instant
    assert detect["ts"] - t_last < 2.0

    # -- per-process SIGTERM/atexit dumps landed too ---------------------
    dumps = read_flight_dir(run_dir / "flight")
    assert sorted(dumps) == [0, 1]

    # -- the postmortem CLI assembles the bundle -------------------------
    from tpucfn.cli.main import main

    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(["obs", "postmortem", "--run-dir", str(run_dir),
                   "--latest", "--json"])
    assert rc == 0
    rep = json.loads(buf.getvalue())

    # the bundle's incident IS the events.jsonl incident
    assert rep["incident"]["incident"] == detect["incident"]
    assert rep["incident"]["action"] == "gang_restart"
    assert rep["incident"]["downtime_s"] > 0
    assert rep["detect_ts"] == pytest.approx(detect["ts"])

    # flight tails from every surviving host cover up to detection
    flight_rows = {(r["source"], r["host"]): r for r in rep["flight"]}
    cap_row = flight_rows[("incident-capture", 1)]
    assert cap_row["samples"] > 0
    assert cap_row["gap_to_detect_s"] < 2.0
    # the dead host was SIGKILLed: its only on-disk dump is its SECOND
    # incarnation's ring (post-detection), which must NOT masquerade as
    # this incident's final seconds — excluded, with a note saying so
    assert ("process-dump", 0) not in flight_rows
    assert any("host 0" in n and "after detection" in n
               for n in rep["notes"])
    # host 1 is covered by the capture, so its (overwritten) dump is
    # not double-reported either
    assert ("process-dump", 1) not in flight_rows

    # the timeline window is skew-corrected: every event annotated and
    # inside the window, both hosts present
    assert rep["timeline"], "empty timeline window"
    hosts_seen = set()
    for e in rep["timeline"]:
        assert "ts_adj" in e and e["ts_adj"] is not None
        assert rep["window"]["start"] <= e["ts_adj"] <= rep["window"]["end"]
        hosts_seen.add(e.get("host"))
    assert {0, 1} <= hosts_seen
    assert set(rep["clock_skew_s"]) == {"host0", "host1"}

    # last heartbeat per host made it in, aged against detection
    hb = {h["host"]: h for h in rep["heartbeats"]}
    assert set(hb) == {0, 1}
    assert hb[0]["age_at_detect_s"] is not None

    # bundle directory materialized
    bundle = Path(rep["bundle"])
    for name in ("report.md", "incident.json", "timeline.jsonl",
                 "goodput.json", "heartbeats.json"):
        assert (bundle / name).is_file(), name
    assert any((bundle / "flight").iterdir())

    # the goodput plane still balances after the forensics additions
    buf2 = io.StringIO()
    with contextlib.redirect_stdout(buf2):
        assert main(["obs", "goodput", "--run-dir", str(run_dir),
                     "--json"]) == 0
    gp = json.loads(buf2.getvalue())
    assert gp["num_hosts"] == 2
    assert abs(gp["accounted_s"] - gp["wall_s"]) <= 0.05 * gp["wall_s"]
    assert gp["restart_downtime_s"] > 0
