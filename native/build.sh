#!/bin/sh
# Build the native tpurecord reader. Invoked automatically by
# tpucfn/data/native.py on first use; safe to run by hand.
set -e
cd "$(dirname "$0")"
g++ -O3 -fPIC -shared -std=c++17 -Wall -o libtpurecord.so tpurecord.cc -lz
echo "built $(pwd)/libtpurecord.so"
