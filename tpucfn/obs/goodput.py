"""Goodput accounting: where did the fleet's paid TPU-seconds go?

The harness's north star is "as fast as the hardware allows", but until
now the repo could only say so *after the fact* (bench.py's offline MFU)
and could not say at all how much fleet time a run lost to compiles,
input stalls, checkpoint pauses, or the ft plane's restart/rewind
cycles.  This module is the per-run ledger that decomposes wall-clock
into named buckets (ISSUE 5 tentpole):

    productive_step  optimizer steps that advanced the run
    compile          the first step of each process incarnation (jit
                     compile + warmup dominated)
    data_wait        the step loop blocked on the input pipeline
    ckpt             checkpoint save calls
    lost_work        steps RE-RUN after rewinding to the latest
                     checkpoint (same step number executed again by a
                     later incarnation — paid twice, credited once)
    restart_downtime gaps between one incarnation's last ledger record
                     and the next incarnation's first (the host was
                     down, being detected, or rebooting)
    idle             whatever of the window's wall time no bucket claims

**Invariant:** per host, the buckets (idle included) sum to that host's
wall span — ``last record t − first window start`` — exactly, because
``idle`` and ``restart_downtime`` are defined as the residuals.  The
fleet view averages per-host seconds, so the invariant survives the
merge.

Write side: :class:`GoodputLedger` — one append-only JSONL per host
(``goodput-host{NNN}.jsonl``), the same shippable-file transport the
metrics/trace/heartbeat planes use.  Append (not truncate) on purpose:
a gang restart relaunches the trainer into the SAME file, and the
window marker it writes at open is what delimits incarnations.

Read side: :func:`read_goodput_dir` + :func:`merge_goodput` — pure
functions over parsed dicts (the ``tpucfn obs goodput`` CLI, tests and
notebooks share one implementation).  Adversarial input — torn lines,
empty dirs, a host that died mid-write — is skipped AND counted, never
raised on.

Ledger line schema (one JSON object per line)::

    {"kind": "window", "host": 0, "t": <wall>, "pid": 4242, "role": "trainer"}
    {"kind": "phase", "bucket": "step", "dur_s": 0.21, "step": 17,
     "t": <wall>, "host": 0}
    {"kind": "close", "host": 0, "t": <wall>}

The ft plane's ``events.jsonl`` feeds incident attribution: the
coordinator appends a ``goodput_incident`` record per recovery
(downtime, estimated detection latency, fleet step at detect), merged
into the report's ``incidents`` list.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from pathlib import Path
from typing import Iterable

# Buckets the writer records explicitly; idle / lost_work /
# restart_downtime are derived by the merge.  ``compile_cached`` is the
# warm-restart refinement (ISSUE 6 satellite): a first step served from
# the persistent compile cache pays deserialization + warmup, not a real
# XLA compile — ``TrainerObs`` splits the two via CompileCacheProbe so
# warm restarts stop inflating ``compile``.  ``compile_fetched`` is the
# fleet refinement (ISSUE 13): a first step whose executable was fetched
# from a peer's artifact cache paid network + deserialization — its own
# column, so the fleet warm-start plane's effect is visible per run
# (old ledgers that only ever wrote ``compile`` merge unchanged).
RECORDED_BUCKETS = ("step", "compile", "compile_cached", "compile_fetched",
                    "data_wait", "ckpt", "act", "learn", "refresh")
DERIVED_BUCKETS = ("idle", "lost_work", "restart_downtime")
# ``act``/``learn``/``refresh`` are the RL plane's phases (tpucfn.rl):
# acting slab on-device, A2C update, device-to-device param copy to the
# actors.  An RL run records those instead of ``step``, so its
# productive_step stays 0 and the three RL columns carry the wall.
REPORT_BUCKETS = ("productive_step", "compile", "compile_cached",
                  "compile_fetched", "data_wait", "ckpt", "act", "learn",
                  "refresh", "lost_work", "idle", "restart_downtime")

LEDGER_GLOB = "goodput-host*.jsonl"

# Canonical record kinds of the per-host ledger files (ISSUE 10):
# "window" opens a process incarnation, "phase" attributes one bucketed
# duration, "close" ends an incarnation cleanly.  The cross-run
# regression ledger (`--ledger`) uses its own row kind.  The
# `vocab-drift` rule of `tpucfn check` reads these tuples via ast, so a
# typo'd literal in a reader or writer is a finding, not silent drift.
LEDGER_KINDS = ("window", "phase", "close")
LEDGER_ROW_KINDS = ("goodput_run",)


def ledger_path(d: str | Path, host_id: int) -> Path:
    return Path(d) / f"goodput-host{host_id:03d}.jsonl"


# --------------------------------------------------------------------------
# cost-analysis helpers (the live-MFU side)
# --------------------------------------------------------------------------

def cost_analysis_value(cost, key: str) -> float | None:
    """One value from a ``compiled.cost_analysis()`` result.

    jax <= 0.4.x returns a per-device LIST of dicts, >= 0.5 a single
    dict — the one unwrap the live gauges and bench.py share; ``None``
    when the backend reports nothing (CPU fallback, mock devices).
    """
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    try:
        v = cost.get(key) if cost else None
    except AttributeError:
        return None
    return float(v) if v else None


def cost_analysis_flops(cost) -> float | None:
    """Per-device FLOPs from a ``compiled.cost_analysis()`` result."""
    return cost_analysis_value(cost, "flops")


# Peak dense bf16 TFLOP/s per chip by device_kind substring (public
# specs) — bench.py's table, exposed here so the LIVE gauge and the
# offline bench agree on the denominator.
PEAK_BF16_TFLOPS = (
    ("v6", 918.0), ("trillium", 918.0),
    ("v5p", 459.0),
    ("v5 lite", 197.0), ("v5e", 197.0), ("v5litepod", 197.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)


def device_peak_flops(device_kind: str) -> float | None:
    """Peak FLOP/s (not TFLOP/s) for ``device_kind``, or None for
    devices without a published peak (CPU hosts: MFU stays unset rather
    than lying)."""
    kind = device_kind.lower()
    for key, tflops in PEAK_BF16_TFLOPS:
        if key in kind:
            return tflops * 1e12
    return None


# --------------------------------------------------------------------------
# write side
# --------------------------------------------------------------------------

class GoodputLedger:
    """Per-host goodput JSONL writer (see module doc for the schema).

    Opens in append mode and immediately writes a ``window`` marker: a
    restarted incarnation appending to the same file is exactly how the
    merge learns where downtime gaps are.  ``GoodputLedger(None)`` is a
    full no-op so instrumentation points can call unconditionally.
    """

    def __init__(self, d: str | Path | None, host_id: int = 0, *,
                 role: str = "trainer", clock=time.time,
                 pid: int | None = None):
        self.host_id = host_id
        self.role = role
        self.clock = clock
        self.path: Path | None = None
        self._f = None
        self._lock = threading.Lock()
        if d is not None:
            dd = Path(d)
            dd.mkdir(parents=True, exist_ok=True)
            self.path = ledger_path(dd, host_id)
            # Line-buffered append, one write per record — a reader never
            # sees a torn line except at a crash boundary (tolerated).
            self._f = open(self.path, "a", buffering=1)
            self._write({"kind": "window", "host": host_id, "role": role,
                         "pid": os.getpid() if pid is None else pid})

    @property
    def enabled(self) -> bool:
        return self._f is not None

    def _write(self, rec: dict) -> None:
        rec.setdefault("t", self.clock())
        line = json.dumps(rec)
        with self._lock:
            if self._f is not None:
                self._f.write(line + "\n")

    def account(self, bucket: str, dur_s: float, *,
                step: int | None = None) -> None:
        """Attribute ``dur_s`` seconds to ``bucket`` (one of
        ``RECORDED_BUCKETS``; unknown buckets are written as-is and
        merged into ``idle``-adjacent custom columns by nobody — keep to
        the vocabulary)."""
        if self._f is None:
            return
        rec = {"kind": "phase", "bucket": bucket, "dur_s": float(dur_s),
               "host": self.host_id}
        if step is not None:
            rec["step"] = int(step)
        self._write(rec)

    def close(self) -> None:
        if self._f is None:
            return
        self._write({"kind": "close", "host": self.host_id})
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# --------------------------------------------------------------------------
# read side
# --------------------------------------------------------------------------

def parse_jsonl_line(line: str | bytes) -> dict | None:
    """The ONE tolerant JSONL line rule every counting reader shares
    (here and aggregate.JsonlTailer): bytes decode with U+FFFD
    replacement, parse failures and non-dict records -> None — the
    caller counts the skip.  Corruption confined to a JSON string
    value still parses (as U+FFFD text) and the record survives;
    structural corruption is what this rejects without raising."""
    if isinstance(line, bytes):
        line = line.decode("utf-8", errors="replace")
    try:
        rec = json.loads(line)
    except json.JSONDecodeError:
        return None
    return rec if isinstance(rec, dict) else None


def read_jsonl_counting(path: str | Path) -> tuple[list[dict], int]:
    """All records of one JSONL; torn/undecodable lines are skipped AND
    counted (the file may still be appended to, or its writer died
    mid-line), non-UTF-8 bytes tolerated — never raised on."""
    out: list[dict] = []
    skipped = 0
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = parse_jsonl_line(line)
                if rec is None:
                    skipped += 1
                else:
                    out.append(rec)
    except OSError:
        return [], 0
    return out, skipped


def host_id_from_path(p: str | Path) -> int | None:
    """``...host{NNN}.jsonl`` -> ``NNN``, or None when the stem doesn't
    parse.  Every per-host-file reader (ledgers here, heartbeats in the
    CLI) goes through this so the naming convention lives in one place."""
    try:
        return int(Path(p).stem.rsplit("host", 1)[1])
    except (IndexError, ValueError):
        return None


def read_goodput_dir(d: str | Path) -> tuple[dict[int, list[dict]], int]:
    """``host_id -> [records]`` for every ledger under ``d`` plus the
    total count of torn/skipped lines.  Missing/empty dir -> ``({}, 0)``
    — the merge renders an empty report, it does not raise."""
    by_host: dict[int, list[dict]] = {}
    skipped = 0
    dd = Path(d)
    if not dd.is_dir():
        return by_host, skipped
    for p in sorted(dd.glob(LEDGER_GLOB)):
        host = host_id_from_path(p)
        if host is None:
            skipped += 1
            continue
        recs, sk = read_jsonl_counting(p)
        skipped += sk
        if recs:
            by_host[host] = recs
    return by_host, skipped


def read_ft_events(path: str | Path) -> tuple[list[dict], int]:
    """The ft plane's ``events.jsonl`` (torn-tolerant, counted)."""
    p = Path(path)
    if not p.is_file():
        return [], 0
    return read_jsonl_counting(p)


def host_goodput(records: Iterable[dict]) -> dict:
    """Decompose one host's ledger into the bucket report.

    Windows are delimited by ``window`` markers; within a window the
    wall is ``last record t − window t`` and ``idle`` is the residual
    after the recorded phases.  Gaps BETWEEN windows are
    ``restart_downtime``.  A ``step``-bucket record whose step number
    does not exceed the largest step already seen is a post-rewind
    re-run and lands in ``lost_work`` instead of ``productive_step``.
    """
    buckets = {b: 0.0 for b in REPORT_BUCKETS}
    windows: list[dict] = []
    cur: dict | None = None
    max_step = None
    productive_steps = 0
    lost_steps = 0
    lost_occurrences: list[dict] = []
    malformed = 0

    def _close_window(end_t: float) -> None:
        nonlocal cur
        if cur is None:
            return
        wall = max(0.0, end_t - cur["start"])
        idle = max(0.0, wall - cur["accounted"])
        buckets["idle"] += idle
        windows.append({"start": cur["start"], "end": end_t,
                        "wall_s": wall, "idle_s": idle})
        cur = None

    for rec in records:
        t = rec.get("t")
        # json.loads accepts the non-standard NaN/Infinity constants, and
        # one NaN accumulated here poisons every downstream sum AND makes
        # the --json output unparseable by strict readers — non-finite is
        # malformed, same as missing.
        if not isinstance(t, (int, float)) or not math.isfinite(t):
            malformed += 1
            continue
        kind = rec.get("kind")
        if kind == "window":
            if cur is not None:
                # previous incarnation died without a close record: its
                # window ends at its last seen t.
                _close_window(cur["last"])
            if windows:
                # the gap since the previous incarnation's end — whether
                # it closed cleanly or died mid-write — is downtime.
                buckets["restart_downtime"] += max(
                    0.0, t - windows[-1]["end"])
            cur = {"start": t, "last": t, "accounted": 0.0}
        elif kind == "phase":
            if cur is None:  # torn head: phase before any window marker
                cur = {"start": t, "last": t, "accounted": 0.0}
            # Any phase record with a finite t is liveness evidence and
            # extends the window, malformed dur/bucket or not — a torn
            # final record must not shrink the window and inflate the
            # next incarnation's restart_downtime.
            cur["last"] = max(cur["last"], t)
            dur = rec.get("dur_s")
            if (not isinstance(dur, (int, float))
                    or not math.isfinite(dur) or dur < 0):
                malformed += 1
                continue
            bucket = rec.get("bucket")
            if bucket not in RECORDED_BUCKETS:
                malformed += 1
                continue
            cur["accounted"] += dur
            step = rec.get("step")
            if bucket == "step":
                if (step is not None and max_step is not None
                        and step <= max_step):
                    buckets["lost_work"] += dur
                    lost_steps += 1
                    lost_occurrences.append({"step": step, "t": t})
                else:
                    buckets["productive_step"] += dur
                    productive_steps += 1
                if step is not None:
                    max_step = step if max_step is None else max(max_step,
                                                                 step)
            else:  # compile* / data_wait / ckpt
                buckets[bucket] += dur
                # compile of a re-run window still advances max_step so
                # the re-run detector has the right horizon
                if bucket in ("compile", "compile_cached",
                              "compile_fetched") and step is not None:
                    max_step = step if max_step is None else max(max_step,
                                                                 step)
        elif kind == "close":
            if cur is not None:
                cur["last"] = max(cur["last"], t)
                _close_window(cur["last"])
        else:
            malformed += 1
    if cur is not None:
        _close_window(cur["last"])

    wall = (windows[-1]["end"] - windows[0]["start"]) if windows else 0.0
    accounted = sum(buckets.values())
    return {
        "wall_s": wall,
        "buckets": buckets,
        "accounted_s": accounted,
        # residual beyond the derived fillers: float noise only, by
        # construction — the invariant the acceptance test pins.
        "unaccounted_s": wall - accounted,
        "windows": len(windows),
        "productive_steps": productive_steps,
        "lost_steps": lost_steps,
        "lost_occurrences": lost_occurrences,
        "malformed_records": malformed,
        "goodput_ratio": (buckets["productive_step"] / wall) if wall > 0
        else None,
    }


def _incidents_from_events(events: Iterable[dict]) -> list[dict]:
    """Incident attribution rows from the ft plane's events.jsonl.

    Prefers the coordinator's enriched ``goodput_incident`` records;
    falls back to pairing ``detect``/``recovered`` (older event files)
    using recovered's ``mttr_s`` as the downtime.  An incident that
    never recovered — the coordinator gave up (budget exhausted) or
    observed-only — still gets a row: its action comes from the
    ``give_up``/``decide`` event and its downtime is unknown (None),
    because the run ended with it.  Dropping it would hide exactly the
    incident whose cost was the whole tail of the run.
    """
    enriched: dict[int, dict] = {}
    detects: dict[int, dict] = {}
    recovered: dict[int, dict] = {}
    give_ups: dict[int, dict] = {}
    decides: dict[int, dict] = {}
    for e in events:
        kind, inc = e.get("kind"), e.get("incident")
        if inc is None:
            continue
        if kind == "goodput_incident":
            enriched[inc] = e
        elif kind == "detect":
            detects[inc] = e
        elif kind == "recovered":
            recovered[inc] = e
        elif kind == "give_up":
            give_ups[inc] = e
        elif kind == "decide":
            decides[inc] = e
    out = []
    for inc in sorted(set(detects) | set(enriched)):
        if inc in enriched:
            e = enriched[inc]
            out.append({"incident": inc, "action": e.get("action"),
                        "ts": e.get("ts"),
                        "downtime_s": e.get("downtime_s"),
                        "detection_s": e.get("detection_s"),
                        "fleet_step": e.get("fleet_step"),
                        "lost_steps": e.get("lost_steps"),
                        # graceful-degradation fields (ISSUE 7): a
                        # planned drain must not read as a downtime
                        # regression; shrink/ckpt carry the N→N-1 and
                        # retried-step detail the renderers show.
                        "planned": bool(e.get("planned", False)),
                        "shrink": e.get("shrink"),
                        "ckpt": e.get("ckpt"),
                        # adopted-coordinator recovery (ISSUE 13
                        # satellite): how much of the downtime was
                        # journal replay — measured by the adopter,
                        # attributed here instead of vanishing into
                        # the restart_downtime residual.
                        "journal_replay_ms": e.get("journal_replay_ms")})
        elif inc in recovered:
            out.append({"incident": inc,
                        "action": recovered[inc].get("action"),
                        "ts": recovered[inc].get("ts"),
                        "downtime_s": recovered[inc].get("mttr_s"),
                        "detection_s": None, "fleet_step": None,
                        "lost_steps": None,
                        "planned": bool(recovered[inc].get("planned",
                                                           False)),
                        "shrink": recovered[inc].get("shrink"),
                        "ckpt": recovered[inc].get("ckpt"),
                        "journal_replay_ms":
                            recovered[inc].get("journal_replay_ms")})
        else:
            e = give_ups.get(inc) or decides.get(inc) or detects[inc]
            action = ("give_up" if inc in give_ups
                      else e.get("action"))
            out.append({"incident": inc, "action": action,
                        "ts": e.get("ts"), "downtime_s": None,
                        "detection_s": None, "fleet_step": None,
                        "lost_steps": None, "planned": False,
                        "shrink": None, "ckpt": None,
                        "journal_replay_ms": None})
    return out


def merge_goodput(by_host: dict[int, list[dict]],
                  ft_events: Iterable[dict] = (),
                  skipped_lines: int = 0) -> dict:
    """Fleet goodput report: per-host decompositions plus the fleet
    average (per-host-mean seconds, so fleet buckets still sum to the
    fleet wall) and the incident attribution rows.

    Hosts with no parseable records are dropped and counted
    (``hosts_empty``) — skip-and-count, never raise.
    """
    hosts = {}
    empty = 0
    for host_id in sorted(by_host):
        rep = host_goodput(by_host[host_id])
        if rep["windows"] == 0:
            empty += 1
            continue
        hosts[host_id] = rep

    fleet_buckets = {b: 0.0 for b in REPORT_BUCKETS}
    n = len(hosts)
    wall = 0.0
    if n:
        for rep in hosts.values():
            wall += rep["wall_s"]
            for b in REPORT_BUCKETS:
                fleet_buckets[b] += rep["buckets"][b]
        wall /= n
        fleet_buckets = {b: v / n for b, v in fleet_buckets.items()}
    incidents = _incidents_from_events(ft_events)
    # Per-incident lost-step attribution: the coordinator cannot know
    # at recovery time how many steps the rewind will cost — the
    # re-runs happen AFTER its goodput_incident event is written — so
    # the ledger answers here, binning by TIME: a re-run executes after
    # its causing incident's recovery (the event's wall ``ts``) and
    # before the next incident's.  Step-number binning would miscredit
    # a later rewind that crosses an earlier incident's fleet_step
    # (incident 1 at step 10 losing nothing, incident 2 rewinding to
    # step 5 — steps 6..10 belong to incident 2).
    occ_times = sorted(o["t"] for rep in hosts.values()
                       for o in rep["lost_occurrences"])
    timed = sorted((i for i in incidents
                    if i.get("ts") is not None
                    and i["lost_steps"] is None),
                   key=lambda i: i["ts"])
    for inc in timed:
        inc["lost_steps"] = 0
    for t in occ_times:
        owner = None
        for inc in timed:
            if inc["ts"] <= t:
                owner = inc
            else:
                break
        if owner is None and timed:
            owner = timed[0]  # clock skew placed the re-run pre-detect
        if owner is not None:
            owner["lost_steps"] += 1
    # lost_occurrences only feeds the binning above: one {step, t} per
    # re-run step is unbounded payload in --json/watch-cached reports,
    # and no renderer reads it (render_goodput shows counts).
    for rep in hosts.values():
        rep.pop("lost_occurrences", None)
    accounted = sum(fleet_buckets.values())
    return {
        "hosts": {str(h): rep for h, rep in hosts.items()},
        "num_hosts": n,
        "hosts_empty": empty,
        "skipped_lines": skipped_lines,
        "wall_s": wall,
        "buckets": fleet_buckets,
        "accounted_s": accounted,
        "unaccounted_s": wall - accounted,
        "goodput_ratio": (fleet_buckets["productive_step"] / wall)
        if wall > 0 else None,
        "productive_steps": sum(r["productive_steps"]
                                for r in hosts.values()),
        "lost_steps": sum(r["lost_steps"] for r in hosts.values()),
        "restart_downtime_s": fleet_buckets["restart_downtime"],
        "lost_work_s": fleet_buckets["lost_work"],
        "incidents": incidents,
        "incident_downtime_s": sum(i["downtime_s"] or 0.0
                                   for i in incidents),
        # Drained preemptions are restarts the fleet CHOSE to make
        # (ISSUE 7) — regression tracking should watch the unplanned
        # number, with the planned share reported alongside.
        "unplanned_downtime_s": sum(i["downtime_s"] or 0.0
                                    for i in incidents
                                    if not i.get("planned")),
        # Of the restart downtime, how much was the adopted
        # coordinator replaying its journal (ISSUE 13 satellite) —
        # the crash-safety plane's own MTTR cost, named.
        "journal_replay_ms": sum(i.get("journal_replay_ms") or 0.0
                                 for i in incidents),
    }


def goodput_report(goodput_dir: str | Path,
                   ft_events_path: str | Path | None = None) -> dict:
    """One-call read+merge: the ``tpucfn obs goodput`` entry point."""
    by_host, skipped = read_goodput_dir(goodput_dir)
    events: list[dict] = []
    if ft_events_path is not None:
        events, ev_skipped = read_ft_events(ft_events_path)
        skipped += ev_skipped
    return merge_goodput(by_host, events, skipped_lines=skipped)


def fleet_window_observation(goodput_dir: str | Path, *,
                             since_t: float | None = None) -> dict | None:
    """Live windowed view of the fleet ledgers for the provisioner
    policy loop (ISSUE 18): bucket *shares* of wall since ``since_t``
    (wall clock, the same clock ledger records carry in ``t``).

    Unlike :func:`merge_goodput` — the end-of-run postmortem — this is
    read mid-run, repeatedly, over ledgers still being appended to, and
    the caller cares about the RECENT window only: a policy must not
    keep acting on starvation that an earlier actuation already fixed.
    Filtering by ``t`` (not by incarnation) is what makes "the window
    since my last actuation" expressible.

    Per host: phase records with finite ``t >= since_t``; the host wall
    is the ``t``-span of its in-window records; ``idle`` is the
    residual.  Shares are averaged across hosts (the same merge rule as
    :func:`merge_goodput`).  Returns ``None`` when no host has a
    usable window (empty dir, all records filtered, zero wall) — the
    policy treats that as "no evidence", never as "healthy".
    """
    by_host, _ = read_goodput_dir(goodput_dir)
    per_host: list[dict] = []
    for records in by_host.values():
        lo = hi = None
        buckets = {b: 0.0 for b in RECORDED_BUCKETS}
        for rec in records:
            t = rec.get("t")
            if not isinstance(t, (int, float)) or not math.isfinite(t):
                continue
            if since_t is not None and t < since_t:
                continue
            lo = t if lo is None else min(lo, t)
            hi = t if hi is None else max(hi, t)
            if rec.get("kind") != "phase":
                continue
            dur = rec.get("dur_s")
            bucket = rec.get("bucket")
            if (isinstance(dur, (int, float)) and math.isfinite(dur)
                    and dur >= 0 and bucket in buckets):
                buckets[bucket] += dur
        if lo is None or hi is None:
            continue
        wall = hi - lo
        if wall <= 0:
            continue
        shares = {b: min(1.0, v / wall) for b, v in buckets.items()}
        shares["idle"] = max(0.0, 1.0 - sum(shares.values()))
        per_host.append({"wall_s": wall, "shares": shares})
    if not per_host:
        return None
    n = len(per_host)
    share_names = set()
    for h in per_host:
        share_names.update(h["shares"])
    shares = {b: sum(h["shares"].get(b, 0.0) for h in per_host) / n
              for b in sorted(share_names)}
    return {
        "wall_s": sum(h["wall_s"] for h in per_host) / n,
        "shares": shares,
        "goodput_ratio": shares.get("step", 0.0),
        "num_hosts": n,
    }


def append_goodput_ledger(path: str | Path, report: dict, *,
                          run_dir: str = "", extra: dict | None = None
                          ) -> Path:
    """Cross-run regression ledger (ISSUE 6 satellite): append ONE
    BENCH-row-style JSON line per run to ``path`` so goodput_ratio and
    bucket shares can be diffed across PRs — a perf change that trades
    step time for data stalls is invisible to MFU alone but obvious
    here.  ``tpucfn obs diff`` compares the last two rows."""
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    wall = report.get("wall_s") or 0.0
    buckets = report.get("buckets") or {}
    row = {
        "kind": "goodput_run",
        "t": time.time(),
        "run_dir": run_dir,
        "wall_s": wall,
        "goodput_ratio": report.get("goodput_ratio"),
        "num_hosts": report.get("num_hosts"),
        "productive_steps": report.get("productive_steps"),
        "lost_steps": report.get("lost_steps"),
        "incidents": len(report.get("incidents") or ()),
        "planned_incidents": sum(
            1 for i in (report.get("incidents") or ())
            if i.get("planned")),
        "unplanned_downtime_s": report.get("unplanned_downtime_s"),
        "journal_replay_ms": report.get("journal_replay_ms"),
        "buckets": dict(buckets),
        "shares": {b: (v / wall if wall > 0 else None)
                   for b, v in buckets.items()},
        **(extra or {}),
    }
    with open(p, "a") as f:
        f.write(json.dumps(row) + "\n")
    return p


def read_goodput_ledger(path: str | Path) -> tuple[list[dict], int]:
    """The ledger's ``goodput_run`` rows in file order (torn/foreign
    lines skipped and counted — the file is append-shared)."""
    recs, skipped = read_jsonl_counting(path)
    rows = [r for r in recs if r.get("kind") == "goodput_run"]
    skipped += len(recs) - len(rows)
    return rows, skipped


def diff_goodput_rows(prev: dict, last: dict) -> dict:
    """Bucket-share and goodput-ratio deltas between two ledger rows
    (``last - prev``; positive share delta = that bucket ate MORE of the
    wall).  Buckets are the union of both rows, REPORT_BUCKETS order
    first so the table reads the same as ``tpucfn obs goodput``."""
    ps, ls = prev.get("shares") or {}, last.get("shares") or {}
    names = [b for b in REPORT_BUCKETS if b in ps or b in ls]
    names += sorted((set(ps) | set(ls)) - set(names))
    rows = []
    for b in names:
        a, z = ps.get(b), ls.get(b)
        rows.append({"bucket": b, "prev_share": a, "last_share": z,
                     "delta": (z - a) if (a is not None and z is not None)
                     else None})
    pr, lr = prev.get("goodput_ratio"), last.get("goodput_ratio")
    return {
        "prev": {"t": prev.get("t"), "run_dir": prev.get("run_dir"),
                 "goodput_ratio": pr, "wall_s": prev.get("wall_s")},
        "last": {"t": last.get("t"), "run_dir": last.get("run_dir"),
                 "goodput_ratio": lr, "wall_s": last.get("wall_s")},
        "goodput_ratio_delta": (lr - pr) if (pr is not None
                                             and lr is not None) else None,
        "buckets": rows,
    }


def render_goodput(report: dict) -> str:
    """Human rendering of :func:`merge_goodput` (tables live in
    aggregate.render_table; this adds the bucket bar summary)."""
    from tpucfn.obs.aggregate import render_table

    lines = [f"# goodput  hosts={report['num_hosts']} "
             f"wall={report['wall_s']:.2f}s "
             f"goodput_ratio="
             + (f"{report['goodput_ratio']:.3f}"
                if report["goodput_ratio"] is not None else "n/a")]
    wall = report["wall_s"] or math.inf
    rows = [{"bucket": b, "seconds": report["buckets"][b],
             "share": report["buckets"][b] / wall}
            for b in REPORT_BUCKETS]
    lines.append(render_table(rows, ["bucket", "seconds", "share"]))
    host_rows = [{"host": h,
                  "wall_s": rep["wall_s"],
                  "productive_s": rep["buckets"]["productive_step"],
                  "lost_work_s": rep["buckets"]["lost_work"],
                  "downtime_s": rep["buckets"]["restart_downtime"],
                  "steps": rep["productive_steps"],
                  "lost_steps": rep["lost_steps"],
                  "windows": rep["windows"],
                  "goodput": rep["goodput_ratio"]}
                 for h, rep in sorted(report["hosts"].items(),
                                      key=lambda kv: int(kv[0]))]
    if host_rows:
        lines.append("")
        lines.append(render_table(host_rows, [
            "host", "wall_s", "productive_s", "lost_work_s", "downtime_s",
            "steps", "lost_steps", "windows", "goodput"]))
    if report["incidents"]:
        lines.append("")
        planned = sum(1 for i in report["incidents"] if i.get("planned"))
        lines.append(
            "== incidents =="
            + (f"  ({planned} planned; unplanned downtime "
               f"{report.get('unplanned_downtime_s', 0.0):.2f}s)"
               if planned else ""))
        lines.append(render_table(report["incidents"], [
            "incident", "action", "planned", "downtime_s", "detection_s",
            "fleet_step", "lost_steps"]))
    if report["skipped_lines"] or report["hosts_empty"]:
        lines.append(f"\n(skipped {report['skipped_lines']} torn lines, "
                     f"{report['hosts_empty']} empty hosts)")
    return "\n".join(lines)
