from tpucfn.obs.metrics import (  # noqa: F401
    Counter,
    Gauge,
    MetricLogger,
    StepTimer,
    Summary,
)
from tpucfn.obs.profiler import (  # noqa: F401
    enable_compile_cache,
    profile_steps,
    start_profiler_server,
)
