"""Input transforms — the preprocessing half of the staging path.

The reference's examples leaned on MXNet DataIter's built-in augmentation
(random crop/mirror for CIFAR, inception-style crops for ImageNet —
SURVEY.md §3.2's DataIter frame). tpucfn keeps preprocessing on the host
side of the S3→HBM path as pure numpy, seeded per (epoch, batch) so any
host can reproduce any batch — determinism the reference's pipeline never
had (SURVEY.md §7.4 item 1).

All transforms take and return example dicts; compose with ``Compose``.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Sequence

import numpy as np

Transform = Callable[[dict, np.random.RandomState], dict]

# Transforms are module-level classes (factory functions below keep the
# call-site API) so they PICKLE — the spawn-based MultiProcessLoader
# ships them to worker processes.


class Compose:
    def __init__(self, transforms: Sequence[Transform]):
        self.transforms = tuple(transforms)

    def __call__(self, ex: dict, rs: np.random.RandomState) -> dict:
        for t in self.transforms:
            ex = t(ex, rs)
        return ex


@dataclasses.dataclass
class RandomFlip:
    key: str = "image"

    def __call__(self, ex, rs):
        if rs.rand() < 0.5:
            ex = {**ex, self.key: ex[self.key][:, ::-1]}
        return ex


def random_flip(key: str = "image") -> Transform:
    return RandomFlip(key)


@dataclasses.dataclass
class RandomCrop:
    """Pad-and-crop (the CIFAR recipe): reflect-pad then take a random
    window of the original size."""

    padding: int = 4
    key: str = "image"

    def __call__(self, ex, rs):
        img = ex[self.key]
        pad = self.padding
        h, w = img.shape[:2]
        padded = np.pad(img, ((pad, pad), (pad, pad), (0, 0)),
                        mode="reflect")
        y = rs.randint(0, 2 * pad + 1)
        x = rs.randint(0, 2 * pad + 1)
        return {**ex, self.key: padded[y:y + h, x:x + w]}


def random_crop(padding: int = 4, key: str = "image") -> Transform:
    return RandomCrop(padding, key)


@dataclasses.dataclass
class RandomResizedCrop:
    """Inception-style crop (the ImageNet ResNet-50 recipe): random area/
    aspect window, resized to ``out_hw`` (nearest-neighbor — host-side
    cheap; bilinear differences wash out under training noise)."""

    out_hw: int
    min_area: float = 0.08
    key: str = "image"

    def __call__(self, ex, rs):
        img = ex[self.key]
        out_hw = self.out_hw
        h, w = img.shape[:2]
        for _ in range(10):
            area = rs.uniform(self.min_area, 1.0) * h * w
            aspect = np.exp(rs.uniform(np.log(3 / 4), np.log(4 / 3)))
            ch = int(round(np.sqrt(area / aspect)))
            cw = int(round(np.sqrt(area * aspect)))
            if ch <= h and cw <= w and ch > 0 and cw > 0:
                y = rs.randint(0, h - ch + 1)
                x = rs.randint(0, w - cw + 1)
                crop = img[y:y + ch, x:x + cw]
                break
        else:
            side = min(h, w)
            crop = img[(h - side) // 2:(h + side) // 2,
                       (w - side) // 2:(w + side) // 2]
        yy = (np.arange(out_hw) * crop.shape[0] / out_hw).astype(np.int64)
        xx = (np.arange(out_hw) * crop.shape[1] / out_hw).astype(np.int64)
        return {**ex, self.key: crop[yy][:, xx]}


def random_resized_crop(out_hw: int, *, min_area: float = 0.08,
                        key: str = "image") -> Transform:
    return RandomResizedCrop(out_hw, min_area, key)


@dataclasses.dataclass
class Normalize:
    mean: tuple
    std: tuple
    key: str = "image"

    def __call__(self, ex, rs):
        m = np.asarray(self.mean, np.float32)
        s = np.asarray(self.std, np.float32)
        return {**ex, self.key: (ex[self.key].astype(np.float32) - m) / s}


def normalize(mean: Sequence[float], std: Sequence[float],
              key: str = "image") -> Transform:
    return Normalize(tuple(mean), tuple(std), key)


CIFAR_TRAIN = Compose([random_crop(4), random_flip()])
IMAGENET_TRAIN = Compose([random_resized_crop(224), random_flip()])

# Channel statistics for real (0-255 uint8) images, in pixel units.
IMAGENET_MEAN = (123.675, 116.28, 103.53)
IMAGENET_STD = (58.395, 57.12, 57.375)
