"""jax-hazards: donated-buffer reuse and per-step recompilation.

Incidents encoded (CHANGES.md):

* PR 4's resume crasher — orbax/tensorstore handed back buffers XLA did
  not own, and the trainer's ``donate_argnums`` freed them through the
  wrong allocator ("corrupted double-linked list" aborts).  The general
  shape the rule catches statically: an argument passed in a donated
  position of a jitted call is **read again after the call** without
  being rebound from its result — donation invalidated that buffer, so
  the read is a use-after-free that jax reports (at best) as
  "buffer deleted" at some later, unrelated line.
* ``jax.jit`` invoked inside a loop body builds a fresh jitted callable
  (and usually a fresh compile-cache miss) per iteration — the classic
  silent 100x step-time bug.  Deliberate compile sweeps (the flash
  autotuner) baseline the finding with a justification.

Both checks resolve ``jax.jit(...)``/``jit(...)`` assignments (including
``self._x = jax.jit(impl, donate_argnums=(0,))`` in ``__init__``) and
then inspect call sites of those targets; unknown call targets are
never flagged.
"""

from __future__ import annotations

import ast

from tpucfn.analysis.core import (
    Analysis,
    Finding,
    FuncInfo,
    calls_in,
    sub_suites,
)

RULE_ID = "jax-hazards"


def _is_jit(call: ast.Call, jit_aliases: set[str]) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "jit" \
            and isinstance(f.value, ast.Name) and f.value.id == "jax":
        return True
    return isinstance(f, ast.Name) and f.id in jit_aliases


def _jit_aliases(mod) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            for a in node.names:
                if a.name == "jit":
                    out.add(a.asname or a.name)
    return out


def _donated_positions(call: ast.Call) -> frozenset[int]:
    """Literal ``donate_argnums`` positions; an ``(0,) if cond else ()``
    conditional donates conservatively (union of both branches)."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        return frozenset(_int_tuple(kw.value))
    return frozenset()


def _int_tuple(node: ast.expr) -> set[int]:
    if isinstance(node, (ast.Tuple, ast.List)):
        return {e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)}
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, ast.IfExp):
        return _int_tuple(node.body) | _int_tuple(node.orelse)
    return set()


# (jit-holding targets and donated-argument expressions share one
# normalizer: _expr_key below)


def check(analysis: Analysis):
    findings: list[Finding] = []
    for mod in analysis.modules:
        aliases = _jit_aliases(mod)
        funcs = analysis.functions(mod)

        # -- jit built inside a loop body ------------------------------
        for qual, info in funcs.items():
            if isinstance(info.node, ast.Lambda):
                continue
            seen_in_func = 0
            for call, in_loop in _calls_with_loop_depth(info.node):
                if in_loop and _is_jit(call, aliases):
                    seen_in_func += 1
                    findings.append(Finding(
                        RULE_ID, mod.rel, call.lineno,
                        f"jax.jit called inside a loop body in {qual} — "
                        "every iteration builds a fresh jitted callable "
                        "(and usually recompiles); hoist the jit out of "
                        "the loop or cache the callable",
                        key=f"jit-in-loop:{qual}:{seen_in_func}"))

        # -- donated argument read after the call ----------------------
        donating: dict[tuple[str | None, str], frozenset[int]] = {}
        for qual, info in funcs.items():
            if isinstance(info.node, ast.Lambda):
                continue
            for node in ast.walk(info.node):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call) and \
                        _is_jit(node.value, aliases):
                    donated = _donated_positions(node.value)
                    if not donated:
                        continue
                    for t in node.targets:
                        k = _expr_key(t)
                        if k:
                            donating[(info.class_name, k)] = donated
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    _is_jit(node.value, aliases):
                donated = _donated_positions(node.value)
                if donated:
                    for t in node.targets:
                        k = _expr_key(t)
                        if k:
                            donating[(None, k)] = donated
        if donating:
            for qual, info in funcs.items():
                if isinstance(info.node, ast.Lambda):
                    continue
                findings.extend(
                    _donated_reads(mod, info, donating))
    return findings


def _calls_with_loop_depth(func: ast.FunctionDef):
    """Yield ``(call, in_loop)`` for calls in the function body, not
    descending into nested defs (a jit built once inside a closure
    factory called from a loop is the factory's business)."""

    def rec(stmts, in_loop):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            here = in_loop or isinstance(stmt, (ast.For, ast.AsyncFor,
                                                ast.While))
            for field in stmt._fields:
                v = getattr(stmt, field, None)
                exprs = []
                if isinstance(v, ast.expr):
                    exprs.append(v)
                elif isinstance(v, list):
                    exprs.extend(x for x in v if isinstance(x, ast.expr))
                    exprs.extend(x.context_expr for x in v
                                 if isinstance(x, ast.withitem))
                for e in exprs:
                    for n in ast.walk(e):
                        if isinstance(n, ast.Call):
                            # lambda bodies belong to the lambda
                            yield n, here
            for sub in sub_suites(stmt):
                yield from rec(sub, here)

    yield from rec(func.body, False)


def _expr_key(e: ast.expr) -> str | None:
    if isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name) \
            and e.value.id == "self":
        return f"self.{e.attr}"
    if isinstance(e, ast.Name):
        return e.id
    return None


def _donated_reads(mod, info: FuncInfo, donating) -> list[Finding]:
    """Find calls to known-donating jitted targets whose donated
    arguments are read after the call without being rebound from its
    result (suite-local: the analysis follows the statement list the
    call lives in)."""
    findings: list[Finding] = []

    def scan_suite(stmts: list[ast.stmt]):
        for i, stmt in enumerate(stmts):
            # only calls lexically in THIS suite: a call inside a nested
            # body (try/if/for) is analyzed by that suite's own pass,
            # where the rebind targets and the read-after horizon are
            # the nested suite's — checking it against the outer suite
            # reported a guarded rebind as a use-after-free
            for call in calls_in(stmt):
                k = _expr_key(call.func)
                if k is None:
                    continue
                donated = donating.get((info.class_name, k)) \
                    or donating.get((None, k))
                if not donated:
                    continue
                rebound = _stmt_targets(stmt)
                for pos in sorted(donated):
                    if pos >= len(call.args):
                        continue
                    argk = _expr_key(call.args[pos])
                    if argk is None or argk in rebound:
                        continue
                    hit = _read_after(stmts[i + 1:], argk)
                    if hit is not None:
                        findings.append(Finding(
                            RULE_ID, mod.rel, hit,
                            f"{argk} is donated to {k} (donate_argnums "
                            f"position {pos}) in {info.qualname} and read "
                            "again after the call without being rebound "
                            "from its result — the donated buffer is "
                            "freed, so this read is a use-after-free "
                            "(\"buffer deleted\" at runtime)",
                            key=f"donated:{info.qualname}:{k}:{argk}"))
            # nested suites get their own pass
            for sub in sub_suites(stmt):
                scan_suite(sub)

    scan_suite(info.node.body)
    return findings


def _stmt_targets(stmt: ast.stmt) -> set[str]:
    out: set[str] = set()
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for t in targets:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                k = _expr_key(e)
                if k:
                    out.add(k)
        else:
            k = _expr_key(t)
            if k:
                out.add(k)
    return out


def _read_after(stmts: list[ast.stmt], key: str) -> int | None:
    """Line of the first read of ``key`` in the following statements, or
    None if it is rebound first (or never touched).  Evaluation order
    matters: an assignment's RHS reads before its targets store, and a
    rebind inside a nested suite (``if retry: x = y + 1``) counts as a
    rebind — flagging the read after it was a review-pass false
    positive.  A store on SOME branch conservatively ends the scan (a
    linter prefers a missed maybe-hazard to a false alarm)."""
    for stmt in stmts:
        verdict = _first_access(stmt, key)
        if verdict is None:
            continue
        kind, line = verdict
        if kind == "read":
            return line
        return None  # rebound (at least on one executed path)


def _first_access(stmt: ast.stmt, key: str):
    """``("read", line)`` / ``("store", line)`` / None for the first
    access of ``key`` in one statement, honoring RHS-before-targets
    evaluation order and recursing into nested suites."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return None

    def reads_in(expr) -> int | None:
        for node in ast.walk(expr):
            if isinstance(node, (ast.Name, ast.Attribute)) \
                    and _expr_key(node) == key \
                    and isinstance(getattr(node, "ctx", None), ast.Load):
                return node.lineno
        return None

    # the statement's own expressions (RHS, test, iter...) read first
    for field in stmt._fields:
        v = getattr(stmt, field, None)
        exprs = [v] if isinstance(v, ast.expr) else \
            [x for x in v if isinstance(x, ast.expr)] \
            if isinstance(v, list) else []
        for e in exprs:
            line = reads_in(e)
            if line is not None:
                return ("read", line)
    if key in _stmt_targets(stmt):
        return ("store", stmt.lineno)
    store = None
    for sub in sub_suites(stmt):
        for s in sub:
            v = _first_access(s, key)
            if v is None:
                continue
            if v[0] == "read":
                return v
            store = v
            break  # this suite rebound it; later stmts here are safe
    return store
