import jax
import jax.numpy as jnp
import numpy as np
import optax

from tpucfn.models import ResNet, ResNetConfig
from tpucfn.parallel import dense_rules, shard_batch
from tpucfn.train import Trainer


def _tiny_cfg():
    # ResNet-20 topology at 1/2 width to keep CPU tests quick.
    return ResNetConfig(
        stage_sizes=(1, 1, 1), num_classes=10, bottleneck=False, width=8,
        cifar_stem=True, dtype=jnp.float32,
    )


def test_resnet20_forward_shape():
    model = ResNet(ResNetConfig.resnet20_cifar())
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32


def test_resnet50_param_count():
    model = ResNet(ResNetConfig.resnet50())
    variables = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.zeros((1, 224, 224, 3)), train=False)
    )
    n = sum(np.prod(p.shape) for p in jax.tree.leaves(variables["params"]))
    # ResNet-50 v1.5: ~25.6M params
    assert 25e6 < n < 26e6


def _resnet_trainer(mesh, cfg, fsdp=False):
    model = ResNet(cfg)
    sample = jnp.zeros((1, 32, 32, 3))

    def init_fn(rng):
        variables = model.init(rng, sample, train=True)
        return variables["params"], {"batch_stats": variables["batch_stats"]}

    def loss_fn(params, model_state, batch, rng):
        logits, updated = model.apply(
            {"params": params, **model_state}, batch["image"], train=True,
            mutable=["batch_stats"],
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]
        ).mean()
        acc = jnp.mean(jnp.argmax(logits, -1) == batch["label"])
        return loss, ({"accuracy": acc}, dict(updated))

    return Trainer(mesh, dense_rules(fsdp=fsdp), loss_fn, optax.sgd(0.1, momentum=0.9), init_fn)


def test_resnet_trains_on_synthetic_batch(mesh_dp8):
    trainer = _resnet_trainer(mesh_dp8, _tiny_cfg())
    state = trainer.init(jax.random.key(0))
    rs = np.random.RandomState(0)
    batch = shard_batch(
        mesh_dp8,
        {
            "image": rs.randn(16, 32, 32, 3).astype(np.float32),
            "label": rs.randint(0, 10, (16,)),
        },
    )
    first = None
    for _ in range(10):
        state, m = trainer.step(state, batch)
        first = first if first is not None else float(m["loss"])
    # memorizing one small batch must drive the loss down
    assert float(m["loss"]) < first
    # batch_stats must have moved off their init values
    bs = jax.tree.leaves(state.model_state["batch_stats"])
    assert any(float(jnp.abs(x).sum()) > 0 for x in bs)


def test_resnet_fsdp_shards_convs(mesh8):
    trainer = _resnet_trainer(mesh8, _tiny_cfg(), fsdp=True)
    state = trainer.init(jax.random.key(0))
    from jax.sharding import PartitionSpec as P

    k = state.params["stage2_block0"]["conv1"]["kernel"]
    assert k.sharding.spec == P(None, None, None, "fsdp")
