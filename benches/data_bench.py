#!/usr/bin/env python
"""Input-pipeline throughput bench (VERDICT r2 item 6; SURVEY.md §7.4
item 4 "keeping TPUs fed").

Host-side measurements — meaningful on any machine, no accelerator
involved. Prints one JSON line per phase:

* ``reader``: raw shard scan MB/s, C++ native reader vs the pure-Python
  fallback, over the same tpurecord shards.
* ``decode``: end-to-end ShardedDataset images/sec per host process on
  JPEG-encoded shards (read → CRC → decode_example → JPEG decode →
  center-crop → stack), streaming mode, with the decoded-array path for
  comparison.

The third leg — proof that training is NOT input-bound — lives inside
``bench.py`` (detail.overlap): step time fed by the real
ShardedDataset+prefetch loader vs the pre-staged batch, on the bench
hardware itself.

Usage: python benches/data_bench.py [--examples N] [--image-size S]
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def _write_raw_shards(tmp: Path, n: int, image_size: int, num_shards: int):
    """Raw float32 image shards — big payloads, measures IO not decode."""
    from tpucfn.data import synthetic_imagenet, write_dataset_shards

    d = tmp / "raw"
    d.mkdir()
    return write_dataset_shards(
        synthetic_imagenet(n, image_size=image_size, classes=100),
        d, num_shards=num_shards)


def _write_jpeg_shards(tmp: Path, n: int, image_size: int, num_shards: int):
    from tpucfn.data import synthetic_imagenet, write_dataset_shards
    from tpucfn.data.images import encode_jpeg

    def gen():
        for ex in synthetic_imagenet(n, image_size=image_size, classes=100):
            img = (np.clip(ex["image"], 0, 1) * 255).astype(np.uint8)
            yield {"image": np.frombuffer(encode_jpeg(img), np.uint8),
                   "label": ex["label"]}

    d = tmp / "jpeg"
    d.mkdir()
    return write_dataset_shards(gen(), d, num_shards=num_shards)


def bench_reader(shards, label) -> dict:
    from tpucfn.data import native, records

    total_bytes = sum(Path(p).stat().st_size for p in shards)

    def scan(read):
        t0 = time.perf_counter()
        n = sum(len(payload) for p in shards for payload in read(p))
        return n, time.perf_counter() - t0

    # Warm the page cache once so both readers measure the same thing.
    scan(records.read_record_shard)

    _, py_s = scan(records.read_record_shard)
    row = {
        "phase": f"reader_{label}",
        "total_mb": round(total_bytes / 1e6, 1),
        "python_mb_s": round(total_bytes / 1e6 / py_s, 1),
        "native_available": native.native_available(),
    }
    if native.native_available():
        _, nat_s = scan(native.read_record_shard_native)
        row["native_mb_s"] = round(total_bytes / 1e6 / nat_s, 1)
        row["native_speedup"] = round(py_s / nat_s, 2)
    return row


def _write_small_record_shards(tmp: Path, n: int, num_shards: int):
    """Token-sized (~4 KB) records — the shape where per-record overhead
    dominates and the native batch path is supposed to win."""
    from tpucfn.data import write_dataset_shards

    rs = np.random.RandomState(0)

    def gen():
        for _ in range(n):
            yield {"tokens": rs.randint(0, 32000, 1024).astype(np.int32)}

    d = tmp / "small"
    d.mkdir()
    return write_dataset_shards(gen(), d, num_shards=num_shards)


def bench_decode(jpeg_shards, raw_shards, batch: int, image_size: int,
                 workers: int = 0) -> dict:
    from tpucfn.data.images import center_crop_resize, decode_transform
    from tpucfn.data.pipeline import ShardedDataset
    from tpucfn.data.transforms import Compose

    crop = image_size - image_size // 8

    def throughput(shards, transform, num_workers=0):
        ds = ShardedDataset(
            shards, batch_size_per_process=batch, seed=0,
            cache_in_memory=False, process_index=0, process_count=1,
            transform=transform, num_workers=num_workers)
        n = 0
        t0 = time.perf_counter()
        for b in ds.epoch(0):
            n += b["image"].shape[0] if hasattr(b["image"], "shape") else batch
        return n / (time.perf_counter() - t0)

    tf = Compose([decode_transform(), center_crop_resize(crop)])
    jpeg_ips = throughput(jpeg_shards, tf)
    raw_ips = throughput(raw_shards, None)
    out = {
        "phase": "decode",
        "jpeg_decode_crop_images_s": round(jpeg_ips, 1),
        "raw_passthrough_images_s": round(raw_ips, 1),
        "batch": batch,
        "image_size": image_size,
    }
    if workers:
        w_ips = throughput(jpeg_shards, tf, num_workers=workers)
        out[f"jpeg_decode_crop_images_s_w{workers}"] = round(w_ips, 1)
        out["worker_speedup"] = round(w_ips / jpeg_ips, 2)
    return out


class _SleepDecode:
    """Deterministic synthetic 'decode': a per-example sleep.  The
    input-bound shape from the bench record (5.50 s loader vs 0.101 s
    step), scaled down — sleep releases the GIL, so the service's
    thread-pooled decode genuinely parallelizes it."""

    def __init__(self, seconds: float):
        self.seconds = seconds

    def __call__(self, ex, rs):
        time.sleep(self.seconds)
        return ex


def bench_service(tmp: Path, *, batches: int, batch: int, compute_s: float,
                  decode_s: float, workers: int, num_shards: int = 4) -> dict:
    """ISSUE 11 acceptance row: step time on a synthetic INPUT-BOUND
    workload, three ways —

    * ``prestaged_step_s``  every batch already in RAM (the floor:
      pure 'compute'),
    * ``loader_step_s``     the local single-threaded loader (decode
      serializes with compute — the recorded stall, in miniature),
    * ``served_step_s``     fed by an in-process InputService whose
      decode runs ``workers`` wide and OVERLAPS compute through the
      adaptive prefetcher.

    ``ok`` gates the acceptance bound: served within 1.5x of prestaged.
    The first few served steps pay the cold stream (no head start) and
    are excluded from the steady-state mean, exactly like a compile
    warmup step.
    """
    from tpucfn.data import write_dataset_shards
    from tpucfn.data.pipeline import ShardedDataset
    from tpucfn.data.service import (AdaptivePrefetcher, InputService,
                                     ServiceBatchStream)

    rs = np.random.RandomState(0)
    d = tmp / "service"
    d.mkdir()
    n = batches * batch
    shards = write_dataset_shards(
        ({"x": rs.randn(64).astype(np.float32)} for _ in range(n)),
        d, num_shards=num_shards)
    tf = _SleepDecode(decode_s)
    # the steady-state window must keep at least one sample, however
    # small --service-batches is
    warmup = min(3, max(0, batches - 1))

    def steady(waits: list, steps: list) -> tuple[float, float]:
        w, s = waits[warmup:], steps[warmup:]
        step = sum(s) / len(s)
        share = sum(w) / sum(s) if sum(s) else 0.0
        return step, share

    def drive(it) -> tuple[float, float]:
        waits, steps = [], []
        for _ in range(batches):
            t0 = time.perf_counter()
            next(it)
            t_wait = time.perf_counter() - t0
            time.sleep(compute_s)
            waits.append(t_wait)
            steps.append(time.perf_counter() - t0)
        return steady(waits, steps)

    def ds(**kw):
        return ShardedDataset(shards, batch_size_per_process=batch, seed=0,
                              process_index=0, process_count=1,
                              transform=tf, **kw)

    # prestaged floor: decode fully paid before the loop starts
    staged = list(ds().epoch(0))[:batches]
    t0 = time.perf_counter()
    for _ in staged:
        time.sleep(compute_s)
    prestaged_step = (time.perf_counter() - t0) / len(staged)

    loader_step, stall_local = drive(iter(ds().batches(None)))

    svc = InputService(shards, num_trainers=1, batch_size_per_process=batch,
                       seed=0, transform=tf, num_workers=workers,
                       queue_batches=4, host="127.0.0.1").start()
    try:
        served_step, stall_served = drive(AdaptivePrefetcher(
            ServiceBatchStream(svc.address, 0, process_count=1,
                               batch_size=batch, seed=0)))
    finally:
        svc.close()
    return {
        "phase": "data_service",
        "loader_step_s": round(loader_step, 5),
        "served_step_s": round(served_step, 5),
        "prestaged_step_s": round(prestaged_step, 5),
        "stall_share_local": round(stall_local, 4),
        "stall_share_served": round(stall_served, 4),
        "batch": batch,
        "batches": batches,
        "decode_s_per_example": decode_s,
        "compute_s": compute_s,
        "service_workers": workers,
        "ok": served_step <= 1.5 * prestaged_step,
    }


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--examples", type=int, default=256)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--num-shards", type=int, default=8)
    p.add_argument("--workers", type=int, default=8,
                   help="also measure the thread-pool decode path at this "
                        "worker count (0 skips)")
    p.add_argument("--service", action="store_true",
                   help="measure ONLY the disaggregated-input row "
                        "(ISSUE 11): local loader vs service-fed vs "
                        "prestaged step time on a synthetic input-bound "
                        "workload; rc 1 unless served is within 1.5x of "
                        "prestaged")
    p.add_argument("--service-batches", type=int, default=24)
    p.add_argument("--service-batch", type=int, default=16)
    p.add_argument("--service-compute-ms", type=float, default=50.0)
    p.add_argument("--service-decode-ms", type=float, default=4.0,
                   help="synthetic per-example decode cost")
    p.add_argument("--service-workers", type=int, default=8)
    args = p.parse_args()

    tmp = Path(tempfile.mkdtemp(prefix="tpucfn-data-bench-"))
    try:
        if args.service:
            row = bench_service(
                tmp, batches=args.service_batches, batch=args.service_batch,
                compute_s=args.service_compute_ms / 1e3,
                decode_s=args.service_decode_ms / 1e3,
                workers=args.service_workers)
            print(json.dumps(row), flush=True)
            return 0 if row["ok"] else 1
        raw = _write_raw_shards(tmp, args.examples, args.image_size,
                                args.num_shards)
        jpeg = _write_jpeg_shards(tmp, args.examples, args.image_size,
                                  args.num_shards)
        small = _write_small_record_shards(tmp, args.examples * 64,
                                           args.num_shards)
        print(json.dumps(bench_reader(raw, "600kb_records")), flush=True)
        print(json.dumps(bench_reader(small, "4kb_records")), flush=True)
        print(json.dumps(bench_decode(jpeg, raw, args.batch,
                                      args.image_size, args.workers)),
              flush=True)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
