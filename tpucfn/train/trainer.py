"""The SPMD trainer: one jit-compiled program per step.

This collapses the reference's entire per-step pipeline — forward/backward
in the MXNet/TF C++ engine, gradients handed to ps-lite push/pull or
Horovod's fusion queue + NCCL ring (SURVEY.md §3.2-§3.4) — into a single
XLA program. The batch arrives sharded over the (data, fsdp) mesh axes,
params/optimizer state live wherever the sharding rules put them, and XLA
inserts every collective (grad all-reduce, FSDP all-gather/reduce-scatter,
TP psum) as part of the same fused computation. There is no framework-owned
wire protocol: the compiler owns the data path (SURVEY.md §5 last row).
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpucfn.parallel.sharding import (
    ShardingRules,
    batch_spec,
    make_partition_spec,
    named_sharding_tree,
)
from tpucfn.train.state import TrainState

# loss_fn(params, model_state, batch, rng)
#   -> (loss, (metrics_dict, new_model_state))
# ``model_state`` carries mutable collections (batch_stats); return it
# unchanged (or {}) for stateless models.
LossFn = Callable[[Any, Any, Any, jax.Array], tuple[jax.Array, tuple[dict, Any]]]

# init_fn(rng) -> (params, model_state)
InitFn = Callable[[jax.Array], tuple[Any, Any]]


class RestoreFailure(RuntimeError):
    """A checkpoint EXISTS but restoring it failed (corruption,
    truncation, a half-written save that slipped past finalization).

    Distinct from "no checkpoint" (which quietly falls back to a fresh
    init) because the two demand opposite recoveries: a missing
    checkpoint means start over, a corrupt one means *retry from the
    previous step* — the trainer's caller should exit with
    ``tpucfn.ft.RESTORE_FAILED_RC`` so the gang coordinator can
    blacklist the bad step instead of crash-looping into give_up
    (ISSUE 7).

    Deliberately broad: any failure restoring an existing checkpoint
    maps here, including non-corruption causes (a sharding/config
    mismatch, a transient allocator failure).  The coordinator's
    response is bounded (``max_ckpt_retries``) and reversible — a
    "quarantined" step is a plain rename into ``<ckpt>/corrupt/`` the
    operator can move back — and with no earlier step to resume from
    it declines to retry and fails loudly rather than re-init fresh."""

    def __init__(self, step: int, cause: BaseException):
        super().__init__(
            f"restoring checkpoint step {step} failed: {cause!r}")
        self.step = step


@dataclasses.dataclass(frozen=True)
class TrainerConfig:
    donate_state: bool = True
    # Extra sharded batch dims after the leading batch axis, e.g.
    # ("context",) when sequence parallelism is on.
    batch_extra_axes: tuple[str | None, ...] = ()
    # Gradient accumulation: split each global batch into this many
    # sequential microbatches inside the step (lax.scan), average grads,
    # apply once. Raises the effective batch without raising peak
    # activation memory — the non-pipeline sibling of GPipe microbatching.
    grad_accum: int = 1
    # Exponential moving average of params (the diffusion-finetune
    # standard): tracked under model_state["ema"] post-update, so it
    # shards like the params, checkpoints with the state, and is ready
    # for eval/export. 0.0 disables.
    ema_decay: float = 0.0


class Trainer:
    """Binds (mesh, sharding rules, loss, optimizer) into jitted init/step.

    Usage::

        trainer = Trainer(mesh, rules, loss_fn, optax.adamw(1e-3), init_fn)
        state = trainer.init(jax.random.key(0))
        state, metrics = trainer.step(state, batch)   # batch: host-local
    """

    def __init__(
        self,
        mesh: Mesh,
        rules: ShardingRules,
        loss_fn: LossFn,
        tx: optax.GradientTransformation,
        init_fn: InitFn,
        config: TrainerConfig = TrainerConfig(),
        eval_loss_fn: LossFn | None = None,
    ):
        """``eval_loss_fn`` runs inference-mode semantics (BN running stats,
        no dropout); models with train/eval divergence must supply it or
        eval metrics are computed in train mode."""
        self.mesh = mesh
        self.rules = rules
        self.loss_fn = loss_fn
        self.eval_loss_fn = eval_loss_fn if eval_loss_fn is not None else loss_fn
        self.tx = tx
        self.init_fn = init_fn
        self.config = config
        self._jit_step = None
        self._jit_eval = None
        self._state_shardings = None
        self._abstract_state = None

    # ---- init ----------------------------------------------------------

    def _state_rules(self) -> ShardingRules:
        # Scalars and rng keys replicate; params/opt_state follow the param
        # rules (optax state mirrors the param tree structure under mu/nu/
        # etc., so path-regex rules written for params still match).
        return self.rules.extended([(r"(^|/)(step|rng|count)($|/)", P())])

    @staticmethod
    def _opt_rank_mismatch(path: str, spec, ndim: int):
        # Factored optimizer state (Adafactor v_row/v_col) mirrors the
        # param path at rank n-1, so the param rule's spec is over-long.
        # Replicate it: the factored vectors are ~params/dim in size, so
        # replication costs nothing next to resharding-rule surgery.
        if path.startswith("opt_state"):
            return P()
        raise ValueError(
            f"rule spec {spec} has {len(spec)} entries but {path!r} has "
            f"rank {ndim}")

    def _create_state(self, rng: jax.Array) -> TrainState:
        params_rng, step_rng = jax.random.split(rng)
        params, model_state = self.init_fn(params_rng)
        if self.config.ema_decay:
            if "ema" in (model_state or {}):
                raise ValueError(
                    "model_state already has an 'ema' entry; ema_decay "
                    "owns that key")
            model_state = {**(model_state or {}),
                           "ema": jax.tree.map(jnp.asarray, params)}
        return TrainState.create(params, self.tx, step_rng, model_state)

    def _abstract(self) -> Any:
        if self._abstract_state is None:
            self._abstract_state = jax.eval_shape(self._create_state, jax.random.key(0))
        return self._abstract_state

    def state_shardings(self) -> Any:
        if self._state_shardings is None:
            self._state_shardings = named_sharding_tree(
                self.mesh, self._state_rules(), self._abstract(),
                self._opt_rank_mismatch,
            )
        return self._state_shardings

    def init(self, rng: jax.Array) -> TrainState:
        """Initialize the state directly into its target sharding — params
        are *born sharded* on their owner devices (no host staging, no
        broadcast; the analogue of the reference's rank-0-initializes-then-
        KVStore-pushes startup, minus the wire traffic)."""
        return self._maybe_warm(
            jax.jit(self._create_state, out_shardings=self.state_shardings()),
            "train_init")(rng)

    def init_or_resume(self, rng: jax.Array, ckpt=None, *,
                       fresh: bool = False) -> tuple[TrainState, int | None]:
        """Resume-from-latest on startup (ISSUE 4): restore the latest
        checkpoint through ``ckpt`` (a :class:`tpucfn.ckpt.
        CheckpointManager`) into this trainer's abstract state, or init
        fresh when there is none (or ``fresh`` forces it).  Returns
        ``(state, resumed_step)`` with ``resumed_step=None`` for a fresh
        init — the one call a gang-restarted job needs to continue from
        the last saved step instead of retraining from 0.

        A checkpoint that exists but will not restore raises
        :class:`RestoreFailure` (never silently re-inits: losing the
        whole run to a corrupt latest step is the coordinator's call,
        not this method's)."""
        if ckpt is not None and not fresh:
            latest = ckpt.latest_step()
            if latest is not None:
                try:
                    return ckpt.restore(self.abstract_state()), latest
                except Exception as e:  # noqa: BLE001 — see docstring
                    raise RestoreFailure(latest, e) from e
        return self.init(rng), None

    def abstract_state(self) -> Any:
        """ShapeDtypeStructs with shardings attached — what checkpoint
        restore needs to re-materialize the state on a (possibly different)
        mesh (SURVEY.md §5 checkpoint/resume row)."""
        sh = self.state_shardings()
        return jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            self._abstract(), sh,
        )

    # ---- fleet warm start (ISSUE 13) ------------------------------------

    def _maybe_warm(self, jitted, label: str):
        """Route this jit through the fleet compile-artifact cache when
        a client is configured (``tpucfn.compilecache`` — the launcher
        fans out ``TPUCFN_COMPILE_CACHE_ADDRS``); with none configured
        ``maybe_warm`` returns the jitted callable UNCHANGED —
        byte-identical behavior, pinned by test_compilecache."""
        from tpucfn.compilecache.jit import maybe_warm

        return maybe_warm(jitted, label=label)

    # ---- step ----------------------------------------------------------

    def _grads(self, state: TrainState, batch: Any, step_rng: jax.Array):
        accum = self.config.grad_accum
        grad_fn = jax.value_and_grad(self.loss_fn, has_aux=True)
        if accum <= 1:
            (loss, (aux, new_model_state)), grads = grad_fn(
                state.params, state.model_state, batch, step_rng
            )
            return loss, aux, new_model_state, grads

        micro = jax.tree.map(
            lambda x: x.reshape(accum, x.shape[0] // accum, *x.shape[1:]), batch
        )

        def body(carry, mb):
            grads_acc, loss_acc, aux_acc, mstate, i = carry
            (loss, (aux, mstate)), grads = grad_fn(
                state.params, mstate, mb, jax.random.fold_in(step_rng, i)
            )
            grads_acc = jax.tree.map(jnp.add, grads_acc, grads)
            loss_acc = loss_acc + loss
            aux_acc = jax.tree.map(jnp.add, aux_acc, aux)
            return (grads_acc, loss_acc, aux_acc, mstate, i + 1), None

        zero_grads = jax.tree.map(jnp.zeros_like, state.params)
        mb0 = jax.tree.map(lambda x: x[0], micro)
        _, (aux0, _) = jax.eval_shape(
            lambda: self.loss_fn(state.params, state.model_state, mb0, step_rng)
        )
        zero_aux = jax.tree.map(lambda a: jnp.zeros(a.shape, a.dtype), aux0)
        (grads, loss, aux, new_model_state, _), _ = jax.lax.scan(
            body,
            (zero_grads, jnp.zeros((), jnp.float32), zero_aux, state.model_state,
             jnp.zeros((), jnp.int32)),
            micro,
        )
        inv = 1.0 / accum
        return (loss * inv,
                jax.tree.map(lambda a: a * inv, aux),
                new_model_state,
                jax.tree.map(lambda g: g * inv, grads))

    def _step_fn(self, state: TrainState, batch: Any):
        step_rng = jax.random.fold_in(state.rng, state.step)
        loss, aux, new_model_state, grads = self._grads(state, batch, step_rng)
        updates, new_opt = self.tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        if self.config.ema_decay:
            # Post-update EMA; owns model_state["ema"] (re-attached even
            # when a loss_fn rebuilds its model_state from scratch).
            d = self.config.ema_decay
            new_model_state = {**new_model_state, "ema": jax.tree.map(
                lambda e, p: e * d + p.astype(e.dtype) * (1.0 - d),
                state.model_state["ema"], new_params)}
        new_state = TrainState(
            step=state.step + 1,
            params=new_params,
            model_state=new_model_state,
            opt_state=new_opt,
            rng=state.rng,
        )
        return new_state, {"loss": loss, **aux}

    def batch_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, batch_spec(self.config.batch_extra_axes))

    def step_cost_flops(self, batch: Any) -> float | None:
        """Per-device FLOPs of one compiled step via XLA cost analysis,
        fed to the live ``train_mfu`` gauge (ISSUE 5).  The AOT
        lower/compile here does NOT share the jit call's executable
        cache and may recompile the program — call it off the hot path
        (examples/common.py arms the gauge from a daemon thread).
        Best-effort: None when the backend reports no cost model (CPU
        fallback, mocked devices)."""
        if self._jit_step is None:
            return None
        from tpucfn.obs.goodput import cost_analysis_flops

        try:
            cost = (self._jit_step.lower(self.abstract_state(), batch)
                    .compile().cost_analysis())
            return cost_analysis_flops(cost)
        except Exception:  # noqa: BLE001 — cost analysis is best-effort
            return None

    def step(self, state: TrainState, batch: Any):
        if self._jit_step is None:
            shardings = self.state_shardings()
            metric_spec = NamedSharding(self.mesh, P())
            self._jit_step = self._maybe_warm(jax.jit(
                self._step_fn,
                in_shardings=(shardings, self.batch_sharding()),
                out_shardings=(shardings, metric_spec),
                donate_argnums=(0,) if self.config.donate_state else (),
            ), "train_step")
        return self._jit_step(state, batch)

    # ---- eval ----------------------------------------------------------

    def eval_step(self, state: TrainState, batch: Any) -> dict[str, jax.Array]:
        if self._jit_eval is None:
            def _eval(state, batch):
                loss, (aux, _) = self.eval_loss_fn(
                    state.params, state.model_state, batch, state.rng
                )
                return {"loss": loss, **aux}
            self._jit_eval = self._maybe_warm(jax.jit(
                _eval,
                in_shardings=(self.state_shardings(), self.batch_sharding()),
                out_shardings=NamedSharding(self.mesh, P()),
            ), "train_eval")
        return self._jit_eval(state, batch)

    def param_spec(self) -> Any:
        return make_partition_spec(self._state_rules(), self._abstract(),
                                   self._opt_rank_mismatch)


class TrainerObs:
    """Observability for the canonical train loop phases.

    The loop a host actually lives in is ``data_wait → step → ckpt``
    repeated; this binds each phase to both planes at once — registry
    metrics (scrapeable via the per-host ``/metrics`` endpoint) and
    trace spans (one JSONL line per phase occurrence, host id attached,
    ``trace_id`` = the global step so ``tpucfn obs`` can line hosts up
    per step and name the straggler).  Phase timings are host-observed
    wall times: ``step`` includes the device dispatch AND the block on
    the result, which is the honest per-step number on an async runtime
    (same rule as StepTimer).

    Usage (what examples/common.py's run_train_loop does)::

        obs = TrainerObs(registry, tracer)
        with obs.data_wait():   batch = next(it)
        with obs.step(step_no): state, m = trainer.step(state, batch); ...
        with obs.ckpt(step_no): ckpt.save(step_no, state)
    """

    def __init__(self, registry=None, tracer=None, *, prefix: str = "train",
                 ledger=None, peak_flops: float | None = None,
                 clock=time.monotonic, flight=None, compile_probe=None):
        """``ledger`` is a :class:`tpucfn.obs.goodput.GoodputLedger` (or
        None): every phase the loop reports is also attributed to the
        per-host goodput JSONL so ``tpucfn obs goodput`` can decompose
        the run's wall clock (ISSUE 5).  ``peak_flops``/:meth:`
        set_model_flops` arm the live ``{prefix}_mfu`` gauge; ``clock``
        is injectable so the gauges are pinned with a fake clock and no
        TPU.

        ``flight`` is a :class:`tpucfn.obs.flight.FlightRecorder` (or
        None): every phase also lands one sample in the in-memory ring,
        plus an ``hbm`` device-memory sample per step — the last-N-
        seconds record a postmortem reads (ISSUE 6).  ``compile_probe``
        is a :class:`tpucfn.obs.profiler.CompileCacheProbe` (or None):
        when it reports the first step was served from the persistent
        compile cache, the ledger charges ``compile_cached`` instead of
        ``compile``, so warm restarts stop inflating the compile
        bucket."""
        from tpucfn.obs.goodput import GoodputLedger
        from tpucfn.obs.registry import default_registry
        from tpucfn.obs.trace import Tracer

        r = self.registry = (registry if registry is not None
                             else default_registry())
        self.tracer = tracer if tracer is not None else Tracer(None)
        self.ledger = ledger if ledger is not None else GoodputLedger(None)
        self.clock = clock
        self.flight = flight
        self.compile_probe = compile_probe
        self.step_time = r.histogram(
            f"{prefix}_step_seconds", "host-observed step wall time")
        self.data_wait_time = r.histogram(
            f"{prefix}_data_wait_seconds",
            "time the step loop blocked on the input pipeline")
        self.ckpt_time = r.summary(
            f"{prefix}_ckpt_seconds", "checkpoint save-call time")
        self.steps_total = r.counter(
            f"{prefix}_steps_total", "completed optimizer steps")
        self.last_step = r.gauge(
            f"{prefix}_last_step", "most recent global step")
        # The live efficiency plane (ISSUE 5): what bench.py computed
        # offline, exported per step on the existing /metrics endpoint.
        self.step_time_g = r.gauge(
            f"{prefix}_step_time_s", "last host-observed step wall time")
        self.mfu_g = r.gauge(
            f"{prefix}_mfu",
            "model FLOPs utilization of the last step (cost-analysis "
            "FLOPs / step time / device peak)")
        self.goodput_ratio_g = r.gauge(
            f"{prefix}_goodput_ratio",
            "productive step seconds / wall seconds since loop start")
        self._flops_per_dev_step: float | None = None
        self._peak_flops = peak_flops
        self._t0 = clock()
        self._productive_s = 0.0
        self._steps_seen = 0

    def set_model_flops(self, flops_per_dev_step: float | None,
                        peak_flops: float | None = None) -> None:
        """Arm the MFU gauge: per-device FLOPs of one step (from
        :meth:`Trainer.step_cost_flops`, captured once at compile) and
        the device's peak FLOP/s (``goodput.device_peak_flops``).
        Either None leaves the gauge unset — no number beats a wrong
        number."""
        self._flops_per_dev_step = flops_per_dev_step
        if peak_flops is not None:
            self._peak_flops = peak_flops

    @contextlib.contextmanager
    def _phase(self, name: str, metric, step: int | None):
        t0 = self.clock()
        try:
            yield
        finally:
            dt = self.clock() - t0
            metric.observe(dt)
            self.tracer.record(name, start=t0, dur_s=dt, trace_id=step)
            if name != "step":  # step attribution happens in step()
                self.ledger.account(name, dt, step=step)
                if self.flight is not None:
                    self.flight.record(name, step=step, dur_s=dt)

    def _compile_bucket(self) -> str:
        """``compile`` vs ``compile_cached`` vs ``compile_fetched`` for
        the first step (ISSUE 6/13): the probe's verdict decides — a
        fleet-fetched AOT executable gets its own bucket so the warm-
        start plane's effect is visible in the ledger; no probe, or an
        unknown/throwing probe, keeps the plain ``compile`` charge."""
        if self.compile_probe is None:
            return "compile"
        try:
            outcome = self.compile_probe.outcome() \
                if hasattr(self.compile_probe, "outcome") \
                else {True: "hit", False: "miss"}.get(
                    self.compile_probe.hit())
        except Exception:  # noqa: BLE001 — the probe is best-effort
            outcome = None
        if outcome is not None:
            self.tracer.event("compile_cache", outcome=outcome,
                              hit=outcome in ("hit", "fetch"))
        if outcome == "fetch":
            return "compile_fetched"
        if outcome == "hit":
            return "compile_cached"
        return "compile"

    def _record_step(self, step: int | None, dur_s: float) -> None:
        """Shared post-step bookkeeping: the first step of a process is
        compile-dominated and lands in the ``compile`` bucket — or
        ``compile_cached`` when the probe says the persistent cache
        served it (the StepTimer warmup-exclusion rule applied to
        accounting); steady steps are ``step`` and feed the live
        efficiency gauges."""
        self._steps_seen += 1
        if self.flight is not None:
            self.flight.record("step", step=step, dur_s=dur_s)
            self.flight.sample_device()
        if self._steps_seen == 1:
            self.ledger.account(self._compile_bucket(), dur_s, step=step)
            return
        self.ledger.account("step", dur_s, step=step)
        self._productive_s += dur_s
        self.step_time_g.set(dur_s)
        elapsed = self.clock() - self._t0
        if elapsed > 0:
            self.goodput_ratio_g.set(self._productive_s / elapsed)
        if (self._flops_per_dev_step and self._peak_flops
                and dur_s > 0):
            self.mfu_g.set(self._flops_per_dev_step
                           / dur_s / self._peak_flops)

    def data_wait(self, step: int | None = None):
        return self._phase("data_wait", self.data_wait_time, step)

    def record_data_wait(self, step: int | None, start: float,
                         dur_s: float, link=None) -> None:
        """Post-hoc form of :meth:`data_wait` (``start`` in
        ``time.monotonic()`` seconds) for loops that must first decide
        whether the fetched batch starts a real step — the end-of-data
        drain wait must not be recorded as a phantom step's data wait.
        ``link`` is the batch's wire context from the input plane
        (``ResilientBatchStream.pop_link()``), recorded as the span's
        remote parent (ISSUE 20): on the merged timeline this wait
        points at the input-host ``input_serve`` span that produced the
        batch; None (local batch, tracing off upstream) records a plain
        local wait."""
        self.data_wait_time.observe(dur_s)
        self.tracer.record("data_wait", start=start, dur_s=dur_s,
                           trace_id=step, remote_parent=link)
        self.ledger.account("data_wait", dur_s, step=step)
        if self.flight is not None:
            self.flight.record("data_wait", step=step, dur_s=dur_s)

    def step(self, step: int | None = None):
        @contextlib.contextmanager
        def _span():
            if self._steps_seen == 0 and self.compile_probe is not None:
                # Arm the hit/miss baseline at the first step's ENTRY:
                # anything the pre-loop path compiled (restore, probes)
                # has already written its cache entries by now, so only
                # this step's own compile moves the count.
                try:
                    self.compile_probe.rearm()
                except Exception:  # noqa: BLE001 — probe is best-effort
                    pass
            t0 = self.clock()
            try:
                with self._phase("step", self.step_time, step):
                    yield
            finally:
                self._record_step(step, self.clock() - t0)
            self.steps_total.add()
            if step is not None:
                self.last_step.set(step)
        return _span()

    def ckpt(self, step: int | None = None):
        return self._phase("ckpt", self.ckpt_time, step)

    def record_ckpt(self, step: int | None, start: float,
                    dur_s: float) -> None:
        """Post-hoc form of :meth:`ckpt` for interval-gated save calls:
        record only saves that actually happened, or the percentiles
        measure no-op call overhead and read ~0 while real saves take
        seconds."""
        self.ckpt_time.observe(dur_s)
        self.tracer.record("ckpt", start=start, dur_s=dur_s, trace_id=step)
        self.ledger.account("ckpt", dur_s, step=step)
        if self.flight is not None:
            self.flight.record("ckpt", step=step, dur_s=dur_s)
