"""``tpucfn check`` over the repo's own package, inside tier-1
(ISSUE 10 CI satellite): every future PR passes through the analyzer —
a non-baselined finding here fails the suite, exactly like a test.

Also pins the two operational guarantees the ISSUE demands: the full
run stays under 10 seconds, and the analyzer never imports jax (a cold
jax import alone would blow the budget on a slow container — and the
analyzer must run in environments that have no accelerator stack at
all).
"""

import subprocess
import sys
import time
from pathlib import Path

import tpucfn
from tpucfn.analysis import apply_baseline, load_baseline, run_check

REPO = Path(__file__).resolve().parent.parent
PACKAGE = Path(tpucfn.__file__).resolve().parent
BASELINE = REPO / "runs" / "analysis_baseline.json"


def test_package_is_clean_under_the_rule_pack():
    t0 = time.monotonic()
    findings = run_check(PACKAGE, repo_root=PACKAGE.parent)
    elapsed = time.monotonic() - t0
    baseline = load_baseline(BASELINE) if BASELINE.is_file() else {}
    active, suppressed, stale = apply_baseline(findings, baseline)
    assert active == [], (
        "tpucfn check found non-baselined findings — fix them or add a "
        "JUSTIFIED baseline entry (runs/analysis_baseline.json):\n"
        + "\n".join(f"  {f.path}:{f.line} [{f.rule}] {f.message} "
                    f"(fingerprint {f.fingerprint})" for f in active))
    assert stale == [], (
        "stale baseline entries suppress nothing — prune with "
        "`tpucfn check --update-baseline`:\n"
        + "\n".join(f"  {e['fingerprint']} [{e.get('rule')}] "
                    f"{e.get('path')}" for e in stale))
    assert elapsed < 10.0, f"analyzer took {elapsed:.1f}s (budget 10s)"


def test_committed_baseline_entries_are_justified():
    baseline = load_baseline(BASELINE)  # raises on missing justification
    for ent in baseline.values():
        assert "TODO" not in ent["justification"], (
            f"baseline entry {ent['fingerprint']} still carries a TODO "
            "justification")


def test_check_cli_runs_without_importing_jax():
    """The whole `tpucfn check` path — CLI import included — must work
    with jax unimportable (and therefore never pay its import cost)."""
    script = (
        "import sys\n"
        "class B:\n"
        "    def find_spec(self, name, path=None, target=None):\n"
        "        if name == 'jax' or name.startswith('jax.'):\n"
        "            raise ImportError('jax import blocked: ' + name)\n"
        "        return None\n"
        "sys.meta_path.insert(0, B())\n"
        "from tpucfn.cli.main import main\n"
        "sys.exit(main(['check']))\n"
    )
    r = subprocess.run([sys.executable, "-c", script], cwd=REPO,
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, (r.stdout, r.stderr)


def test_diff_mode_reports_only_changed_files(tmp_path):
    """--diff restricts reporting to files changed vs a ref while still
    parsing the whole package (cross-module context), so the builder
    loop can run it incrementally."""
    from tpucfn.analysis import changed_files

    changed = changed_files(REPO, "HEAD")
    findings = run_check(PACKAGE, repo_root=PACKAGE.parent, only=changed)
    assert all(f.path in changed for f in findings)
