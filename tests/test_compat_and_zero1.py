"""Compat shims (kvstore/horovod) + ZeRO-1 sharded optimizer + restart
supervisor."""

import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import tpucfn.compat.horovod as hvd
from tpucfn.compat import kvstore_create
from tpucfn.parallel import ShardingRules, shard_batch, zero1_rules
from tpucfn.train import Trainer

REPO = Path(__file__).resolve().parent.parent


# ---- kvstore shim -------------------------------------------------------


def test_kvstore_dist_sync_maps_to_dp():
    kv = kvstore_create("dist_sync")
    assert kv.num_workers == jax.process_count()
    assert kv.rank == jax.process_index()
    specs = kv.rules().spec_for("anything/kernel", 2)
    assert specs == P()


def test_kvstore_dist_async_rejected_with_guidance():
    with pytest.raises(NotImplementedError, match="dist_sync"):
        kvstore_create("dist_async")


def test_kvstore_unknown_mode():
    with pytest.raises(ValueError):
        kvstore_create("dist_quantum")


# ---- horovod shim -------------------------------------------------------


def test_horovod_surface():
    hvd.init()  # no cluster env -> no-op
    assert hvd.rank() == 0
    assert hvd.size() == 1
    assert hvd.local_rank() == 0
    tx = optax.adam(1e-3)
    assert hvd.DistributedOptimizer(tx) is tx
    hvd.broadcast_parameters(None, root_rank=0)


# ---- ZeRO-1 -------------------------------------------------------------


def _mlp_init(rng):
    k1, k2 = jax.random.split(rng)
    return {
        "fc1": {"kernel": jax.random.normal(k1, (4, 32)) * 0.1, "bias": jnp.zeros(32)},
        "fc2": {"kernel": jax.random.normal(k2, (32, 8)) * 0.1, "bias": jnp.zeros(8)},
    }, {}


def _mlp_loss(params, mstate, batch, rng):
    h = jnp.tanh(batch["x"] @ params["fc1"]["kernel"] + params["fc1"]["bias"])
    pred = h @ params["fc2"]["kernel"] + params["fc2"]["bias"]
    return jnp.mean((pred - batch["y"]) ** 2), ({}, mstate)


def _rules_dense_fsdp():
    return ShardingRules(((r"(fc1|fc2)/kernel$", P(None, "fsdp")), (r".*", P())))


def test_zero1_params_replicated_optstate_sharded(mesh8):
    rules = zero1_rules(_rules_dense_fsdp())
    trainer = Trainer(mesh8, rules, _mlp_loss, optax.adam(1e-2), _mlp_init)
    state = trainer.init(jax.random.key(0))
    # params fully replicated
    assert state.params["fc1"]["kernel"].sharding.spec == P()
    # adam mu sharded over fsdp on the same dim the model rules name
    mu = state.opt_state[0].mu["fc1"]["kernel"]
    assert mu.sharding.spec == P(None, "fsdp")
    assert mu.addressable_shards[0].data.shape == (4, 16)


def test_zero1_training_matches_replicated(mesh8):
    rs = np.random.RandomState(0)
    batch_np = {"x": rs.randn(16, 4).astype(np.float32),
                "y": rs.randn(16, 8).astype(np.float32)}
    losses = {}
    for name, rules in [
        ("dp", ShardingRules(((r".*", P()),))),
        ("zero1", zero1_rules(_rules_dense_fsdp())),
    ]:
        trainer = Trainer(mesh8, rules, _mlp_loss, optax.adam(1e-2), _mlp_init)
        state = trainer.init(jax.random.key(0))
        batch = shard_batch(mesh8, batch_np)
        for _ in range(5):
            state, m = trainer.step(state, batch)
        losses[name] = float(m["loss"])
    np.testing.assert_allclose(losses["dp"], losses["zero1"], rtol=1e-5)


# ---- restart supervisor -------------------------------------------------


def test_run_with_restarts_recovers(tmp_path):
    from tpucfn.bootstrap import EnvContract
    from tpucfn.launch import Launcher, LocalTransport, run_with_restarts

    hostfile = tmp_path / "hostfile"
    hostfile.write_text("127.0.0.1:0\n")
    contract = EnvContract(
        workers_path=str(hostfile), workers_count=1, worker_chip_count=1,
        coordinator="127.0.0.1:0", host_id=0, storage=str(tmp_path), generation=1,
    )
    launcher = Launcher(contract, LocalTransport())
    marker = tmp_path / "attempts"
    # crash on the first attempt, succeed on the second (≈ resume path)
    script = (
        "import pathlib,sys;p=pathlib.Path(r'%s');"
        "n=int(p.read_text()) if p.exists() else 0;p.write_text(str(n+1));"
        "sys.exit(1 if n==0 else 0)" % marker
    )
    rc = run_with_restarts(launcher, [sys.executable, "-c", script], max_restarts=2)
    assert rc == 0
    assert marker.read_text() == "2"


def test_kill_host_after_then_recover(tmp_path):
    """The SURVEY §5 fault-injection drill: a rank is killed mid-run on
    attempt 1; the supervisor relaunches and the job completes."""
    from tpucfn.bootstrap import EnvContract
    from tpucfn.launch import Launcher, LocalTransport, run_with_restarts

    hostfile = tmp_path / "hostfile"
    hostfile.write_text("127.0.0.1:0\n127.0.0.1:0\n")
    contract = EnvContract(
        workers_path=str(hostfile), workers_count=2, worker_chip_count=1,
        coordinator="127.0.0.1:0", host_id=0, storage=str(tmp_path), generation=1,
    )
    launcher = Launcher(contract, LocalTransport())
    marker = tmp_path / "done"
    script = (
        "import os,time,pathlib\n"
        "time.sleep(1.0)\n"
        f"pathlib.Path(r'{marker}').mkdir(exist_ok=True)\n"
        f"pathlib.Path(r'{marker}').joinpath(os.environ['TPUCFN_HOST_ID']"
        ").write_text('ok')\n"
    )
    rc = run_with_restarts(
        launcher, [sys.executable, "-c", script],
        max_restarts=1, kill_host_after=(1, 0.2),
    )
    assert rc == 0
    assert sorted(p.name for p in marker.iterdir()) == ["0", "1"]


def test_run_with_restarts_gives_up(tmp_path):
    from tpucfn.bootstrap import EnvContract
    from tpucfn.launch import Launcher, LocalTransport, run_with_restarts

    hostfile = tmp_path / "hostfile"
    hostfile.write_text("127.0.0.1:0\n")
    contract = EnvContract(
        workers_path=str(hostfile), workers_count=1, worker_chip_count=1,
        coordinator="127.0.0.1:0", host_id=0, storage=str(tmp_path), generation=1,
    )
    launcher = Launcher(contract, LocalTransport())
    rc = run_with_restarts(launcher, [sys.executable, "-c", "import sys;sys.exit(7)"],
                           max_restarts=2)
    assert rc == 7
