"""Stable-Diffusion-1.5-class UNet for latent diffusion finetuning
(BASELINE config 5: "SD 1.5 UNet finetune, S3→HBM image streaming path").

Architecturally faithful to the SD 1.5 UNet: 4-channel latent i/o,
sinusoidal timestep embedding → 2-layer MLP, ResBlocks with GroupNorm/
SiLU and time-embedding injection, spatial transformer blocks (self-attn
+ cross-attn over a 768-dim text context + GEGLU FF) at the three
attention resolutions, skip-connected down/up path, (320, 640, 1280,
1280) widths. TPU-first choices: NHWC convs, bf16 compute with fp32
GroupNorm/softmax, attention projections named q_proj/k_proj/v_proj/
o_proj + fc1/fc2 so the standard transformer sharding presets TP/FSDP-
shard the hot matmuls unchanged.

``UNetConfig.sd15()`` is the real shape (~860M params);
``UNetConfig.tiny()`` keeps CI fast.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp



@dataclasses.dataclass(frozen=True)
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    base_width: int = 320
    width_mults: Sequence[int] = (1, 2, 4, 4)
    blocks_per_stage: int = 2
    attn_stages: Sequence[bool] = (True, True, True, False)
    n_heads: int = 8
    context_dim: int = 768
    groups: int = 32
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @classmethod
    def sd15(cls) -> "UNetConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "UNetConfig":
        return cls(base_width=32, width_mults=(1, 2), blocks_per_stage=1,
                   attn_stages=(True, False), n_heads=2, context_dim=32,
                   groups=8, dtype=jnp.float32)


def timestep_embedding(t: jax.Array, dim: int, max_period: float = 10000.0) -> jax.Array:
    """Sinusoidal embeddings, fp32. t: (B,) → (B, dim)."""
    half = dim // 2
    freqs = jnp.exp(-jnp.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


class ResBlock(nn.Module):
    out_ch: int
    cfg: UNetConfig

    @nn.compact
    def __call__(self, x, temb):
        cfg = self.cfg
        conv = lambda ch, name: nn.Conv(  # noqa: E731
            ch, (3, 3), padding=1, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            name=name,
        )
        gn = lambda name: nn.GroupNorm(  # noqa: E731
            num_groups=min(cfg.groups, self.out_ch), dtype=jnp.float32,
            param_dtype=cfg.param_dtype, name=name,
        )
        h = nn.silu(gn("norm1")(x.astype(jnp.float32)).astype(cfg.dtype))
        h = conv(self.out_ch, "conv1")(h)
        emb = nn.DenseGeneral(self.out_ch, dtype=cfg.dtype,
                              param_dtype=cfg.param_dtype, name="time_proj")(
            nn.silu(temb)
        )
        h = h + emb[:, None, None, :]
        h = nn.silu(gn("norm2")(h.astype(jnp.float32)).astype(cfg.dtype))
        h = conv(self.out_ch, "conv2")(h)
        if x.shape[-1] != self.out_ch:
            x = nn.Conv(self.out_ch, (1, 1), dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name="skip")(x)
        return x + h


class SpatialTransformer(nn.Module):
    """GN → 1x1 in-proj → [self-attn, cross-attn(context), GEGLU FF] → out."""

    cfg: UNetConfig

    @nn.compact
    def __call__(self, x, context):
        cfg = self.cfg
        b, hh, ww, c = x.shape
        head_dim = c // cfg.n_heads
        residual = x
        h = nn.GroupNorm(num_groups=min(cfg.groups, c), dtype=jnp.float32,
                         param_dtype=cfg.param_dtype, name="norm")(
            x.astype(jnp.float32)
        ).astype(cfg.dtype)
        h = h.reshape(b, hh * ww, c)
        h = nn.DenseGeneral(c, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                            name="proj_in")(h)

        dense = lambda feat, name: nn.DenseGeneral(  # noqa: E731
            feat, use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            name=name,
        )

        def attn(q_in, kv_in, name):
            from tpucfn.kernels.auto import full_attention_auto

            q = dense(c, f"{name}/q_proj")(q_in).reshape(b, -1, cfg.n_heads, head_dim)
            k = dense(c, f"{name}/k_proj")(kv_in).reshape(b, -1, cfg.n_heads, head_dim)
            v = dense(c, f"{name}/v_proj")(kv_in).reshape(b, -1, cfg.n_heads, head_dim)
            # Spatial self-attention at 64x64 is S=4096 both sides — the
            # auto dispatcher routes it through the flash kernel on TPU
            # (dense materializes 4G fp32 score temps per layer, the
            # measured batch-8 OOM); the 77-key cross-attention and the
            # short inner stages stay dense.
            o = full_attention_auto(q, k, v)
            return dense(c, f"{name}/o_proj")(o.reshape(b, -1, c))

        ln = lambda name: nn.LayerNorm(  # noqa: E731
            dtype=jnp.float32, param_dtype=cfg.param_dtype, name=name
        )
        # self-attention
        hs = ln("norm_self")(h.astype(jnp.float32)).astype(cfg.dtype)
        h = h + attn(hs, hs, "self_attn")
        # cross-attention over the text context
        hc = ln("norm_cross")(h.astype(jnp.float32)).astype(cfg.dtype)
        ctx = context.astype(cfg.dtype)
        h = h + attn(hc, ctx, "cross_attn")
        # GEGLU feed-forward
        hf = ln("norm_ff")(h.astype(jnp.float32)).astype(cfg.dtype)
        gate_up = nn.DenseGeneral(c * 8, dtype=cfg.dtype,
                                  param_dtype=cfg.param_dtype, name="fc1")(hf)
        gate, up = jnp.split(gate_up, 2, axis=-1)
        h = h + nn.DenseGeneral(c, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                                name="fc2")(nn.gelu(gate) * up)

        h = nn.DenseGeneral(c, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                            name="proj_out")(h)
        return residual + h.reshape(b, hh, ww, c)


class UNet(nn.Module):
    cfg: UNetConfig

    @nn.compact
    def __call__(self, latents, timesteps, context):
        """latents (B,H,W,4) + timesteps (B,) + context (B,L,ctx) → eps (B,H,W,4)."""
        cfg = self.cfg
        temb = timestep_embedding(timesteps, cfg.base_width)
        temb = nn.DenseGeneral(cfg.base_width * 4, dtype=cfg.dtype,
                               param_dtype=cfg.param_dtype, name="time_fc1")(
            temb.astype(cfg.dtype)
        )
        temb = nn.DenseGeneral(cfg.base_width * 4, dtype=cfg.dtype,
                               param_dtype=cfg.param_dtype, name="time_fc2")(
            nn.silu(temb)
        )

        conv = lambda ch, name, stride=1: nn.Conv(  # noqa: E731
            ch, (3, 3), strides=(stride, stride), padding=1, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name=name,
        )

        h = conv(cfg.base_width, "conv_in")(latents.astype(cfg.dtype))
        skips = [h]
        # down path
        for si, mult in enumerate(cfg.width_mults):
            ch = cfg.base_width * mult
            for bi in range(cfg.blocks_per_stage):
                h = ResBlock(ch, cfg, name=f"down{si}_res{bi}")(h, temb)
                if cfg.attn_stages[si]:
                    h = SpatialTransformer(cfg, name=f"down{si}_attn{bi}")(h, context)
                skips.append(h)
            if si != len(cfg.width_mults) - 1:
                h = conv(ch, f"down{si}_downsample", stride=2)(h)
                skips.append(h)

        # mid
        mid_ch = cfg.base_width * cfg.width_mults[-1]
        h = ResBlock(mid_ch, cfg, name="mid_res1")(h, temb)
        h = SpatialTransformer(cfg, name="mid_attn")(h, context)
        h = ResBlock(mid_ch, cfg, name="mid_res2")(h, temb)

        # up path (skip connections, one extra block per stage)
        for si, mult in reversed(list(enumerate(cfg.width_mults))):
            ch = cfg.base_width * mult
            for bi in range(cfg.blocks_per_stage + 1):
                h = jnp.concatenate([h, skips.pop()], axis=-1)
                h = ResBlock(ch, cfg, name=f"up{si}_res{bi}")(h, temb)
                if cfg.attn_stages[si]:
                    h = SpatialTransformer(cfg, name=f"up{si}_attn{bi}")(h, context)
            if si != 0:
                b, hh, ww, c = h.shape
                h = jax.image.resize(h, (b, hh * 2, ww * 2, c), "nearest")
                h = conv(ch, f"up{si}_upsample")(h)

        h = nn.GroupNorm(num_groups=min(cfg.groups, h.shape[-1]), dtype=jnp.float32,
                         param_dtype=cfg.param_dtype, name="norm_out")(
            h.astype(jnp.float32)
        )
        h = nn.silu(h).astype(cfg.dtype)
        # zero-init output conv: finetuning starts as identity-eps predictor
        return nn.Conv(cfg.out_channels, (3, 3), padding=1, dtype=jnp.float32,
                       param_dtype=cfg.param_dtype, name="conv_out",
                       kernel_init=nn.initializers.zeros)(h)


def ddpm_loss(model: UNet, params, batch, rng, *, num_train_timesteps: int = 1000):
    """ε-prediction MSE: sample t and noise, noise the latents with the
    standard DDPM cosine-free (linear beta) schedule, predict ε."""
    latents = batch["latents"]
    context = batch["context"]
    b = latents.shape[0]
    t_rng, n_rng = jax.random.split(rng)
    t = jax.random.randint(t_rng, (b,), 0, num_train_timesteps)
    noise = jax.random.normal(n_rng, latents.shape, latents.dtype)

    betas = jnp.linspace(1e-4, 0.02, num_train_timesteps, dtype=jnp.float32)
    alphas_bar = jnp.cumprod(1.0 - betas)
    a = jnp.sqrt(alphas_bar[t])[:, None, None, None]
    s = jnp.sqrt(1.0 - alphas_bar[t])[:, None, None, None]
    noised = a * latents + s * noise

    eps = model.apply({"params": params}, noised, t, context)
    loss = jnp.mean((eps.astype(jnp.float32) - noise.astype(jnp.float32)) ** 2)
    return loss
