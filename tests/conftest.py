"""Test harness: 8 fake CPU devices, per SURVEY.md §4.

The reference had no test suite at all (its only "integration test" was a
CloudFormation stack reaching CREATE_COMPLETE); we test every parallelism
path on a virtual 8-device CPU mesh so multi-chip behavior is exercised in
CI without TPU hardware.

Env must be adjusted before the first JAX backend initialization. The image
ships an `axon` TPU plugin that force-registers itself via sitecustomize
when PALLAS_AXON_POOL_IPS is set, so we both scrub the env and pin
jax_platforms to cpu explicitly.
"""

import importlib.util
import os
from pathlib import Path

# One shared scrub rule (tpucfn/utils/env.py), loaded by file path so no
# package (and no jax) import happens before the environment is fixed.
_spec = importlib.util.spec_from_file_location(
    "_tpucfn_env",
    Path(__file__).resolve().parent.parent / "tpucfn" / "utils" / "env.py")
_envmod = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_envmod)
_clean = _envmod.scrub_accelerator_env(os.environ, n_devices=8)
os.environ.clear()
os.environ.update(_clean)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# ---------------------------------------------------------------------------
# Tier-1 duration artifact (ISSUE 5 satellite): the 25 slowest test phases
# land in runs/tier1_durations.txt — the equivalent of `--durations=25`
# captured to a file, so PR-over-PR runtime drift toward the 870s tier-1
# budget is visible in the repo without re-running anything.  Only
# UNFILTERED runs (no -k / --deselect / explicit paths) rewrite it: the
# artifact is committed, and a `pytest -k foo` run's totals would read
# as full-suite drift numbers.
# Best-effort by design: writing a debug artifact must never fail a test run.
# ---------------------------------------------------------------------------

_PHASE_DURATIONS: list[tuple[float, str, str]] = []
_PHASE_TOTAL_S = [0.0]  # ALL phases, including the ones filtered below
_TESTS_RUN: set[str] = set()


def pytest_runtest_logreport(report):
    _PHASE_TOTAL_S[0] += report.duration
    _TESTS_RUN.add(report.nodeid)
    if report.duration >= 0.005:  # keep the accumulator small
        _PHASE_DURATIONS.append((report.duration, report.when, report.nodeid))


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    try:
        opt = config.option
        if (getattr(opt, "keyword", "") or getattr(opt, "deselect", None)
                or getattr(opt, "file_or_dir", [])
                # tier-1 itself is `-m 'not slow'`; any other markexpr
                # (e.g. `-m slow`) is a selective run
                or getattr(opt, "markexpr", "") not in ("", "not slow")):
            return  # filtered/selective run: keep the full-suite numbers
        out = Path(__file__).resolve().parent.parent / "runs"
        out.mkdir(exist_ok=True)
        top = sorted(_PHASE_DURATIONS, reverse=True)[:25]
        argv = " ".join(config.invocation_params.args) or "<all>"
        lines = [f"# pytest args: {argv}",
                 f"# {len(_TESTS_RUN)} tests ran; slowest 25 phases (of "
                 f"{len(_PHASE_DURATIONS)} >=5ms; sum of all phases "
                 f"{_PHASE_TOTAL_S[0]:.1f}s; tier-1 budget 870s)"]
        lines += [f"{d:8.2f}s {when:8s} {nodeid}" for d, when, nodeid in top]
        (out / "tier1_durations.txt").write_text("\n".join(lines) + "\n")
    except OSError:
        pass


@pytest.fixture(scope="session", autouse=True)
def _assert_fake_devices():
    assert jax.devices()[0].platform == "cpu"
    assert len(jax.devices()) == 8, (
        "tests need 8 fake CPU devices; got "
        f"{len(jax.devices())} — check XLA_FLAGS handling in conftest"
    )
    yield


@pytest.fixture()
def mesh8():
    """A full 6-axis mesh over the 8 fake devices: 2 data × 2 fsdp × 2 tensor."""
    from tpucfn.mesh import MeshSpec, build_mesh

    return build_mesh(MeshSpec(data=2, fsdp=2, tensor=2))


@pytest.fixture()
def mesh_dp8():
    """Pure-DP mesh (data=8) — the reference-equivalent topology."""
    from tpucfn.mesh import MeshSpec, build_mesh

    return build_mesh(MeshSpec(data=8))
