"""Speculative decoding — draft-model propose/verify over paired
ServeEngines (ISSUE 14 tentpole).

Per-token decode steps dominate serve cost on TPU (PAPERS.md, the
Gemma-on-TPU serving economics): every decode round is one full
target-model dispatch that emits ONE token per slot.  Speculative
decoding amortizes that dispatch: a second, much smaller ``ServeEngine``
(the draft) at the SAME slot layout autoregressively proposes ``k``
tokens per running slot, then the target scores all ``k + 1`` positions
in ONE batched verify dispatch (``ServeEngine.verify``).  Standard
greedy verification accepts the longest proposed prefix the target
agrees with, plus the target's own corrected token — so the emitted
sequence is **bit-identical to plain greedy decode**: every emitted
token is the argmax of the target's logits over exactly the cache a
plain decode would have had (pinned on CPU in
``tests/test_serve_spec.py``).

The propose/verify round (:meth:`SpecDecoder.run_round`):

1. **Resync.**  Any active slot whose draft cache has fallen out of
   mirror (spec was off for a while, or a prefix-hit copy came from a
   stale draft slot) is re-synced through the draft's OWN bucketed
   prefill machinery (``prefill_batch`` with a start offset) — draft
   state is a pure accelerant, never a correctness input, so a slot
   that cannot be resynced just proposes garbage that verification
   rejects.
2. **Propose.**  ``k`` draft decode dispatches produce ``k`` greedy
   proposals per slot (the draft's own cache advances as it goes).
3. **Verify.**  One target dispatch scores ``k + 1`` positions per
   slot.  Position 0 is sampled exactly as plain decode samples (same
   ``_sample``, same temps); positions 1+ are greedy argmax.  Slots
   with ``temperature > 0`` accept no proposals (budget 1): greedy
   verification would change their sampling distribution, so they ride
   the round as plain one-token decodes.
4. **Accept.**  Per slot: the longest prefix of proposals matching the
   target's verdicts, plus one corrected token, capped by the slot's
   budget (``remaining`` tokens) — between 1 and ``k + 1`` tokens.
5. **Commit.**  After the scheduler records what actually landed (EOS
   or a dry block pool can truncate), both engines' caches roll back to
   the accepted position (``ServeEngine.rollback``) — K/V written past
   it is dead by the standard write-before-read argument.

The verify width is shape-bucketed (``1 + pow2`` proposals, capped by
the round's minimum per-slot headroom) so the compile family stays
bounded the same way prefill buckets are.

**The controller** (:class:`SpecKController`, window-reset like PR 11's
``PrefetchController``) keeps the worst case bounded: the measured
acceptance rate over a rolling window shrinks ``k`` toward 1 when the
draft stops earning its dispatches, and — below that — turns
speculation OFF entirely (plain decode rounds, zero draft cost),
probing every ``probe_every``-th round so a workload shift can turn it
back on.  A zero-acceptance adversarial workload therefore costs plain
decode plus one amortized probe, not plain-plus-k-drafts forever
(``benches/serve_bench.py --spec`` rc-gates the bound).

Draft-side cache accounting: the draft runs at the same slot layout
(same ``max_batch``, same ``cache_len``), mirrors every prefill /
copy_prefix / rollback, and writes strictly fewer positions per round
than the target's verify does — so the scheduler's single
``KVCacheManager`` accounting bounds BOTH caches and admission can
never over-commit either (see ``serve/kvcache.py``).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque

from tpucfn.serve.scheduler import prefill_bucket


@dataclasses.dataclass
class SpecRoundStats:
    """One round's observability payload: the serve loop turns this
    into ``spec_propose``/``spec_verify`` spans and the
    ``serve_spec_*`` counters."""

    mode: str                 # "spec" | "off"
    width: int                # verify width (k_round + 1); 1 when off
    proposed: int = 0         # draft tokens proposed (greedy slots only)
    accepted: int = 0         # proposed tokens the target agreed with
    resyncs: int = 0          # draft slots re-prefilled this round
    t_propose0: float = 0.0
    t_propose1: float = 0.0
    t_verify0: float = 0.0
    t_verify1: float = 0.0


class SpecKController:
    """Acceptance-driven proposal depth: shrink ``k`` when the measured
    acceptance rate over a rolling window drops below threshold, grow it
    back when the draft is earning its dispatches, and turn speculation
    off entirely (with periodic probes) when even ``min_k`` is waste.

    Pure and clock-free (the window is rounds, not seconds) so it tests
    with zero sleeps — the ``PrefetchController`` discipline.  Window
    RESET on every decision: each k is judged on fresh evidence, not on
    the regime that preceded it.
    """

    def __init__(self, *, k: int = 4, min_k: int = 1, max_k: int | None = None,
                 shrink_below: float = 0.35, grow_above: float = 0.75,
                 window: int = 8, allow_off: bool = True,
                 probe_every: int = 64, adaptive: bool = True):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        max_k = k if max_k is None else max_k
        if not 1 <= min_k <= max_k:
            raise ValueError(
                f"need 1 <= min_k <= max_k, got {min_k}..{max_k}")
        if not 0.0 <= shrink_below <= grow_above <= 1.0:
            raise ValueError("need 0 <= shrink_below <= grow_above <= 1")
        if probe_every < 2:
            raise ValueError(f"probe_every must be >= 2, got {probe_every}")
        self.k = max(min_k, min(k, max_k))
        self.min_k = min_k
        self.max_k = max_k
        self.shrink_below = shrink_below
        self.grow_above = grow_above
        self.window = max(1, int(window))
        self.allow_off = allow_off
        self.probe_every = probe_every
        self.adaptive = adaptive
        self._hist: deque[tuple[int, int]] = deque(maxlen=self.window)
        self._off_rounds = 0
        self._probing = False

    @property
    def off(self) -> bool:
        return self.k == 0

    def round_k(self) -> int:
        """Proposal depth for the NEXT round.  0 = plain decode (spec
        off); while off, every ``probe_every``-th round runs a
        ``min_k`` probe whose observation is the re-enable signal."""
        if self.k > 0:
            return self.k
        self._off_rounds += 1
        if self._off_rounds % self.probe_every == 0:
            self._probing = True
            return self.min_k
        self._probing = False
        return 0

    def acceptance_rate(self) -> float:
        """Windowed acceptance rate (accepted / proposed over the
        rolling window); 0.0 before any proposing round."""
        prop = sum(p for p, _ in self._hist)
        return (sum(a for _, a in self._hist) / prop) if prop else 0.0

    def observe(self, proposed: int, accepted: int) -> int:
        """Feed one PROPOSING round's counts; returns the (possibly
        updated) k.  Rounds that proposed nothing carry no signal."""
        if proposed <= 0:
            return self.k
        self._hist.append((proposed, accepted))
        if not self.adaptive:
            return self.k
        rate = self.acceptance_rate()
        if self.k == 0:
            # A probe: one good round re-enables at min_k (optimistic —
            # the normal window then takes over); a bad one stays off.
            if self._probing and rate >= self.grow_above:
                self.k = self.min_k
                self._off_rounds = 0
                self._hist.clear()
            else:
                self._hist.clear()
            self._probing = False
            return self.k
        if len(self._hist) >= self.window:
            if rate < self.shrink_below:
                nk = self.k // 2
                self.k = (0 if nk < self.min_k and self.allow_off
                          else max(self.min_k, nk))
                self._hist.clear()
            elif rate > self.grow_above and self.k < self.max_k:
                self.k = min(self.max_k, self.k * 2)
                self._hist.clear()
        return self.k


def _down_pow2(n: int) -> int:
    """Largest power of two <= n (n >= 1) — the verify-width bucket
    family: one compile per width, like prefill buckets."""
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


class SpecDecoder:
    """Engine-protocol wrapper pairing a target ``ServeEngine`` with a
    smaller draft at the same slot layout.

    Presents the exact duck-typed surface ``serve/frontend.Server``
    drives (``prefill_batch`` / ``prefill`` / ``copy_prefix`` /
    ``decode`` / ``max_batch`` / ``cache_len`` / ``prefill_width``),
    mirroring every cache-shaping call onto the draft, plus the
    propose-verify round (:meth:`run_round` / :meth:`commit_round`) the
    spec-aware decode branch uses.  ``spec_enabled`` is the branch
    flag; a Server holding a bare engine never takes the spec path,
    which is what keeps the no-draft configuration byte-identical.
    """

    spec_enabled = True

    def __init__(self, target, draft, *, k: int = 4,
                 controller: SpecKController | None = None,
                 adaptive: bool = True):
        if draft.max_batch != target.max_batch \
                or draft.cache_len != target.cache_len:
            raise ValueError(
                f"draft slot layout ({draft.max_batch} slots x "
                f"{draft.cache_len}) must match the target's "
                f"({target.max_batch} x {target.cache_len}) — slots and "
                "positions are shared identities in the propose-verify "
                "round")
        if getattr(draft, "prefill_width", 1) \
                < getattr(target, "prefill_width", 1):
            raise ValueError(
                "draft prefill_width must cover the target's: mirrored "
                "prefill batches are sized by the target's width")
        self.target = target
        self.draft = draft
        self.controller = (controller if controller is not None
                           else SpecKController(k=k, adaptive=adaptive))
        # Per-slot count of leading draft-cache positions that are a
        # byte-valid mirror of the target's slot.  Pure bookkeeping on
        # the host: staleness costs acceptance, never correctness.
        self._draft_sync: dict[int, int] = {}
        self._pending: dict[int, int] | None = None  # round in flight

    # -- engine-protocol proxies -------------------------------------------
    @property
    def max_batch(self) -> int:
        return self.target.max_batch

    @property
    def cache_len(self) -> int:
        return self.target.cache_len

    @property
    def prefill_width(self) -> int:
        return self.target.prefill_width

    @property
    def params(self):
        return self.target.params

    def prefill_batch(self, items, bucket: int) -> dict[int, int]:
        out = self.target.prefill_batch(items, bucket)
        # Draft mirror at temperature 0: proposals are always greedy.
        self.draft.prefill_batch(
            [(slot, toks, start, 0.0) for slot, toks, start, _t in items],
            bucket)
        for slot, toks, start, _t in items:
            prev = self._draft_sync.get(slot, 0)
            self._draft_sync[slot] = (start + len(toks) if start <= prev
                                      else prev)
        return out

    def prefill(self, slot: int, prefix: list[int], bucket: int,
                temperature: float = 0.0, start: int = 0) -> int:
        return self.prefill_batch([(slot, prefix, start, temperature)],
                                  bucket)[slot]

    def copy_prefix(self, src_slot: int, dst_slot: int,
                    n_tokens: int) -> None:
        self.target.copy_prefix(src_slot, dst_slot, n_tokens)
        # Mirror unconditionally so the draft's cache_index stays in
        # lockstep; validity is whatever the source slot really held.
        self.draft.copy_prefix(src_slot, dst_slot, n_tokens)
        self._draft_sync[dst_slot] = min(
            n_tokens, self._draft_sync.get(src_slot, 0))

    def decode(self, tokens_by_slot: dict[int, int]) -> dict[int, int]:
        """Plain one-token round on the TARGET only (protocol
        completeness for direct engine users); the draft is not fed, so
        those slots resync lazily at the next proposing round."""
        return self.target.decode(tokens_by_slot)

    def compile_counts(self) -> dict:
        return {"target": self.target.compile_counts(),
                "draft": self.draft.compile_counts()}

    # -- the propose-verify round ------------------------------------------
    def _resync(self, slots, n_by_slot: dict[int, int]) -> int:
        """Re-mirror stale draft slots through the draft's bucketed
        prefill: tokens ``prefix[sync:-1]`` at start ``sync`` (or the
        whole history from 0 when the suffix bucket cannot fit).
        Returns how many slots were resynced."""
        need: list[tuple[int, list[int], int]] = []  # (slot, toks, start)
        for slot, seq in slots.items():
            n = n_by_slot[slot]
            if self._draft_sync.get(slot, -1) == n:
                continue
            start = self._draft_sync.get(slot, 0)
            if not 0 <= start < n:
                start = 0
            toks = list(seq.prefix[start:n])
            bucket = prefill_bucket(len(toks), self.cache_len)
            if start + bucket > self.cache_len:
                start, toks = 0, list(seq.prefix[:n])
                bucket = prefill_bucket(len(toks), self.cache_len)
            need.append((slot, toks, start))
        # Group into same-bucket draft prefill batches (the engine's
        # one-compile-per-bucket contract).
        by_bucket: dict[int, list[tuple[int, list[int], int]]] = {}
        for slot, toks, start in need:
            by_bucket.setdefault(
                prefill_bucket(len(toks), self.cache_len), []).append(
                (slot, toks, start))
        width = getattr(self.draft, "prefill_width", 1)
        for bucket, group in sorted(by_bucket.items()):
            for i in range(0, len(group), width):
                chunk = group[i:i + width]
                self.draft.prefill_batch(
                    [(slot, toks, start, 0.0)
                     for slot, toks, start in chunk], bucket)
                for slot, toks, start in chunk:
                    self._draft_sync[slot] = start + len(toks)
        return len(need)

    def run_round(self, slots) -> tuple[dict[int, list[int]],
                                        SpecRoundStats]:
        """One decode round over ``slots`` (slot -> Sequence-like with
        ``prefix`` / ``last_token`` / ``remaining`` / ``temperature``).
        Returns per-slot CANDIDATE emissions (1..k+1 tokens each, every
        one bit-identical to what plain greedy decode would emit) and
        the round's stats.  The caller records them through the
        scheduler — which may truncate on EOS/max_new or a dry block
        pool — then MUST :meth:`commit_round` with the final lengths."""
        if self._pending is not None:
            raise RuntimeError("run_round before commit_round of the "
                               "previous round")
        n_by_slot = {slot: len(seq.prefix) - 1
                     for slot, seq in slots.items()}
        budgets = {slot: (1 if seq.temperature > 0
                          else max(1, seq.remaining))
                   for slot, seq in slots.items()}
        k_round = self.controller.round_k()
        # Width safety: the verify writes W positions from each slot's
        # current length; headroom per slot is remaining + 1, so the
        # width is capped by the round's minimum remaining (then
        # bucketed to a power of two to bound the compile family).
        k_cap = min([k_round] + [seq.remaining for seq in slots.values()])
        if k_round == 0 or k_cap < 1 or max(budgets.values()) <= 1:
            # Spec off, no headroom, or nothing in the batch CAN accept
            # (all sampled / all on their last token): one plain target
            # dispatch — never pay a draft that cannot earn anything.
            t0 = time.monotonic()
            out = self.target.decode(
                {slot: seq.last_token for slot, seq in slots.items()})
            t1 = time.monotonic()
            self._pending = {}  # decode advanced exactly one: no repair
            return ({slot: [tok] for slot, tok in out.items()},
                    SpecRoundStats(mode="off", width=1, t_verify0=t0,
                                   t_verify1=t1))
        k_eff = _down_pow2(k_cap)
        width = k_eff + 1
        stats = SpecRoundStats(mode="spec", width=width)
        stats.t_propose0 = time.monotonic()
        stats.resyncs = self._resync(slots, n_by_slot)
        cur = {slot: seq.last_token for slot, seq in slots.items()}
        proposed: dict[int, list[int]] = {slot: [] for slot in slots}
        for _ in range(k_eff):
            cur = self.draft.decode(cur)
            for slot, tok in cur.items():
                proposed[slot].append(tok)
        stats.t_propose1 = stats.t_verify0 = time.monotonic()
        outs = self.target.verify(
            {slot: [slots[slot].last_token] + proposed[slot]
             for slot in slots}, width)
        stats.t_verify1 = time.monotonic()
        emitted: dict[int, list[int]] = {}
        extra_feed = False
        for slot, verdict in outs.items():
            m = 0
            while m < k_eff and proposed[slot][m] == verdict[m]:
                m += 1
            j = min(m + 1, budgets[slot])
            emitted[slot] = verdict[:j]
            if j == width:
                extra_feed = True
            if budgets[slot] > 1:
                stats.proposed += k_eff
                stats.accepted += j - 1
        if extra_feed:
            # A fully-accepted slot's last proposal was never fed to the
            # draft (it was the draft's OUTPUT); one more draft step
            # writes its K/V so the mirror stays exact.  Slots that
            # accepted less get the write rolled back with everything
            # else.
            self.draft.decode({slot: proposed[slot][-1] for slot in slots})
        self.controller.observe(stats.proposed, stats.accepted)
        # Draft cache positions written this round: k_eff (+1 on the
        # extra feed) from each slot's synced length.
        self._pending = n_by_slot
        return emitted, stats

    def abandon_round(self) -> None:
        """Drop a round that will never be committed (the replica died
        between run_round and commit_round — ``Server._fail_all`` calls
        this).  Cache repair is NOT needed: a failed replica never runs
        another step, and a relaunched incarnation re-prefills every
        slot before decoding it, which rewrites the row and its
        ``cache_index`` on both engines."""
        self._pending = None

    def commit_round(self, final_lengths: dict[int, int]) -> None:
        """Repair both caches to the per-slot lengths the scheduler
        actually recorded (``len(prefix) - 1`` after appending — for
        retired slots too, so their residue stays a valid prefix-cache
        backer).  A round that ran in off mode advanced exactly one
        position per slot and needs no repair."""
        if self._pending is None:
            raise RuntimeError("commit_round without a pending round")
        pending, self._pending = self._pending, None
        if not pending:
            return  # off-mode round: plain decode left the cache exact
        self.target.rollback(final_lengths)
        self.draft.rollback(final_lengths)
        self._draft_sync.update(final_lengths)
