from tpucfn.kernels.flash_attention import (  # noqa: F401
    flash_attention,
    flash_attention_with_lse,
)
from tpucfn.kernels.ring_attention import make_ring_attention, ring_attention  # noqa: F401
from tpucfn.kernels.ulysses import make_ulysses_attention  # noqa: F401
from tpucfn.kernels.auto import (  # noqa: F401
    auto_attention,
    auto_attention_static_zero,
    should_use_flash,
)
from tpucfn.kernels import flash_autotune  # noqa: F401
