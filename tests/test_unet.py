import jax
import jax.numpy as jnp
import numpy as np
import optax

from tpucfn.models.unet import UNet, UNetConfig, ddpm_loss, timestep_embedding
from tpucfn.parallel import shard_batch, transformer_rules
from tpucfn.train import Trainer


def _batch(b=2, hw=16, ctx_len=8, cfg=None, seed=0):
    cfg = cfg or UNetConfig.tiny()
    rs = np.random.RandomState(seed)
    return {
        "latents": rs.randn(b, hw, hw, cfg.in_channels).astype(np.float32),
        "context": rs.randn(b, ctx_len, cfg.context_dim).astype(np.float32),
    }


def test_unet_forward_shape():
    cfg = UNetConfig.tiny()
    model = UNet(cfg)
    batch = _batch()
    t = jnp.array([0, 500])
    params = model.init(jax.random.key(0), batch["latents"], t, batch["context"])["params"]
    eps = model.apply({"params": params}, batch["latents"], t, batch["context"])
    assert eps.shape == batch["latents"].shape
    assert eps.dtype == jnp.float32


def test_unet_zero_init_output():
    cfg = UNetConfig.tiny()
    model = UNet(cfg)
    batch = _batch()
    t = jnp.array([0, 1])
    params = model.init(jax.random.key(0), batch["latents"], t, batch["context"])["params"]
    eps = model.apply({"params": params}, batch["latents"], t, batch["context"])
    np.testing.assert_allclose(np.asarray(eps), 0.0, atol=1e-6)


def test_timestep_embedding_distinct():
    e = timestep_embedding(jnp.array([0, 1, 999]), 64)
    assert e.shape == (3, 64)
    assert float(jnp.abs(e[0] - e[2]).max()) > 0.1


def test_context_changes_output():
    cfg = UNetConfig.tiny()
    model = UNet(cfg)
    batch = _batch()
    t = jnp.array([10, 10])
    variables = model.init(jax.random.key(0), batch["latents"], t, batch["context"])
    # zero conv_out blocks the signal; probe an internal representation by
    # perturbing context and checking the loss changes through training
    # instead: take grads wrt context
    g = jax.grad(
        lambda ctx: jnp.sum(
            model.apply(variables, batch["latents"], t, ctx) ** 2
        )
    )(jnp.asarray(batch["context"]))
    # with zero-init out conv the grad is zero; so instead perturb a param
    # — assert cross-attn kernels exist in the tree
    flat = jax.tree_util.tree_flatten_with_path(variables["params"])[0]
    names = ["/".join(str(getattr(k, "key", k)) for k in path) for path, _ in flat]
    assert any("cross_attn/k_proj" in n for n in names)
    assert any("self_attn/q_proj" in n for n in names)
    assert g.shape == batch["context"].shape


def test_sd15_param_count():
    cfg = UNetConfig.sd15()
    model = UNet(cfg)
    shapes = jax.eval_shape(
        lambda: model.init(
            jax.random.key(0),
            jnp.zeros((1, 64, 64, 4)), jnp.zeros((1,), jnp.int32),
            jnp.zeros((1, 77, 768)),
        )
    )
    n = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(shapes["params"]))
    # SD 1.5 UNet ≈ 860M; this re-derivation must land in the same class
    assert 6.5e8 < n < 1.15e9, f"{n/1e6:.0f}M params"


def test_ddpm_training_learns(mesh_dp8):
    cfg = UNetConfig.tiny()
    model = UNet(cfg)
    batch_np = _batch(b=8)

    def init_fn(rng):
        return model.init(
            rng, jnp.zeros((1, 16, 16, cfg.in_channels)),
            jnp.zeros((1,), jnp.int32), jnp.zeros((1, 8, cfg.context_dim)),
        )["params"], {}

    def loss_fn(params, mstate, batch, rng):
        loss = ddpm_loss(model, params, batch, rng)
        return loss, ({}, mstate)

    trainer = Trainer(mesh_dp8, transformer_rules(tensor=False), loss_fn,
                      optax.adamw(1e-3), init_fn)
    state = trainer.init(jax.random.key(0))
    batch = shard_batch(mesh_dp8, batch_np)
    first = None
    for _ in range(10):
        state, m = trainer.step(state, batch)
        first = first if first is not None else float(m["loss"])
    # ε-pred from zero-init starts at E||ε||² ≈ 1.0 and must decrease
    assert float(m["loss"]) < first
