from tpucfn.spec.cluster import ClusterSpec, ACCELERATOR_TYPES, AcceleratorType  # noqa: F401
