"""Packed-sequence training: many short documents per (B, S) row.

Short-document corpora waste most of a fixed-shape (B, S) batch on
padding; packing concatenates documents into full rows and uses
segment ids to keep attention and the LM loss from crossing document
boundaries.  TPU-first reasoning: XLA wants static shapes, so variable-
length batching is out — packing is THE static-shape answer (same
trade the reference's RecordIO batching made, minus the correctness
bugs of naive concatenation).

Three pieces, composable with everything else in the stack:

* :func:`pack_sequences` — greedy first-fit packing of variable-length
  token lists into (N, S) ``tokens`` + 1-based ``segments`` (0 = pad).
* :func:`packed_attention_fn` — AttentionFn that masks cross-segment
  attention: the Pallas flash kernel's native ``segment_ids`` path on
  TPU (hardware-layout masking, no (S, S) materialization), an explicit
  mask on the dense path elsewhere.
* :func:`packed_causal_lm_loss` — next-token CE only where target and
  input share a segment (no cross-document prediction, no loss on pad).

Parity is tested against running each document through the model alone.
"""

from __future__ import annotations

from typing import Iterable

import numpy as np

# jax is imported lazily inside the two jax-consuming functions (the
# annotations are strings via __future__): the data package must stay
# importable on jax-free INPUT hosts (ISSUE 11 — `tpucfn data serve`
# pulls tpucfn.data.__init__, which pulls this module).


def pack_sequences(
    sequences: Iterable[np.ndarray],
    seq_len: int,
    *,
    pad_id: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Greedy first-fit packing. Returns (tokens (N, S) int32,
    segments (N, S) int32 — 1-based per-row document ids, 0 on padding).

    Documents longer than ``seq_len`` raise (chunk upstream — silently
    truncating data is how eval numbers lie)."""
    rows: list[list[int]] = []
    segs: list[list[int]] = []
    counts: list[int] = []
    # Shortest document in the corpus: any row whose remaining capacity
    # drops below it can never accept another document, so it leaves the
    # open list for good. First-fit results are bit-identical (a dropped
    # row would never have been chosen), but the per-document scan is
    # over OPEN rows only — on real corpora that is what keeps packing
    # from going quadratic in document count (ADVICE r3).
    # Materialize first: the pre-scan below iterates the input a second
    # time, and a one-pass iterator/generator (part of the accepted
    # Iterable contract) would arrive at the main loop already consumed
    # (ADVICE r4).
    sequences = [np.asarray(s) for s in sequences]
    lens = [len(s) for s in sequences]
    min_len = min((n for n in lens if n > 0), default=0)
    open_rows: list[int] = []  # indices into rows, in creation order
    for seq in sequences:
        if seq.ndim != 1:
            raise ValueError(f"sequences must be rank-1, got shape {seq.shape}")
        if len(seq) > seq_len:
            raise ValueError(
                f"document of length {len(seq)} exceeds seq_len {seq_len}; "
                "chunk it upstream")
        if len(seq) == 0:
            continue
        placed_at = None
        for pos, i in enumerate(open_rows):
            if len(rows[i]) + len(seq) <= seq_len:
                counts[i] += 1
                rows[i].extend(int(t) for t in seq)
                segs[i].extend([counts[i]] * len(seq))
                placed_at = pos
                break
        if placed_at is not None:
            i = open_rows[placed_at]
            if seq_len - len(rows[i]) < min_len:
                open_rows.pop(placed_at)
        else:
            rows.append([int(t) for t in seq])
            segs.append([1] * len(seq))
            counts.append(1)
            if seq_len - len(rows[-1]) >= min_len:
                open_rows.append(len(rows) - 1)
    if not rows:
        raise ValueError("no non-empty sequences to pack")
    n = len(rows)
    tokens = np.full((n, seq_len), pad_id, np.int32)
    segments = np.zeros((n, seq_len), np.int32)
    for i, (row, seg) in enumerate(zip(rows, segs)):
        tokens[i, :len(row)] = row
        segments[i, :len(seg)] = seg
    return tokens, segments


def packed_attention_fn(segments: jax.Array):
    """AttentionFn masking attention across segment boundaries (and off
    padding, segment 0).  Flash kernel on TPU above the dispatch
    threshold — its segment path masks in hardware layout; explicit
    dense mask elsewhere."""
    from tpucfn.kernels.auto import should_use_flash

    def att(q, k, v, *, causal=True, mask=None, q_offset=0, k_offset=0):
        if mask is not None:
            raise NotImplementedError(
                "packed attention owns the mask; combine masks upstream")
        static_offsets = isinstance(q_offset, int) and isinstance(k_offset, int)
        if (static_offsets and q_offset == 0 and k_offset == 0
                and should_use_flash(q.shape[1], causal=causal,
                                     d=q.shape[-1], dtype=q.dtype)):
            from tpucfn.kernels.flash_attention import flash_attention

            return flash_attention(q, k, v, causal=causal,
                                   segment_ids=segments)
        from tpucfn.ops.attention import dot_product_attention

        same = (segments[:, None, :, None] == segments[:, None, None, :])
        valid = (segments > 0)[:, None, :, None]  # pad queries attend nothing
        return dot_product_attention(q, k, v, causal=causal,
                                     mask=same & valid,
                                     q_offset=q_offset, k_offset=k_offset)

    return att


def packed_causal_lm_loss(
    logits: jax.Array,    # (B, S, V)
    tokens: jax.Array,    # (B, S)
    segments: jax.Array,  # (B, S)
    *,
    z_loss: float = 0.0,
) -> tuple[jax.Array, jax.Array]:
    """Next-token CE averaged over positions whose TARGET shares the
    input's segment (and is not padding). Returns (loss, accuracy)."""
    import jax
    import jax.numpy as jnp
    import optax

    targets = tokens[:, 1:]
    pred = logits[:, :-1]
    valid = (segments[:, 1:] == segments[:, :-1]) & (segments[:, 1:] > 0)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(pred, targets)
    if z_loss:
        per_tok = per_tok + z_loss * jax.nn.logsumexp(pred, axis=-1) ** 2
    denom = jnp.maximum(valid.sum(), 1)
    loss = jnp.where(valid, per_tok, 0.0).sum() / denom
    correct = jnp.where(valid, jnp.argmax(pred, -1) == targets, False)
    return loss, correct.sum() / denom
