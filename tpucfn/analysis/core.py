"""Analysis engine: module walker, call-graph, findings, baselines.

``tpucfn check`` (ISSUE 10) turns the repo's incident history into
machine-checked rules: every rule under :mod:`tpucfn.analysis.rules`
encodes a bug class this codebase has actually shipped (a lock acquired
inside a SIGTERM handler, a ``Thread.join`` under the router lock, an
unregistered metric silently missing from ``/metrics``...).  This module
is the rule-independent substrate:

* **Module loading** — :func:`load_modules` parses every ``*.py`` under
  a package root with stdlib :mod:`ast`; nothing is imported, so the
  analyzer runs in well under a second with no jax in the process.
* **Resolution** — :class:`Analysis` indexes classes and functions so
  rules can resolve ``self.m()`` / ``obj.m()`` / bare-name calls to
  their definitions (including cross-module, via a unique-class-name
  index) and classify lock attributes as reentrant or not
  (:meth:`Analysis.lock_kind`).
* **Findings** — :class:`Finding` carries a *stable fingerprint* built
  from ``(rule, path, key)`` where ``key`` is a rule-chosen token
  (function qualname + lock attr, metric name...), **never** the line
  number — so reformatting or unrelated edits do not invalidate a
  baseline.
* **Suppression** — two escape hatches, both explicit: an inline
  ``# tpucfn: allow[rule-id]`` pragma on (or one line above) the
  flagged line, and a baseline file mapping fingerprints to one-line
  justifications (:func:`load_baseline` refuses entries without one —
  silent suppressions are the thing this tool exists to end).
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import re
import subprocess
from pathlib import Path
from typing import Callable, Iterable

# -- findings ---------------------------------------------------------------


def fingerprint(rule: str, path: str, key: str) -> str:
    """Stable identity of one finding: rule + repo-relative path +
    rule-chosen key.  Line numbers are deliberately excluded so code
    motion above a finding does not orphan its baseline entry."""
    h = hashlib.sha1(f"{rule}|{path}|{key}".encode()).hexdigest()
    return h[:16]


@dataclasses.dataclass
class Finding:
    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str
    key: str  # stable token; see fingerprint()

    @property
    def fingerprint(self) -> str:
        return fingerprint(self.rule, self.path, self.key)

    def to_json(self) -> dict:
        return {"file": self.path, "line": self.line, "rule": self.rule,
                "fingerprint": self.fingerprint, "message": self.message}


# -- modules ----------------------------------------------------------------


@dataclasses.dataclass
class Module:
    path: Path  # absolute
    rel: str    # repo-relative posix path ("tpucfn/serve/router.py")
    tree: ast.Module
    lines: list[str]


def load_modules(package_root: Path,
                 repo_root: Path) -> tuple[list[Module], list[Finding]]:
    """Parse every ``*.py`` under ``package_root``.  Unparseable files
    become ``parse-error`` findings instead of crashing the run — a
    syntax error is the one bug every rule would otherwise miss."""
    modules: list[Module] = []
    findings: list[Finding] = []
    for p in sorted(package_root.rglob("*.py")):
        if "__pycache__" in p.parts:
            continue
        rel = p.relative_to(repo_root).as_posix()
        try:
            src = p.read_text(encoding="utf-8", errors="replace")
            tree = ast.parse(src, filename=str(p))
        except SyntaxError as e:
            findings.append(Finding(
                "parse-error", rel, e.lineno or 1,
                f"file does not parse: {e.msg}", key="syntax"))
            continue
        modules.append(Module(p, rel, tree, src.splitlines()))
    return modules, findings


# -- function / class indexes ----------------------------------------------


@dataclasses.dataclass
class FuncInfo:
    """One function or method definition, with enough context to walk
    calls out of it."""

    qualname: str                 # "Class.method" / "func" / "func.<nested>"
    node: ast.FunctionDef | ast.AsyncFunctionDef | ast.Lambda
    module: Module
    class_name: str | None = None

    @property
    def params(self) -> list[str]:
        a = self.node.args
        return [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]


def sub_suites(stmt: ast.stmt):
    """Every nested statement suite of one compound statement —
    if/for/while/with bodies, else/finally, exception handlers, AND
    ``match`` case bodies.  The ONE place suite recursion is defined:
    hand-rolled body/orelse loops scattered across rules went blind
    inside ``match`` statements (review finding)."""
    for attr in ("body", "orelse", "finalbody"):
        v = getattr(stmt, attr, None)
        if v and isinstance(v[0], ast.stmt):
            yield v
    for h in getattr(stmt, "handlers", ()) or ():
        yield h.body
    for c in getattr(stmt, "cases", ()) or ():
        yield c.body


def _walk_funcs(mod: Module):
    """Yield (qualname, node, class_name) for every def in the module,
    including methods and functions nested inside other functions — and
    inside any compound statement (a handler defined in a ``try:`` or a
    ``for`` loop is still a function)."""

    def rec(body, prefix: str, class_name: str | None):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{node.name}"
                yield q, node, class_name
                yield from rec(node.body, q + ".", class_name)
            elif isinstance(node, ast.ClassDef):
                yield from rec(node.body, f"{prefix}{node.name}.",
                               node.name if not prefix else class_name)
            else:
                for b in sub_suites(node):
                    yield from rec(b, prefix, class_name)

    yield from rec(mod.tree.body, "", None)


class Analysis:
    """Shared context handed to every rule: the parsed modules plus
    lazily-built cross-module indexes."""

    def __init__(self, modules: list[Module], *, package_root: Path,
                 repo_root: Path, tests_dir: Path | None = None,
                 readme: Path | None = None):
        self.modules = modules
        self.package_root = package_root
        self.repo_root = repo_root
        self.tests_dir = tests_dir
        self.readme = readme
        self._funcs: dict[str, dict[str, FuncInfo]] = {}
        self._classes: dict[str, list[tuple[Module, ast.ClassDef]]] | None = None
        self._locks: dict[tuple[str, str | None], dict[str, str]] = {}

    # -- indexes -----------------------------------------------------------

    def functions(self, mod: Module) -> dict[str, FuncInfo]:
        if mod.rel not in self._funcs:
            self._funcs[mod.rel] = {
                q: FuncInfo(q, node, mod, cls)
                for q, node, cls in _walk_funcs(mod)}
        return self._funcs[mod.rel]

    @property
    def class_index(self) -> dict[str, list[tuple[Module, ast.ClassDef]]]:
        """Class name -> definitions across the whole package (used to
        resolve ``obj = ClassName(...)`` constructor calls; ambiguous
        names resolve to nothing)."""
        if self._classes is None:
            self._classes = {}
            for mod in self.modules:
                for node in ast.walk(mod.tree):
                    if isinstance(node, ast.ClassDef):
                        self._classes.setdefault(node.name, []).append(
                            (mod, node))
        return self._classes

    # -- lock classification ----------------------------------------------

    def lock_kinds(self, mod: Module,
                   class_name: str | None) -> dict[str, tuple[str, str]]:
        """``attr -> (kind, canonical_attr)`` for ``self.<attr>``
        (methods) or bare names (module level).  ``threading.Lock()`` ->
        non-reentrant ``"lock"``; ``RLock()`` -> ``"rlock"``;
        ``Condition(self.x)`` is an ALIAS of ``x`` — acquiring the
        condition acquires x, so both resolve to x's kind and identity
        (bare ``Condition()`` builds its own RLock)."""
        cache_key = (mod.rel, class_name)
        if cache_key in self._locks:
            return self._locks[cache_key]
        out: dict[str, tuple[str, str]] = {}
        aliases: dict[str, str] = {}  # attr wrapped by a Condition

        if class_name is None:
            assigns = [n for n in mod.tree.body if isinstance(n, ast.Assign)]
        else:
            assigns = []
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef) and node.name == class_name:
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Assign):
                            assigns.append(sub)
        for a in assigns:
            kind, wrapped = _lock_ctor_kind(a.value)
            if kind is None and wrapped is None:
                continue
            for t in a.targets:
                attr = _self_attr_or_name(t)
                if attr is None:
                    continue
                if wrapped is not None:
                    aliases[attr] = wrapped
                else:
                    out[attr] = (kind, attr)
        for attr, wrapped in aliases.items():
            kind, canon = out.get(wrapped, ("rlock", wrapped))
            out[attr] = (kind, canon)
        self._locks[cache_key] = out
        return out

    def lock_kind(self, mod: Module, class_name: str | None,
                  expr: ast.expr) -> tuple[str | None, str | None]:
        """Classify a ``with <expr>:`` context manager.  Returns
        ``(kind, name)`` where kind is "lock"/"rlock"/None (not a lock
        we can see) and name is the normalized lock identity (aliases —
        a Condition over a lock — collapse onto the wrapped lock)."""
        attr = _self_attr_or_name(expr)
        if attr is None:
            return None, None
        kinds = self.lock_kinds(mod, class_name)
        if attr in kinds:
            kind, canon = kinds[attr]
            scope = class_name or "<module>"
            return kind, f"{scope}.{canon}"
        if class_name is not None:
            # module-level lock used from a method
            mkinds = self.lock_kinds(mod, None)
            if attr in mkinds:
                kind, canon = mkinds[attr]
                return kind, f"<module>.{canon}"
        return None, None

    # -- call resolution ---------------------------------------------------

    def resolve_call(self, mod: Module, caller: FuncInfo,
                     call: ast.Call) -> FuncInfo | None:
        """Best-effort static resolution of one call site:

        * ``name(...)``      -> function in the same module (nested defs
          in the caller win over module-level ones);
        * ``self.m(...)``    -> method of the caller's class;
        * ``obj.m(...)``     -> ``Cls.m`` when ``obj`` was assigned
          ``Cls(...)`` in the caller (or at module level) and ``Cls``
          names exactly one class in the package.

        Unresolvable calls return None — rules stay conservative.
        """
        funcs = self.functions(mod)
        f = call.func
        if isinstance(f, ast.Name):
            nested = f"{caller.qualname}.{f.id}"
            if nested in funcs:
                return funcs[nested]
            return funcs.get(f.id)
        if not isinstance(f, ast.Attribute):
            return None
        if isinstance(f.value, ast.Name) and f.value.id == "self" \
                and caller.class_name is not None:
            return self._method(mod, caller.class_name, f.attr)
        if isinstance(f.value, ast.Name):
            cls = self._var_class(mod, caller, f.value.id)
            if cls is not None:
                cmod, cname = cls
                return self._method(cmod, cname, f.attr)
        return None

    def _method(self, mod: Module, class_name: str,
                name: str) -> FuncInfo | None:
        q = f"{class_name}.{name}"
        info = self.functions(mod).get(q)
        if info is not None:
            return info
        # single-level base-class lookup by name, package-wide
        for m, node in self.class_index.get(class_name, []):
            for base in node.bases:
                if isinstance(base, ast.Name):
                    for bm, bnode in self.class_index.get(base.id, []):
                        hit = self.functions(bm).get(f"{base.id}.{name}")
                        if hit is not None:
                            return hit
        return None

    def _var_class(self, mod: Module, caller: FuncInfo,
                   var: str) -> tuple[Module, str] | None:
        """Which class (if exactly one, package-wide) ``var`` was
        constructed from — in the caller's body, any enclosing
        function's body (closure variables: the signal-handler idiom is
        a nested ``_on_term`` closing over ``server``), or at module
        level."""
        funcs = self.functions(mod)
        spots = list(ast.walk(caller.node))
        parts = caller.qualname.split(".")
        for i in range(len(parts) - 1, 0, -1):
            enclosing = funcs.get(".".join(parts[:i]))
            if enclosing is not None and \
                    not isinstance(enclosing.node, ast.Lambda):
                spots.extend(enclosing.node.body)
        spots.extend(mod.tree.body)
        classes: set[str] = set()
        for node in spots:
            if not isinstance(node, ast.Assign):
                continue
            if not any(isinstance(t, ast.Name) and t.id == var
                       for t in node.targets):
                continue
            v = node.value
            if isinstance(v, ast.Call) and isinstance(v.func, ast.Name):
                classes.add(v.func.id)
        for cname in classes:
            defs = self.class_index.get(cname, [])
            if len(defs) == 1:
                return defs[0][0], cname
        return None

    # -- inline suppression ------------------------------------------------

    def allowed(self, mod: Module, line: int, rule: str) -> bool:
        """True when the flagged line (or the one above it) carries an
        explicit ``# tpucfn: allow[<rule>]`` pragma."""
        tag = f"tpucfn: allow[{rule}]"
        for ln in (line, line - 1):
            if 1 <= ln <= len(mod.lines) and tag in mod.lines[ln - 1]:
                return True
        return False


def _self_attr_or_name(node: ast.expr) -> str | None:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _lock_ctor_kind(value: ast.expr) -> tuple[str | None, str | None]:
    """``(kind, wrapped_attr)`` for a lock-constructing RHS, else
    ``(None, None)``.  ``Condition(self.x)`` reports ``(None, "x")`` so
    the caller can alias it to x's kind."""
    if not isinstance(value, ast.Call):
        return None, None
    f = value.func
    name = f.attr if isinstance(f, ast.Attribute) else \
        f.id if isinstance(f, ast.Name) else None
    if name == "Lock":
        return "lock", None
    if name == "RLock":
        return "rlock", None
    if name == "Condition":
        if value.args:
            wrapped = _self_attr_or_name(value.args[0])
            if wrapped is not None:
                return None, wrapped
        return "rlock", None
    return None, None


# -- constant-aware statement iteration ------------------------------------


def live_statements(body: list[ast.stmt],
                    consts: dict[str, object] | None = None):
    """Yield the statements of ``body`` recursively, pruning ``if``
    branches decidable from ``consts`` (parameter-name -> constant).
    This is what lets a call like ``drain(wait=False)`` analyze only the
    signal-handler-safe early-return path instead of flagging the
    lock-taking ``wait=True`` body it never reaches (the PR 8 fixed
    shape).  Nested function definitions are NOT descended into — they
    only run if called, and call edges are walked separately."""
    consts = consts or {}
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if isinstance(stmt, ast.If):
            verdict = _const_test(stmt.test, consts)
            if verdict is True:
                yield from live_statements(stmt.body, consts)
                if _terminates(stmt.body):
                    return  # early-return guard: the rest never runs
                continue
            if verdict is False:
                yield from live_statements(stmt.orelse, consts)
                if stmt.orelse and _terminates(stmt.orelse):
                    return
                continue
            yield stmt
            yield from live_statements(stmt.body, consts)
            yield from live_statements(stmt.orelse, consts)
            continue
        yield stmt
        for sub in sub_suites(stmt):
            yield from live_statements(sub, consts)


def _terminates(body: list[ast.stmt]) -> bool:
    """Does this suite unconditionally leave the enclosing block?"""
    if not body:
        return False
    last = body[-1]
    if isinstance(last, (ast.Return, ast.Raise, ast.Break, ast.Continue)):
        return True
    if isinstance(last, ast.If):
        return bool(last.orelse) and _terminates(last.body) \
            and _terminates(last.orelse)
    return False


def _const_test(test: ast.expr, consts: dict[str, object]):
    """Truth value of an ``if`` test under ``consts``, or None."""
    if isinstance(test, ast.Name) and test.id in consts:
        return bool(consts[test.id])
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        inner = _const_test(test.operand, consts)
        return None if inner is None else not inner
    return None


def call_consts(call: ast.Call, callee: FuncInfo) -> dict[str, object]:
    """Constant arguments of ``call`` mapped to ``callee`` parameter
    names (positional and keyword) — the input to branch pruning."""
    out: dict[str, object] = {}
    params = callee.params
    if params and params[0] == "self":
        params = params[1:]
    for i, a in enumerate(call.args):
        if isinstance(a, ast.Constant) and i < len(params):
            out[params[i]] = a.value
    for kw in call.keywords:
        if kw.arg is not None and isinstance(kw.value, ast.Constant):
            out[kw.arg] = kw.value.value
    return out


def calls_in(stmt: ast.stmt) -> Iterable[ast.Call]:
    """Every Call expression directly inside one statement (does not
    recurse into nested statement bodies — pair with live_statements)."""
    children = []
    for field in stmt._fields:
        v = getattr(stmt, field, None)
        if isinstance(v, ast.expr):
            children.append(v)
        elif isinstance(v, list):
            children.extend(x for x in v if isinstance(x, ast.expr))
        # withitem list
        if field == "items" and isinstance(v, list):
            children.extend(x.context_expr for x in v
                            if isinstance(x, ast.withitem))
    for c in children:
        for node in ast.walk(c):
            if isinstance(node, ast.Call):
                yield node


# -- baseline ---------------------------------------------------------------

BASELINE_VERSION = 1


def load_baseline(path: str | Path) -> dict[str, dict]:
    """``fingerprint -> entry``.  Every entry MUST carry a non-empty
    one-line justification; a baseline that silently suppresses is the
    exact failure mode this tool exists to prevent, so it raises."""
    p = Path(path)
    try:
        data = json.loads(p.read_text())
    except (OSError, json.JSONDecodeError) as e:
        raise ValueError(f"cannot read baseline {p}: {e}")
    if not isinstance(data, dict) or "suppressions" not in data:
        raise ValueError(f"baseline {p}: expected {{'suppressions': [...]}}")
    out: dict[str, dict] = {}
    for ent in data["suppressions"]:
        fp = ent.get("fingerprint")
        just = (ent.get("justification") or "").strip()
        if not fp:
            raise ValueError(f"baseline {p}: entry missing fingerprint: {ent}")
        if not just:
            raise ValueError(
                f"baseline {p}: suppression {fp} ({ent.get('rule')}) has no "
                "justification — every baselined finding must say why it is "
                "deliberately kept")
        out[fp] = ent
    return out


def write_baseline(path: str | Path, findings: list[Finding],
                   previous: dict[str, dict] | None = None) -> Path:
    """Write a baseline covering exactly ``findings``; justifications of
    entries already present in ``previous`` are preserved, new ones get
    an explicit TODO the author must fill in before review."""
    previous = previous or {}
    ents = []
    for f in sorted(findings, key=lambda f: (f.path, f.rule, f.key)):
        prev = previous.get(f.fingerprint, {})
        ents.append({
            "fingerprint": f.fingerprint,
            "rule": f.rule,
            "path": f.path,
            "key": f.key,
            "message": f.message,
            "justification": prev.get("justification")
            or "TODO: one line on why this finding is deliberately kept",
        })
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(json.dumps({"version": BASELINE_VERSION,
                             "suppressions": ents}, indent=2) + "\n")
    return p


def apply_baseline(findings: list[Finding], baseline: dict[str, dict]
                   ) -> tuple[list[Finding], list[Finding], list[dict]]:
    """``(active, suppressed, stale_entries)`` — stale entries suppress
    nothing anymore (the finding was fixed) and should be pruned with
    ``--update-baseline``."""
    active, suppressed = [], []
    seen: set[str] = set()
    for f in findings:
        if f.fingerprint in baseline:
            suppressed.append(f)
            seen.add(f.fingerprint)
        else:
            active.append(f)
    stale = [ent for fp, ent in baseline.items() if fp not in seen]
    return active, suppressed, stale


# -- git / diff mode --------------------------------------------------------


def changed_files(repo_root: Path, ref: str) -> set[str]:
    """Repo-root-relative paths changed vs ``ref`` — committed, staged,
    worktree, AND untracked (``git diff`` alone never lists the brand-
    new files a PR adds, which are exactly where new findings live).
    Git reports toplevel-relative paths; they are re-anchored onto
    ``repo_root`` so ``--diff`` works from a subdirectory checkout too.
    Raises ValueError when git cannot answer (not a repo, bad ref)."""

    def _git(*args: str) -> list[str]:
        try:
            out = subprocess.run(["git", "-C", str(repo_root), *args],
                                 capture_output=True, text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired) as e:
            raise ValueError(f"git {args[0]} failed: {e}")
        if out.returncode != 0:
            raise ValueError(
                f"git {' '.join(args)} failed: {out.stderr.strip()}")
        return [line.strip() for line in out.stdout.splitlines()
                if line.strip()]

    toplevel = Path(_git("rev-parse", "--show-toplevel")[0]).resolve()
    # --full-name: ls-files otherwise prints cwd-relative paths (unlike
    # git diff, which is always toplevel-relative) — joining those onto
    # toplevel silently dropped untracked files in subdirectory checkouts
    names = _git("diff", "--name-only", ref, "--") \
        + _git("ls-files", "--others", "--exclude-standard", "--full-name")
    root = Path(repo_root).resolve()
    out: set[str] = set()
    for name in names:
        p = (toplevel / name).resolve()
        try:
            out.add(p.relative_to(root).as_posix())
        except ValueError:
            continue  # changed file outside this package's repo_root
    return out


# -- runner -----------------------------------------------------------------


def run_check(package_root: str | Path, *,
              rules: Iterable[str] | None = None,
              repo_root: str | Path | None = None,
              tests_dir: str | Path | None = None,
              readme: str | Path | None = None,
              only: set[str] | None = None) -> list[Finding]:
    """Run the rule pack over ``package_root`` and return findings
    (inline-pragma suppressions already dropped; baseline is the
    caller's business so ``--update-baseline`` can see everything).

    ``only`` restricts REPORTING to the given repo-relative paths
    (``--diff`` mode) — the whole package is still parsed so cross-
    module rules (metric hygiene, vocabularies) keep full context.
    """
    from tpucfn.analysis.rules import resolve_rules

    package_root = Path(package_root).resolve()
    repo_root = (Path(repo_root).resolve() if repo_root is not None
                 else package_root.parent)
    if tests_dir is None:
        cand = repo_root / "tests"
        tests_dir = cand if cand.is_dir() else None
    if readme is None:
        cand = repo_root / "README.md"
        readme = cand if cand.is_file() else None

    modules, findings = load_modules(package_root, repo_root)
    analysis = Analysis(modules, package_root=package_root,
                        repo_root=repo_root,
                        tests_dir=Path(tests_dir) if tests_dir else None,
                        readme=Path(readme) if readme else None)
    mod_by_rel = {m.rel: m for m in modules}
    for rule in resolve_rules(rules):
        for f in rule.check(analysis):
            mod = mod_by_rel.get(f.path)
            if mod is not None and analysis.allowed(mod, f.line, f.rule):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.key))
    # identical (rule, path, key) triples get stable ordinals so every
    # finding keeps a distinct fingerprint
    counts: dict[str, int] = {}
    for f in findings:
        fp = f.fingerprint
        n = counts.get(fp, 0) + 1
        counts[fp] = n
        if n > 1:
            f.key = f"{f.key}#{n}"
    if only is not None:
        findings = [f for f in findings if f.path in only]
    return findings
