"""Paged KV-cache allocator/manager invariants (tpucfn.serve.kvcache):
atomic allocation, validated frees, leak-free lifecycle, fragmentation
and eviction accounting, plus the ISSUE-3 prefix cache: ref-counted
sharing, COW on the divergent write, eviction refusal on shared blocks,
and index survival across holder turnover."""

import pytest

from tpucfn.serve.kvcache import (
    BlockAllocator,
    KVCacheManager,
    OutOfBlocksError,
)


def test_allocator_alloc_free_roundtrip():
    a = BlockAllocator(8, 16)
    got = a.alloc(5)
    assert len(got) == 5 and len(set(got)) == 5
    assert a.num_free == 3 and a.num_used == 5
    a.free(got[:2])
    assert a.num_free == 5
    more = a.alloc(5)
    assert set(more) & set(got[2:]) == set()  # still-held blocks not reissued
    a.free(more)
    a.free(got[2:])
    assert a.num_free == 8 and a.num_used == 0
    assert a.high_water == 8  # 3 held + 5 allocated at the peak


def test_allocator_exhaustion_is_atomic():
    a = BlockAllocator(4, 16)
    a.alloc(3)
    with pytest.raises(OutOfBlocksError):
        a.alloc(2)  # only 1 free
    assert a.num_free == 1  # nothing partially taken


def test_allocator_double_free_rejected():
    a = BlockAllocator(4, 16)
    got = a.alloc(2)
    a.free(got)
    with pytest.raises(ValueError, match="not allocated"):
        a.free([got[0]])
    with pytest.raises(ValueError, match="not allocated"):
        a.free([99])


def test_manager_admit_grow_release_is_leak_free():
    m = KVCacheManager(num_blocks=8, block_size=4)
    m.admit("a", 5)  # 2 blocks (5 tokens / 4 per block)
    assert m.allocator.num_used == 2
    assert m.internal_fragmentation() == 3
    # Growth: tokens 6..8 fill block 2; token 9 needs block 3.
    for _ in range(3):
        m.reserve_next("a")
        m.commit_token("a")
    assert m.allocator.num_used == 2
    m.reserve_next("a")
    assert m.allocator.num_used == 3
    m.commit_token("a")
    assert m.table("a").num_tokens == 9
    m.release("a")
    assert m.allocator.num_free == 8
    assert m.num_sequences == 0


def test_manager_commit_without_reserve_fails():
    m = KVCacheManager(num_blocks=4, block_size=2)
    m.admit("a", 2)  # exactly one full block
    with pytest.raises(RuntimeError, match="reserve_next"):
        m.commit_token("a")


def test_manager_eviction_accounting():
    m = KVCacheManager(num_blocks=8, block_size=4)
    m.admit("a", 8)
    m.admit("b", 4)
    m.release("a", evicted=True)
    m.release("b")
    assert m.evictions == 1
    assert m.blocks_evicted == 2
    assert m.allocator.num_free == 8


def test_manager_occupancy_and_feasibility():
    m = KVCacheManager(num_blocks=4, block_size=8)
    assert m.fits_at_all(32) and not m.fits_at_all(33)
    assert m.can_admit(32)
    m.admit("a", 17)  # 3 blocks
    assert m.occupancy() == 0.75
    assert m.can_admit(8) and not m.can_admit(9)


def test_manager_interleaved_sequences_restore_free_count():
    """Many sequences with interleaved admit/grow/release: the free count
    must return exactly to the initial pool — the zero-leak acceptance
    invariant at the accounting layer."""
    m = KVCacheManager(num_blocks=32, block_size=4)
    live = {}
    for i in range(10):
        live[i] = m.admit(i, 1 + (i * 7) % 9)
        if i % 3 == 2:  # retire one early, evict another
            m.release(i - 1, evicted=True)
            del live[i - 1]
        for j in list(live):
            m.reserve_next(j)
            m.commit_token(j)
    for j in list(live):
        m.release(j)
    assert m.allocator.num_free == 32
    assert m.allocator.num_used == 0
    assert m.internal_fragmentation() == 0


# ---- ref-counted prefix cache (ISSUE 3) ---------------------------------

def _pm(num_blocks=16, block_size=4):
    return KVCacheManager(num_blocks, block_size, prefix_cache=True)


def test_refcount_share_then_release_cycles_to_zero():
    """Shared-prefix admit/release cycles end at zero used blocks: N
    sequences share one prompt's blocks via incref and the pool is whole
    after the LAST holder releases."""
    m = _pm()
    prompt = list(range(8))          # 2 full blocks
    a = m.admit("a", tokens=prompt + [99, 98])   # 3 blocks, registers index
    assert a.cached_len == 0 and a.suffix == prompt + [99, 98]
    match = m.match_prefix(prompt + [50])
    assert match.cached_len == 8 and match.holders == {"a"}
    b = m.admit("b", tokens=prompt + [50], match=match)
    assert b.cached_len == 8 and b.suffix == [50]
    # 3 (a) + 1 fresh (b's tail): the two prefix blocks are shared.
    assert m.allocator.num_used == 4
    assert m.table("b").blocks[:2] == m.table("a").blocks[:2]
    assert m.allocator.ref(m.table("a").blocks[0]) == 2
    assert m.prefix_hits == 1 and m.prefix_hit_tokens == 8
    m.release("a")
    assert m.allocator.num_used == 3  # shared blocks survive a's release
    m.release("b")
    assert m.allocator.num_used == 0
    assert m.prefix_cache_stats()["indexed_blocks"] == 0


def test_cow_triggers_on_divergent_write_of_aligned_match():
    """A prompt whose full-block match covers the WHOLE prompt must
    still prefill >= 1 token — that write diverges into the last matched
    block, so the match drops it (a private copy) and counts a COW."""
    m = _pm()
    prompt = list(range(8))          # exactly 2 full blocks
    m.admit("a", tokens=prompt)
    match = m.match_prefix(prompt)   # both blocks indexed...
    assert match.cow is True         # ...but the write-target is dropped
    assert match.cached_len == 4 and match.num_blocks == 1
    b = m.admit("b", tokens=prompt, match=match)
    assert b.cached_len == 4
    assert m.cow_copies == 1
    # b's second block is PRIVATE, not a's.
    assert m.table("b").blocks[0] == m.table("a").blocks[0]
    assert m.table("b").blocks[1] != m.table("a").blocks[1]
    m.release("a")
    m.release("b")
    assert m.allocator.num_used == 0


def test_eviction_of_shared_block_refused_until_refcount_one():
    """Evicting one holder of a shared block must NOT free it: the block
    returns to the free list only when the last reference drops."""
    m = _pm(num_blocks=8)
    prompt = list(range(4))          # 1 full block
    m.admit("a", tokens=prompt + [7])
    match = m.match_prefix(prompt + [8])
    b_blocks = m.admit("b", tokens=prompt + [8], match=match).table.blocks
    shared = b_blocks[0]
    assert m.allocator.ref(shared) == 2
    m.release("b", evicted=True)     # eviction refused for the shared block
    assert m.allocator.ref(shared) == 1
    assert m.blocks_evicted == 1     # only b's private tail block freed
    assert m.evictions == 1
    m.release("a", evicted=True)     # last holder: now it frees
    assert m.allocator.ref(shared) == 0
    assert m.blocks_evicted == 3
    assert m.allocator.num_used == 0


def test_index_repoints_to_surviving_holder():
    """When the index-registered holder releases, entries re-point to a
    live sharer's block instead of dangling at a freed id."""
    m = _pm()
    prompt = list(range(8))
    m.admit("a", tokens=prompt + [1])
    ma = m.match_prefix(prompt + [2])
    m.admit("b", tokens=prompt + [2], match=ma)
    m.release("a")
    mc = m.match_prefix(prompt + [3])
    assert mc.cached_len == 8 and mc.holders == {"b"}
    assert all(blk in m.table("b").blocks for blk in mc.blocks)
    c = m.admit("c", tokens=prompt + [3], match=mc)
    assert c.cached_len == 8
    m.release("b")
    m.release("c")
    assert m.allocator.num_used == 0


def test_generated_tokens_extend_the_chain():
    """commit_token(token=...) registers full GENERATED blocks, so a
    later prompt can hit on prompt + generated history."""
    m = _pm(block_size=2)
    m.admit("a", tokens=[5, 6])      # 1 full block
    for tok in (7, 8):
        m.reserve_next("a")
        m.commit_token("a", token=tok)
    # a's cache now holds [5, 6, 7, 8] = 2 full blocks.
    match = m.match_prefix([5, 6, 7, 8, 9])
    assert match.cached_len == 4 and match.holders == {"a"}
    m.release("a")
    assert m.allocator.num_used == 0
    assert m.match_prefix([5, 6, 7, 8, 9]).cached_len == 0


def test_disabled_prefix_cache_never_matches():
    m = KVCacheManager(8, 4, prefix_cache=False)
    m.admit("a", tokens=list(range(8)))
    assert m.match_prefix(list(range(8))).cached_len == 0
    m.release("a")
    assert m.allocator.num_used == 0


def test_admit_with_match_is_atomic_when_pool_dry():
    """A failed shared admit must not half-apply: no increfs survive an
    OutOfBlocksError on the fresh-suffix allocation."""
    m = _pm(num_blocks=3, block_size=4)
    m.admit("a", tokens=list(range(8)))          # 2 of 3 blocks
    match = m.match_prefix(list(range(8)) + [1] * 8)  # needs 2 fresh
    assert match.cached_len == 8
    ref0 = m.allocator.ref(m.table("a").blocks[0])
    with pytest.raises(OutOfBlocksError):
        m.admit("b", tokens=list(range(8)) + [1] * 8, match=match)
    assert m.allocator.ref(m.table("a").blocks[0]) == ref0
    m.release("a")
    assert m.allocator.num_used == 0


def test_shared_mixed_lifecycle_zero_leaks():
    """Hits, misses, growth, evictions interleaved: the pool must return
    exactly to empty and the index must drain with its holders."""
    m = _pm(num_blocks=32, block_size=4)
    base = list(range(12))           # 3 full blocks
    live = []
    for i in range(9):
        toks = base + [100 + i, 200 + i ** 2]
        match = m.match_prefix(toks)
        m.admit(i, tokens=toks, match=match if match.cached_len else None)
        live.append(i)
        for j in list(live):
            m.reserve_next(j)
            m.commit_token(j, token=300 + j)
        if i % 3 == 2:
            m.release(live.pop(0), evicted=(i % 2 == 0))
    for j in live:
        m.release(j)
    assert m.allocator.num_used == 0
    assert m.allocator.num_free == 32
    assert m.prefix_cache_stats()["indexed_blocks"] == 0
    assert m.prefix_hits > 0


def test_hash_collision_degrades_to_miss(monkeypatch):
    """_block_hash is the fast builtin, so lookups re-verify token
    content: a colliding hash must read as a MISS, never share a
    stranger's KV.  Forced by stubbing the hash to a constant."""
    import tpucfn.serve.kvcache as kvmod

    monkeypatch.setattr(kvmod, "_block_hash", lambda prev, toks: 42)
    m = kvmod.KVCacheManager(16, 4, prefix_cache=True)
    m.admit("a", tokens=list(range(8)))
    # Different content under the same hash: no match.
    assert m.match_prefix([9, 9, 9, 9, 9]).cached_len == 0
    # Identical content still matches through the content check.
    assert m.match_prefix(list(range(8)) + [1]).cached_len == 4
    m.release("a")
    assert m.allocator.num_used == 0
