#!/usr/bin/env python
"""Fleet warm-start bench (ISSUE 13 acceptance): the artifact cache's
two claims, measured and rc-gated, ONE JSON line out in the standard
BENCH row schema.

* **warm-start** — a cold child process compiles a grad program and
  publishes its serialized executable into a fresh artifact store; a
  second process on the same machine starts the same program through
  the store and must reach its first step in ``--warm-ratio`` (default
  0.35) of the cold time.  Time-to-first-step is measured from
  jax-imported to first-result-ready inside each child, so the number
  isolates what the cache changes (compile vs deserialize), not
  interpreter boot.
* **fan-out** — a simulated 2-host cold fleet: an in-process
  :class:`~tpucfn.compilecache.service.ArtifactServer` plus two child
  processes racing the same cold key must produce exactly 1 compile and
  1 fetch (the single-flight guard, pinned).

Children are this same file (``TPUCFN_COMPILE_BENCH_CHILD=1``), so the
bench exercises the real cross-process path — separate interpreters,
separate jax runtimes, artifacts only through the store/server.

Usage: python benches/compile_bench.py [--layers 48 --width 128 ...]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


# -- the measured program ---------------------------------------------------
#
# A residual-MLP grad: enough distinct fused ops that XLA:CPU pays a
# real compile (seconds at the default depth), while the serialized
# executable deserializes in tens of milliseconds.

def child() -> int:
    layers = int(os.environ["TPUCFN_COMPILE_BENCH_LAYERS"])
    width = int(os.environ["TPUCFN_COMPILE_BENCH_WIDTH"])

    import numpy as np

    import jax
    import jax.numpy as jnp

    from tpucfn.compilecache import configure_from_env
    from tpucfn.compilecache.jit import maybe_warm

    client = configure_from_env()

    def loss(params, x):
        h = x
        for w, b in params:
            h = jnp.tanh(h @ w + b) + 0.1 * h
        return (h ** 2).mean()

    rs = np.random.RandomState(0)
    params = [(rs.randn(width, width).astype(np.float32) * 0.1,
               np.zeros(width, np.float32)) for _ in range(layers)]
    x = rs.randn(8, width).astype(np.float32)

    t0 = time.perf_counter()  # jax imported, program built: the clock
    step = maybe_warm(jax.jit(jax.grad(loss)), label="compile_bench")
    out = step(params, x)
    jax.block_until_ready(out)
    ttfs = time.perf_counter() - t0
    digest = float(sum(float(jnp.sum(w)) for w, _ in out))
    print(json.dumps({
        "ttfs_s": round(ttfs, 4),
        "outcome": client.last_outcome if client is not None else None,
        "digest": digest,
    }))
    return 0


# -- the orchestrator -------------------------------------------------------

def _run_child(args, *, store_dir: str | None, addrs: str | None,
               env_extra: dict | None = None) -> dict:
    env = {**os.environ,
           "TPUCFN_COMPILE_BENCH_CHILD": "1",
           "TPUCFN_COMPILE_BENCH_LAYERS": str(args.layers),
           "TPUCFN_COMPILE_BENCH_WIDTH": str(args.width),
           "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu")}
    env.pop("TPUCFN_COMPILE_CACHE_DIR", None)
    env.pop("TPUCFN_COMPILE_CACHE_ADDRS", None)
    if store_dir is not None:
        env["TPUCFN_COMPILE_CACHE_DIR"] = store_dir
    if addrs is not None:
        env["TPUCFN_COMPILE_CACHE_ADDRS"] = addrs
    env.update(env_extra or {})
    proc = subprocess.run([sys.executable, __file__], env=env,
                          capture_output=True, text=True,
                          timeout=args.timeout)
    if proc.returncode != 0:
        raise RuntimeError(
            f"bench child failed rc={proc.returncode}:\n{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _spawn_child(args, *, store_dir: str, addrs: str) -> subprocess.Popen:
    env = {**os.environ,
           "TPUCFN_COMPILE_BENCH_CHILD": "1",
           "TPUCFN_COMPILE_BENCH_LAYERS": str(args.layers),
           "TPUCFN_COMPILE_BENCH_WIDTH": str(args.width),
           "TPUCFN_COMPILE_CACHE_DIR": store_dir,
           "TPUCFN_COMPILE_CACHE_ADDRS": addrs,
           "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu")}
    return subprocess.Popen([sys.executable, __file__], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True)


def main() -> int:
    if os.environ.get("TPUCFN_COMPILE_BENCH_CHILD") == "1":
        return child()

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--layers", type=int, default=48,
                   help="program depth — sizes the cold compile")
    p.add_argument("--width", type=int, default=128)
    p.add_argument("--warm-ratio", type=float, default=0.35,
                   help="acceptance gate: warm ttfs must be <= this "
                        "fraction of cold ttfs")
    p.add_argument("--timeout", type=float, default=300.0)
    p.add_argument("--skip-fanout", action="store_true")
    args = p.parse_args()

    tmp = Path(tempfile.mkdtemp(prefix="tpucfn-compile-bench-"))
    try:
        # -- phase 1: same-machine warm start via the artifact store --
        store = str(tmp / "store")
        cold = _run_child(args, store_dir=store, addrs=None)
        warm = _run_child(args, store_dir=store, addrs=None)
        ratio = warm["ttfs_s"] / cold["ttfs_s"] if cold["ttfs_s"] else 1.0
        warm_ok = (cold["outcome"] == "compile"
                   and warm["outcome"] == "store"
                   and warm["digest"] == cold["digest"]
                   and ratio <= args.warm_ratio)

        # -- phase 2: 2-host cold-fleet fan-out: 1 compile + 1 fetch --
        fanout: dict = {"skipped": True}
        fan_ok = True
        if not args.skip_fanout:
            from tpucfn.compilecache.service import ArtifactServer

            srv = ArtifactServer(tmp / "server-store",
                                 host="127.0.0.1").start()
            try:
                procs = [
                    _spawn_child(args, store_dir=str(tmp / f"host{i}"),
                                 addrs=srv.address)
                    for i in range(2)]
                outs = []
                for proc in procs:
                    stdout, stderr = proc.communicate(timeout=args.timeout)
                    if proc.returncode != 0:
                        raise RuntimeError(
                            f"fan-out child rc={proc.returncode}:"
                            f"\n{stderr[-2000:]}")
                    outs.append(json.loads(
                        stdout.strip().splitlines()[-1]))
            finally:
                srv.close()
            outcomes = sorted(o["outcome"] for o in outs)
            fan_ok = (outcomes == ["compile", "fetch"]
                      and outs[0]["digest"] == outs[1]["digest"])
            fanout = {"outcomes": outcomes,
                      "ttfs_s": [o["ttfs_s"] for o in outs],
                      "digests_equal": outs[0]["digest"] == outs[1]["digest"],
                      "ok": fan_ok}

        ok = warm_ok and fan_ok
        print(f"# compile_bench cold={cold['ttfs_s']}s "
              f"warm={warm['ttfs_s']}s ratio={ratio:.3f} "
              f"(gate {args.warm_ratio}) fanout={fanout} ok={ok}",
              file=sys.stderr)
        row = {
            "metric": "compile_warm_start_ratio",
            "value": round(ratio, 4),
            "unit": "warm/cold time-to-first-step",
            "vs_baseline": 0.0,
            "detail": {
                "baseline_note": "no fleet artifact plane existed "
                                 "before ISSUE 13; the cold number is "
                                 "the baseline",
                "ok": ok,
                "cold_time_to_first_step_s": cold["ttfs_s"],
                "warm_time_to_first_step_s": warm["ttfs_s"],
                "cold_outcome": cold["outcome"],
                "warm_outcome": warm["outcome"],
                "digest_bit_identical": warm["digest"] == cold["digest"],
                "gate_ratio": args.warm_ratio,
                "layers": args.layers,
                "width": args.width,
                "fanout": fanout,
            },
        }
        print(json.dumps(row))
        return 0 if ok else 1
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
