import numpy as np

from tpucfn.data import ShardedDataset, synthetic_cifar10, write_dataset_shards
from tpucfn.data.transforms import (
    CIFAR_TRAIN,
    Compose,
    normalize,
    random_crop,
    random_flip,
    random_resized_crop,
)


def _img(h=8, w=8):
    return {"image": np.arange(h * w * 3, dtype=np.float32).reshape(h, w, 3),
            "label": np.int32(1)}


def test_flip_is_mirror():
    rs = np.random.RandomState(0)
    ex = _img()
    flipped_any = False
    for _ in range(20):
        out = random_flip()(ex, rs)
        assert out["image"].shape == ex["image"].shape
        if not np.array_equal(out["image"], ex["image"]):
            np.testing.assert_array_equal(out["image"], ex["image"][:, ::-1])
            flipped_any = True
    assert flipped_any


def test_crop_preserves_shape_and_content_window():
    rs = np.random.RandomState(0)
    out = random_crop(2)(_img(), rs)
    assert out["image"].shape == (8, 8, 3)


def test_resized_crop_output_shape():
    rs = np.random.RandomState(0)
    out = random_resized_crop(16)({"image": np.random.rand(64, 48, 3).astype(np.float32)}, rs)
    assert out["image"].shape == (16, 16, 3)


def test_normalize():
    rs = np.random.RandomState(0)
    ex = {"image": np.ones((4, 4, 3), np.float32) * 2}
    out = normalize([1, 1, 1], [2, 2, 2])(ex, rs)
    np.testing.assert_allclose(out["image"], 0.5)


def test_compose_order():
    rs = np.random.RandomState(0)
    t = Compose([normalize([0, 0, 0], [2, 2, 2]), normalize([1, 1, 1], [1, 1, 1])])
    out = t({"image": np.full((2, 2, 3), 4.0, np.float32)}, rs)
    np.testing.assert_allclose(out["image"], 1.0)  # (4/2) - 1


def test_dataset_transform_deterministic_per_epoch(tmp_path):
    paths = write_dataset_shards(synthetic_cifar10(32), tmp_path, num_shards=2)
    mk = lambda: ShardedDataset(  # noqa: E731
        paths, batch_size_per_process=8, transform=CIFAR_TRAIN, seed=3
    )
    a = [b["image"] for b in mk().epoch(0)]
    b = [b["image"] for b in mk().epoch(0)]
    c = [b_["image"] for b_ in mk().epoch(1)]
    np.testing.assert_array_equal(np.stack(a), np.stack(b))
    assert not np.array_equal(np.stack(a), np.stack(c))  # new epoch, new augs
    assert a[0].shape == (8, 32, 32, 3)
