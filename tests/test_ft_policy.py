"""Recovery policy semantics (tpucfn.ft.policy): budget accounting,
deterministic backoff+jitter, the failure-class decision table, and the
gang-vs-solo restart shapes."""

import random

import pytest

from tpucfn.ft import (
    Action,
    Failure,
    FailureKind,
    GangRestart,
    RestartBudget,
    SoloRestart,
    policy_from_name,
)


def _crash(host, rc=1):
    return Failure(host, FailureKind.CRASH, rc=rc)


def test_budget_backoff_is_exponential_capped_and_seeded(tmp_path=None):
    b = RestartBudget(10, backoff_s=1.0, multiplier=2.0, max_backoff_s=5.0,
                      jitter=0.5, rng=random.Random(7))
    ref = random.Random(7)
    seen = []
    for k in range(5):
        base = min(1.0 * 2.0 ** k, 5.0)
        expect = base * (1.0 + ref.uniform(-0.5, 0.5))
        got = b.next_delay()
        assert got == pytest.approx(expect), k
        seen.append(got)
        assert b.consume()
    assert seen[4] <= 5.0 * 1.5  # cap applies before jitter
    # same seed → identical delay stream (the chaos determinism contract)
    b2 = RestartBudget(10, backoff_s=1.0, multiplier=2.0, max_backoff_s=5.0,
                      jitter=0.5, rng=random.Random(7))
    replay = []
    for _ in range(5):
        replay.append(b2.next_delay())
        b2.consume()
    assert replay == seen


def test_budget_zero_backoff_and_exhaustion():
    b = RestartBudget(2)
    assert b.next_delay() == 0.0
    assert b.consume() and b.consume()
    assert not b.consume()
    assert b.remaining == 0


def test_budget_validation():
    with pytest.raises(ValueError):
        RestartBudget(-1)
    with pytest.raises(ValueError):
        RestartBudget(1, jitter=1.5)


def test_gang_policy_restarts_whole_gang_for_crash():
    p = GangRestart(RestartBudget(1))
    d = p.decide([_crash(2, rc=137)])
    assert d.action is Action.GANG_RESTART
    assert d.hosts == ()  # whole gang
    assert p.budget.used == 1


def test_clean_exit_and_straggler_burn_no_budget():
    p = GangRestart(RestartBudget(1))
    d = p.decide([Failure(0, FailureKind.CLEAN_EXIT, rc=0),
                  Failure(1, FailureKind.STRAGGLER, step=5)])
    assert d.action is Action.NONE
    assert p.budget.used == 0  # the exit-cause-accounting satellite
    # the budget slot is still there for a real failure
    assert p.decide([_crash(1)]).action is Action.GANG_RESTART


def test_budget_exhaustion_gives_up_with_reason():
    p = GangRestart(RestartBudget(1))
    assert p.decide([_crash(0)]).action is Action.GANG_RESTART
    d = p.decide([_crash(0)])
    assert d.action is Action.GIVE_UP
    assert "budget exhausted" in d.reason


def test_solo_policy_singles_vs_correlated_failures():
    p = SoloRestart(RestartBudget(5))
    d = p.decide([Failure(1, FailureKind.HANG)])
    assert d.action is Action.SOLO_RESTART and d.hosts == (1,)
    # two hosts at once: correlated death → escalate to gang restart
    d = p.decide([_crash(0), Failure(2, FailureKind.HANG)])
    assert d.action is Action.GANG_RESTART
    assert p.budget.used == 2


def test_decision_table_override_makes_straggler_actionable():
    p = SoloRestart(RestartBudget(3),
                    table={FailureKind.STRAGGLER: Action.SOLO_RESTART})
    d = p.decide([Failure(3, FailureKind.STRAGGLER, step=10)])
    assert d.action is Action.SOLO_RESTART and d.hosts == (3,)


def test_policy_from_name():
    assert isinstance(policy_from_name("gang", RestartBudget(0)), GangRestart)
    assert isinstance(policy_from_name("solo", RestartBudget(0)), SoloRestart)
    with pytest.raises(ValueError):
        policy_from_name("yolo", RestartBudget(0))
