"""Speculative decoding (tpucfn.serve.spec, ISSUE 14): the greedy
bit-identity pin (spec output == plain engine output across mixed
prefill/decode workloads, preemption, slot reuse, prefix hits, and a
DIVERGENT draft), the k-controller's shrink/off/probe behavior, the
multi-token record path through the Server, and the no-draft
byte-identity guarantee.

Compile-budget note: jax tests share module-scoped engines (tiny
target, self and divergent drafts) the same way test_serve_engine.py
does — slots are fully overwritten per prefill, so cross-test state
cannot leak.
"""

import dataclasses
import time

import pytest

from tpucfn.serve.spec import SpecDecoder, SpecKController

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from tpucfn.models.llama import Llama, LlamaConfig  # noqa: E402
from tpucfn.serve import Cancelled, ServeEngine, Server  # noqa: E402


# ---- SpecKController (pure, no jax needed but grouped here) -------------

def test_controller_validation():
    with pytest.raises(ValueError, match="k must be"):
        SpecKController(k=0)
    with pytest.raises(ValueError, match="min_k"):
        SpecKController(k=4, min_k=5)
    with pytest.raises(ValueError, match="shrink_below"):
        SpecKController(k=4, shrink_below=0.9, grow_above=0.5)
    with pytest.raises(ValueError, match="probe_every"):
        SpecKController(k=4, probe_every=1)


def test_controller_shrinks_to_off_and_probes():
    ctl = SpecKController(k=4, window=4, probe_every=3)
    # Four zero-acceptance rounds per window: 4 -> 2 -> 1 -> off.
    for expect in (2, 1, 0):
        for _ in range(4):
            ctl.observe(proposed=8, accepted=0)
        assert ctl.k == expect, expect
    # Off: only every probe_every-th round proposes.
    ks = [ctl.round_k() for _ in range(6)]
    assert ks == [0, 0, 1, 0, 0, 1]
    # A failed probe stays off; a perfect probe re-enables at min_k.
    ctl.observe(proposed=8, accepted=0)
    assert ctl.k == 0
    ctl.round_k()
    ctl.round_k()
    assert ctl.round_k() == 1  # the probe round
    ctl.observe(proposed=8, accepted=8)
    assert ctl.k == 1


def test_controller_grows_on_sustained_acceptance():
    ctl = SpecKController(k=2, max_k=8, window=4)
    for _ in range(4):
        ctl.observe(proposed=8, accepted=8)
    assert ctl.k == 4
    for _ in range(4):
        ctl.observe(proposed=16, accepted=16)
    assert ctl.k == 8
    for _ in range(8):
        ctl.observe(proposed=32, accepted=32)
    assert ctl.k == 8  # capped at max_k


def test_controller_window_resets_on_decision():
    ctl = SpecKController(k=4, window=4)
    for _ in range(4):
        ctl.observe(proposed=8, accepted=0)
    assert ctl.k == 2
    # Fresh evidence after the shrink: three good rounds must NOT be
    # judged against the stale bad window.
    for _ in range(3):
        ctl.observe(proposed=4, accepted=4)
    assert ctl.k == 2 and ctl.acceptance_rate() == 1.0


def test_controller_non_adaptive_pins_k():
    ctl = SpecKController(k=3, adaptive=False)
    for _ in range(32):
        ctl.observe(proposed=8, accepted=0)
    assert ctl.k == 3


# ---- shared engines ------------------------------------------------------

@pytest.fixture(scope="module")
def tiny():
    cfg = dataclasses.replace(LlamaConfig.tiny(), max_seq=64)
    params = Llama(cfg).init(jax.random.key(2),
                             jnp.zeros((2, 8), jnp.int32))["params"]
    divergent = Llama(cfg).init(jax.random.key(77),
                                jnp.zeros((2, 8), jnp.int32))["params"]
    return cfg, params, divergent


def _eng(cfg, params, max_batch=4):
    return ServeEngine.from_llama(cfg, params, max_batch=max_batch,
                                  cache_len=64)


@pytest.fixture(scope="module")
def spec_self(tiny):
    cfg, params, _ = tiny
    return SpecDecoder(_eng(cfg, params), _eng(cfg, params), k=4)


@pytest.fixture(scope="module")
def spec_div(tiny):
    """Divergent draft: proposals are near-always wrong — the output
    must STILL be bit-identical (acceptance is a perf dial, never a
    correctness input)."""
    cfg, params, divergent = tiny
    return SpecDecoder(_eng(cfg, params), _eng(cfg, divergent), k=4,
                       adaptive=False)


def _run_server(engine, prompts, max_new, **kw):
    server = Server(engine, **{"num_blocks": 48, "block_size": 8, **kw})
    reqs = [server.submit(p, max_new_tokens=max_new) for p in prompts]
    server.run_until_idle()
    assert server.kv.allocator.num_used == 0, "KV blocks leaked"
    return [r.result(timeout=0) if r.error is None else r.error
            for r in reqs], server


# ---- engine-level verify/rollback ---------------------------------------

def test_engine_verify_matches_sequential_decode(tiny):
    cfg, params, _ = tiny
    eng_a = _eng(cfg, params)
    eng_b = _eng(cfg, params)
    prompt = [5, 9, 2, 77, 31]
    ref = [eng_a.prefill(slot=1, prefix=prompt, bucket=16)]
    for _ in range(6):
        ref.append(eng_a.decode({1: ref[-1]})[1])
    assert eng_b.prefill(slot=1, prefix=prompt, bucket=16) == ref[0]
    out = eng_b.verify({1: ref[:3]}, 3)   # all "proposals" correct
    assert out[1] == ref[1:4]
    eng_b.rollback({1: len(prompt) + 3})
    # Wrong proposals: position 0 must still match plain decode, and
    # after rollback the plain path continues bit-identically.
    out2 = eng_b.verify({1: [ref[3], 1234 % cfg.vocab_size, 7]}, 3)
    assert out2[1][0] == ref[4]
    eng_b.rollback({1: len(prompt) + 4})
    assert eng_b.decode({1: ref[4]})[1] == ref[5]
    counts = eng_b.compile_counts()
    assert "verify" in counts and "rollback" in counts


def test_engine_rollback_is_masked(tiny):
    """Rolling back one slot must not disturb another slot's position
    (free slots hold prefix-cache residue the scheduler still uses)."""
    cfg, params, _ = tiny
    eng = _eng(cfg, params)
    p = [3, 1, 4, 1, 5]
    a = [eng.prefill(slot=0, prefix=p, bucket=16)]
    b = [eng.prefill(slot=2, prefix=p, bucket=16)]
    eng.verify({0: [a[0], 1, 2]}, 3)
    # Discard the whole verify (roll slot 0 back to just-prefilled);
    # slot 2 is NOT listed and must keep its own position.
    eng.rollback({0: len(p)})
    for _ in range(3):
        out = eng.decode({0: a[-1], 2: b[-1]})
        a.append(out[0])
        b.append(out[2])
    assert a == b  # identical prompts, identical greedy continuations


def test_engine_verify_validates(tiny):
    cfg, params, _ = tiny
    eng = _eng(cfg, params)
    with pytest.raises(ValueError, match="width"):
        eng.verify({0: [1, 2]}, 3)
    with pytest.raises(ValueError, match="width must be"):
        eng.verify({}, 0)
    with pytest.raises(ValueError, match="rollback length"):
        eng.rollback({0: 65})


# ---- the bit-identity pins ----------------------------------------------

def _mixed_prompts(cfg, seed=0, n=10):
    rs = np.random.RandomState(seed)
    system = rs.randint(0, cfg.vocab_size, 16).tolist()
    out = []
    for i in range(n):
        if i % 3 == 0:  # shared-prefix arrivals exercise copy_prefix
            out.append(system + rs.randint(
                0, cfg.vocab_size, 2 + i % 4).tolist())
        else:
            out.append(rs.randint(
                0, cfg.vocab_size, rs.randint(3, 14)).tolist())
    return out


def test_spec_bit_identical_mixed_workload(tiny, spec_self, spec_div):
    """THE acceptance pin: the full emitted sequence with a draft —
    agreeing or divergent — equals the plain engine's over a mixed
    prefill/decode workload with prefix-cache hits."""
    cfg, params, _ = tiny
    prompts = _mixed_prompts(cfg)
    ref, rs_ = _run_server(_eng(cfg, params), prompts, 6)
    out_self, s_self = _run_server(spec_self, prompts, 6)
    out_div, s_div = _run_server(spec_div, prompts, 6)
    assert out_self == ref
    assert out_div == ref
    snap = s_self.metrics.snapshot()
    assert snap["spec_accepted"] == snap["spec_proposed"] > 0
    assert snap["tokens_per_target_step"] > 1.5
    assert s_self.metrics.registry.varz()["metrics"][
        "serve_spec_acceptance_rate"] == 1.0
    # Divergent draft: near-zero acceptance, same output.
    dsnap = s_div.metrics.snapshot()
    assert dsnap["spec_accepted"] < dsnap["spec_proposed"]


def test_spec_bit_identical_across_prefix_hits(tiny):
    """Staged arrivals so the second wave HITS the prefix cache (a
    prefilled backer exists): the copy_prefix mirror and the residue
    path must keep spec output identical to plain."""
    cfg, params, _ = tiny
    rs = np.random.RandomState(11)
    system = rs.randint(0, cfg.vocab_size, 16).tolist()
    first = [system + rs.randint(0, cfg.vocab_size, 2).tolist()]
    second = [system + rs.randint(0, cfg.vocab_size, 3 + i).tolist()
              for i in range(3)]

    def staged(engine):
        server = Server(engine, num_blocks=48, block_size=8)
        reqs = [server.submit(p, max_new_tokens=5) for p in first]
        server.run_until_idle()   # retired: residue backs later hits
        reqs += [server.submit(p, max_new_tokens=5) for p in second]
        server.run_until_idle()
        assert server.kv.allocator.num_used == 0
        return [r.result(timeout=0) for r in reqs], server

    ref, _ = staged(_eng(cfg, params))
    spec = SpecDecoder(_eng(cfg, params), _eng(cfg, params), k=3)
    out, server = staged(spec)
    assert out == ref
    assert server.metrics.snapshot()["prefix_hit_requests"] > 0


def test_spec_bit_identical_after_preemption_and_slot_reuse(tiny):
    """Preempt-during-verify coverage: a pool the batch outgrows forces
    evictions in the SAME steps that run propose-verify rounds; the
    recompute (and the reused slots' spec rounds) stay bit-identical."""
    cfg, params, divergent = tiny
    rs = np.random.RandomState(1)
    prompts = [rs.randint(0, cfg.vocab_size, 5).tolist() for _ in range(3)]
    ref, _ = _run_server(_eng(cfg, params), prompts, 6,
                         num_blocks=9, block_size=2)
    spec = SpecDecoder(_eng(cfg, params), _eng(cfg, divergent), k=2,
                       adaptive=False)
    out, server = _run_server(spec, prompts, 6, num_blocks=9, block_size=2)
    assert out == ref
    assert server.metrics.snapshot()["preemptions"] > 0


def test_spec_deadline_expiry_mid_flight(tiny, spec_self):
    cfg, params, _ = tiny
    server = Server(spec_self, num_blocks=48, block_size=8)
    dead = server.submit([1, 2, 3, 4, 5], max_new_tokens=4, deadline_s=-1.0)
    live = server.submit([1, 2, 3, 4, 5], max_new_tokens=4)
    server.run_until_idle()
    assert dead.error is not None
    assert live.error is None
    assert server.kv.allocator.num_used == 0


def test_spec_off_probe_resync_recovers(tiny):
    """Speculation forced OFF goes stale (draft unfed while the target
    advances); the probe round's resync re-mirrors through the draft's
    prefill machinery and a perfect probe re-enables speculation —
    output bit-identical throughout."""
    cfg, params, _ = tiny
    prompts = _mixed_prompts(cfg, seed=3, n=4)
    ref, _ = _run_server(_eng(cfg, params), prompts, 10)
    spec = SpecDecoder(_eng(cfg, params), _eng(cfg, params),
                       controller=SpecKController(k=2, probe_every=3))
    spec.controller.k = 0  # force off, as a zero-acceptance run would
    out, server = _run_server(spec, prompts, 10)
    assert out == ref
    assert spec.controller.k >= 1, "perfect probe should re-enable"
    snap = server.metrics.snapshot()
    assert snap["spec_rounds"] < snap["decode_rounds"]  # off rounds ran
    assert snap["spec_accepted"] > 0  # post-resync proposals landed


def test_spec_cancel_with_proposed_tokens_in_flight(tiny, spec_self):
    """A cancel arriving while a propose-verify round is executing lands
    at the next step boundary: the cancelled handle settles, the
    survivor's output is unaffected, nothing leaks."""
    cfg, params, _ = tiny
    ref, _ = _run_server(_eng(cfg, params), [[7, 11, 2]], 12)
    server = Server(spec_self, num_blocks=48, block_size=8)
    server.start()
    try:
        victim = server.submit([9, 8, 7], max_new_tokens=40)
        keeper = server.submit([7, 11, 2], max_new_tokens=12)
        time.sleep(0.05)  # let rounds (with proposals) get in flight
        server.cancel(victim.req_id)
        out = keeper.result(timeout=120)
    finally:
        server.stop()
    assert out == ref[0]
    assert victim.done.wait(10)
    assert victim.status in ("cancelled", "ok")  # ok iff it outran us
    if victim.status == "cancelled":
        assert isinstance(victim.error, Cancelled)
    assert server.kv.allocator.num_used == 0


def test_spec_abandon_round_on_replica_failure(tiny):
    cfg, params, _ = tiny
    spec = SpecDecoder(_eng(cfg, params), _eng(cfg, params), k=2)
    server = Server(spec, num_blocks=48, block_size=8)
    req = server.submit([1, 2, 3], max_new_tokens=8)
    server.step()  # prefill
    # Simulate dying between run_round and commit_round.
    outs, _ = spec.run_round(server.scheduler.running)
    assert spec._pending is not None
    server.fail()
    assert spec._pending is None  # _fail_all abandoned the round
    assert req.error is not None
    # The pair is reusable by a fresh incarnation: a new server
    # re-prefills and decodes bit-identically.
    ref, _ = _run_server(_eng(cfg, params), [[4, 5, 6]], 5)
    out, _ = _run_server(spec, [[4, 5, 6]], 5)
    assert out == ref


def test_spec_layout_validation(tiny):
    cfg, params, _ = tiny
    with pytest.raises(ValueError, match="slot layout"):
        SpecDecoder(_eng(cfg, params, max_batch=4),
                    _eng(cfg, params, max_batch=2))
    small = ServeEngine.from_llama(cfg, params, max_batch=4, cache_len=32)
    with pytest.raises(ValueError, match="slot layout"):
        SpecDecoder(_eng(cfg, params), small)
    with pytest.raises(ValueError, match="prefill_width"):
        SpecDecoder(_eng(cfg, params),
                    ServeEngine.from_llama(cfg, params, max_batch=4,
                                           cache_len=64, prefill_width=1))


def test_spec_round_protocol_misuse_raises(tiny):
    cfg, params, _ = tiny
    spec = SpecDecoder(_eng(cfg, params), _eng(cfg, params), k=2)
    with pytest.raises(RuntimeError, match="without a pending round"):
        spec.commit_round({})


# ---- no-draft byte-identity ---------------------------------------------

def test_no_draft_engine_path_untouched(tiny):
    """The PR 13 idiom, applied here: without a SpecDecoder the Server
    holds the engine ITSELF (is-level) and the engine never builds the
    spec programs — the plain path is byte-identical to pre-spec."""
    cfg, params, _ = tiny
    eng = _eng(cfg, params)
    server = Server(eng, num_blocks=16, block_size=8)
    assert server.engine is eng
    server.submit([1, 2, 3], max_new_tokens=3)
    server.run_until_idle()
    assert eng._verify_jit is None and eng._rollback_jit is None
    assert set(eng.compile_counts()) == {"prefill", "decode",
                                         "copy_prefix"}
    snap = server.metrics.snapshot()
    assert snap["spec_rounds"] == 0 and snap["spec_proposed"] == 0
    assert snap["tokens_per_target_step"] == 1.0
    # No spec gauges registered for a plain engine.
    assert "serve_spec_acceptance_rate" not in \
        server.metrics.registry.varz()["metrics"]


# ---- observability -------------------------------------------------------

def test_spec_spans_and_breakdown(tiny, tmp_path):
    """spec_propose/spec_verify spans are balanced (real durations) and
    consumed by the request breakdown: per-request decode time splits
    into draft and verify halves."""
    import json

    from tpucfn.obs.aggregate import request_breakdown
    from tpucfn.obs.trace import Tracer

    cfg, params, _ = tiny
    spec = SpecDecoder(_eng(cfg, params), _eng(cfg, params), k=2)
    tracer = Tracer(tmp_path, host_id=0, role="server")
    server = Server(spec, num_blocks=48, block_size=8, tracer=tracer)
    reqs = [server.submit([5, 4, 3, 2, 1], max_new_tokens=8)]
    server.run_until_idle()
    tracer.close()
    assert reqs[0].error is None
    events = []
    for f in tmp_path.glob("trace-*.jsonl"):
        events += [json.loads(ln) for ln in f.read_text().splitlines()]
    spans = {e["name"] for e in events if e.get("kind") == "span"}
    assert {"spec_propose", "spec_verify", "decode_round"} <= spans
    for e in events:
        if e.get("kind") == "span" and e["name"].startswith("spec_"):
            assert e["dur_s"] > 0.0  # balanced, not a zero-width stub
    rows, agg = request_breakdown(events)
    assert rows and rows[0]["spec_propose_s"] > 0.0
    assert rows[0]["spec_verify_s"] > 0.0
    assert "spec_propose_s" in agg and "spec_verify_s" in agg


def test_spec_flight_ring_carries_round_shape(tiny):
    from tpucfn.obs.flight import FlightRecorder

    cfg, params, _ = tiny
    flight = FlightRecorder(host_id=0, role="server")
    spec = SpecDecoder(_eng(cfg, params), _eng(cfg, params), k=2)
    server = Server(spec, num_blocks=48, block_size=8, flight=flight)
    server.submit([3, 2, 1], max_new_tokens=4)
    server.run_until_idle()
    decode_samples = [s for s in flight.snapshot()["samples"]
                      if s.get("kind") == "sched"
                      and s.get("work") == "decode"]
    assert decode_samples
    assert any(s.get("spec") == "spec" and s.get("proposed", 0) > 0
               for s in decode_samples)


def test_spec_mixed_temperature_batch(tiny, spec_self):
    """A sampled request riding a spec batch accepts no proposals
    (budget 1 — greedy verification would change its distribution),
    while its greedy batch-mates stay bit-identical to the plain run."""
    cfg, params, _ = tiny
    greedy = [[5, 9, 2], [7, 1, 3, 8]]
    ref, _ = _run_server(_eng(cfg, params), greedy, 6)

    def submit_mixed(engine):
        server = Server(engine, num_blocks=48, block_size=8)
        reqs = [server.submit(p, max_new_tokens=6) for p in greedy]
        sampled = server.submit([2, 4, 6], max_new_tokens=6,
                                temperature=0.9)
        server.run_until_idle()
        assert server.kv.allocator.num_used == 0
        return [r.result(timeout=0) for r in reqs], sampled

    outs, sampled = submit_mixed(spec_self)
    assert outs == ref
    assert sampled.error is None and len(sampled.tokens) == 6
