"""MoE expert-parallel layer: routing math, capacity, aux losses, Llama
integration with the expert mesh axis."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tpucfn.mesh import MeshSpec, build_mesh
from tpucfn.models.llama import Llama, LlamaConfig, causal_lm_loss, sharding_rules
from tpucfn.models.moe import MoEConfig, MoEMLP, collect_moe_aux
from tpucfn.parallel import shard_batch
from tpucfn.train import Trainer


def _apply(model, x, seed=0):
    variables = model.init(jax.random.key(seed), x)
    out, muts = model.apply(variables, x, mutable=["losses", "metrics"])
    return out, muts


def test_moe_forward_shape():
    model = MoEMLP(ffn_dim=32, moe=MoEConfig(n_experts=4, top_k=2), dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(0), (2, 8, 16))
    out, muts = _apply(model, x)
    assert out.shape == x.shape
    assert "losses" in muts


def test_moe_generous_capacity_drops_nothing():
    model = MoEMLP(ffn_dim=32,
                   moe=MoEConfig(n_experts=4, top_k=2, capacity_factor=4.0),
                   dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(0), (2, 8, 16))
    _, muts = _apply(model, x)
    dropped = float(jax.tree.leaves(muts["metrics"])[0])
    assert dropped == 0.0


def test_moe_tiny_capacity_drops_tokens():
    model = MoEMLP(ffn_dim=32,
                   moe=MoEConfig(n_experts=8, top_k=1, capacity_factor=0.25),
                   dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(0), (2, 16, 16))
    out, muts = _apply(model, x)
    dropped = float(jax.tree.leaves(muts["metrics"])[0])
    assert dropped > 0.0
    assert bool(jnp.isfinite(out).all())


def test_moe_aux_loss_finite_and_positive():
    model = MoEMLP(ffn_dim=32, moe=MoEConfig(n_experts=4, top_k=2), dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(0), (2, 8, 16))
    _, muts = _apply(model, x)
    aux = collect_moe_aux(muts)
    assert float(aux) > 0.0


def test_collect_moe_aux_empty_is_zero():
    assert float(collect_moe_aux({})) == 0.0


@pytest.fixture()
def mesh_ep():
    return build_mesh(MeshSpec(data=2, expert=4))


def _moe_llama_cfg():
    return dataclasses.replace(
        LlamaConfig.tiny(),
        moe=MoEConfig(n_experts=4, top_k=2, capacity_factor=2.0),
    )


def test_moe_llama_trains(mesh_ep):
    cfg = _moe_llama_cfg()
    model = Llama(cfg)
    sample = jnp.zeros((2, 16), jnp.int32)

    def init_fn(rng):
        return model.init(rng, sample)["params"], {}

    def loss_fn(params, mstate, batch, rng):
        logits, muts = model.apply({"params": params}, batch["tokens"],
                                   mutable=["losses", "metrics"])
        loss, acc = causal_lm_loss(logits, batch["tokens"])
        loss = loss + collect_moe_aux(muts)
        return loss, ({"accuracy": acc}, mstate)

    trainer = Trainer(mesh_ep, sharding_rules(cfg, tensor=False), loss_fn,
                      optax.adamw(3e-3), init_fn)
    state = trainer.init(jax.random.key(0))

    # expert dim sharded over the expert axis (scan lead dim first)
    wk = state.params["layers"]["mlp"]["experts/gate_proj/kernel"]
    assert wk.sharding.spec == P(None, "expert", "fsdp")
    assert wk.addressable_shards[0].data.shape[1] == 1  # 4 experts / 4-way axis

    rs = np.random.RandomState(0)
    batch = shard_batch(mesh_ep, {"tokens": rs.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32)})
    first = None
    for _ in range(10):
        state, m = trainer.step(state, batch)
        first = first if first is not None else float(m["loss"])
    assert float(m["loss"]) < first
