#!/usr/bin/env python
"""End-to-end train-to-accuracy run (the framework closing its own loop).

The reference's de-facto integration test was "the stack comes up and
CIFAR-10 *converges*" (SURVEY.md §4). Zero egress means no real CIFAR-10
in this environment, so the documented substitution is the procgen-shapes
dataset (tpucfn/data/shapes.py): 10 shape classes whose ONLY class signal
is geometry — a linear probe on raw pixels sits near chance (measured
below), while ResNet-20 is expected to reach >=90% eval accuracy.

This driver runs the full user path, every hop through the framework's
own surfaces (no bespoke training code):

  1. generate PNG image trees (train/eval) — "the user's dataset on disk"
  2. ``tpucfn convert-dataset --kind image-tree`` -> encoded tpurecord shards
  3. ``tpucfn create-stack`` (fake control plane, cpu-1)
  4. ``tpucfn launch examples/cifar10_resnet20.py`` — multi-epoch train
     with --eval-every, STOPPED early by a step cap (simulated
     interruption), checkpointing throughout
  5. relaunch with the full budget — restart-implies-resume picks up the
     checkpoint and trains to the end (final eval logged)
  6. relaunch once more — resumes at the final step, re-runs eval on the
     restored weights; accuracy must match step 5's final eval
  7. gates: final eval_accuracy >= 0.90 AND |resume re-eval - final| tiny
  8. writes ACCURACY_RUN.md + copies the metrics JSONL into runs/

Run from the repo root: ``python examples/accuracy_run_shapes.py``
(takes ~1-2 h on a 1-core CPU host; all subprocesses run on a scrubbed
8-fake-device CPU backend, so a wedged TPU tunnel cannot affect it).
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

# Import the env scrub by file path (this process must never import jax —
# same rule as __graft_entry__).
_spec = importlib.util.spec_from_file_location(
    "_tpucfn_env", REPO / "tpucfn" / "utils" / "env.py")
_envmod = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_envmod)

N_TRAIN = int(os.environ.get("TPUCFN_ACC_TRAIN", "10000"))
N_EVAL = int(os.environ.get("TPUCFN_ACC_EVAL", "2000"))
EPOCHS = int(os.environ.get("TPUCFN_ACC_EPOCHS", "30"))
BATCH = int(os.environ.get("TPUCFN_ACC_BATCH", "128"))
LR = float(os.environ.get("TPUCFN_ACC_LR", "0.15"))
ACC_GATE = float(os.environ.get("TPUCFN_ACC_GATE", "0.90"))


def _env() -> dict[str, str]:
    env = _envmod.scrub_accelerator_env(os.environ, n_devices=8)
    env["PYTHONPATH"] = str(REPO) + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    return env


def run(argv: list[str], **kw) -> subprocess.CompletedProcess:
    print(f"+ {' '.join(str(a) for a in argv)}", flush=True)
    return subprocess.run([str(a) for a in argv], env=_env(), cwd=REPO,
                          text=True, capture_output=True, **kw)


def must(r: subprocess.CompletedProcess, what: str) -> str:
    if r.returncode != 0:
        sys.stderr.write(r.stdout[-4000:] + "\n" + r.stderr[-4000:])
        raise SystemExit(f"{what} failed rc={r.returncode}")
    return r.stdout


def cli(*argv, state: Path) -> str:
    return must(run([sys.executable, "-m", "tpucfn.cli",
                     "--state-dir", state, *argv]),
                f"tpucfn {argv[0]}")


def read_metrics(run_dir: Path) -> list[dict]:
    rows = []
    for p in sorted((run_dir / "logs").glob("*.jsonl")):
        for line in p.read_text().splitlines():
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    return rows


def eval_rows(rows: list[dict]) -> list[tuple[int, float]]:
    """Eval points in CHRONOLOGICAL (file) order — relaunches append, so
    the last row is always the newest measurement even when a resumed
    leg re-evals at an already-seen step."""
    return [(r["step"], r["eval_accuracy"]) for r in rows
            if "eval_accuracy" in r]


def linear_probe(work: Path) -> float:
    """Ridge-regression probe on raw pixels of the SAME staged shards —
    the documented non-linear-separability evidence."""
    code = f"""
import numpy as np, jax
jax.config.update("jax_platforms", "cpu")
from tpucfn.data import ShardedDataset, decode_transform
import glob
def load(split):
    X, y = [], []
    paths = sorted(glob.glob(r"{work}/shards/" + split + "/*.tpurec"))
    assert paths, "no shards staged for " + split
    ds = ShardedDataset(paths, batch_size_per_process=256, shuffle=False,
                        drop_remainder=False, transform=decode_transform(),
                        process_index=0, process_count=1)
    for b in ds.epoch(0):
        X += [np.asarray(img, np.float32).reshape(-1) for img in b["image"]]
        y += list(b["label"])
    return np.stack(X) / 255.0, np.asarray(y)
Xtr, ytr = load("train"); Xte, yte = load("eval")
Xtr, ytr = Xtr[:6000], ytr[:6000]
W = np.linalg.solve(Xtr.T @ Xtr + 10.0 * np.eye(Xtr.shape[1]), Xtr.T @ np.eye(10)[ytr])
print("PROBE", float((np.argmax(Xte @ W, 1) == yte).mean()))
"""
    out = must(run([sys.executable, "-c", code]), "linear probe")
    for line in out.splitlines():
        if line.startswith("PROBE"):
            return float(line.split()[1])
    raise SystemExit("probe printed no result")


def main() -> int:
    t0 = time.time()
    work = Path(os.environ.get("TPUCFN_ACC_WORK", "/tmp/tpucfn-accuracy"))
    state = work / "state"
    run_dir = work / "run"
    work.mkdir(parents=True, exist_ok=True)

    # 1. the "user's dataset": PNG trees on disk
    if not (work / "tree" / "train").exists():
        must(run([sys.executable, "-c",
                  "from tpucfn.data.shapes import write_shapes_image_tree as w;"
                  f"w(r'{work}/tree/train', {N_TRAIN}, seed=0);"
                  f"w(r'{work}/tree/eval', {N_EVAL}, seed=1)"]),
             "tree generation")

    # 2. convert: image tree -> encoded tpurecord shards
    for split in ("train", "eval"):
        if not (work / "shards" / split).exists():
            cli("convert-dataset", "--kind", "image-tree",
                "--src", work / "tree" / split,
                "--out", work / "shards" / split,
                "--num-shards", "8", state=state)

    probe_acc = linear_probe(work)
    print(f"linear probe on raw pixels: {probe_acc:.3f}", flush=True)

    # 3. stack up (fake control plane — no cloud in this environment)
    cli("create-stack", "--name", "acc", "--accelerator", "cpu-1",
        "--storage", work / "efs", state=state)

    total_steps = (N_TRAIN // BATCH) * EPOCHS
    train_argv = [
        sys.executable, str(REPO / "examples" / "cifar10_resnet20.py"),
        "--data-url", work / "shards" / "train",
        "--eval-url", work / "shards" / "eval",
        "--augment", "--cosine", "--lr", LR, "--batch-size", BATCH,
        "--num-epochs", EPOCHS, "--eval-every", "200",
        "--ckpt-every", "100", "--loader-workers", "2",
        "--log-every", "50", "--run-dir", run_dir,
    ]

    # 4. first leg: interrupted at ~half the budget. --stop-after halts
    # execution WITHOUT redefining the budget, so the cosine schedule is
    # identical across legs (a real preemption does not change the LR
    # plan — using --steps here would anneal to zero by the cap and the
    # resumed leg's restored LR would kick the model out of its minimum;
    # observed exactly that on the first full run: eval 99.6% at the
    # interruption, 81.9% twenty steps after resume).
    half = total_steps // 2
    out1 = cli("launch", "--name", "acc", "--",
               *train_argv, "--stop-after", str(half), state=state)
    print(out1[-600:], flush=True)

    # 5. relaunch, full budget: restart-implies-resume from the checkpoint
    out2 = cli("launch", "--name", "acc", "--", *train_argv, state=state)
    print(out2[-600:], flush=True)
    assert "resumed from step" in out2, "second leg did not resume"
    curve = eval_rows(read_metrics(run_dir))
    if not curve:
        raise SystemExit("no eval_accuracy rows logged")
    final_step, final_acc = curve[-1]

    # 6. third leg: resumes at the final step, re-evals restored weights
    out3 = cli("launch", "--name", "acc", "--", *train_argv, state=state)
    assert "resumed from step" in out3, "third leg did not resume"
    curve3 = eval_rows(read_metrics(run_dir))
    re_step, re_acc = curve3[-1]
    assert re_step == final_step, (re_step, final_step)

    cli("delete", "--name", "acc", state=state)

    # 7. gates
    resume_delta = abs(re_acc - final_acc)
    ok = final_acc >= ACC_GATE and resume_delta < 5e-3
    mins = (time.time() - t0) / 60

    # 8. report + committed metrics artifact
    runs = REPO / "runs"
    runs.mkdir(exist_ok=True)
    merged = runs / "accuracy_shapes_metrics.jsonl"
    with merged.open("w") as f:
        for r in read_metrics(run_dir):
            f.write(json.dumps(r) + "\n")
    md = REPO / "ACCURACY_RUN.md"
    lines = [
        "# End-to-end accuracy run — procgen-shapes, ResNet-20",
        "",
        f"Date: {time.strftime('%Y-%m-%d %H:%M UTC', time.gmtime())} · "
        f"wall clock {mins:.0f} min · host: 1-core CPU, 8 fake JAX devices "
        "(zero-egress environment; see substitution note)",
        "",
        "## Substitution note (read first)",
        "",
        "The reference's integration test trains REAL CIFAR-10 staged from",
        "S3 (SURVEY.md §4). This build environment has **zero egress** — no",
        "public dataset can be downloaded — so the run substitutes the",
        "procedurally generated **procgen-shapes** dataset",
        "(`tpucfn/data/shapes.py`): 10 shape classes, class signal carried",
        "by geometry only (random position/scale/rotation/colors/gradient",
        "background/noise). It is honestly hard in the sense that matters:",
        f"a ridge linear probe on raw pixels scores **{probe_acc:.1%}**",
        "(chance = 10%), so the accuracy below is earned by representation",
        "learning, not template matching.",
        "",
        "## The path exercised (every hop a framework surface)",
        "",
        "PNG image tree → `tpucfn convert-dataset --kind image-tree` →",
        "encoded tpurecord shards → `tpucfn create-stack` (fake control",
        "plane) → `tpucfn launch examples/cifar10_resnet20.py` (streaming",
        "ShardedDataset, host decode + pad-crop/mirror augmentation, 2",
        "decode threads, warmup-cosine SGD, Orbax checkpoints every 100",
        "steps, eval every 200) → **interrupted first leg** (--stop-after",
        "at half budget — halts execution without changing the LR",
        "schedule, like a real preemption) → relaunch auto-resumes from the",
        "checkpoint → trains to the full budget → relaunch again re-evals",
        "the restored weights.",
        "",
        "## Config",
        "",
        f"- train/eval examples: {N_TRAIN}/{N_EVAL} (balanced, 10 classes)",
        f"- ResNet-20 (cifar stem), global batch {BATCH}, {EPOCHS} epochs "
        f"= {total_steps} steps, warmup-cosine peak lr {LR}",
        "",
        "## Results",
        "",
        "| gate | value | pass |",
        "|---|---|---|",
        f"| final eval accuracy (step {final_step}) | **{final_acc:.4f}** "
        f"| {'YES' if final_acc >= ACC_GATE else 'NO'} (gate {ACC_GATE}) |",
        f"| resume re-eval == final (step {re_step}) | Δ={resume_delta:.2e} "
        f"| {'YES' if resume_delta < 5e-3 else 'NO'} |",
        f"| linear probe (hardness) | {probe_acc:.4f} | "
        "near chance as required |",
        "",
        "## Eval curve",
        "",
        "| step | eval accuracy |",
        "|---|---|",
    ]
    lines += [f"| {s} | {a:.4f} |" for s, a in curve]
    lines += [
        "",
        f"Raw metrics: `runs/{merged.name}` (per-step train loss/accuracy, "
        "step_time, time_to_first_step, eval rows).",
        "",
        "Reproduce: `python examples/accuracy_run_shapes.py` from the repo "
        "root (env knobs TPUCFN_ACC_{TRAIN,EVAL,EPOCHS,BATCH,LR,GATE}).",
    ]
    md.write_text("\n".join(lines) + "\n")
    print(f"final eval accuracy {final_acc:.4f} (gate {ACC_GATE}) "
          f"resume delta {resume_delta:.2e} -> {'PASS' if ok else 'FAIL'}",
          flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
