import json

import pytest

from tpucfn.obs import MetricLogger, StepTimer


def test_jsonl_records(tmp_path):
    logger = MetricLogger(tmp_path, stdout_every=0)
    logger.log(1, {"loss": 2.5, "note": "hi"})
    logger.log(2, {"loss": 2.0})
    logger.close()
    lines = [json.loads(line) for line in logger.path.read_text().splitlines()]
    assert lines[0]["loss"] == 2.5
    assert lines[0]["note"] == "hi"
    assert lines[1]["step"] == 2


def test_tensorboard_events_written(tmp_path):
    tf = pytest.importorskip("tensorflow")
    logger = MetricLogger(tmp_path, stdout_every=0, tensorboard=True)
    if logger._tb is None:
        pytest.skip("tf.summary unavailable")
    logger.log(1, {"loss": 1.5})
    logger.close()
    events = list((tmp_path / "tb").glob("events.out.tfevents.*"))
    assert events and events[0].stat().st_size > 0
    del tf


def test_step_timer_warmup_exclusion():
    t = StepTimer(warmup=1)
    import time

    t.tick()
    time.sleep(0.01)
    t.tick()  # warmup tick, excluded
    time.sleep(0.01)
    t.tick()
    assert t.mean_step_time is not None
    assert t.throughput(100) > 0
    assert t.per_chip_throughput(100) is not None


def test_step_timer_no_steady_state_is_none():
    t = StepTimer(warmup=5)
    t.tick()
    t.tick()
    assert t.mean_step_time is None
    assert t.throughput(10) is None


def test_profiler_server_starts_and_listens():
    """--profile-server wiring (SURVEY.md §5 tracing row): the per-host
    profiler server binds its port so XProf/TensorBoard can attach."""
    import socket

    from tpucfn.obs import start_profiler_server

    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
    start_profiler_server(port)
    # idempotent: second call must not try to rebind
    start_profiler_server(port)
    with socket.create_connection(("127.0.0.1", port), timeout=5):
        pass


def test_enable_compile_cache_sets_config(tmp_path):
    import jax

    from tpucfn.obs import enable_compile_cache

    d = enable_compile_cache(str(tmp_path / "cache"))
    assert jax.config.jax_compilation_cache_dir == d


def test_metric_logger_log_works_without_jax(tmp_path):
    """MetricLogger serves the jax-free planes (ISSUE 10): with stdout
    mirroring off, log() must write the JSONL record without ever
    importing jax (pinned with a meta-path hook making jax
    unimportable)."""
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    script = (
        "import sys\n"
        "class B:\n"
        "    def find_spec(self, name, path=None, target=None):\n"
        "        if name == 'jax' or name.startswith('jax.'):\n"
        "            raise ImportError('blocked: ' + name)\n"
        "        return None\n"
        "sys.meta_path.insert(0, B())\n"
        "from tpucfn.obs.metrics import MetricLogger\n"
        "ml = MetricLogger(None, stdout_every=0)\n"
        "ml.log(1, {'loss': 0.5})\n"
        "ml.close()\n"
        "print('OK')\n"
    )
    r = subprocess.run([sys.executable, "-c", script], cwd=repo,
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0 and "OK" in r.stdout, (r.stdout, r.stderr)
