from tpucfn.mesh.mesh import (  # noqa: F401
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_TENSOR,
    AXIS_CONTEXT,
    AXIS_PIPELINE,
    AXIS_EXPERT,
    ALL_AXES,
    BATCH_AXES,
    MeshSpec,
    build_mesh,
    build_multislice_mesh,
    local_mesh_devices,
)
