"""HuggingFace Llama checkpoint import.

The adoption path for users arriving with standard weights: map a HF
``LlamaForCausalLM`` state dict onto the tpucfn param tree (same
rotate-half RoPE convention, so the mapping is transpose/stack only —
no head permutation) and derive :class:`LlamaConfig` from the HF config.
The parity test pins our Llama's logits against the canonical HF torch
implementation on a tiny random model — a cross-implementation
correctness check of attention/RoPE/RMSNorm/SwiGLU, not just plumbing.

Torch is only needed at conversion time (CPU is fine); nothing else in
tpucfn imports it.
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from tpucfn.models.llama import LlamaConfig


def config_from_hf(hf_config: Any, **overrides) -> LlamaConfig:
    """LlamaConfig from a transformers ``LlamaConfig``-like object.

    Raises on HF features tpucfn's Llama does not implement rather than
    converting to silently-wrong numerics."""
    import dataclasses

    scaling = getattr(hf_config, "rope_scaling", None)
    if scaling not in (None, {}):
        raise NotImplementedError(
            f"rope_scaling={scaling!r} is not implemented in tpucfn's RoPE "
            "(plain theta frequencies); converting would produce silently "
            "wrong positions (Llama-3.1+ checkpoints use this)")
    explicit_hd = getattr(hf_config, "head_dim", None)
    derived_hd = hf_config.hidden_size // hf_config.num_attention_heads
    if explicit_hd not in (None, derived_hd):
        raise NotImplementedError(
            f"head_dim={explicit_hd} != hidden_size//num_heads={derived_hd}: "
            "tpucfn's LlamaConfig derives head_dim, so this checkpoint's "
            "projection shapes cannot be represented")
    cfg = LlamaConfig(
        vocab_size=hf_config.vocab_size,
        dim=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(hf_config, "num_key_value_heads",
                           hf_config.num_attention_heads),
        ffn_dim=hf_config.intermediate_size,
        max_seq=hf_config.max_position_embeddings,
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        norm_eps=float(hf_config.rms_norm_eps),
    )
    return dataclasses.replace(cfg, **overrides) if overrides else cfg


def _np(x) -> np.ndarray:
    if hasattr(x, "detach"):
        x = x.detach().cpu().numpy()
    return np.asarray(x, np.float32)


def params_from_hf_state_dict(state_dict: Mapping[str, Any],
                              cfg: LlamaConfig) -> dict:
    """HF ``model.state_dict()`` → the tpucfn Llama param tree
    (scan-stacked when ``cfg.scan_layers``).  Torch Linear stores
    (out, in); flax DenseGeneral kernels are (in, out) — transposed
    here.  Tied embeddings (no ``lm_head.weight``) reuse the embedding
    transposed."""
    if not cfg.scan_layers:
        raise NotImplementedError(
            "HF import targets the scanned layout (cfg.scan_layers=True) — "
            "the unrolled layout is a test-only configuration")
    sd = state_dict
    L = cfg.n_layers
    consumed: set[str] = set()

    def take(name):
        consumed.add(name)
        return _np(sd[name])

    def lstack(fmt, transpose=True):
        mats = [take(fmt.format(i=i)) for i in range(L)]
        if transpose:
            mats = [m.T for m in mats]
        return np.stack(mats)

    embed = take("model.embed_tokens.weight")
    if "lm_head.weight" in sd:
        lm_head = take("lm_head.weight").T
    else:
        lm_head = embed.T.copy()

    layers = {
        "attn": {p: {"kernel": lstack(
            "model.layers.{i}.self_attn.%s.weight" % p)}
            for p in ("q_proj", "k_proj", "v_proj", "o_proj")},
        "mlp": {p: {"kernel": lstack("model.layers.{i}.mlp.%s.weight" % p)}
                for p in ("gate_proj", "up_proj", "down_proj")},
        "input_norm": {"scale": lstack(
            "model.layers.{i}.input_layernorm.weight", transpose=False)},
        "post_attn_norm": {"scale": lstack(
            "model.layers.{i}.post_attention_layernorm.weight",
            transpose=False)},
    }
    params = {
        "embed_tokens": {"embedding": embed},
        "layers": layers,
        "final_norm": {"scale": take("model.norm.weight")},
        "lm_head": {"kernel": lm_head},
    }
    # A dropped tensor is silently-wrong logits (e.g. attention biases
    # from attention_bias=True checkpoints) — refuse instead.
    ignorable = {k for k in sd
                 if k.endswith("rotary_emb.inv_freq")}  # legacy buffer
    leftover = sorted(set(sd) - consumed - ignorable)
    if leftover:
        raise NotImplementedError(
            f"unmapped tensors in the HF state dict (first 5: "
            f"{leftover[:5]}) — this checkpoint uses features tpucfn's "
            "Llama does not implement (e.g. attention biases)")
    return params


def from_hf_llama(hf_model: Any, **config_overrides
                  ) -> tuple[LlamaConfig, dict]:
    """(cfg, params) from a live ``transformers.LlamaForCausalLM``."""
    cfg = config_from_hf(hf_model.config, **config_overrides)
    return cfg, params_from_hf_state_dict(hf_model.state_dict(), cfg)
