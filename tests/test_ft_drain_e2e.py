"""End-to-end preemption-drain drill (ISSUE 7 acceptance): a chaos
``preempt_notice`` mid-run makes the coordinator drain the gang — every
host runs to one converged step boundary, force-saves, exits clean —
and relaunch it as a PLANNED restart: ``lost_work == 0`` in the goodput
report, ``planned=true`` on the incident row, and zero restart budget
consumed.

Own slow-marked file on purpose: stacked multi-second drills flake on
this container (see runs/tier1_durations.txt discipline).
"""

import json
import os
import sys
from pathlib import Path

import pytest

from tpucfn.bootstrap import EnvContract
from tpucfn.ft import (
    ChaosEvent,
    ChaosSpec,
    GangCoordinator,
    GangRestart,
    HeartbeatMonitor,
    MonitorConfig,
    RestartBudget,
)
from tpucfn.launch import Launcher, LocalTransport
from tpucfn.obs import MetricRegistry
from tpucfn.obs.goodput import goodput_report

pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parent.parent
WORKER = str(REPO / "tests" / "ft_e2e_worker.py")

TOTAL_STEPS = 40
CKPT_EVERY = 10
NOTICE_AT_STEP = 18


def _contract(tmp_path, n) -> EnvContract:
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("".join("127.0.0.1:0\n" for _ in range(n)))
    return EnvContract(
        workers_path=str(hostfile), workers_count=n, worker_chip_count=1,
        coordinator="127.0.0.1:1234", host_id=0, storage=str(tmp_path),
        generation=1)


def _losses(run_dir, host=0) -> list[dict]:
    p = run_dir / f"losses-host{host:03d}.jsonl"
    return [json.loads(s) for s in p.read_text().splitlines() if s.strip()]


def test_preempt_notice_drains_with_zero_lost_work(tmp_path):
    run_dir = tmp_path / "run"
    ft_dir = run_dir / "ft"
    run_dir.mkdir()
    os.environ.update({
        "FT_E2E_RUN_DIR": str(run_dir),
        "FT_E2E_TOTAL_STEPS": str(TOTAL_STEPS),
        "FT_E2E_CKPT_EVERY": str(CKPT_EVERY),
        "FT_E2E_STEP_SLEEP": "0.05",
        "PYTHONPATH": str(REPO) + os.pathsep + os.environ.get(
            "PYTHONPATH", ""),
    })
    launcher = Launcher(_contract(tmp_path, 2), LocalTransport(),
                        ft_dir=str(ft_dir), ft_heartbeat_s=0.2)
    registry = MetricRegistry()
    monitor = HeartbeatMonitor(
        ft_dir, expected_hosts=2,
        config=MonitorConfig(interval_s=0.2, startup_grace_s=120.0))
    chaos = ChaosSpec(events=(
        ChaosEvent(action="preempt_notice", at_step=NOTICE_AT_STEP,
                   host=0, duration_s=60.0),))
    coord = GangCoordinator(
        launcher, [sys.executable, WORKER],
        # ZERO budget: a drained preemption must not need a restart slot
        policy=GangRestart(RestartBudget(0)), monitor=monitor,
        registry=registry, ft_dir=ft_dir, ckpt_dir=run_dir / "ckpt",
        poll_interval=0.02, term_grace_s=1.0, chaos=chaos,
        # generous margin: the fleet step is observe-throttled, so the
        # target must sit past any host's true position at drain time
        drain_step_margin=4)
    rc = coord.run()
    assert rc == 0, "planned drain + relaunch must finish clean"
    assert coord.chaos.done()

    m = registry.varz()["metrics"]
    assert m["ft_preempt_drains_total"] == 1
    assert m["ft_planned_restarts_total"] == 1
    assert m["ft_restarts_total"] == 0, "no budget slot consumed"
    assert m["ft_planned_mttr_seconds"]["count"] == 1

    events = [json.loads(s) for s in
              (ft_dir / "events.jsonl").read_text().splitlines()]
    kinds = [e["kind"] for e in events]
    assert "drain" in kinds and "done" in kinds
    detect = next(e for e in events if e["kind"] == "detect")
    assert detect["failures"][0]["kind"] == "preempt"
    assert detect["failures"][0]["lead_s"] == 60.0
    drain = next(e for e in events if e["kind"] == "drain")
    target = drain["step"]
    assert target is not None and target >= NOTICE_AT_STEP
    recovered = next(e for e in events if e["kind"] == "recovered")
    assert recovered["planned"] is True
    assert recovered["escalated"] == 0, "every rank drained cleanly"
    assert recovered["dirty_exits"] == []

    # -- both hosts stopped AT the target and resumed right after it ---
    for host in (0, 1):
        rows = _losses(run_dir, host)
        pids = list(dict.fromkeys(r["pid"] for r in rows))
        assert len(pids) == 2, "one planned restart of each host"
        first = [r for r in rows if r["pid"] == pids[0]]
        resumed = [r for r in rows if r["pid"] == pids[1]]
        assert first[-1]["step"] == target, "drained exactly at the target"
        assert resumed[0]["step"] == target + 1, "zero re-executed steps"
        assert resumed[-1]["step"] == TOTAL_STEPS
        # no step was paid for twice
        steps = [r["step"] for r in rows]
        assert len(steps) == len(set(steps))

    # -- the goodput plane agrees: planned incident, zero lost work ----
    report = goodput_report(run_dir / "goodput", ft_dir / "events.jsonl")
    assert report["lost_work_s"] == 0.0
    assert report["lost_steps"] == 0
    [inc] = report["incidents"]
    assert inc["planned"] is True
    assert inc["action"] == "drain_restart"
    assert report["unplanned_downtime_s"] == 0.0
    assert report["incident_downtime_s"] > 0  # the drain took real time
    # budget untouched, visible to `tpucfn ft status`
    snap = json.loads((ft_dir / "supervisor.json").read_text())
    assert snap["budget"] == {"max_restarts": 0, "used": 0}
