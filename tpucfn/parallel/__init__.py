from tpucfn.parallel.sharding import (  # noqa: F401
    Rule,
    ShardingRules,
    batch_spec,
    make_partition_spec,
    named_sharding_tree,
    partition_spec_tree,
    shard_batch,
    shard_batch_device_layout,
)
from tpucfn.parallel.presets import (  # noqa: F401
    PRESETS,
    dense_rules,
    transformer_rules,
    zero1_rules,
)
from tpucfn.parallel.pipeline import (  # noqa: F401
    bubble_fraction,
    gpipe,
    microbatch,
    pipeline_1f1b,
    unmicrobatch,
)
