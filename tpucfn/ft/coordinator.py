"""Gang supervision: detect → decide → act → recovered.

The :class:`GangCoordinator` owns the launcher's process table and runs
the whole fault-tolerance loop in one place (ISSUE 4 tentpole):

* **detect** — polls every rank's exit code each ``poll_interval`` and,
  when a :class:`~tpucfn.ft.heartbeat.HeartbeatMonitor` is attached,
  consumes its verdicts (a DEAD heartbeat on a live process is a HANG;
  process exit codes are CRASH / CLEAN_EXIT).
* **decide** — hands the failure set to the
  :class:`~tpucfn.ft.policy.RecoveryPolicy` (gang vs solo restart,
  budget + backoff, per-failure-class table).
* **act** — SIGTERM→SIGKILL escalation through
  :meth:`~tpucfn.launch.launcher.Launcher.stop_all`, then relaunch:
  the whole gang (resume happens in the job via its CheckpointManager —
  ``Trainer.init_or_resume``) or just the dead host with its original
  ``host_env`` (same host_id, obs port, heartbeat file).
* **record** — every incident becomes ``ft_*`` registry metrics (MTTR
  included), one line each in ``<ft_dir>/events.jsonl``, a trace span,
  and a refreshed ``<ft_dir>/supervisor.json`` snapshot that ``tpucfn
  ft status`` renders.

``launch.run_with_restarts`` is a thin shim over this class (gang
policy, no monitor), preserving its signature and its ``supervisor_*``
metric names.

Graceful degradation (ISSUE 7) — four paths beyond restart-at-same-size:

* **preemption drain** — an advance notice (chaos op, or an external
  daemon writing ``<ft_dir>/preempt.json``) raises ``FailureKind.
  PREEMPT``; the decision table maps it to a *planned* drain: every
  rank runs to one converged step boundary (``<ft_dir>/drain.json``),
  force-saves through its own ckpt layer, exits clean, and the gang is
  relaunched with zero lost work and zero budget consumed.
* **elastic shrink** — a failed host that cannot be re-acquired (chaos
  ``lose_host``, or ``reacquire_check`` says the control plane lost it)
  shrinks the gang: the ``EnvContract`` re-converges at N-1 with a new
  generation and the smaller gang resumes cross-topology from the
  latest checkpoint.
* **checkpoint-corruption retry** — a rank exiting with
  ``RESTORE_FAILED_RC`` means the latest checkpoint would not restore;
  instead of crash-looping the same artifact into give_up, the
  coordinator quarantines the bad step, blacklists it for the ranks
  (``TPUCFN_CKPT_BLACKLIST`` fan-out), and relaunches to resume from
  the previous finalized step — without touching the restart budget.
* **straggler eviction** — STRAGGLER verdicts pass through a
  :class:`~tpucfn.ft.policy.StragglerGuard` (hysteresis window +
  per-host flap budget, re-armed on return to LIVE) before the
  STRAGGLER→SOLO_RESTART row — on by default since ISSUE 7 — may evict.

Crash-safety (ISSUE 12): every state transition is appended to a
checksummed, fsync'd write-ahead journal under ``<ft_dir>/journal/``
*before* its action runs (:mod:`tpucfn.ft.journal`), restart decisions
carry an intent/commit pair, and a restarted coordinator (``adopt`` —
the default whenever an unfinished journal exists) replays the
journal, re-attaches to the running fleet (journal pids + heartbeat
liveness), finishes any mid-flight incident exactly once, and
continues the *same* restart budget.  ``tpucfn launch --supervise``
wraps the whole loop in a jax-free re-exec supervisor
(:mod:`tpucfn.launch.supervise`); the ``kill_coordinator`` chaos op is
the drill that proves the watchman itself is expendable.

The coordinator is also a :class:`~tpucfn.ft.chaos.ChaosTarget`: a
:class:`~tpucfn.ft.chaos.ChaosSpec` passed in is replayed against the
real subprocess table (SIGKILL / SIGSTOP / heartbeat delay / preemption
notice / host loss / checkpoint corruption) on the same supervision
clock, which is what makes the end-to-end recovery drills deterministic.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from pathlib import Path
from typing import Callable, Sequence

from tpucfn.bootstrap import shrink_contract
from tpucfn.ft.chaos import ChaosEngine, ChaosSpec, ChaosTarget, \
    corrupt_latest_checkpoint
from tpucfn.ft.heartbeat import (
    HeartbeatMonitor,
    HostState,
    read_heartbeats,
)
from tpucfn.ft.journal import (
    AdoptedProcess,
    JournalWriter,
    PendingIntent,
    clear_rc_dir,
    compact_journal,
    crash_point,
    journal_path,
    pid_alive,
    pid_start_time,
    read_rc,
    repair_torn_tail,
    replay_journal,
    rotate_journal,
)
from tpucfn.ft.policy import (
    CKPT_BLACKLIST_ENV,
    RESTORE_FAILED_RC,
    Action,
    Decision,
    Failure,
    FailureKind,
    GangRestart,
    RecoveryPolicy,
    RestartBudget,
    StragglerGuard,
    format_ckpt_blacklist,
)
from tpucfn.ft.preempt import (
    PreemptNotice,
    clear_drain,
    consume_notice,
    request_drain,
)


# How long an adopting coordinator waits for the supervise reaper to
# land a dead rank's rc file before treating the death as unexplained
# (matches AdoptedProcess.poll's default rc_grace_s).
ADOPT_RC_GRACE_S = 2.0

# Spawn-window hazard (ISSUE 13 satellite, closing the PR 12 gap): a
# coordinator killed between the pre-spawn ``launching`` journal record
# and the pid-bearing launch record leaves ranks that may be alive with
# NO journal trace.  Adoption waits this long for such a rank's first
# heartbeat to name a pid before declaring it dead and relaunching over
# it — milliseconds-wide on LocalTransport, seconds on SSH fan-outs.
ADOPT_SPAWN_GRACE_S = 10.0


class GangCoordinator(ChaosTarget):
    def __init__(
        self,
        launcher,
        argv: Sequence[str],
        *,
        policy: RecoveryPolicy | None = None,
        monitor: HeartbeatMonitor | None = None,
        ft_dir: str | Path | None = None,
        registry=None,
        tracer=None,
        poll_interval: float = 0.05,
        term_grace_s: float = 5.0,
        chaos: ChaosSpec | ChaosEngine | None = None,
        kill_host_after: tuple[int, float] | None = None,
        ckpt_dir: str | Path | None = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        capture_flight: bool = True,
        flight_timeout_s: float = 2.0,
        capture_spans: bool = True,
        span_tail_lines: int = 500,
        profile_on_incident_s: float = 0.0,
        clock_probe_interval_s: float = 30.0,
        drain_grace_s: float = 30.0,
        drain_step_margin: int = 2,
        allow_shrink: bool = True,
        reacquire_check: Callable[[str], bool] | None = None,
        max_ckpt_retries: int = 3,
        straggler_guard: StragglerGuard | None = None,
        restart_input_hosts: bool = False,
        max_input_restarts: int = 1,
        adopt: bool | str = "auto",
        adopt_spawn_grace_s: float = ADOPT_SPAWN_GRACE_S,
        net_proxies: Sequence | None = None,
        journal_compact_records: int = 4096,
        provision_policy=None,
        goodput_dir: str | Path | None = None,
        provision_interval_s: float = 5.0,
    ):
        """Graceful-degradation knobs (ISSUE 7): ``drain_grace_s`` caps
        how long a preemption drain waits for clean exits when the
        notice carried no lead time (a notice's ``lead_s`` wins when
        shorter — the drain must beat the preemption); the drain target
        step is fleet max + ``drain_step_margin`` so every rank can
        still converge on it.  ``reacquire_check(address) -> bool`` asks
        the control plane whether a failed host is coming back; False
        (or a chaos ``lose_host``) routes the restart through an
        elastic N-1 shrink when ``allow_shrink``.  ``max_ckpt_retries``
        bounds the corruption retry-from-previous loop (each retry
        blacklists one more step; past the cap the normal policy
        decides).  ``straggler_guard`` defaults to a 30s-hysteresis,
        3-flap guard on this coordinator's clock."""
        self.launcher = launcher
        self.argv = list(argv)
        self.policy = policy if policy is not None else GangRestart(
            RestartBudget(0))
        self.monitor = monitor
        self.ft_dir = Path(ft_dir) if ft_dir is not None else None
        self.tracer = tracer
        self.poll_interval = poll_interval
        self.term_grace_s = term_grace_s
        self.kill_host_after = kill_host_after
        self.ckpt_dir = Path(ckpt_dir) if ckpt_dir is not None else None
        self.clock = clock
        self.sleep = sleep
        self.capture_flight = capture_flight
        self.flight_timeout_s = flight_timeout_s
        # Fleet timeline plane (ISSUE 20): at detect time the survivors'
        # span tails (and optionally a short jax profile) join the
        # flight rings in the forensics pull; on the heartbeat cadence
        # the coordinator probes every live obs /clock endpoint so the
        # merged timeline aligns hosts on MEASURED offsets instead of
        # the step-anchored estimate.  profile_on_incident_s is 0 (off)
        # by default — a profile capture blocks the incident path for
        # its whole duration, which is an operator's call, not ours.
        self.capture_spans = capture_spans
        self.span_tail_lines = span_tail_lines
        self.profile_on_incident_s = profile_on_incident_s
        self.clock_probe_interval_s = float(clock_probe_interval_s)
        self._next_clock_probe = 0.0
        self._clock_probe_thread: threading.Thread | None = None
        self.drain_grace_s = drain_grace_s
        self.drain_step_margin = drain_step_margin
        self.allow_shrink = allow_shrink
        self.reacquire_check = reacquire_check
        self.max_ckpt_retries = max_ckpt_retries
        self.straggler_guard = (straggler_guard if straggler_guard is not None
                                else StragglerGuard(clock=clock))
        # Disaggregated input plane (ISSUE 11): failures of input-role
        # hosts NEVER restart the gang or burn budget — trainers degrade
        # to local loading on their own (the service client's resilient
        # stream), the coordinator just records it and, optionally,
        # solo-relaunches the input host (bounded per host so a
        # crash-looping service cannot relaunch forever).
        self.input_host_ids = frozenset(
            getattr(launcher, "input_host_ids", ()) or ())
        self.restart_input_hosts = restart_input_hosts
        self.max_input_restarts = max_input_restarts
        self._input_restarts: dict[int, int] = {}
        # Provisioner policy loop (ISSUE 18): a ProvisionPolicy
        # (tpucfn.provision.policy) observing the fleet's goodput
        # ledgers and actuating topology through existing primitives —
        # grow = activate the launcher's deferred input plane via a
        # planned drain-relaunch, shrink = stop input hosts (trainers
        # degrade to local at the exact batch cursor), chronic
        # starvation = flag only.  Validation at construction, same as
        # the chaos/net checks below: a policy with no ledger to read
        # would silently never decide anything.
        self.provision_policy = provision_policy
        self.goodput_dir = Path(goodput_dir) if goodput_dir is not None \
            else None
        self.provision_interval_s = float(provision_interval_s)
        self._next_provision = 0.0
        self._provision_since_t: float | None = None
        self._provision_flagged = False
        if provision_policy is not None and self.goodput_dir is None:
            raise ValueError(
                "provision_policy needs goodput_dir — the policy reads "
                "the fleet goodput ledgers (GoodputLedger files) to "
                "classify the run; without them it can never decide")
        # Crash-safety (ISSUE 12): a write-ahead journal under
        # <ft_dir>/journal/ records every state transition BEFORE the
        # action runs; a restarted coordinator replays it and ADOPTS
        # the running fleet instead of spawning a second one.  `adopt`
        # is "auto" (adopt iff an unfinished journal exists), True
        # (require it when a journal exists), or False (always fresh).
        self.adopt = adopt
        self.adopt_spawn_grace_s = adopt_spawn_grace_s
        # Network fault-injection plane (ISSUE 15): ChaosProxy instances
        # (tpucfn.net.proxy) fronting fleet-plane ports, the targets of
        # the net_* chaos ACTIONS — so launch-level chaos specs schedule
        # gray network failures exactly like kills.
        self.net_proxies = list(net_proxies or ())
        # Journal compaction threshold (ISSUE 15 satellite): at
        # adoption, a journal longer than this folds into one snapshot
        # record so replay stays O(recent) on week-long runs.
        self.journal_compact_records = journal_compact_records
        self._journal: JournalWriter | None = None
        self._adopted = False
        self._adopt_failures: list[Failure] = []
        self._journal_replay_ms: float | None = None

        if registry is None:
            # Throwaway registry: identical flow, nothing exported —
            # keeps the loop free of per-metric None guards.
            from tpucfn.obs.registry import MetricRegistry

            registry = MetricRegistry()
        self.registry = registry
        r = registry
        # supervisor_* names predate the ft plane (obs PR) and stay for
        # dashboard compatibility; ft_* is the recovery-plane surface.
        self.attempts_c = r.counter(
            "supervisor_launch_attempts_total",
            "gang launches (incl. the first)")
        self.restarts_c = r.counter(
            "supervisor_restarts_total", "relaunches after a failure")
        self.failures_c = r.counter(
            "supervisor_failures_total",
            "gang-level failures observed (clean exits excluded)")
        self.hosts_g = r.gauge(
            "supervisor_gang_hosts", "hosts in the launched gang")
        self.rc_g = r.gauge(
            "supervisor_last_exit_code", "exit code of the last finished gang")
        self.ft_failures_c = r.counter(
            "ft_failures_detected_total",
            "host failures detected (crash + hang)")
        self.ft_restarts_c = r.counter(
            "ft_restarts_total", "recovery restarts executed (gang + solo)")
        self.ft_gang_restarts_c = r.counter(
            "ft_gang_restarts_total", "whole-gang restarts")
        self.ft_solo_restarts_c = r.counter(
            "ft_solo_restarts_total", "single-host restarts into a live gang")
        self.ft_incidents_c = r.counter(
            "ft_incidents_total", "detect→decide→act cycles")
        self.ft_give_ups_c = r.counter(
            "ft_give_ups_total", "incidents abandoned (budget exhausted)")
        self.ft_mttr_s = r.summary(
            "ft_mttr_seconds", "detect → relaunch-complete recovery time")
        self.ft_hosts_live_g = r.gauge(
            "ft_hosts_live", "hosts LIVE per the heartbeat monitor")
        self.ft_stragglers_g = r.gauge(
            "ft_stragglers", "hosts flagged STRAGGLER by step lag")
        # Graceful-degradation surface (ISSUE 7)
        self.ft_preempt_drains_c = r.counter(
            "ft_preempt_drains_total",
            "preemption notices drained into planned restarts")
        self.ft_planned_restarts_c = r.counter(
            "ft_planned_restarts_total",
            "planned relaunches (drains) — budget untouched")
        self.ft_planned_mttr_s = r.summary(
            "ft_planned_mttr_seconds",
            "notice → drained-and-relaunched time for planned restarts")
        self.ft_shrinks_c = r.counter(
            "ft_shrinks_total",
            "elastic shrinks (gang re-converged at fewer hosts)")
        self.ft_ckpt_retries_c = r.counter(
            "ft_ckpt_retries_total",
            "checkpoint-corruption retries from a previous step")
        self.ft_evictions_c = r.counter(
            "ft_straggler_evictions_total",
            "stragglers evicted past hysteresis/flap budget")
        # Input-plane surface (ISSUE 11)
        self.ft_input_degraded_c = r.counter(
            "ft_input_degradations_total",
            "input hosts lost; trainers degraded to local loading")
        self.ft_input_restarts_c = r.counter(
            "ft_input_restarts_total",
            "input hosts solo-relaunched (budget untouched)")
        # Crash-safety surface (ISSUE 12)
        self.coord_adoptions_c = r.counter(
            "coordinator_adoptions_total",
            "restarted coordinators that adopted a running fleet")
        self.coord_journal_c = r.counter(
            "coordinator_journal_records_total",
            "write-ahead journal records appended")
        self.coord_pending_g = r.gauge(
            "coordinator_pending_intent",
            "1 while a journaled restart intent awaits its commit")
        # Provisioner policy surface (ISSUE 18)
        self.provision_decisions_c = r.counter(
            "provision_decisions_total",
            "provisioner decisions acted on (grow/shrink/flag)")
        self.provision_grow_c = r.counter(
            "provision_grow_total",
            "input-plane grow actuations (deferred hosts activated)")
        self.provision_shrink_c = r.counter(
            "provision_shrink_total",
            "input-plane shrink actuations (input hosts released)")
        self.provision_flagged_g = r.gauge(
            "provision_flagged",
            "1 while the fleet is flagged chronically starved")
        self.provision_data_wait_share_g = r.gauge(
            "provision_data_wait_share",
            "fleet data_wait share in the last policy window")
        self.provision_goodput_ratio_g = r.gauge(
            "provision_goodput_ratio",
            "fleet step share (goodput) in the last policy window")
        self.provision_actuation_s = r.summary(
            "provision_actuation_seconds",
            "decision → actuated latency of provisioner actuations")
        self.provision_input_hosts_g = r.gauge(
            "provision_input_hosts",
            "input hosts currently active (reserved-but-deferred excluded)")

        hosts = self.launcher.contract.hosts()[
            : self.launcher.contract.workers_count]
        self.host_ids = list(range(len(hosts)))
        self._procs: dict[int, object] = {}  # host_id → live Popen
        self._finished: dict[int, int] = {}  # host_id → clean rc (0)
        self._incident = 0
        # Per-host post-(re)launch window during which monitor verdicts
        # for that host are ignored — a fleet-wide window would let one
        # solo restart blind hang detection for every other host.
        self._blind_until: dict[int, float] = {}
        self._next_observe = 0.0  # monitor read throttle (see _detect)
        self._last_fleet_step: int | None = None
        # HANG/DEAD verdicts the policy already declined to act on
        # (observe-only tables): suppressed until the host beats again,
        # or the detect loop would re-open the same incident every tick.
        self._suppressed_hangs: set[int] = set()
        # Graceful-degradation state (ISSUE 7)
        self._pending_notices: list[PreemptNotice] = []
        self._lost_hosts: set[int] = set()   # chaos lose_host / reacquire
        self._ckpt_blacklist: set[int] = set()
        self._ckpt_retries = 0
        if isinstance(chaos, ChaosSpec):
            chaos = ChaosEngine(chaos, self)
        self.chaos = chaos
        if self.chaos is not None and self.chaos.on_fire is None:
            # Write-ahead: every firing is journaled BEFORE the action
            # runs (a kill_coordinator must be journaled before it kills
            # the journaler), so an adopting restart replays the spec
            # minus what already fired.
            self.chaos.on_fire = self._on_chaos_fire
        if (self.chaos is not None and self.monitor is None
                and any(e.at_step is not None and e.at_s is None
                        for e in self.chaos.spec.events)):
            # Fleet step comes from heartbeat observations; without a
            # monitor an at_step-only event would silently never fire
            # and the drill would pass vacuously.
            raise ValueError(
                "chaos events with only an at_step trigger need a "
                "HeartbeatMonitor attached (fleet step comes from "
                "heartbeats)")
        if (self.chaos is not None and not self.net_proxies
                and any(e.action.startswith("net_")
                        for e in self.chaos.spec.events)):
            # Same discipline as the monitor check above: a net_* event
            # with nowhere to land must refuse at CONSTRUCTION — firing
            # raises mid-supervision, which tears down the gang (and the
            # journaled chaos_fired would make an adopted run silently
            # skip the event forever).
            raise ValueError(
                "chaos net_* events need net_proxies registered on the "
                "coordinator (tpucfn launch --chaos-proxy LISTEN:HOST:"
                "PORT, or pass ChaosProxy instances)")
        if self.ft_dir is not None:
            self.ft_dir.mkdir(parents=True, exist_ok=True)

    # -- ChaosTarget ------------------------------------------------------

    def num_hosts(self) -> int:
        return len(self.host_ids)

    def kill_host(self, host_id: int) -> None:
        p = self._procs.get(host_id)
        if p is not None and p.poll() is None:
            p.kill()

    def hang_host(self, host_id: int) -> None:
        p = self._procs.get(host_id)
        if p is not None and p.poll() is None:
            os.kill(p.pid, signal.SIGSTOP)

    def resume_host(self, host_id: int) -> None:
        p = self._procs.get(host_id)
        if p is not None and p.poll() is None:
            os.kill(p.pid, signal.SIGCONT)

    def delay_heartbeats(self, host_id: int, duration_s: float) -> None:
        if self.monitor is None:
            raise ValueError(
                "chaos delay_heartbeats needs a HeartbeatMonitor attached")
        self.monitor.inject_heartbeat_delay(
            host_id, extra_age_s=duration_s, duration_s=duration_s)

    def preempt_notice(self, host_id: int, lead_s: float) -> None:
        self._pending_notices.append(
            PreemptNotice(host=host_id,
                          lead_s=lead_s if lead_s > 0 else None))
        self._event("chaos_preempt_notice", host=host_id, lead_s=lead_s)

    def lose_host(self, host_id: int) -> None:
        self._lost_hosts.add(host_id)
        self.kill_host(host_id)
        self._event("host_lost", host=host_id)

    def corrupt_latest_checkpoint(self, rng, step=None) -> None:
        if self.ckpt_dir is None:
            raise ValueError(
                "chaos corrupt_ckpt fired but GangCoordinator has no "
                "ckpt_dir configured")
        victim = corrupt_latest_checkpoint(self.ckpt_dir, rng, step=step)
        self._event("chaos_ckpt_corrupted",
                    path=None if victim is None else str(victim))

    def net_fault(self, proxy: int | None, kind: str, *,
                  duration_s: float, delay_s: float, rate_bps: float,
                  direction: str, after_bytes: int | None) -> None:
        """Chaos op (ISSUE 15): inject a network gray failure through
        the registered :class:`~tpucfn.net.proxy.ChaosProxy` instances
        — unpinned hits every proxy, a pinned ``host`` is a proxy
        index.  The firing is journaled by ``_on_chaos_fire`` like any
        other chaos op, so adopted runs never re-fire it."""
        if not self.net_proxies:
            raise ValueError(
                "chaos net_* ops need net_proxies registered on the "
                "coordinator (tpucfn launch --chaos-proxy, or pass "
                "ChaosProxy instances)")
        if proxy is not None and not 0 <= proxy < len(self.net_proxies):
            raise ValueError(
                f"net fault proxy index {proxy} out of range for "
                f"{len(self.net_proxies)} registered proxies")
        targets = ([self.net_proxies[proxy]] if proxy is not None
                   else self.net_proxies)
        for p in targets:
            if kind == "clear":
                p.clear()
            else:
                p.inject(kind, duration_s=duration_s, delay_s=delay_s,
                         rate_bps=rate_bps, direction=direction,
                         after_bytes=after_bytes)
        self._event("chaos_net_fault", fault=kind, proxy=proxy,
                    duration_s=duration_s, delay_s=delay_s,
                    rate_bps=rate_bps, direction=direction,
                    after_bytes=after_bytes)

    def kill_coordinator(self) -> None:
        """Chaos op (ISSUE 12): SIGKILL ourselves mid-supervision.  The
        event row is best-effort bookkeeping; the journal's chaos_fired
        record (written by _on_chaos_fire BEFORE dispatch) is what keeps
        a supervised relaunch from re-firing the same kill forever."""
        self._event("coordinator_killed", pid=os.getpid())
        os.kill(os.getpid(), signal.SIGKILL)

    def _on_chaos_fire(self, index: int, ev, host) -> None:
        self._j("chaos_fired", index=index, action=ev.action, host=host)

    # -- flight capture (ISSUE 6) -----------------------------------------

    def _capture_flight(self, incident: int, failed: set[int]) -> None:
        """Pull every surviving host's flight-recorder ring over its obs
        endpoint BEFORE the gang is stopped — the dead host's last
        seconds are in its own signal/atexit dump, but the survivors'
        rings live only in memory and the restart is about to erase
        them.  Best-effort and CONCURRENT with one shared deadline:
        MTTR includes this call by design (forensics are part of
        incident handling), so its cost must be ~``flight_timeout_s``
        total, not per survivor — a 32-host gang with several
        unreachable endpoints must not serialize 2s timeouts while the
        doomed gang keeps executing steps that will be rewound."""
        base = getattr(self.launcher, "obs_base_port", None)
        if not base or self.ft_dir is None or not self.capture_flight:
            return
        import concurrent.futures
        import urllib.request

        from tpucfn.obs.flight import incident_flight_path, write_flight_dump

        hosts = self.launcher.contract.hosts()[
            : self.launcher.contract.workers_count]
        targets = [(h, hosts[h].rsplit(":", 1)[0])
                   for h, p in sorted(self._procs.items())
                   if h not in failed and p.poll() is None]
        if not targets:
            return

        def fetch(host_id: int, addr: str):
            url = f"http://{addr}:{base + 1 + host_id}/flightrecorder"
            with urllib.request.urlopen(
                    url, timeout=self.flight_timeout_s) as r:
                return json.loads(r.read().decode())

        out_dir = self.ft_dir / "flight"
        captured, errors = [], 0
        # One worker PER survivor, not a smaller pool: with a capped
        # pool, >=cap hung endpoints (plausibly the incident itself)
        # would hold every worker for the whole deadline and the
        # healthy hosts' queued fetches would never start — losing the
        # captures for exactly the hosts that could answer.
        pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=len(targets),
            thread_name_prefix="flight-capture")
        try:
            futs = {pool.submit(fetch, h, addr): h for h, addr in targets}
            done, pending = concurrent.futures.wait(
                futs, timeout=self.flight_timeout_s + 0.5)
            errors += len(pending)
            for f in done:
                host_id = futs[f]
                try:
                    body = f.result()
                except Exception:  # noqa: BLE001 — best-effort
                    errors += 1
                    continue
                if not isinstance(body, dict):
                    errors += 1
                    continue
                out_dir.mkdir(parents=True, exist_ok=True)
                write_flight_dump(
                    incident_flight_path(out_dir, incident, host_id), body)
                captured.append(host_id)
        finally:
            # don't block recovery on stragglers: per-request socket
            # timeouts bound the leaked workers' lifetimes anyway
            pool.shutdown(wait=False)
        captured.sort()
        if captured or errors:
            self._event("flight_capture", incident=incident,
                        hosts=captured, errors=errors)

    # -- span-tail + profile capture (ISSUE 20) ---------------------------

    def _capture_spans(self, incident: int, failed: set[int]) -> None:
        """Pull every surviving host's span tail (``GET /tracetail``)
        — and, when ``profile_on_incident_s`` > 0, a short
        ``POST /profile`` — into ``<ft_dir>/spans/`` BEFORE the gang is
        stopped.  Same concurrency contract as :meth:`_capture_flight`
        (one worker per survivor, one shared deadline): span tails are
        the causal half of the flight rings — the rings say what each
        host was doing, the tails say which remote spans CAUSED it —
        and both die with the restart."""
        base = getattr(self.launcher, "obs_base_port", None)
        if not base or self.ft_dir is None or not self.capture_spans:
            return
        import concurrent.futures
        import urllib.request

        hosts = self.launcher.contract.hosts()[
            : self.launcher.contract.workers_count]
        targets = [(h, hosts[h].rsplit(":", 1)[0])
                   for h, p in sorted(self._procs.items())
                   if h not in failed and p.poll() is None]
        if not targets:
            return
        profile_s = self.profile_on_incident_s
        deadline = self.flight_timeout_s + max(0.0, profile_s)

        def fetch(host_id: int, addr: str) -> dict:
            port = base + 1 + host_id
            url = (f"http://{addr}:{port}/tracetail"
                   f"?lines={self.span_tail_lines}")
            with urllib.request.urlopen(
                    url, timeout=self.flight_timeout_s) as r:
                body = json.loads(r.read().decode())
            if profile_s > 0:
                try:
                    req = urllib.request.Request(
                        f"http://{addr}:{port}/profile?seconds={profile_s}",
                        method="POST")
                    with urllib.request.urlopen(
                            req, timeout=deadline) as r:
                        body["profile"] = json.loads(r.read().decode())
                except Exception:  # noqa: BLE001 — profile is optional
                    pass
            return body

        out_dir = self.ft_dir / "spans"
        captured, errors = [], 0
        pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=len(targets), thread_name_prefix="span-capture")
        try:
            futs = {pool.submit(fetch, h, addr): h for h, addr in targets}
            done, pending = concurrent.futures.wait(
                futs, timeout=deadline + 0.5)
            errors += len(pending)
            for f in done:
                host_id = futs[f]
                try:
                    body = f.result()
                except Exception:  # noqa: BLE001 — best-effort
                    errors += 1
                    continue
                events = body.get("events") if isinstance(body, dict) \
                    else None
                if not isinstance(events, list):
                    errors += 1
                    continue
                out_dir.mkdir(parents=True, exist_ok=True)
                # One JSON line per event — the same shape as the source
                # trace file, so read_trace_file / the postmortem's
                # timeline merge ingest a tail exactly like a full file.
                path = out_dir / (f"incident{incident:03d}"
                                  f"-host{host_id:03d}.jsonl")
                with open(path, "w") as fh:
                    for e in events:
                        fh.write(json.dumps(e) + "\n")
                if isinstance(body.get("profile"), dict):
                    (out_dir / (f"incident{incident:03d}"
                                f"-host{host_id:03d}-profile.json")
                     ).write_text(json.dumps(body["profile"], indent=2))
                captured.append(host_id)
        finally:
            pool.shutdown(wait=False)
        captured.sort()
        if captured or errors:
            self._event("span_capture", incident=incident,
                        hosts=captured, errors=errors,
                        profiled=bool(profile_s > 0))

    # -- clock probes (ISSUE 20) ------------------------------------------

    def _clock_probe_tick(self, now: float) -> None:
        """On the probe cadence, measure every live host's wall-clock
        offset over its obs ``/clock`` route and append the probes to
        ``<ft_dir>/clock-offsets.jsonl`` — the measured half of the
        merged timeline's fleet clock (``obs.timeline.fleet_skew``).
        Probing runs on a background daemon thread (skipped while the
        previous round is still in flight) so a slow endpoint can never
        stretch the supervise loop's poll cadence."""
        base = getattr(self.launcher, "obs_base_port", None)
        if (not base or self.ft_dir is None
                or self.clock_probe_interval_s <= 0
                or now < self._next_clock_probe):
            return
        t = self._clock_probe_thread
        if t is not None and t.is_alive():
            return  # previous round still probing — keep its cadence
        self._next_clock_probe = now + self.clock_probe_interval_s
        hosts = self.launcher.contract.hosts()[
            : self.launcher.contract.workers_count]
        targets = [(h, hosts[h].rsplit(":", 1)[0])
                   for h, p in sorted(self._procs.items())
                   if p.poll() is None]
        if not targets:
            return
        path = self.ft_dir / "clock-offsets.jsonl"

        def probe_round() -> None:
            from tpucfn.obs.timeline import probe_clock

            rows = []
            for host_id, addr in targets:
                url = f"http://{addr}:{base + 1 + host_id}/clock"
                try:
                    pr = probe_clock(url, timeout_s=self.flight_timeout_s)
                except Exception:  # noqa: BLE001 — a dead endpoint is
                    continue       # the incident path's problem, not ours
                rows.append({"kind": "clock_probe",
                             "host": host_id if pr.host is None else pr.host,
                             "role": pr.role,
                             "offset_s": round(pr.offset_s, 9),
                             "unc_s": round(pr.unc_s, 9),
                             "rtt_s": round(pr.rtt_s, 9),
                             "t": time.time()})
            if rows:
                with open(path, "a") as f:
                    for r in rows:
                        f.write(json.dumps(r) + "\n")
            else:
                # nothing answered — almost always startup: the workers'
                # obs servers aren't bound yet.  Retry soon instead of
                # burning the whole cadence (a short run would otherwise
                # never land a single probe).
                self._next_clock_probe = min(
                    self._next_clock_probe,
                    time.monotonic() + min(5.0, self.clock_probe_interval_s))

        self._clock_probe_thread = threading.Thread(
            target=probe_round, daemon=True,
            name="tpucfn-clock-probe")
        self._clock_probe_thread.start()

    # -- event / snapshot plumbing ---------------------------------------

    def _event(self, kind: str, **fields) -> None:
        from tpucfn.ft.events import append_event

        if self.ft_dir is None:
            return
        # append_event flushes AND fsyncs (ISSUE 12 satellite): the
        # detect/decide record of the very incident that kills the
        # coordinator must survive the coordinator.
        append_event(self.ft_dir, kind, **fields)
        self._write_snapshot()

    def _j(self, kind: str, **fields) -> None:
        """Append one write-ahead journal record (no-op without a
        journal — ft_dir unset, or a ctor-only coordinator that never
        entered run()).  The fsync'd commit is timed as a
        ``journal_commit`` span (ISSUE 20): on the merged timeline the
        coordinator plane's cost per incident is visible next to the
        recovery spans it gates."""
        if self._journal is None:
            return
        t0 = time.monotonic()
        self._journal.append(kind, **fields)
        if self.tracer is not None:
            self.tracer.record("journal_commit", start=t0,
                               end=time.monotonic(), journal_kind=kind)
        self.coord_journal_c.add()

    def _write_snapshot(self) -> None:
        if self.ft_dir is None:
            return
        hb = None
        if self.monitor is not None:
            hb = self.monitor.config.interval_s
        snap = {
            "updated_ts": time.time(),
            "pid": os.getpid(),
            "argv": self.argv,
            "gang_hosts": len(self.host_ids),
            "policy": self.policy.name,
            "budget": {"max_restarts": self.policy.budget.max_restarts,
                       "used": self.policy.budget.used},
            "heartbeat_interval_s": hb,
            **self.journal_status(),
            **self.registry.varz(),
        }
        tmp = self.ft_dir / "supervisor.json.tmp"
        tmp.write_text(json.dumps(snap, indent=2))
        tmp.replace(self.ft_dir / "supervisor.json")

    def journal_status(self) -> dict:
        """Crash-safety state for supervisor.json and /healthz detail:
        is this incarnation adopted, how deep is the journal, and is a
        restart intent currently awaiting its commit."""
        j = self._journal
        return {
            "adopted": self._adopted,
            "journal": None if j is None else {
                "path": str(j.path),
                "records": j.seq,
                "pending_intent": bool(self.coord_pending_g.value),
            },
        }

    def health(self) -> tuple[bool, dict]:
        """``obs.server`` HealthFn: the heartbeat monitor's fleet view
        (when attached) plus the journal/adoption state — the probe
        surface that lets an operator see 'this supervisor is a
        restarted incarnation that adopted N hosts'."""
        healthy, detail = (self.monitor.health() if self.monitor is not None
                           else (True, {}))
        return healthy, {**detail, **self.journal_status()}

    # -- supervision loop -------------------------------------------------

    def _launch_gang(self, *, first: bool) -> None:
        inject = self.kill_host_after if first else None
        # Pre-spawn write-ahead (ISSUE 13 satellite): pids exist only
        # after launch() returns, so a coordinator killed mid-spawn
        # would otherwise leave ranks NO journal record and an adoption
        # would relaunch over them.  The `launching` record makes the
        # window visible; adoption gives those hosts a heartbeat grace.
        # Deferred input hosts (ISSUE 18) are reserved in the topology
        # but not spawned until the provisioner activates them — every
        # bookkeeping structure below must cover LAUNCHED hosts only, or
        # the monitor would condemn (and adoption would mourn) ranks
        # that were never supposed to exist yet.
        deferred = set(getattr(self.launcher, "deferred_input_host_ids",
                               ()) or ())
        launched = [h for h in self.host_ids if h not in deferred]
        self._j("launching", hosts=launched, first=first)
        crash_point("during_spawn", self.ft_dir)
        procs = self.launcher.launch(self.argv, kill_host_after=inject)
        self._procs = dict(zip(launched, procs))
        # pids AND their kernel start times: the (pid, starttime) pair
        # is the identity adoption trusts across a machine reboot — a
        # recycled pid alone would adopt (and later kill) a stranger.
        self._j("gang_launched", first=first,
                pids={str(h): p.pid for h, p in self._procs.items()},
                starts={str(h): pid_start_time(p.pid)
                        for h, p in self._procs.items()})
        self._finished.clear()
        self.straggler_guard.reset_all()
        self._suppressed_hangs.clear()
        self._input_restarts.clear()
        self.attempts_c.add()
        self.hosts_g.set(len(procs))
        if self.monitor is not None:
            self.monitor.restart_grace()
            for h in launched:
                self.monitor.activate_host(h)
            blind = self.clock() + self.monitor.config.grace_s
            self._blind_until = {h: blind for h in launched}
        self.provision_input_hosts_g.set(
            sum(1 for h in self.input_host_ids if h not in deferred))
        self._event("launch", first=first, hosts=len(procs),
                    pids=[p.pid for p in procs])

    def _launch_solo(self, host_id: int) -> None:
        # Same host_env as the rank it replaces (host_id, obs port,
        # heartbeat file) — the gang must not notice the substitution.
        self._j("launching", hosts=[host_id])
        self._procs[host_id] = self.launcher.launch_host(self.argv, host_id)
        self._j("solo_launched", host=host_id,
                pid=self._procs[host_id].pid,
                start=pid_start_time(self._procs[host_id].pid))
        self._finished.pop(host_id, None)
        self._suppressed_hangs.discard(host_id)
        self.straggler_guard.reset(host_id)
        if self.monitor is not None:
            self.monitor.activate_host(host_id)
            # Blind only the replaced host: its stale heartbeat must not
            # re-condemn it while it boots, but the REST of the gang
            # keeps full-rate hang detection.
            self._blind_until[host_id] = (self.clock()
                                          + self.monitor.config.grace_s)
        self._event("solo_launch", host=host_id,
                    pid=self._procs[host_id].pid)

    def _straggler_actionable(self) -> bool:
        return self.policy.table.get(
            FailureKind.STRAGGLER, Action.NONE) is not Action.NONE

    def _detect(self, now: float) -> list[Failure]:
        failures: list[Failure] = []
        if self._adopt_failures:
            # Hosts that died while no coordinator was watching
            # (adoption found their pid gone): raised exactly once,
            # through the normal detect→decide path.
            failures.extend(self._adopt_failures)
            self._adopt_failures = []
        # Preemption notices (ISSUE 7): chaos-delivered plus the external
        # sentinel file an out-of-band notice daemon writes.  Consumed
        # here so one notice raises exactly one PREEMPT failure; a
        # notice for a host that already exited is moot.
        if self.ft_dir is not None:
            n = consume_notice(self.ft_dir)
            if n is not None:
                self._pending_notices.append(n)
        if self._pending_notices:
            notices, self._pending_notices = self._pending_notices, []
            for n in notices:
                if n.host in self._procs:
                    failures.append(Failure(
                        n.host, FailureKind.PREEMPT, lead_s=n.lead_s,
                        detail="preemption notice"))
        for host_id, p in list(self._procs.items()):
            rc = p.poll()
            if rc is None:
                continue
            if rc == 0:
                self._j("host_exit", host=host_id, rc=0)
                del self._procs[host_id]
                self._finished[host_id] = 0
                if self.monitor is not None:
                    # a finished rank's heartbeat going stale is
                    # retirement, not death — keep /healthz green
                    self.monitor.retire_host(host_id)
                self._event("host_exit", host=host_id, rc=0)
            else:
                failures.append(Failure(host_id, FailureKind.CRASH, rc=rc))
        if (self.monitor is not None and self._procs
                and now >= self._next_observe):
            # Throttle to half the heartbeat interval: heartbeat files
            # change once per interval, so tail-reading every 50ms poll
            # tick is pure redundant I/O (process-exit CRASH detection
            # above still runs at full poll rate).
            self._next_observe = now + self.monitor.config.interval_s / 2.0
            view = self.monitor.observe()
            self._last_fleet_step = view.max_step()
            counts = view.counts()
            self.ft_hosts_live_g.set(counts[HostState.LIVE.value])
            self.ft_stragglers_g.set(counts[HostState.STRAGGLER.value])
            crashed = {f.host_id for f in failures}
            for v in view.hosts:
                if v.host_id not in self._procs or v.host_id in crashed:
                    continue
                if now < self._blind_until.get(v.host_id, 0.0):
                    # Per-host post-(re)launch blind window: a stale
                    # heartbeat from the previous incarnation must not
                    # condemn a rank that is still importing jax.
                    continue
                if v.state is HostState.DEAD:
                    if v.host_id in self._suppressed_hangs:
                        continue  # policy already declined to act
                    failures.append(Failure(v.host_id, FailureKind.HANG,
                                            step=v.step, detail=v.reason))
                else:
                    # the host came back (fresh beat): re-arm reporting
                    self._suppressed_hangs.discard(v.host_id)
                    # Straggler verdicts go through the guard (ISSUE 7):
                    # hysteresis + flap budget decide when lag becomes
                    # an eviction.  A SUSPECT host (stale beat) freezes
                    # the episode — neither lag evidence nor recovery.
                    if (v.state in (HostState.LIVE, HostState.STRAGGLER)
                            and self._straggler_actionable()
                            and self.straggler_guard.observe(
                                v.host_id,
                                v.state is HostState.STRAGGLER, now=now)):
                        self._j("straggler_probation", host=v.host_id)
                        failures.append(
                            Failure(v.host_id, FailureKind.STRAGGLER,
                                    step=v.step, detail=v.reason))
        return failures

    def _stop_hosts(self, host_ids: Sequence[int]) -> None:
        procs = [self._procs[h] for h in host_ids if h in self._procs]
        self.launcher.stop_all(procs, grace_s=self.term_grace_s,
                               poll_interval=self.poll_interval)
        for h in host_ids:
            self._procs.pop(h, None)

    def _failure_rc(self, failures: list[Failure]) -> int:
        for f in failures:
            if f.rc is not None and f.rc != 0:
                return f.rc
        return 1  # hang/straggler incidents have no exit code

    def run(self) -> int:
        """Supervise until the gang finishes cleanly (0), a failure
        exhausts the policy budget (the failing rc), or the policy
        declines to act on a fatal class.  With a journal on disk from
        a previous incarnation (and ``adopt`` not False), the running
        fleet is adopted instead of relaunched — see
        :meth:`_adopt_fleet`."""
        try:
            if not self._startup_adopt():
                if self.ft_dir is not None:
                    # A previous incarnation aborted mid-drain
                    # (supervisor SIGKILLed inside the wait loop) leaves
                    # drain.json / preempt.json behind; the fresh gang
                    # would self-drain at its first boundary and
                    # "finish" rc 0 having trained nothing.  Stale
                    # protocol files die here — along with stale rc
                    # files and the previous run's journal.
                    clear_drain(self.ft_dir)
                    consume_notice(self.ft_dir)
                    clear_rc_dir(self.ft_dir)
                    rotate_journal(journal_path(self.ft_dir))
                    self._journal = JournalWriter(
                        journal_path(self.ft_dir))
                    self._j("run_start", argv=self.argv,
                            hosts=len(self.host_ids),
                            policy=self.policy.name,
                            max_restarts=self.policy.budget.max_restarts)
                self._launch_gang(first=True)
            start = self.clock()
            while True:
                self.sleep(self.poll_interval)
                now = self.clock()
                if self.chaos is not None and not self.chaos.done():
                    self.chaos.tick(now - start, self._last_fleet_step)
                failures = self._detect(now)
                if failures and self.input_host_ids:
                    # Input-role failures are degradations, not
                    # incidents: handled apart from the policy so they
                    # can never gang-restart trainers or burn budget.
                    failures = self._handle_input_failures(failures)
                if not failures:
                    self._release_idle_input_hosts()
                    if not self._procs:  # every supervised rank exited
                        rc = next((r for r in self._finished.values() if r),
                                  0)
                        self.rc_g.set(rc)
                        self._j("done", rc=rc)
                        self._event("done", rc=rc)
                        return rc
                    self._provision_tick(now)
                    self._clock_probe_tick(now)
                    continue
                rc = self._handle_incident(failures)
                if rc is not None:
                    self._j("done", rc=rc)
                    return rc
        finally:
            if self._procs:
                self.launcher.stop_all(list(self._procs.values()),
                                       grace_s=self.term_grace_s,
                                       poll_interval=self.poll_interval)
                self._procs.clear()
            self._write_snapshot()
            if self._journal is not None:
                self._journal.close()

    # -- crash-safety: fleet adoption (ISSUE 12) --------------------------

    def _startup_adopt(self) -> bool:
        """Fresh launch vs adoption.  True when a previous incarnation's
        unfinished journal was found and the running fleet was adopted
        (the caller must then skip the first launch).  A journal whose
        run already ended (done record) is history, not a fleet — the
        caller rotates it and starts fresh.  A corrupt journal raises
        :class:`~tpucfn.ft.journal.JournalError` loudly: reconstructing
        a plausible-but-wrong fleet state would be worse."""
        if self.ft_dir is None or self.adopt is False:
            return False
        jp = journal_path(self.ft_dir)
        if not jp.exists():
            return False
        t0 = self.clock()
        st, records, torn = replay_journal(jp)
        # Replay time is real restart downtime (ISSUE 13 satellite):
        # measured here, attributed through the recovered /
        # goodput_incident detail so `tpucfn obs goodput` can name the
        # crash-safety plane's own MTTR cost.
        self._journal_replay_ms = round((self.clock() - t0) * 1e3, 3)
        if not st.started or st.done_rc is not None:
            return False
        self._adopt_fleet(st, torn, n_records=len(records))
        return True

    def _adopt_fleet(self, st, torn: bool, *, n_records: int = 0) -> None:
        """Attach to the fleet a dead coordinator left running: restore
        the durable state (budget, incident counter, shrinks, ckpt
        blacklist, input restarts), re-attach to live children by pid
        (journal incarnations first, heartbeat pids as the fallback for
        a crash that landed between spawn and journal append), raise
        exactly one CRASH failure per child that died unwatched, and
        finish any mid-flight restart intent exactly once."""
        t0 = self.clock()
        self._adopted = True
        if torn:
            # The torn final record is the tolerated crash boundary —
            # but JournalWriter appends, and appending after a partial
            # line would glue the next record onto the torn bytes: one
            # garbled line that is no longer final, which the NEXT
            # replay would refuse as corruption.  Drop the tail first.
            repair_torn_tail(journal_path(self.ft_dir))
        # Compaction (ISSUE 15 satellite): a week of incidents replays
        # O(run lifetime) — past the threshold, fold the state we just
        # replayed into one snapshot record so the NEXT adoption (and
        # every tool reading the journal) stays O(recent).
        compacted = False
        if self.journal_compact_records:
            # the (state, count) we JUST replayed — compaction must not
            # pay the O(N) parse a second time on the biggest journals
            compacted = compact_journal(
                journal_path(self.ft_dir),
                max_records=self.journal_compact_records,
                replayed=(st, n_records))
        self._journal = JournalWriter(journal_path(self.ft_dir),
                                      start_seq=st.seq)
        self._incident = st.incident
        self.policy.budget.used = max(self.policy.budget.used,
                                      st.budget_used)
        for lost in st.shrinks:
            # Re-apply recorded shrinks in order: the launcher was
            # rebuilt from the original contract, but the fleet on disk
            # is already the shrunk one.
            self.launcher.contract = shrink_contract(
                self.launcher.contract, sorted(lost))
            self.host_ids = list(
                range(self.launcher.contract.workers_count))
            if self.monitor is not None:
                self.monitor.set_expected_hosts(len(self.host_ids))
        self._input_restarts = dict(st.input_restarts)
        self._ckpt_blacklist = set(st.ckpt_blacklist)
        self._ckpt_retries = st.ckpt_retries
        if self._ckpt_blacklist:
            self.launcher.extra_env[CKPT_BLACKLIST_ENV] = \
                format_ckpt_blacklist(self._ckpt_blacklist)
        self._finished = dict(st.finished)
        if self.chaos is not None and st.chaos_fired:
            # Scripted events that already fired must not re-fire in
            # this incarnation — a kill_coordinator spec would
            # otherwise kill every adoption forever.
            self.chaos.skip_fired(st.chaos_fired)
        beats = read_heartbeats(self.ft_dir)
        pending_failures: list[Failure] = []
        adopted_hosts: list[int] = []
        dead: list[tuple[int, list[int]]] = []
        # Spawn-window hosts (ISSUE 13 satellite): a `launching` record
        # with no pid record means the predecessor died mid-spawn — the
        # rank may be alive with no journal trace.  Wait a heartbeat
        # grace for its beat to name a pid before condemning it; an
        # immediate relaunch here is exactly the double-spawn the
        # hazard describes.  A RELAUNCH window is the same hazard with
        # a twist: st.procs (and the heartbeat file) still carry the
        # dead predecessor incarnation's pid, so the grace must wait
        # for a beat naming a DIFFERENT pid — the spawned rank's first
        # beat — not just any pid.
        stale = {h: st.procs[h] for h in st.launching if h in st.procs}
        spawning = {h for h in st.launching
                    if h in self.host_ids and h not in self._finished}
        if spawning:
            deadline = self.clock() + self.adopt_spawn_grace_s
            while spawning and self.clock() < deadline:
                missing = []
                for h in spawning:
                    pid = (beats.get(h) or {}).get("pid")
                    if not isinstance(pid, int) or pid == stale.get(h):
                        missing.append(h)
                if not missing:
                    break
                self.sleep(0.1)
                beats = read_heartbeats(self.ft_dir)
        deferred = set(getattr(self.launcher, "deferred_input_host_ids",
                               ()) or ())
        for host in self.host_ids:
            if host in deferred and host not in st.procs:
                # Reserved-but-never-activated input host (ISSUE 18):
                # no incarnation ever existed; mourning it as a crash
                # would degrade an input plane that was never up.
                continue
            if host in self._finished:
                if self.monitor is not None:
                    self.monitor.retire_host(host)
                continue
            # candidates are (pid, journaled start time | None): the
            # start time is the recycling guard (ISSUE 15 satellite) —
            # across a machine reboot the same pid number names a
            # stranger, and the stranger must read as a dead rank, not
            # a live one we would adopt and later SIGKILL.
            cands: list[tuple[int, int | None]] = []
            if host in st.procs:
                # A spawn-window host's st.procs pid IS the dead
                # predecessor being replaced (`launching` postdates
                # it): never a candidate — the OS may have recycled it
                # onto an unrelated process we would adopt and later
                # kill.  The grace loop above already distrusts it.
                if host not in st.launching:
                    cands.append((st.procs[host],
                                  st.proc_starts.get(host)))
            hb_pid = (beats.get(host) or {}).get("pid")
            if isinstance(hb_pid, int) \
                    and hb_pid not in [p for p, _ in cands] \
                    and not (host in st.launching
                             and hb_pid == stale.get(host)):
                cands.append((hb_pid, None))
            live = next(((p, s) for p, s in cands
                         if self._cand_alive(p, s)), None)
            if live is not None:
                self._procs[host] = AdoptedProcess(
                    live[0], host_id=host, ft_dir=self.ft_dir,
                    start_time=live[1])
                adopted_hosts.append(host)
                if self.monitor is not None:
                    self.monitor.activate_host(host)
            else:
                dead.append((host, [p for p, _ in cands]))
        # Resolve the unwatched deaths.  The supervise reaper may still
        # be racing us to land their rc files (it reaps our
        # predecessor's orphans only when it re-enters waitpid after
        # spawning us), so give it the same grace AdoptedProcess.poll
        # gives it — without it, a rank that finished rc 0 during the
        # downtime reads as a CRASH and burns a budget slot relaunching
        # a host that was already done.
        rcs: dict[int, int | None] = {}
        for host, cands in dead:
            rcs[host] = next((r for r in (read_rc(self.ft_dir, p)
                                          for p in cands)
                              if r is not None), None)
        waiting = [h for h, c in dead if c and rcs[h] is None]
        if waiting:
            deadline = self.clock() + ADOPT_RC_GRACE_S
            while waiting and self.clock() < deadline:
                self.sleep(0.05)
                for host, cands in dead:
                    if rcs[host] is None:
                        rcs[host] = next(
                            (r for r in (read_rc(self.ft_dir, p)
                                         for p in cands)
                             if r is not None), None)
                waiting = [h for h, c in dead if c and rcs[h] is None]
        for host, cands in dead:
            rc = rcs[host]
            if rc == 0:
                self._j("host_exit", host=host, rc=0)
                self._finished[host] = 0
                if self.monitor is not None:
                    self.monitor.retire_host(host)
            else:
                pending_failures.append(Failure(
                    host, FailureKind.CRASH, rc=rc,
                    detail="died while the coordinator was down"
                           if cands else "no incarnation on record"))
        self.hosts_g.set(len(self._procs))
        self.coord_adoptions_c.add()
        self._j("adopted", hosts=adopted_hosts,
                dead=[f.host_id for f in pending_failures],
                pending=None if st.pending is None else st.pending.incident,
                replay_ms=self._journal_replay_ms,
                compacted=compacted)
        self._event("coordinator_adopted", hosts=adopted_hosts,
                    dead=[f.host_id for f in pending_failures],
                    budget_used=self.policy.budget.used,
                    incident=self._incident,
                    pending_incident=(None if st.pending is None
                                      else st.pending.incident),
                    torn=bool(torn),
                    journal_replay_ms=self._journal_replay_ms)
        if st.pending is None \
                or st.pending.action != Action.DRAIN_RESTART.value:
            # No drain is in flight: drain/notice files (and a notice
            # consumed into memory pre-crash) are stale protocol state.
            clear_drain(self.ft_dir)
            consume_notice(self.ft_dir)
        if st.pending is not None:
            completed = self._complete_pending(st.pending, t0)
            pending_failures = [f for f in pending_failures
                                if f.host_id not in completed]
        self._adopt_failures = pending_failures

    @staticmethod
    def _cand_alive(pid: int, expect_start: int | None) -> bool:
        """Is this candidate pid the journaled incarnation?  Alive AND
        — when the journal recorded a start time — bearing the same
        kernel start time; a live pid with a DIFFERENT start time is a
        recycled number on an unrelated process (machine rebooted, or
        a long downtime), and the rank it claimed is dead-unwatched."""
        if not pid_alive(pid):
            return False
        if expect_start is not None:
            cur = pid_start_time(pid)
            if cur is not None and cur != expect_start:
                return False
        return True

    def _complete_pending(self, p: PendingIntent, t0: float) -> set[int]:
        """Finish a restart intent whose commit never landed — exactly
        once: when the launch half already ran (launch records after
        the intent), only the commit is written; otherwise the act runs
        now.  Either way the budget draw journaled with the intent is
        never re-drawn.  Returns the hosts the completion relaunched
        (their unwatched deaths are moot)."""
        action = p.action
        self.coord_pending_g.set(1)
        if not p.launched:
            if action == Action.SOLO_RESTART.value:
                # Hosts whose solo_launched already landed pre-crash got
                # their restart — redoing them would be the double the
                # intent/commit pair exists to prevent.
                todo = [h for h in p.hosts if h not in p._solo_done]
                for h in todo:
                    if h in self._procs:
                        self._stop_hosts([h])
                    self._launch_solo(h)
                self.ft_solo_restarts_c.add(len(todo))
                self.ft_restarts_c.add(len(todo))
                self.restarts_c.add(len(todo))
                completed = set(todo)
            else:  # gang-shaped: gang_restart / drain_restart / ckpt_retry
                self._stop_hosts(list(self._procs))
                if self.ft_dir is not None:
                    clear_drain(self.ft_dir)
                if action == "provision_grow":
                    # The predecessor died between its grow intent and
                    # the relaunch: the activation must still happen or
                    # the completed relaunch would re-defer the input
                    # plane the decision already paid for.
                    self.launcher.activate_input_plane()
                self._launch_gang(first=False)
                if action == Action.DRAIN_RESTART.value:
                    self.ft_preempt_drains_c.add()
                    self.ft_planned_restarts_c.add()
                else:
                    self.ft_gang_restarts_c.add()
                    self.ft_restarts_c.add()
                    self.restarts_c.add()
                completed = set(self.host_ids)
        else:
            completed = set()  # acted pre-crash; only the commit is owed
        crash_point("adopt_before_commit", self.ft_dir)
        self._j("restart_commit", incident=p.incident, action=action)
        self.coord_pending_g.set(0)
        mttr = self.clock() - t0
        planned = p.planned or action == Action.DRAIN_RESTART.value
        (self.ft_planned_mttr_s if planned else self.ft_mttr_s).observe(mttr)
        self._event("recovered", incident=p.incident, action=action,
                    planned=planned, mttr_s=round(mttr, 4), adopted=True,
                    journal_replay_ms=self._journal_replay_ms)
        # Goodput attribution for the adoption-completed incident: the
        # pre-crash coordinator died before it could write this row, and
        # the replay share of the downtime is named (ISSUE 13 satellite).
        self._event("goodput_incident", incident=p.incident, action=action,
                    planned=planned, downtime_s=round(mttr, 4),
                    detection_s=round(self.poll_interval, 4),
                    fleet_step=self._last_fleet_step,
                    journal_replay_ms=self._journal_replay_ms)
        return completed

    def _handle_input_failures(self, failures: list[Failure]
                               ) -> list[Failure]:
        """Strip and absorb failures of input-role hosts (ISSUE 11).

        A dead input host is a capacity loss, not a gang failure: the
        trainers' resilient streams fail over to the surviving input
        hosts and then degrade to LOCAL loading from the exact batch
        cursor — the run's trajectory is unchanged, only its input
        throughput.  So: stop/reap the host, retire its heartbeat,
        record ``input_degraded``, optionally solo-relaunch (bounded,
        budget untouched), and hand everything else back to the normal
        detect→decide path."""
        inputs = [f for f in failures if f.host_id in self.input_host_ids]
        if not inputs:
            return failures
        for f in inputs:
            self._j("input_degraded", host=f.host_id)
            if f.host_id in self._procs:
                # a hung service still holds its socket: stop it so
                # trainer recv calls fail fast instead of timing out
                self._stop_hosts([f.host_id])
            self._finished.setdefault(f.host_id, 0)
            self._suppressed_hangs.discard(f.host_id)
            if self.monitor is not None:
                self.monitor.retire_host(f.host_id)
            self.ft_input_degraded_c.add()
            self._event("input_degraded", host=f.host_id,
                        failure=f.kind.value, rc=f.rc, detail=f.detail)
            used = self._input_restarts.get(f.host_id, 0)
            if self.restart_input_hosts and used < self.max_input_restarts:
                self._input_restarts[f.host_id] = used + 1
                self._j("input_restarted", host=f.host_id,
                        restarts=used + 1)
                self._launch_solo(f.host_id)
                self.ft_input_restarts_c.add()
                self._event("input_recovered", host=f.host_id,
                            restarts=used + 1)
        return [f for f in failures if f.host_id not in self.input_host_ids]

    def _release_idle_input_hosts(self) -> None:
        """Once every trainer rank has finished, surviving input hosts
        are holding the run open for nobody — stop them cleanly so the
        supervisor can declare the run done (the trainer rc decides)."""
        if not self.input_host_ids or not self._procs:
            return
        if any(h not in self.input_host_ids for h in self._procs):
            return  # a trainer is still running
        ids = sorted(self._procs)
        self._stop_hosts(ids)
        for h in ids:
            self._j("host_exit", host=h, rc=0)
            self._finished.setdefault(h, 0)
            if self.monitor is not None:
                self.monitor.retire_host(h)
            self._event("host_exit", host=h, rc=0,
                        note="input host stopped after trainers finished")

    # -- provisioner policy loop (ISSUE 18) --------------------------------

    def _provision_tick(self, now: float) -> None:
        """One observe→decide→actuate cycle of the provisioner policy,
        throttled to ``provision_interval_s`` and run only from the
        no-failure branch of the supervision loop (an incident in
        flight owns the fleet; resizing under it would race the
        restart).

        The observation window is filtered by wall-clock ``t`` (the
        clock ledger records carry) from the last actuation forward —
        NOT this coordinator's injectable monotonic clock — so a grow
        is judged by post-grow evidence only."""
        if self.provision_policy is None or self.goodput_dir is None:
            return
        if now < self._next_provision:
            return
        self._next_provision = now + self.provision_interval_s
        from tpucfn.obs.goodput import fleet_window_observation
        from tpucfn.provision.policy import FleetObservation, PolicyAction

        raw = fleet_window_observation(self.goodput_dir,
                                       since_t=self._provision_since_t)
        obs = None
        if raw is not None:
            obs = FleetObservation(
                wall_s=raw["wall_s"], goodput_ratio=raw["goodput_ratio"],
                shares=raw["shares"], num_hosts=raw["num_hosts"])
            self.provision_data_wait_share_g.set(
                round(obs.data_wait_share, 6))
            self.provision_goodput_ratio_g.set(
                round(obs.goodput_ratio, 6))
        deferred = set(getattr(self.launcher, "deferred_input_host_ids",
                               ()) or ())
        active_inputs = sum(1 for h in self.input_host_ids
                            if h not in deferred)
        self.provision_input_hosts_g.set(active_inputs)
        decision = self.provision_policy.decide(
            obs, input_hosts=active_inputs, now=now)
        if decision.action is PolicyAction.HOLD:
            return
        self.provision_decisions_c.add()
        self._j("provision_decision", action=decision.action.value,
                signal=decision.signal.value,
                data_wait_share=round(decision.data_wait_share, 6))
        self._event("provision_decision", action=decision.action.value,
                    signal=decision.signal.value, reason=decision.reason,
                    data_wait_share=round(decision.data_wait_share, 6),
                    goodput_ratio=round(decision.goodput_ratio, 6),
                    input_hosts=active_inputs)
        if decision.action is PolicyAction.GROW_INPUT_HOSTS:
            self._provision_grow(decision, sorted(deferred))
        elif decision.action is PolicyAction.SHRINK_INPUT_HOSTS:
            self._provision_shrink(decision)
        elif decision.action is PolicyAction.FLAG_STARVED:
            self.provision_flagged_g.set(1)
            if not self._provision_flagged:
                # one event per chronic episode; the gauge stays up
                self._provision_flagged = True
                self._event(
                    "provision_flagged", reason=decision.reason,
                    data_wait_share=round(decision.data_wait_share, 6))

    def _provision_grow(self, decision, deferred: list[int]) -> None:
        """Actuate a grow decision: drain the trainers to one step
        boundary (the force-save lands there; the relaunch re-executes
        nothing), activate the launcher's reserved input plane, and
        relaunch the gang — trainers now see TPUCFN_INPUT_ADDRS and
        stream served batches.  A PLANNED restart: zero budget, and the
        latency is the real-world measurement of the policy's
        actuation-latency model (fetch-warm relaunch, ISSUE 13)."""
        if not deferred:
            return  # nothing reserved to activate
        t0 = self.clock()
        self._incident += 1
        incident = self._incident
        self._j("restart_intent", incident=incident,
                action="provision_grow", hosts=[],
                budget_used=self.policy.budget.used, planned=True)
        self.coord_pending_g.set(1)
        crash_point("after_intent", self.ft_dir)
        target = None
        if self._last_fleet_step is not None:
            target = self._last_fleet_step + self.drain_step_margin
        drain_file = None
        if self.ft_dir is not None:
            drain_file = request_drain(self.ft_dir, step=target)
            self._j("drain_armed", incident=incident, step=target)
        self._event("drain", incident=incident, hosts=deferred,
                    step=target, grace_s=round(self.drain_grace_s, 3),
                    file=None if drain_file is None else str(drain_file))
        if drain_file is not None:
            deadline = self.clock() + self.drain_grace_s
            while (any(p.poll() is None for p in self._procs.values())
                   and self.clock() < deadline):
                self.sleep(self.poll_interval)
        leftovers = [p for p in self._procs.values() if p.poll() is None]
        if leftovers:
            self.launcher.stop_all(leftovers, grace_s=self.term_grace_s,
                                   poll_interval=self.poll_interval)
        self._procs.clear()
        if self.ft_dir is not None:
            clear_drain(self.ft_dir)
        self.launcher.activate_input_plane()
        self._launch_gang(first=False)
        crash_point("before_commit", self.ft_dir)
        self._j("restart_commit", incident=incident,
                action="provision_grow")
        self.coord_pending_g.set(0)
        latency = self.clock() - t0
        self.provision_grow_c.add()
        self.ft_planned_restarts_c.add()
        self.ft_planned_mttr_s.observe(latency)
        self.provision_actuation_s.observe(latency)
        # Judge the grow by post-grow evidence only.
        self._provision_since_t = time.time()
        self._event("provision_actuated", incident=incident,
                    action="grow_input_hosts", hosts=deferred,
                    latency_s=round(latency, 4),
                    model_latency_s=round(decision.actuation_latency_s, 4))
        self._event("recovered", incident=incident,
                    action="provision_grow", planned=True,
                    mttr_s=round(latency, 4))
        self._event("goodput_incident", incident=incident,
                    action="provision_grow", planned=True,
                    downtime_s=round(latency, 4),
                    detection_s=round(self.provision_interval_s, 4),
                    fleet_step=self._last_fleet_step)

    def _provision_shrink(self, decision) -> None:
        """Actuate a shrink decision: stop the live input hosts.  No
        trainer restart — the resilient service streams (ISSUE 11)
        degrade to local loading at the exact batch cursor, so the
        trajectory is untouched; only the input topology changes.  The
        hosts go back to reserved-but-deferred, so a later starvation
        verdict can grow them again."""
        live = sorted(h for h in self._procs if h in self.input_host_ids)
        if not live:
            return
        t0 = self.clock()
        self._j("provision_shrink", hosts=live)
        self._stop_hosts(live)
        for h in live:
            self._j("host_exit", host=h, rc=0)
            self._finished.setdefault(h, 0)
            if self.monitor is not None:
                self.monitor.retire_host(h)
        if hasattr(self.launcher, "defer_input_plane"):
            self.launcher.defer_input_plane = True
        latency = self.clock() - t0
        self.provision_shrink_c.add()
        self.provision_actuation_s.observe(latency)
        self.provision_input_hosts_g.set(0)
        self._provision_since_t = time.time()
        self._event("provision_actuated", action="shrink_input_hosts",
                    hosts=live, latency_s=round(latency, 4))

    def _handle_incident(self, failures: list[Failure]) -> int | None:
        """One detect→decide→act→recovered cycle; returns the run's exit
        code when the incident ends the run, else None."""
        t_detect = self.clock()
        self._incident += 1
        incident = self._incident
        self.ft_incidents_c.add()
        self._refresh_ckpt_blacklist()
        real = [f for f in failures if f.kind in (FailureKind.CRASH,
                                                  FailureKind.HANG)]
        if real:
            self.ft_failures_c.add(len(real))
            self.failures_c.add()
            self.rc_g.set(self._failure_rc(real))
        fail_json = [{"host": f.host_id, "kind": f.kind.value, "rc": f.rc,
                      "step": f.step, "detail": f.detail,
                      **({"lead_s": f.lead_s} if f.lead_s is not None
                         else {})} for f in failures]
        self._j("incident_open", incident=incident, failures=[
            {"host": f.host_id, "kind": f.kind.value, "rc": f.rc}
            for f in failures])
        self._event("detect", incident=incident, failures=fail_json)
        crash_point("after_detect", self.ft_dir)
        if self.tracer is not None:
            self.tracer.event("ft_detect", trace_id=incident,
                              failures=fail_json)
        if real:
            # Forensics before recovery: the survivors' flight rings
            # and span tails are about to be killed with the gang
            # (ISSUE 6 tentpole; span tails ISSUE 20).
            self._capture_flight(incident, {f.host_id for f in real})
            self._capture_spans(incident, {f.host_id for f in real})
        # Checkpoint-corruption retry (ISSUE 7): a gang whose ranks exit
        # with the restore-failure rc is not a fleet failure — the
        # artifact is bad.  Retry from the previous finalized step
        # instead of crash-looping the same corrupt checkpoint through
        # the restart budget into give_up.  Handled before the policy so
        # the budget is untouched; past max_ckpt_retries (or with no
        # finalized step left to blacklist) the normal table decides.
        if (real and self.ckpt_dir is not None
                and self._ckpt_retries < self.max_ckpt_retries
                and all(f.kind is FailureKind.CRASH
                        and f.rc == RESTORE_FAILED_RC for f in real)):
            bad = _latest_finalized_step(self.ckpt_dir,
                                         exclude=self._ckpt_blacklist)
            # Retry only when there is BOTH a step to blacklist and an
            # earlier finalized step to resume from.  Quarantining the
            # last remaining checkpoint would make the relaunch init
            # fresh and "succeed" from step 0 — recovery must not
            # silently retrain; crash-looping into a loud give_up (the
            # restore-failure rc) is the honest outcome, and the
            # quarantined steps are plain renames under corrupt/ the
            # operator can move back.
            if bad is not None and _latest_finalized_step(
                    self.ckpt_dir,
                    exclude=self._ckpt_blacklist | {bad}) is not None:
                return self._ckpt_retry(incident, bad, t_detect)
        decision = self.policy.decide(failures)
        self._event("decide", incident=incident,
                    action=decision.action.value,
                    hosts=list(decision.hosts),
                    delay_s=round(decision.delay_s, 3),
                    planned=decision.planned,
                    reason=decision.reason)

        if decision.action is Action.NONE:
            # A table can declare a failure non-actionable (observe-
            # only); the incident must then be closed, not re-detected
            # every poll tick: reap crashed hosts with their rc, and
            # suppress further HANG verdicts until the host beats again.
            for f in failures:
                if f.kind is FailureKind.CRASH and f.host_id in self._procs:
                    self._j("host_exit", host=f.host_id,
                            rc=f.rc if f.rc else 1)
                    del self._procs[f.host_id]
                    self._finished[f.host_id] = f.rc if f.rc else 1
                elif f.kind is FailureKind.HANG:
                    self._suppressed_hangs.add(f.host_id)
            self._j("incident_closed", incident=incident, action="none")
            return None
        if decision.action is Action.GIVE_UP:
            rc = self._failure_rc(failures)
            self._j("give_up", incident=incident, rc=rc)
            self.ft_give_ups_c.add()
            self._stop_hosts(list(self._procs))
            self.rc_g.set(rc)
            self._event("give_up", incident=incident, rc=rc,
                        reason=decision.reason)
            if self.tracer is not None:
                self.tracer.record("ft_give_up", start=t_detect,
                                   end=self.clock(), trace_id=incident,
                                   rc=rc)
            return rc

        # Write-ahead intent (ISSUE 12): the decision — including the
        # budget slot it drew — is durable BEFORE any process is
        # touched.  A coordinator crash anywhere between here and the
        # matching restart_commit leaves a pending intent the adopting
        # incarnation completes exactly once.
        self._j("restart_intent", incident=incident,
                action=decision.action.value, hosts=list(decision.hosts),
                budget_used=self.policy.budget.used,
                planned=decision.planned)
        self.coord_pending_g.set(1)
        crash_point("after_intent", self.ft_dir)

        if decision.action is Action.DRAIN_RESTART:
            return self._drain_restart(incident, decision, failures,
                                       t_detect)

        if decision.delay_s > 0:
            self.sleep(decision.delay_s)
        # A preemption notice that arrived in the same tick as a real
        # failure lost the decision to the restart — but the machine is
        # still going away, and the notice was already one-shot
        # consumed.  Re-queue it so the next tick raises a PREEMPT-only
        # incident against the relaunched gang and the drain still
        # happens ahead of the actual preemption.  (Only on restart
        # shapes: an observe-only NONE table would re-fire forever, and
        # after GIVE_UP there is nothing left to drain.)
        for f in failures:
            if f.kind is FailureKind.PREEMPT:
                self._pending_notices.append(
                    PreemptNotice(host=f.host_id, lead_s=f.lead_s))
        extra: dict = {}
        # Elastic shrink (ISSUE 7): a restart cannot bring back a host
        # the fleet has lost for good (chaos lose_host, or the control
        # plane reports it gone) — re-converge the contract at N-k and
        # relaunch the smaller gang instead of crash-looping relaunches
        # of a machine that no longer exists.
        failed_hosts = {f.host_id for f in real} | set(decision.hosts)
        lost = {h for h in failed_hosts
                if h in self.host_ids and self._host_lost(h)}
        if lost and self.allow_shrink:
            if len(self.host_ids) - len(lost) < 1:
                rc = self._failure_rc(failures)
                self._j("give_up", incident=incident, rc=rc)
                self.coord_pending_g.set(0)
                self.ft_give_ups_c.add()
                self._stop_hosts(list(self._procs))
                self.rc_g.set(rc)
                self._event("give_up", incident=incident, rc=rc,
                            reason=f"all {len(self.host_ids)} host(s) "
                                   "lost — nothing left to shrink to")
                if self.tracer is not None:
                    self.tracer.record("ft_give_up", start=t_detect,
                                       end=self.clock(), trace_id=incident,
                                       rc=rc)
                return rc
            self._stop_hosts(list(self._procs))
            extra["shrink"] = self._do_shrink(incident, lost)
            self._launch_gang(first=False)
            self.ft_gang_restarts_c.add()
            self.ft_restarts_c.add()
            self.restarts_c.add()
        elif decision.action is Action.SOLO_RESTART:
            self._stop_hosts(decision.hosts)
            for h in decision.hosts:
                self._launch_solo(h)
            evicted = sum(1 for f in failures
                          if f.kind is FailureKind.STRAGGLER
                          and f.host_id in decision.hosts)
            if evicted:
                self.ft_evictions_c.add(evicted)
            self.ft_solo_restarts_c.add(len(decision.hosts))
            self.ft_restarts_c.add(len(decision.hosts))
            self.restarts_c.add(len(decision.hosts))
        else:  # GANG_RESTART
            self._stop_hosts(list(self._procs))
            self._launch_gang(first=False)
            self.ft_gang_restarts_c.add()
            self.ft_restarts_c.add()
            self.restarts_c.add()
        crash_point("before_commit", self.ft_dir)
        self._j("restart_commit", incident=incident,
                action=decision.action.value)
        self.coord_pending_g.set(0)
        mttr = self.clock() - t_detect
        self.ft_mttr_s.observe(mttr)
        self._event("recovered", incident=incident,
                    action=decision.action.value, mttr_s=round(mttr, 4),
                    **extra)
        # Goodput attribution (ISSUE 5): one ledger row per incident so
        # `tpucfn obs goodput` can name who stole the fleet's seconds.
        # detection_s is the estimated failure→detect latency: a HANG is
        # by construction dead_after_s of silent heartbeats old when the
        # verdict lands; a CRASH is caught within one poll tick.
        detection_s = self.poll_interval
        if self.monitor is not None and any(
                f.kind is FailureKind.HANG for f in failures):
            detection_s = self.monitor.config.dead_s
        self._event("goodput_incident", incident=incident,
                    action=decision.action.value,
                    planned=False,
                    downtime_s=round(mttr, 4),
                    detection_s=round(detection_s, 4),
                    fleet_step=self._last_fleet_step,
                    **extra)
        if self.tracer is not None:
            self.tracer.record("ft_recover", start=t_detect, dur_s=mttr,
                               trace_id=incident,
                               action=decision.action.value,
                               hosts=list(decision.hosts))
        return None

    # -- graceful degradation (ISSUE 7) -----------------------------------

    def _refresh_ckpt_blacklist(self) -> None:
        """Expire the corruption blacklist once the run has finalized a
        step NEWER than everything on it: the re-run has re-saved past
        the quarantined artifact, and keeping the stale blacklist would
        make every later ordinary restart skip a perfectly good latest
        checkpoint and silently rewind a full interval of real work.
        The retry budget re-arms with it — its job is to stop loops on
        the SAME artifacts, and those are gone."""
        if not self._ckpt_blacklist or self.ckpt_dir is None:
            return
        newest = _latest_finalized_step(self.ckpt_dir,
                                        exclude=self._ckpt_blacklist)
        if newest is not None and newest > max(self._ckpt_blacklist):
            self._event("ckpt_blacklist_expired",
                        blacklist=sorted(self._ckpt_blacklist),
                        newest_step=newest)
            self._ckpt_blacklist.clear()
            self._ckpt_retries = 0
            self.launcher.extra_env.pop(CKPT_BLACKLIST_ENV, None)

    def _host_lost(self, host_id: int) -> bool:
        """Is this host gone for good?  Chaos ``lose_host`` marks it
        directly; otherwise the control plane is asked through
        ``reacquire_check(address)`` — best-effort, because a flaky
        control-plane answer must degrade to a same-size restart, not
        block recovery."""
        if host_id in self._lost_hosts:
            return True
        if self.reacquire_check is None:
            return False
        hosts = self.launcher.contract.hosts()[
            : self.launcher.contract.workers_count]
        if not 0 <= host_id < len(hosts):
            return False
        try:
            return not self.reacquire_check(hosts[host_id])
        except Exception:  # noqa: BLE001 — see docstring
            return False

    def _drain_restart(self, incident: int, decision: Decision,
                       failures: list[Failure], t_detect: float) -> None:
        """Preemption drain: converge the gang on one step boundary via
        the drain file, let every rank force-save and exit clean, then
        relaunch as a PLANNED restart — zero lost work, zero budget.
        The drain target is fleet max step + margin so laggards can
        still reach it inside the notice's lead time."""
        leads = [f.lead_s for f in failures
                 if f.kind is FailureKind.PREEMPT and f.lead_s]
        grace = min([*leads, self.drain_grace_s]) if leads \
            else self.drain_grace_s
        target = None
        if self._last_fleet_step is not None:
            target = self._last_fleet_step + self.drain_step_margin
        # Input hosts don't watch drain.json (they have no step to
        # converge on) — stop them up front (SIGTERM drains the service
        # cleanly) so the wait below covers only trainer ranks instead
        # of burning the whole grace on a role that can never exit it.
        input_live = [h for h in self._procs if h in self.input_host_ids]
        if input_live:
            self._stop_hosts(input_live)
        drain_file = None
        if self.ft_dir is not None:
            drain_file = request_drain(self.ft_dir, step=target)
            self._j("drain_armed", incident=incident, step=target)
        self._event("drain", incident=incident, hosts=list(decision.hosts),
                    step=target, grace_s=round(grace, 3),
                    file=None if drain_file is None else str(drain_file))
        escalated = 0
        if drain_file is not None:
            deadline = self.clock() + grace
            while (any(p.poll() is None for p in self._procs.values())
                   and self.clock() < deadline):
                self.sleep(self.poll_interval)
        leftovers = [p for p in self._procs.values() if p.poll() is None]
        if leftovers:
            # No drain channel (ft_dir unset), or the lead time ran out:
            # stop the stragglers the hard way.  Still a planned
            # restart — the preemption was coming either way — just a
            # less graceful one, and the event says so.
            escalated = self.launcher.stop_all(
                leftovers, grace_s=self.term_grace_s,
                poll_interval=self.poll_interval)
        dirty = sorted(h for h, p in self._procs.items()
                       if p.poll() not in (0, None))
        self._procs.clear()
        if self.ft_dir is not None:
            # A relaunched gang polling a stale drain file would
            # immediately drain itself again.
            clear_drain(self.ft_dir)
        extra: dict = {}
        # A preempted host the control plane will not give back turns
        # the planned relaunch into a planned shrink.
        lost = {h for h in self.host_ids if self._host_lost(h)}
        if (lost and self.allow_shrink
                and len(self.host_ids) - len(lost) >= 1):
            extra["shrink"] = self._do_shrink(incident, lost)
        self._launch_gang(first=False)
        crash_point("before_commit", self.ft_dir)
        self._j("restart_commit", incident=incident,
                action=decision.action.value)
        self.coord_pending_g.set(0)
        self.ft_preempt_drains_c.add()
        self.ft_planned_restarts_c.add()
        mttr = self.clock() - t_detect
        self.ft_planned_mttr_s.observe(mttr)
        self._event("recovered", incident=incident,
                    action=decision.action.value, planned=True,
                    mttr_s=round(mttr, 4), escalated=escalated,
                    dirty_exits=dirty, **extra)
        self._event("goodput_incident", incident=incident,
                    action=decision.action.value, planned=True,
                    downtime_s=round(mttr, 4),
                    detection_s=round(self.poll_interval, 4),
                    fleet_step=self._last_fleet_step, **extra)
        if self.tracer is not None:
            self.tracer.record("ft_recover", start=t_detect, dur_s=mttr,
                               trace_id=incident,
                               action=decision.action.value,
                               hosts=list(decision.hosts))
        return None

    def _do_shrink(self, incident: int, lost: set[int]) -> dict:
        """Re-converge the contract at N-k (stopped gang assumed):
        survivors renumber to 0..N-k-1, the monitor re-scopes (the old
        highest ids' heartbeat files must stop being judged), and the
        launcher's next launch uses the new generation's hostfile.  The
        caller relaunches."""
        old_n = len(self.host_ids)
        new_contract = shrink_contract(self.launcher.contract, sorted(lost))
        self.launcher.contract = new_contract
        new_n = new_contract.workers_count
        if self.monitor is not None:
            for h in range(new_n, old_n):
                self.monitor.retire_host(h)
            self.monitor.set_expected_hosts(new_n)
        self.host_ids = list(range(new_n))
        # Renumbered ids make the old lost-markers meaningless; a host
        # lost in the NEW numbering will be re-marked when it fails.
        self._lost_hosts.clear()
        self.ft_shrinks_c.add()
        info = {"from_hosts": old_n, "to_hosts": new_n,
                "lost": sorted(lost),
                "generation": new_contract.generation}
        self._j("shrink", incident=incident, **info)
        self._event("shrink", incident=incident, **info)
        return info

    def _ckpt_retry(self, incident: int, bad_step: int,
                    t_detect: float) -> None:
        """Blacklist + quarantine the checkpoint that failed to restore
        and relaunch to resume from the previous finalized step.  The
        quarantine rename is what frees the step number for a fresh
        save after the re-run; the env blacklist is the belt-and-braces
        for ranks whose manager opened before the rename (or if the
        rename failed)."""
        self._ckpt_retries += 1
        self._ckpt_blacklist.add(bad_step)
        self._j("ckpt_retry", incident=incident, bad_step=bad_step,
                blacklist=sorted(self._ckpt_blacklist))
        self._j("restart_intent", incident=incident, action="ckpt_retry",
                hosts=[], budget_used=self.policy.budget.used)
        self.coord_pending_g.set(1)
        crash_point("after_intent", self.ft_dir)
        self.ft_ckpt_retries_c.add()
        quarantine = None
        src = self.ckpt_dir / str(bad_step)
        if src.is_dir():
            dst = self.ckpt_dir / "corrupt" / str(bad_step)
            try:
                dst.parent.mkdir(parents=True, exist_ok=True)
                src.rename(dst)
                quarantine = str(dst)
            except OSError:
                pass  # blacklist env still steers the resume past it
        self.launcher.extra_env[CKPT_BLACKLIST_ENV] = \
            format_ckpt_blacklist(self._ckpt_blacklist)
        retry_from = _latest_finalized_step(self.ckpt_dir,
                                            exclude=self._ckpt_blacklist)
        ckpt_info = {"bad_step": bad_step, "retry_from": retry_from}
        self._event("ckpt_retry", incident=incident,
                    blacklist=sorted(self._ckpt_blacklist),
                    quarantine=quarantine, **ckpt_info)
        self._stop_hosts(list(self._procs))
        self._launch_gang(first=False)
        crash_point("before_commit", self.ft_dir)
        self._j("restart_commit", incident=incident, action="ckpt_retry")
        self.coord_pending_g.set(0)
        self.ft_gang_restarts_c.add()
        self.ft_restarts_c.add()
        self.restarts_c.add()
        mttr = self.clock() - t_detect
        self.ft_mttr_s.observe(mttr)
        self._event("recovered", incident=incident, action="ckpt_retry",
                    mttr_s=round(mttr, 4), ckpt=ckpt_info)
        self._event("goodput_incident", incident=incident,
                    action="ckpt_retry", planned=False,
                    downtime_s=round(mttr, 4),
                    detection_s=round(self.poll_interval, 4),
                    fleet_step=self._last_fleet_step, ckpt=ckpt_info)
        if self.tracer is not None:
            self.tracer.record("ft_recover", start=t_detect, dur_s=mttr,
                               trace_id=incident, action="ckpt_retry",
                               hosts=[])
        return None


def _latest_finalized_step(ckpt_dir: str | Path,
                           exclude: set[int] | frozenset[int] = frozenset()
                           ) -> int | None:
    """Latest finalized checkpoint step by scanning the directory —
    finalized step dirs are bare numbers; in-flight orbax saves carry a
    tmp suffix and quarantined corrupt steps live under ``corrupt/``,
    so neither matches.  (Orbax's own ``latest_step()`` serves a list
    cached at manager init, which the supervisor never opened.)"""
    try:
        entries = list(Path(ckpt_dir).iterdir())
    except OSError:
        return None
    steps = [int(p.name) for p in entries
             if p.is_dir() and p.name.isdigit()
             and int(p.name) not in exclude]
    return max(steps, default=None)
