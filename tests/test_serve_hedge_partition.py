"""``net_partition`` drill for the serve router's hedging path
(ISSUE 19 satellite): a :class:`~tpucfn.net.proxy.ChaosProxy` sits in
front of ONE replica's (real, TCP) engine backend and silently drops
its bytes — the gray failure where the connection stays up but answers
never come.  The drill pins that

* a **hedge** fired onto the healthy replica delivers the bit-identical
  answer inside the deadline bound while the partitioned attempt is
  still hanging, and
* without hedging, the partitioned attempt's timeout converts into a
  **failover** retry that also lands the identical answer in budget.

The router runs unthreaded (scripted pumps, FakeClock for hedge
scheduling) so the interleaving is deterministic; the partition itself
is real — engine calls genuinely block on a socket until their recv
timeout fires.
"""

import socket
import socketserver
import threading
import time

import pytest

from tpucfn.net.proxy import ChaosProxy
from tpucfn.obs import MetricRegistry
from tpucfn.serve import ReplicaFailed, ReplicaRouter, Server

RECV_TIMEOUT_S = 0.3
DEADLINE_S = 5.0


class _TokenHandler(socketserver.StreamRequestHandler):
    """One request line per connection: ``P <ids...>`` -> prefill token,
    ``D <slot:tok,...>`` -> decode tokens.  Same deterministic math as
    the router tests' FakeEngine, just on the far side of a socket."""

    def handle(self):
        line = self.rfile.readline().decode().strip()
        if not line:
            return
        op, _, rest = line.partition(" ")
        if op == "P":
            out = str(sum(int(t) for t in rest.split()) % 97)
        else:
            pairs = (p.split(":") for p in rest.split(",") if p)
            out = ",".join(f"{s}:{(int(t) * 7 + 1) % 97}" for s, t in pairs)
        self.wfile.write((out + "\n").encode())


class _TokenServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class NetEngine:
    """Engine whose prefill/decode are REAL TCP round-trips to the token
    server — through whatever address it is given, which is where the
    chaos proxy slots in.  A partition upstream shows up here exactly as
    it would in production: the call hangs, then times out."""

    def __init__(self, address, max_batch=4, cache_len=64):
        host, _, port = address.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self.max_batch = max_batch
        self.cache_len = cache_len

    def _ask(self, line):
        with socket.create_connection(self._addr,
                                      timeout=RECV_TIMEOUT_S) as s:
            s.sendall((line + "\n").encode())
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = s.recv(256)  # raises socket.timeout if partitioned
                if not chunk:
                    raise RuntimeError("token server hung up")
                buf += chunk
        return buf.decode().strip()

    def prefill(self, slot, prefix, bucket, temperature=0.0):
        return int(self._ask("P " + " ".join(str(t) for t in prefix)))

    def decode(self, tokens_by_slot):
        line = "D " + ",".join(f"{s}:{t}" for s, t in tokens_by_slot.items())
        return {int(s): int(t) for s, t in
                (p.split(":") for p in self._ask(line).split(","))}


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture()
def token_server():
    srv = _TokenServer(("127.0.0.1", 0), _TokenHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield f"127.0.0.1:{srv.server_address[1]}"
    finally:
        srv.shutdown()
        srv.server_close()


def _make_router(addresses, clock, **kw):
    engines = [NetEngine(a) for a in addresses]

    def factory(i):
        return Server(engines[i], num_blocks=64, block_size=8)

    kw.setdefault("registry", MetricRegistry())
    return ReplicaRouter(factory, len(addresses), clock=clock, **kw)


def pump(router, i):
    try:
        router.replicas[i].server.run_until_idle()
    except ReplicaFailed:
        pass


def _reference_tokens(upstream, prompt, n):
    """The clean-path answer: both replicas straight at the server."""
    router = _make_router([upstream, upstream], FakeClock())
    req = router.submit(prompt, max_new_tokens=n, deadline_s=DEADLINE_S)
    pump(router, req.attempts[0].replica)
    assert req.status == "ok"
    return list(req.tokens)


PROMPT = [3, 1, 4, 1, 5]
N_NEW = 4


def test_hedge_beats_partition_inside_deadline(token_server):
    ref = _reference_tokens(token_server, PROMPT, N_NEW)

    with ChaosProxy(token_server).start() as proxy:
        clk = FakeClock()
        # replica 0 talks through the proxy, replica 1 goes direct
        router = _make_router([proxy.address, token_server], clk,
                              hedge_ms=100.0)
        proxy.inject("partition", direction="both")  # answers stop dead
        t0 = time.monotonic()
        req = router.submit(PROMPT, max_new_tokens=N_NEW,
                            deadline_s=DEADLINE_S)
        primary = req.attempts[0]
        clk.advance(0.2)  # straggler threshold passes -> hedge is due
        assert router._fire_due_hedges() == 1
        hedge = next(a for a in req.attempts if a.hedge)
        assert hedge.replica != primary.replica
        # the healthy replica races ahead while the partitioned attempt
        # is still queued behind a dead socket
        pump(router, hedge.replica)
        elapsed = time.monotonic() - t0
        assert req.status == "ok" and req.done.is_set()
        assert list(req.tokens) == ref, "hedged answer must be bit-identical"
        assert router.hedges_c.value == 1
        assert router.hedges_won_c.value == 1
        assert elapsed < DEADLINE_S, "hedge must deliver inside the deadline"
        assert elapsed < RECV_TIMEOUT_S, \
            "the win must not have waited out the partition timeout"
        # the partitioned loser genuinely hits the timeout and cannot
        # re-deliver or change the answer
        pump(router, primary.replica)
        assert list(req.tokens) == ref
        assert router.completed_c.value == 1


def test_partition_timeout_fails_over_inside_deadline(token_server):
    ref = _reference_tokens(token_server, PROMPT, N_NEW)

    with ChaosProxy(token_server).start() as proxy:
        clk = FakeClock()
        router = _make_router([proxy.address, token_server], clk)
        proxy.inject("partition", direction="both")
        t0 = time.monotonic()
        req = router.submit(PROMPT, max_new_tokens=N_NEW,
                            deadline_s=DEADLINE_S)
        first = req.attempts[0]
        assert first.replica == 0
        # pump the partitioned replica FIRST: its engine call must hang
        # until the socket timeout, fail the attempt, and trigger the
        # router's deadline-budgeted failover to the healthy replica
        pump(router, 0)
        assert req.status != "ok"
        retry = req.attempts[-1]
        assert retry.replica == 1 and req.retries >= 1
        pump(router, 1)
        elapsed = time.monotonic() - t0
        assert req.status == "ok"
        assert list(req.tokens) == ref, "failover answer must be identical"
        assert elapsed >= RECV_TIMEOUT_S, \
            "the partition must actually have been waited out"
        assert elapsed < DEADLINE_S
        assert router.retries_c.value >= 1
        assert proxy.dropped_c.value > 0, "partition never dropped bytes"
