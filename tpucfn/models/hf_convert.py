"""HuggingFace Llama / Mixtral checkpoint import.

The adoption path for users arriving with standard weights: map a HF
``LlamaForCausalLM`` (or ``MixtralForCausalLM``) state dict onto the
tpucfn param tree (same rotate-half RoPE convention, so the mapping is
transpose/stack only — no head permutation) and derive
:class:`LlamaConfig` from the HF config. The parity tests pin our
models' logits against the canonical HF torch implementations on tiny
random models — a cross-implementation correctness check of
attention/RoPE/RMSNorm/SwiGLU (and, for Mixtral, the MoE routing/
expert math), not just plumbing.

Mixtral routing equivalence: HF's sparse MoE block softmaxes ALL
router logits, takes top-k, and renormalizes the kept probabilities —
literally the same order as tpucfn's ``_route``. The only semantic
difference is that HF is dropless while tpucfn is capacity-based, so
the import pins ``capacity_factor = E / top_k`` (capacity = every
token, exactly dropless for ANY routing; lower it after import if you
accept drops for memory).

Torch is only needed at conversion time (CPU is fine); nothing else in
tpucfn imports it.
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from tpucfn.models.llama import LlamaConfig


def config_from_hf(hf_config: Any, **overrides) -> LlamaConfig:
    """LlamaConfig from a transformers ``LlamaConfig``-like object.

    Raises on HF features tpucfn's Llama does not implement rather than
    converting to silently-wrong numerics."""
    import dataclasses

    scaling = getattr(hf_config, "rope_scaling", None)
    if scaling not in (None, {}):
        raise NotImplementedError(
            f"rope_scaling={scaling!r} is not implemented in tpucfn's RoPE "
            "(plain theta frequencies); converting would produce silently "
            "wrong positions (Llama-3.1+ checkpoints use this)")
    explicit_hd = getattr(hf_config, "head_dim", None)
    derived_hd = hf_config.hidden_size // hf_config.num_attention_heads
    if explicit_hd not in (None, derived_hd):
        raise NotImplementedError(
            f"head_dim={explicit_hd} != hidden_size//num_heads={derived_hd}: "
            "tpucfn's LlamaConfig derives head_dim, so this checkpoint's "
            "projection shapes cannot be represented")
    cfg = LlamaConfig(
        vocab_size=hf_config.vocab_size,
        dim=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(hf_config, "num_key_value_heads",
                           hf_config.num_attention_heads),
        ffn_dim=hf_config.intermediate_size,
        max_seq=hf_config.max_position_embeddings,
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        norm_eps=float(hf_config.rms_norm_eps),
    )
    return dataclasses.replace(cfg, **overrides) if overrides else cfg


def _np(x) -> np.ndarray:
    """Torch tensor → numpy, PRESERVING dtype: a forced fp32 copy would
    quadruple host RAM on a real bf16 checkpoint (Mixtral-8x7B's expert
    stack alone is ~90 GB in fp32). bf16 has no native numpy dtype, so
    it round-trips through a uint16 view into ``ml_dtypes.bfloat16``
    (the dtype jax arrays use anyway)."""
    if hasattr(x, "detach"):
        t = x.detach().cpu()
        import torch

        if t.dtype == torch.bfloat16:
            import ml_dtypes

            return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
        x = t.numpy()
    return np.asarray(x)


def _convert_hf_state_dict(state_dict: Mapping[str, Any],
                           cfg: LlamaConfig, mlp_fn) -> dict:
    """Shared HF→tpucfn mapping core: embed, tied-or-separate lm_head,
    attention projections, norms, and the leftover-tensor refusal are
    identical across architectures; ``mlp_fn(take, lstack)`` supplies
    the per-architecture MLP sub-dict (dense SwiGLU for Llama, router +
    stacked experts for Mixtral). Torch Linear stores (out, in); flax
    DenseGeneral kernels are (in, out) — ``lstack`` transposes."""
    if not cfg.scan_layers:
        raise NotImplementedError(
            "HF import targets the scanned layout (cfg.scan_layers=True) — "
            "the unrolled layout is a test-only configuration")
    sd = state_dict
    L = cfg.n_layers
    consumed: set[str] = set()

    def take(name):
        consumed.add(name)
        return _np(sd[name])

    def lstack(fmt, transpose=True):
        mats = [take(fmt.format(i=i)) for i in range(L)]
        if transpose:
            mats = [m.T for m in mats]
        return np.stack(mats)

    embed = take("model.embed_tokens.weight")
    if "lm_head.weight" in sd:
        lm_head = take("lm_head.weight").T
    else:
        lm_head = embed.T.copy()

    layers = {
        "attn": {p: {"kernel": lstack(
            "model.layers.{i}.self_attn.%s.weight" % p)}
            for p in ("q_proj", "k_proj", "v_proj", "o_proj")},
        "mlp": mlp_fn(take, lstack),
        "input_norm": {"scale": lstack(
            "model.layers.{i}.input_layernorm.weight", transpose=False)},
        "post_attn_norm": {"scale": lstack(
            "model.layers.{i}.post_attention_layernorm.weight",
            transpose=False)},
    }
    params = {
        "embed_tokens": {"embedding": embed},
        "layers": layers,
        "final_norm": {"scale": take("model.norm.weight")},
        "lm_head": {"kernel": lm_head},
    }
    # A dropped tensor is silently-wrong logits (e.g. attention biases
    # from attention_bias=True checkpoints) — refuse instead.
    ignorable = {k for k in sd
                 if k.endswith("rotary_emb.inv_freq")}  # legacy buffer
    leftover = sorted(set(sd) - consumed - ignorable)
    if leftover:
        raise NotImplementedError(
            f"unmapped tensors in the HF state dict (first 5: "
            f"{leftover[:5]}) — this checkpoint uses features tpucfn "
            "does not implement (e.g. attention biases)")
    return params


def params_from_hf_state_dict(state_dict: Mapping[str, Any],
                              cfg: LlamaConfig) -> dict:
    """HF Llama ``model.state_dict()`` → the tpucfn param tree
    (scan-stacked when ``cfg.scan_layers``)."""
    def mlp(take, lstack):
        return {p: {"kernel": lstack("model.layers.{i}.mlp.%s.weight" % p)}
                for p in ("gate_proj", "up_proj", "down_proj")}

    return _convert_hf_state_dict(state_dict, cfg, mlp)


def from_hf_llama(hf_model: Any, **config_overrides
                  ) -> tuple[LlamaConfig, dict]:
    """(cfg, params) from a live ``transformers.LlamaForCausalLM``."""
    cfg = config_from_hf(hf_model.config, **config_overrides)
    return cfg, params_from_hf_state_dict(hf_model.state_dict(), cfg)


def config_from_hf_mixtral(hf_config: Any, **overrides) -> LlamaConfig:
    """LlamaConfig (with ``moe``) from a transformers ``MixtralConfig``.

    Capacity is pinned exactly dropless (see module docstring): the
    layer computes ``capacity = round(cf * T * k / E)``, so cf = E/k
    yields exactly T (round, not truncate — float dust must not shave
    one slot off when k does not divide E). Aux-loss coefficients are
    tpucfn defaults (they do not affect the forward)."""
    import dataclasses

    from tpucfn.models.moe import MoEConfig

    sliding = getattr(hf_config, "sliding_window", None)
    if sliding is not None:
        raise NotImplementedError(
            f"sliding_window={sliding} attention is not implemented "
            "(tpucfn attends full-causal); converting would silently "
            "change the attention pattern")
    base = config_from_hf(
        # MixtralConfig carries the same attention/embedding fields.
        hf_config)
    e = hf_config.num_local_experts
    k = hf_config.num_experts_per_tok
    cfg = dataclasses.replace(
        base, moe=MoEConfig(n_experts=e, top_k=k,
                            capacity_factor=float(e) / k))
    return dataclasses.replace(cfg, **overrides) if overrides else cfg


def params_from_hf_mixtral_state_dict(state_dict: Mapping[str, Any],
                                      cfg: LlamaConfig) -> dict:
    """HF Mixtral ``state_dict()`` → the tpucfn param tree. Attention,
    norms, embed and head map exactly as Llama (shared core); per-expert
    torch Linears w1/w3/w2 (gate/up/down, (out, in)) stack into the
    (E, D, F)/(E, F, D) expert kernels, and the router ``gate`` maps to
    ``router/kernel`` (D, E)."""
    if cfg.moe is None:
        raise ValueError("params_from_hf_mixtral_state_dict needs a MoE "
                         "config (use config_from_hf_mixtral)")
    E = cfg.moe.n_experts

    def mlp(take, lstack):
        def estack(w):  # (L, E, in, out) from per-layer per-expert Linears
            return np.stack([np.stack([take(
                f"model.layers.{i}.block_sparse_moe.experts.{e}.{w}.weight"
            ).T for e in range(E)]) for i in range(cfg.n_layers)])

        return {
            "router": {"kernel": lstack(
                "model.layers.{i}.block_sparse_moe.gate.weight")},
            # Mixtral MLP is w2(silu(w1 x) * w3 x) == our
            # wd(silu(x wg) * (x wu)).
            "experts/gate_proj/kernel": estack("w1"),
            "experts/up_proj/kernel": estack("w3"),
            "experts/down_proj/kernel": estack("w2"),
        }

    return _convert_hf_state_dict(state_dict, cfg, mlp)


def from_hf_mixtral(hf_model: Any, **config_overrides
                    ) -> tuple[LlamaConfig, dict]:
    """(cfg, params) from a live ``transformers.MixtralForCausalLM``."""
    cfg = config_from_hf_mixtral(hf_model.config, **config_overrides)
    return cfg, params_from_hf_mixtral_state_dict(hf_model.state_dict(), cfg)
