"""Mixture-of-Experts MLP with expert parallelism.

Net-new vs the reference (SURVEY.md §2.3: EP row — "experts sharded on
mesh axis"). GShard/Switch-style capacity-based routing expressed as
dense einsums: top-k routing builds one-hot dispatch/combine tensors, the
expert computation is a single batched matmul over the stacked expert
weights, and sharding the expert dimension over the ``expert`` mesh axis
makes XLA emit the dispatch/return all-to-alls. No ragged shapes, no
scatter — everything stays MXU-friendly and statically shaped (tokens
overflowing an expert's capacity are dropped, the standard TPU trade).

Param layout matches the preset conventions (``experts/...`` with a
leading expert dim, ``router/kernel``): tpucfn/parallel/presets.py rules
shard it as P(expert, fsdp, tensor).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    router_z_loss: float = 1e-3
    load_balance_loss: float = 1e-2


class MoEMLP(nn.Module):
    """Drop-in replacement for a dense SwiGLU MLP block."""

    ffn_dim: int
    moe: MoEConfig
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):  # (B, S, D) -> (B, S, D), plus aux losses via sow
        cfg = self.moe
        b, s, d = x.shape
        e = cfg.n_experts
        k = cfg.top_k
        n_tokens = b * s
        capacity = max(1, int(cfg.capacity_factor * n_tokens * k / e))

        # --- routing (fp32 for a stable softmax) -------------------------
        router_logits = nn.DenseGeneral(
            e, use_bias=False, dtype=jnp.float32, param_dtype=self.param_dtype,
            name="router",
        )(x.astype(jnp.float32)).reshape(n_tokens, e)
        probs = jax.nn.softmax(router_logits, axis=-1)

        gate_vals, expert_idx = jax.lax.top_k(probs, k)  # (T, k)

        # Position of each token in its chosen expert's buffer, assigned in
        # token order per (expert, k-slot) via a cumulative count.
        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.int32)  # (T, k, E)
        flatoh = onehot.reshape(n_tokens * k, e)
        pos_in_expert = (jnp.cumsum(flatoh, axis=0) - flatoh).reshape(n_tokens, k, e)
        pos_in_expert = (pos_in_expert * onehot).sum(-1)  # (T, k)
        within_cap = pos_in_expert < capacity  # overflow tokens dropped

        gate_vals = gate_vals * within_cap
        # Renormalize kept gates so each surviving token's weights sum to 1.
        denom = jnp.maximum(gate_vals.sum(-1, keepdims=True), 1e-9)
        gate_vals = gate_vals / denom

        # dispatch (T, E, C) one-hot; combine = dispatch * gate
        cap_oh = jax.nn.one_hot(pos_in_expert, capacity, dtype=jnp.float32)  # (T,k,C)
        disp = jnp.einsum("tke,tkc->tec", onehot.astype(jnp.float32),
                          cap_oh * within_cap[..., None])
        combine = jnp.einsum("tke,tkc,tk->tec", onehot.astype(jnp.float32),
                             cap_oh, gate_vals)

        # --- expert compute ----------------------------------------------
        xt = x.reshape(n_tokens, d)
        expert_in = jnp.einsum("tec,td->ecd", disp, xt.astype(jnp.float32)).astype(
            self.dtype
        )  # (E, C, D)

        wg = self.param("experts/gate_proj/kernel", nn.initializers.lecun_normal(),
                        (e, d, self.ffn_dim), self.param_dtype)
        wu = self.param("experts/up_proj/kernel", nn.initializers.lecun_normal(),
                        (e, d, self.ffn_dim), self.param_dtype)
        wd = self.param("experts/down_proj/kernel", nn.initializers.lecun_normal(),
                        (e, self.ffn_dim, d), self.param_dtype)

        h = nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, wg.astype(self.dtype))) \
            * jnp.einsum("ecd,edf->ecf", expert_in, wu.astype(self.dtype))
        expert_out = jnp.einsum("ecf,efd->ecd", h, wd.astype(self.dtype))  # (E, C, D)

        out = jnp.einsum("tec,ecd->td", combine, expert_out.astype(jnp.float32))
        out = out.reshape(b, s, d).astype(self.dtype)

        # --- aux losses (sown; the loss_fn adds them) --------------------
        # Switch load-balance: E * sum_e fraction_tokens_e * mean_prob_e
        token_frac = disp.sum((0, 2)) / jnp.maximum(disp.sum(), 1.0)
        prob_frac = probs.mean(0)
        lb = e * jnp.sum(token_frac * prob_frac) * cfg.load_balance_loss
        zl = jnp.mean(jax.nn.logsumexp(router_logits, axis=-1) ** 2) * cfg.router_z_loss
        self.sow("losses", "moe_aux", lb + zl)
        self.sow("metrics", "moe_dropped_frac",
                 1.0 - jnp.minimum(disp.sum() / (n_tokens * k), 1.0))
        return out


def collect_moe_aux(variables: dict) -> jax.Array:
    """Sum all sown MoE aux losses (0.0 if the model has no MoE layers)."""
    losses = variables.get("losses", {})
    total = 0.0
    for leaf in jax.tree.leaves(losses):
        total = total + jnp.sum(leaf)
    return jnp.asarray(total, jnp.float32)
