"""procgen-shapes dataset properties (tpucfn/data/shapes.py).

The dataset substitutes real CIFAR-10 in the end-to-end accuracy run
(zero-egress environment — SURVEY.md §4 integration-test row), so the
properties that make the substitution honest are pinned here:
determinism, balance, and hardness (a linear probe on raw pixels must
sit near chance — the class signal is geometry, not color/position).
"""

import numpy as np
import pytest

from tpucfn.data.shapes import (
    SHAPE_CLASSES,
    render_shape,
    synthetic_shapes,
    write_shapes_image_tree,
)


def test_deterministic_in_seed():
    a = [r["image"] for r in synthetic_shapes(20, seed=3)]
    b = [r["image"] for r in synthetic_shapes(20, seed=3)]
    c = [r["image"] for r in synthetic_shapes(20, seed=4)]
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    assert any(not np.array_equal(x, y) for x, y in zip(a, c))


def test_shapes_and_balance():
    rows = list(synthetic_shapes(40, seed=0))
    labels = [int(r["label"]) for r in rows]
    # Balanced round-robin labels, full uint8 HWC images.
    assert labels == [i % 10 for i in range(40)]
    for r in rows:
        assert r["image"].shape == (32, 32, 3)
        assert r["image"].dtype == np.uint8


def test_every_class_renders_nonempty():
    rs = np.random.RandomState(0)
    for y in range(len(SHAPE_CLASSES)):
        img = render_shape(y, rs).astype(np.float32)
        # The shape must be visible: some spatial variance beyond noise.
        assert img.std() > 10.0


def test_linear_probe_near_chance():
    """The hardness property: ridge regression on raw pixels must not
    get far above chance (10%). This is what separates procgen-shapes
    from the class-conditional-mean synthetic streams."""
    n_tr, n_te = 1500, 500
    tr = list(synthetic_shapes(n_tr, seed=0))
    te = list(synthetic_shapes(n_te, seed=9))
    Xtr = np.stack([r["image"].reshape(-1) for r in tr]).astype(np.float32) / 255.0
    ytr = np.asarray([int(r["label"]) for r in tr])
    Xte = np.stack([r["image"].reshape(-1) for r in te]).astype(np.float32) / 255.0
    yte = np.asarray([int(r["label"]) for r in te])
    W = np.linalg.solve(
        Xtr.T @ Xtr + 10.0 * np.eye(Xtr.shape[1]), Xtr.T @ np.eye(10)[ytr]
    )
    acc = float((np.argmax(Xte @ W, 1) == yte).mean())
    assert acc < 0.35, f"linear probe too strong ({acc:.3f}) — dataset leaks"


def test_image_tree_layout(tmp_path):
    root = write_shapes_image_tree(tmp_path / "tree", 20, seed=0)
    dirs = sorted(p.name for p in root.iterdir())
    assert dirs == sorted(SHAPE_CLASSES)
    pngs = list(root.rglob("*.png"))
    assert len(pngs) == 20
    from PIL import Image

    img = np.asarray(Image.open(pngs[0]))
    assert img.shape == (32, 32, 3)


def test_tree_matches_stream(tmp_path):
    """PNG round-trip is lossless: the tree and the stream agree, so the
    convert-dataset path trains on exactly the generated pixels."""
    from PIL import Image

    root = write_shapes_image_tree(tmp_path / "tree", 10, seed=5)
    stream = list(synthetic_shapes(10, seed=5))
    for i, row in enumerate(stream):
        cls = SHAPE_CLASSES[int(row["label"])]
        disk = np.asarray(Image.open(root / cls / f"{i:06d}.png"))
        np.testing.assert_array_equal(disk, row["image"])
