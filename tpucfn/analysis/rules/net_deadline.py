"""net-deadline: blocking socket ops in the fleet planes are reachable
only after a timeout/deadline is set on that socket (ISSUE 15).

The incident encoded: the fleet planes' ``recv_frame`` loops ran
per-chunk timeouts that a trickling peer reset forever, and one
``accept``/``connect``/``sendall`` on a timeout-less socket blocks
unboundedly — the gray-failure class the ``tpucfn.net`` deadline layer
exists to close.  This rule makes the rewiring a proven property
instead of a one-time cleanup: any NEW blocking socket op added to a
plane without a ``settimeout`` (or a deadline-layer call, which sets
one per chunk) fires here.

Scope and mechanics (deliberately provenance-based — conservative,
like every rule in the pack):

* Only modules that ``import socket`` are scanned; only names whose
  socket-ness is statically visible are tracked: ``socket.socket(...)``
  results, ``accept()`` results of tracked sockets, aliases and
  ``self.attr`` stores of those.
* A tracked socket becomes *deadlined* at ``x.settimeout(t)`` with a
  non-``None`` literal ``t`` (``settimeout(None)`` un-deadlines: that
  is blocking mode), and stays so through plain aliasing.  A
  ``self.attr`` is deadlined class-wide when ANY method settimeouts it
  or stores a deadlined local into it.
* Blocking ops: ``recv`` / ``recv_into`` / ``accept`` / ``connect`` /
  ``send`` / ``sendall``.  Flagged on a tracked, un-deadlined receiver
  — directly, or by passing it into a helper (same module) that blocks
  on the corresponding parameter without its own prior ``settimeout``,
  including one constructor hop (a class whose ``__init__`` stores the
  parameter into an attr some method blocks on).
* Unresolvable receivers (function parameters at the top of a call
  chain, returns of opaque calls) stay silent — the rule prefers a
  missed maybe-hazard to a false alarm, per the pack's standing rule.
"""

from __future__ import annotations

import ast

from tpucfn.analysis.core import Analysis, Finding, Module, sub_suites

RULE_ID = "net-deadline"

BLOCKING_OPS = frozenset(
    {"recv", "recv_into", "accept", "connect", "send", "sendall"})


def _imports_socket(mod: Module) -> bool:
    for node in mod.tree.body:
        if isinstance(node, ast.Import):
            if any(a.name == "socket" for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom) and node.module == "socket":
            return True
    return False


def _recv_name(node: ast.expr) -> str | None:
    """Normalized receiver identity: bare name, or ``self.attr`` as
    ``"self.attr"`` (other attribute chains are untracked)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return f"self.{node.attr}"
    return None


def _is_socket_ctor(value: ast.expr) -> bool:
    """``socket.socket(...)`` / bare ``socket(...)`` (from-import)."""
    if not isinstance(value, ast.Call):
        return False
    f = value.func
    if isinstance(f, ast.Attribute):
        return (f.attr == "socket" and isinstance(f.value, ast.Name)
                and f.value.id == "socket")
    return isinstance(f, ast.Name) and f.id == "socket"


def _accept_call(value: ast.expr) -> str | None:
    """Receiver name of an ``<recv>.accept()`` RHS, else None."""
    if isinstance(value, ast.Call) and isinstance(value.func, ast.Attribute) \
            and value.func.attr == "accept":
        return _recv_name(value.func.value)
    return None


class _ClassInfo:
    def __init__(self):
        self.deadlined_attrs: set[str] = set()   # settimeout'd somewhere
        self.blocking_attrs: dict[str, tuple[int, str]] = {}  # attr->(line,op)
        self.ctor_param_attrs: dict[str, str] = {}  # attr -> __init__ param


class _FuncScan:
    """One lexical pass over a function: tracks socket provenance and
    deadlined-ness per name, records blocking uses."""

    def __init__(self, rule, mod: Module, info, class_info: _ClassInfo | None):
        self.rule = rule
        self.mod = mod
        self.info = info
        self.class_info = class_info
        self.params = set(info.params)
        self.tracked: set[str] = set()     # names with socket provenance
        self.deadlined: set[str] = set()
        # params that received a blocking op before any settimeout —
        # this function's summary (callers must pass deadlined sockets)
        self.blocking_params: set[str] = set()
        self.findings: list[Finding] = []
        self._reported: set[tuple[str, str]] = set()  # (recv, op) dedupe

    # -- events ------------------------------------------------------------

    def _settimeout(self, recv: str, call: ast.Call) -> None:
        none_arg = (len(call.args) >= 1
                    and isinstance(call.args[0], ast.Constant)
                    and call.args[0].value is None)
        if none_arg:
            self.deadlined.discard(recv)
            return
        self.deadlined.add(recv)
        if recv.startswith("self.") and self.class_info is not None:
            self.class_info.deadlined_attrs.add(recv[5:])

    def _blocking_use(self, recv: str, op: str, line: int) -> None:
        if recv in self.deadlined:
            return
        if (recv, op) in self._reported:
            return  # e.g. an accept() seen by both _assign and _call
        self._reported.add((recv, op))
        if recv.startswith("self."):
            attr = recv[5:]
            if self.class_info is not None:
                self.class_info.blocking_attrs.setdefault(attr, (line, op))
            return  # resolved class-wide after all methods scanned
        if recv in self.params:
            self.blocking_params.add(recv)
            return
        if recv in self.tracked:
            self.findings.append(Finding(
                RULE_ID, self.mod.rel, line,
                f"blocking socket op {op!r} on {recv!r} in "
                f"{self.info.qualname} with no timeout/deadline set on "
                "that socket — a stalled or trickling peer blocks this "
                "call forever; settimeout() first (or route through the "
                "tpucfn.net deadline layer, which sets one per chunk)",
                key=f"netdl:{self.info.qualname}:{recv}:{op}"))

    def _assign(self, stmt: ast.Assign) -> None:
        v = stmt.value
        src: str | None = None
        fresh = False
        if _is_socket_ctor(v):
            fresh = True
        else:
            acc = _accept_call(v)
            if acc is not None:
                self._blocking_use(acc, "accept", stmt.lineno)
                fresh = True  # the accepted conn: a new, timeout-less socket
            elif isinstance(v, ast.Name) or (
                    isinstance(v, ast.Attribute)
                    and isinstance(v.value, ast.Name)
                    and v.value.id == "self"):
                src = _recv_name(v)
        for t in stmt.targets:
            names = []
            if isinstance(t, (ast.Tuple, ast.List)) and t.elts:
                # `conn, addr = s.accept()`: the socket is element 0
                n0 = _recv_name(t.elts[0])
                if n0 is not None:
                    names.append(n0)
            else:
                n = _recv_name(t)
                if n is not None:
                    names.append(n)
            for name in names:
                if fresh:
                    self.tracked.add(name)
                    self.deadlined.discard(name)
                elif src is not None and src in self.tracked:
                    self.tracked.add(name)
                    if src in self.deadlined:
                        self.deadlined.add(name)
                    else:
                        self.deadlined.discard(name)
                else:
                    # reassigned from something untracked: stop tracking
                    self.tracked.discard(name)
                    self.deadlined.discard(name)
                    continue
                if name.startswith("self.") and self.class_info is not None \
                        and src is not None and src in self.deadlined:
                    self.class_info.deadlined_attrs.add(name[5:])

    def _call(self, call: ast.Call, line: int) -> None:
        f = call.func
        if isinstance(f, ast.Attribute):
            recv = _recv_name(f.value)
            if recv is not None:
                if f.attr == "settimeout":
                    self._settimeout(recv, call)
                    return
                if f.attr in BLOCKING_OPS:
                    self._blocking_use(recv, f.attr, line)
                    return
        # passing a tracked socket into a helper that blocks on it
        blocking_idx = self.rule.blocking_param_indices(self.mod, call)
        if blocking_idx:
            for i, arg in enumerate(call.args):
                if i not in blocking_idx:
                    continue
                name = _recv_name(arg)
                if name is None:
                    continue
                self._blocking_use(name, f"arg{i} of helper", line)

    # -- the walk ----------------------------------------------------------

    def run(self) -> None:
        self._walk(self.info.node.body)

    def _walk(self, body) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested defs are their own scan
            if isinstance(stmt, ast.Assign):
                self._assign(stmt)
            for call in _calls_of(stmt):
                self._call(call, getattr(call, "lineno", stmt.lineno))
            for suite in sub_suites(stmt):
                self._walk(suite)


def _calls_of(stmt: ast.stmt):
    """Call nodes in this statement's own expressions (not nested
    suites — the walk recurses those, keeping lexical order), not
    inside nested defs/lambdas."""
    for field in stmt._fields:
        if field in ("body", "orelse", "finalbody", "handlers", "cases"):
            continue
        v = getattr(stmt, field, None)
        exprs = v if isinstance(v, list) else [v]
        for e in exprs:
            if isinstance(e, ast.withitem):
                e = e.context_expr
            if not isinstance(e, ast.expr):
                continue
            stack = [e]
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.Lambda, ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue
                if isinstance(node, ast.Call):
                    yield node
                stack.extend(ast.iter_child_nodes(node))


class _NetDeadlineRule:
    def __init__(self, analysis: Analysis):
        self.analysis = analysis
        # per-module: func qualname -> set of blocking param indices
        self._summaries: dict[str, dict[str, set[int]]] = {}
        self._class_infos: dict[str, dict[str, _ClassInfo]] = {}

    # -- summaries ---------------------------------------------------------

    def blocking_param_indices(self, mod: Module,
                               call: ast.Call) -> set[int]:
        """Which positional args of this call feed a parameter the
        callee blocks on without its own settimeout — bare-name helper
        calls and one-level constructor calls of same-module classes."""
        f = call.func
        summaries = self._summaries.get(mod.rel, {})
        if isinstance(f, ast.Name):
            if f.id in summaries:
                return summaries[f.id]
            # constructor hop: Cls(...) whose __init__ stores a param
            # into an attr some method blocks on, class-undeadlined
            cls_infos = self._class_infos.get(mod.rel, {})
            ci = cls_infos.get(f.id)
            if ci is not None:
                funcs = self.analysis.functions(mod)
                init = funcs.get(f"{f.id}.__init__")
                if init is not None:
                    params = [p for p in init.params if p != "self"]
                    out = set()
                    for attr, param in ci.ctor_param_attrs.items():
                        if attr in ci.blocking_attrs \
                                and attr not in ci.deadlined_attrs \
                                and param in params:
                            out.add(params.index(param))
                    return out
        return set()

    def check(self):
        findings: list[Finding] = []
        mods = [m for m in self.analysis.modules if _imports_socket(m)]
        for mod in mods:
            self._summaries[mod.rel] = {}
            self._class_infos[mod.rel] = {}
        # Two fixpoint rounds: round 1 builds per-function summaries
        # (direct blocking params) and class info; round 2 sees calls
        # into those summaries (the recv_frame -> _recv_exact chain and
        # the constructor hop).  Findings are taken from the LAST round
        # only — earlier rounds exist to converge the summaries.
        for round_ in range(2):
            last = round_ == 1
            for mod in mods:
                funcs = self.analysis.functions(mod)
                cls_infos = self._class_infos[mod.rel]
                scans: list[tuple[str, _FuncScan]] = []
                for q, info in funcs.items():
                    if isinstance(info.node, ast.Lambda):
                        continue
                    ci = None
                    if info.class_name is not None:
                        ci = cls_infos.setdefault(info.class_name,
                                                  _ClassInfo())
                    scan = _FuncScan(self, mod, info, ci)
                    scan.run()
                    scans.append((q, scan))
                    # __init__ param -> attr flow for the ctor hop
                    if ci is not None and q.endswith(".__init__"):
                        self._ctor_flow(info, ci)
                summ = self._summaries[mod.rel]
                for q, scan in scans:
                    # only module-level helpers are resolvable at their
                    # bare-name call sites; methods reach sockets via
                    # self-attrs, which the class resolution covers
                    if scan.blocking_params and "." not in q:
                        params = scan.info.params
                        summ[q] = {params.index(p)
                                   for p in scan.blocking_params}
                if last:
                    for _q, scan in scans:
                        findings.extend(scan.findings)
        if not mods:
            return findings
        # class-wide resolution: blocking attrs never deadlined
        # anywhere in the class, and not fed by a ctor param (those are
        # the caller's obligation, checked at the constructor call)
        for mod in mods:
            for cname, ci in self._class_infos[mod.rel].items():
                for attr, (line, op) in sorted(ci.blocking_attrs.items()):
                    if attr in ci.deadlined_attrs:
                        continue
                    if attr in ci.ctor_param_attrs:
                        continue
                    findings.append(Finding(
                        RULE_ID, mod.rel, line,
                        f"blocking socket op {op!r} on self.{attr} but no "
                        f"method of {cname} ever sets a timeout/deadline "
                        "on it — a stalled or trickling peer blocks "
                        "forever; settimeout() it (or route through the "
                        "tpucfn.net deadline layer)",
                        key=f"netdl:{cname}.{attr}:{op}"))
        return findings

    @staticmethod
    def _ctor_flow(init_info, ci: _ClassInfo) -> None:
        params = set(init_info.params) - {"self"}

        def walk(body):
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                if isinstance(stmt, ast.Assign) \
                        and isinstance(stmt.value, ast.Name) \
                        and stmt.value.id in params:
                    for t in stmt.targets:
                        name = _recv_name(t)
                        if name is not None and name.startswith("self."):
                            ci.ctor_param_attrs[name[5:]] = stmt.value.id
                for suite in sub_suites(stmt):
                    walk(suite)

        walk(init_info.node.body)


def check(analysis: Analysis):
    return _NetDeadlineRule(analysis).check()
