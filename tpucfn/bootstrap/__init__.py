from tpucfn.bootstrap.contract import EnvContract, converge  # noqa: F401
