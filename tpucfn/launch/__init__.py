from tpucfn.launch.launcher import (  # noqa: F401
    Launcher,
    LocalTransport,
    SSHTransport,
    initialize_runtime,
    run_with_restarts,
)
from tpucfn.launch.supervise import (  # noqa: F401
    run_supervised,
    supervised_cli_argv,
)
