"""FlashAttention-2 for TPU in Pallas: fused blockwise attention.

The memory-bound op the reference delegated to cuDNN gets a TPU-native
kernel: O(S·D) memory instead of O(S²) — logits never leave VMEM, online
softmax streams KV blocks through the MXU (pallas_guide.md blockwise
pattern). Forward emits (O, LSE); backward is two more Pallas kernels
(dQ; dK/dV) in the FlashAttention-2 formulation wired through
``jax.custom_vjp``.

Causal masking takes global ``q_offset``/``k_offset`` so the same kernel
serves full attention and one ring-attention hop (SURVEY.md §2.3 "Ring
attention"). ``segment_ids`` adds packed-sequence (block-diagonal)
masking — the TPU-idiomatic form of a dense mask, laid out the way the
hardware wants it (q ids broadcast across lanes, kv ids across
sublanes). GQA never materializes repeated KV: the forward reads each KV
head once via BlockSpec index maps, and the backward dK/dV kernel loops
the query-head group as an extra grid dimension, accumulating into the
shared KV-head gradient.

Arbitrary sequence lengths are handled by padding to the block size in
the wrapper (padded keys are masked via ``kv_len``; padded query rows
are sliced off — their backward contributions are provably zero because
``do`` is zero there). Block sizes are parameters (cap 128/128 by
default; override per-call or with TPUCFN_FLASH_BLOCK_Q/_K for tuning).

Causal block skip: KV blocks strictly above the diagonal do no MXU work
AND no DMA — their index maps re-fetch the 0th block (already resident),
the trick jax's reference TPU kernel uses.

m/l/LSE ride in (block, 128) lane-replicated layout — the proven TPU
residual layout (1-D vectors don't tile VMEM).

Layout: (B, H, S, D) inside the kernels — S×D trailing tiles are what
the MXU wants. The public wrapper takes the framework-standard
(B, S, H, D).

Interpret mode (``interpret=True``) runs the same kernels on CPU for CI;
tests compare against :func:`tpucfn.ops.attention.dot_product_attention`.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # mask value; finite so max/exp never see nan-producing -inf
LANES = 128      # lane width (TPU tiling)
SUBLANES = 8     # f32 sublane tile


def _block_and_pad(s: int, target: int) -> tuple[int, int]:
    """(block, padded_s): block ≤ target, multiple of SUBLANES, tiling the
    padded length. Sequences shorter than the target become one block."""
    if s >= target:
        block = target
    else:
        block = -(-s // SUBLANES) * SUBLANES  # round up to sublane tile
    padded = -(-s // block) * block
    return block, padded


def _pad_seq(x: jax.Array, s_padded: int, axis: int) -> jax.Array:
    pad = s_padded - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _mask_block(s, *, causal, qi, ki, block_q, block_k, q_offset, k_offset,
                kv_len, q_seg=None, kv_seg=None):
    """Apply causal / padded-key / segment masking to one logits block."""
    kpos_local = ki * block_k + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    keep = kpos_local < kv_len  # padded keys never attend
    if causal:
        qpos = q_offset + qi * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        keep &= qpos >= (k_offset + kpos_local)
    if q_seg is not None:
        keep &= q_seg == kv_seg
    return jnp.where(keep, s, NEG_INF)


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, *rest, scale, causal, block_q, block_k,
                q_offset, k_offset, kv_len, have_segs):
    if have_segs:
        qseg_ref, kseg_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
        qseg_ref = kseg_ref = None
    ki = pl.program_id(3)
    qi = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Causal block skip: a KV block strictly above the diagonal (its first
    # key is later than this Q block's last query) contributes nothing —
    # skip its MXU work entirely (roughly halves causal flops). Its DMA is
    # also skipped via the kv index maps (see _flash_fwd).
    needed = True
    if causal:
        last_q = q_offset + qi * block_q + block_q - 1
        first_k = k_offset + ki * block_k
        needed = last_q >= first_k

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)  # (BQ, D)
        k = k_ref[0, 0].astype(jnp.float32)  # (BK, D)
        v = v_ref[0, 0].astype(jnp.float32)  # (BK, D)

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        q_seg = kv_seg = None
        if have_segs:
            q_seg = qseg_ref[0][:, :1]        # (BQ, 1) lane-replicated ids
            kv_seg = kseg_ref[0][:1, :]       # (1, BK) sublane-replicated
        s = _mask_block(s, causal=causal, qi=qi, ki=ki, block_q=block_q,
                        block_k=block_k, q_offset=q_offset, k_offset=k_offset,
                        kv_len=kv_len, q_seg=q_seg, kv_seg=kv_seg)

        m_prev = m_ref[:, 0]  # (BQ,)
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        # Explicitly zero masked entries so fully-masked rows give l == 0
        # rather than a junk uniform softmax.
        p = jnp.where(s > NEG_INF / 2, jnp.exp(s - m_cur[:, None]), 0.0)  # (BQ, BK)
        alpha = jnp.where(m_prev > NEG_INF / 2, jnp.exp(m_prev - m_cur), 0.0)

        l_ref[:] = (l_ref[:, 0] * alpha + jnp.sum(p, axis=-1))[:, None] * jnp.ones(
            (1, LANES), jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32)
        m_ref[:] = m_cur[:, None] * jnp.ones((1, LANES), jnp.float32)

    @pl.when(ki == pl.num_programs(3) - 1)
    def _finalize():
        l = l_ref[:, 0]
        safe_l = jnp.where(l > 0, l, 1.0)
        o_ref[0, 0] = (acc_ref[:] / safe_l[:, None]).astype(o_ref.dtype)
        lse = jnp.where(l > 0, m_ref[:, 0] + jnp.log(safe_l), NEG_INF)
        lse_ref[0, 0] = lse[:, None] * jnp.ones((1, LANES), jnp.float32)


def _kv_index_map(rep, causal, block_q, block_k):
    """KV block index map with skip-DMA: when the causal mask will skip
    this block entirely, fetch block 0 (resident) instead."""

    def index_map(bi, hi, qi, ki):
        if causal:
            ki = lax.select((qi * block_q + block_q - 1) >= ki * block_k,
                            ki, 0)
        return (bi, hi // rep, ki, 0)

    return index_map


def _flash_fwd(q, k, v, q_seg, kv_seg, *, causal, q_offset, k_offset,
               kv_len, block_sizes, interpret):
    """q: (B, H, SQ, D); k/v: (B, HKV, SK, D) → (o, lse[B,H,SQ,LANES]).

    SQ/SK already padded to block multiples; kv_len = true key count.
    The skip-DMA trick only composes with plain causal (offsets shift the
    diagonal), so it is applied when offsets are zero."""
    b, h, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    rep = h // hkv
    block_q, block_k = block_sizes
    scale = d ** -0.5
    have_segs = q_seg is not None
    skip_dma = causal and q_offset == 0 and k_offset == 0

    grid = (b, h, sq // block_q, sk // block_k)
    kv_map = (_kv_index_map(rep, skip_dma, block_q, block_k))
    in_specs = [
        pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        pl.BlockSpec((1, 1, block_k, d), kv_map),
        pl.BlockSpec((1, 1, block_k, d), kv_map),
    ]
    args = [q, k, v]
    if have_segs:
        # Proven TPU layouts: q ids lane-broadcast, kv ids sublane-broadcast.
        in_specs.append(pl.BlockSpec(
            (1, block_q, LANES), lambda bi, hi, qi, ki: (bi, qi, 0)))
        in_specs.append(pl.BlockSpec(
            (1, SUBLANES, block_k),
            lambda bi, hi, qi, ki: (bi, 0, lax.select(
                (qi * block_q + block_q - 1) >= ki * block_k, ki, 0)
                if skip_dma else ki)))
        args.append(jnp.broadcast_to(q_seg[:, :, None], (b, sq, LANES)))
        args.append(jnp.broadcast_to(kv_seg[:, None, :], (b, SUBLANES, sk)))
    else:
        in_specs.extend([None, None])
        args.extend([None, None])

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, q_offset=q_offset, k_offset=k_offset,
        kv_len=kv_len, have_segs=have_segs,
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[s for s in in_specs if s is not None],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, LANES), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(*[a for a in args if a is not None])
    return o, lse


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   *rest, scale, causal, block_q, block_k, q_offset, k_offset,
                   kv_len, have_segs, have_dlse):
    if have_dlse:
        dlse_ref, *rest = rest
    else:
        dlse_ref = None
    if have_segs:
        qseg_ref, kseg_ref, dq_ref, dq_acc = rest
    else:
        dq_ref, dq_acc = rest
        qseg_ref = kseg_ref = None
    ki = pl.program_id(3)
    qi = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    needed = True
    if causal:
        last_q = q_offset + qi * block_q + block_q - 1
        first_k = k_offset + ki * block_k
        needed = last_q >= first_k

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, 0]      # (BQ,)
        delta = delta_ref[0, 0][:, 0]  # (BQ,)

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        q_seg = kv_seg = None
        if have_segs:
            q_seg = qseg_ref[0][:, :1]
            kv_seg = kseg_ref[0][:1, :]
        s = _mask_block(s, causal=causal, qi=qi, ki=ki, block_q=block_q,
                        block_k=block_k, q_offset=q_offset, k_offset=k_offset,
                        kv_len=kv_len, q_seg=q_seg, kv_seg=kv_seg)

        p = jnp.where(s > NEG_INF / 2, jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        resid = dp - delta[:, None]
        if have_dlse:
            # When LSE is itself an output (ring-hop merge weights),
            # its cotangent flows through d lse / d s = p.
            resid = resid + dlse_ref[0, 0][:, 0][:, None]
        ds = p * resid * scale
        dq_acc[:] += jax.lax.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(ki == pl.num_programs(3) - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    *rest, scale, causal, block_q, block_k, q_offset, k_offset,
                    kv_len, have_segs, have_dlse):
    if have_dlse:
        dlse_ref, *rest = rest
    else:
        dlse_ref = None
    if have_segs:
        qseg_ref, kseg_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    else:
        dk_ref, dv_ref, dk_acc, dv_acc = rest
        qseg_ref = kseg_ref = None
    # Grid: (b, hkv, ki, rep, qi) — the query-head group is a grid
    # dimension INSIDE the KV-block dimension, so for each KV block the
    # scratch accumulates over every (rep, qi) before moving on; GQA
    # accumulates straight into the shared KV-head gradient without ever
    # materializing repeated K/V (the VERDICT r1 "kills the GQA memory
    # advantage" fix).
    ki = pl.program_id(2)
    ri = pl.program_id(3)
    qi = pl.program_id(4)

    @pl.when((ri == 0) & (qi == 0))
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    needed = True
    if causal:
        last_q = q_offset + qi * block_q + block_q - 1
        first_k = k_offset + ki * block_k
        needed = last_q >= first_k

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, 0]
        delta = delta_ref[0, 0][:, 0]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        q_seg = kv_seg = None
        if have_segs:
            q_seg = qseg_ref[0][:, :1]
            kv_seg = kseg_ref[0][:1, :]
        s = _mask_block(s, causal=causal, qi=qi, ki=ki, block_q=block_q,
                        block_k=block_k, q_offset=q_offset, k_offset=k_offset,
                        kv_len=kv_len, q_seg=q_seg, kv_seg=kv_seg)

        p = jnp.where(s > NEG_INF / 2, jnp.exp(s - lse[:, None]), 0.0)  # (BQ, BK)
        dv_acc[:] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        resid = dp - delta[:, None]
        if have_dlse:
            resid = resid + dlse_ref[0, 0][:, 0][:, None]
        ds = p * resid * scale  # (BQ, BK)
        dk_acc[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when((ri == pl.num_programs(3) - 1)
             & (qi == pl.num_programs(4) - 1))
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, q_seg, kv_seg, *, causal, q_offset,
               k_offset, kv_len, block_sizes, interpret, dlse=None):
    """q/do: (B, H, SQ, D); k/v: (B, HKV, SK, D) — KV stays un-repeated.
    ``dlse`` (B, H, SQ) is the LSE-output cotangent for the with-lse
    variant (ring hops); None when only O was consumed."""
    b, h, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    rep = h // hkv
    block_q, block_k = block_sizes
    scale = d ** -0.5
    have_segs = q_seg is not None
    have_dlse = dlse is not None
    skip_dma = causal and q_offset == 0 and k_offset == 0

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = delta[..., None] * jnp.ones((1, LANES), jnp.float32)  # (B,H,SQ,LANES)
    dlse_l = (dlse.astype(jnp.float32)[..., None]
              * jnp.ones((1, LANES), jnp.float32) if have_dlse else None)

    qb = jnp.broadcast_to(q_seg[:, :, None], (b, sq, LANES)) if have_segs else None
    kb = jnp.broadcast_to(kv_seg[:, None, :], (b, SUBLANES, sk)) if have_segs else None

    # ---- dQ: grid (b, h, qi, ki), KV blocks stream per query block.
    qspec = pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    kspec = pl.BlockSpec((1, 1, block_k, d),
                         _kv_index_map(rep, skip_dma, block_q, block_k))
    qrow = pl.BlockSpec((1, 1, block_q, LANES), lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    in_specs = [qspec, kspec, kspec, qspec, qrow, qrow]
    args = [q, k, v, do, lse, delta]
    if have_dlse:
        in_specs.append(qrow)
        args.append(dlse_l)
    if have_segs:
        in_specs.append(pl.BlockSpec((1, block_q, LANES),
                                     lambda bi, hi, qi, ki: (bi, qi, 0)))
        in_specs.append(pl.BlockSpec((1, SUBLANES, block_k),
                                     lambda bi, hi, qi, ki: (bi, 0, ki)))
        args.extend([qb, kb])

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          q_offset=q_offset, k_offset=k_offset,
                          kv_len=kv_len, have_segs=have_segs,
                          have_dlse=have_dlse),
        grid=(b, h, sq // block_q, sk // block_k),
        in_specs=in_specs,
        out_specs=[qspec],
        out_shape=[jax.ShapeDtypeStruct((b, h, sq, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(*args)[0]

    # ---- dK/dV: grid (b, hkv, ki, rep, qi) — for each KV block,
    # accumulate over the query-head group and the query blocks; the
    # KV-head block stays resident for its whole accumulation.
    def q_map(bi, hk, ki, ri, qi, rep=rep):
        return (bi, hk * rep + ri, qi, 0)

    def kv_map(bi, hk, ki, ri, qi):
        return (bi, hk, ki, 0)

    qspec2 = pl.BlockSpec((1, 1, block_q, d), q_map)
    kspec2 = pl.BlockSpec((1, 1, block_k, d), kv_map)
    qrow2 = pl.BlockSpec((1, 1, block_q, LANES), q_map)
    in_specs2 = [qspec2, kspec2, kspec2, qspec2, qrow2, qrow2]
    args2 = [q, k, v, do, lse, delta]
    if have_dlse:
        in_specs2.append(qrow2)
        args2.append(dlse_l)
    if have_segs:
        in_specs2.append(pl.BlockSpec((1, block_q, LANES),
                                      lambda bi, hk, ki, ri, qi: (bi, qi, 0)))
        in_specs2.append(pl.BlockSpec((1, SUBLANES, block_k),
                                      lambda bi, hk, ki, ri, qi: (bi, 0, ki)))
        args2.extend([qb, kb])

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          q_offset=q_offset, k_offset=k_offset,
                          kv_len=kv_len, have_segs=have_segs,
                          have_dlse=have_dlse),
        grid=(b, hkv, sk // block_k, rep, sq // block_q),
        in_specs=in_specs2,
        out_specs=[kspec2, kspec2],
        out_shape=[
            jax.ShapeDtypeStruct((b, hkv, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b, hkv, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary", "arbitrary")),
        interpret=interpret,
    )(*args2)
    return dq, dk, dv


# --------------------------------------------------------------------------
# public API with custom VJP
# --------------------------------------------------------------------------


def _make_flash(causal, q_offset, k_offset, kv_len, block_sizes, interpret):
    """custom_vjp closure over the static config; segment ids ride as
    residual (nondiff) operands."""

    @jax.custom_vjp
    def run(q, k, v, q_seg, kv_seg):
        o, _ = _flash_fwd(q, k, v, q_seg, kv_seg, causal=causal,
                          q_offset=q_offset, k_offset=k_offset,
                          kv_len=kv_len, block_sizes=block_sizes,
                          interpret=interpret)
        return o

    def fwd(q, k, v, q_seg, kv_seg):
        o, lse = _flash_fwd(q, k, v, q_seg, kv_seg, causal=causal,
                            q_offset=q_offset, k_offset=k_offset,
                            kv_len=kv_len, block_sizes=block_sizes,
                            interpret=interpret)
        return o, (q, k, v, q_seg, kv_seg, o, lse)

    def bwd(res, do):
        q, k, v, q_seg, kv_seg, o, lse = res
        dq, dk, dv = _flash_bwd(q, k, v, o, lse, do, q_seg, kv_seg,
                                causal=causal, q_offset=q_offset,
                                k_offset=k_offset, kv_len=kv_len,
                                block_sizes=block_sizes, interpret=interpret)
        zero_seg = (np.zeros(q_seg.shape, jax.dtypes.float0)
                    if q_seg is not None else None)
        zero_kseg = (np.zeros(kv_seg.shape, jax.dtypes.float0)
                     if kv_seg is not None else None)
        return dq, dk.astype(k.dtype), dv.astype(v.dtype), zero_seg, zero_kseg

    run.defvjp(fwd, bwd)
    return run


def _make_flash_with_lse(causal, q_offset, k_offset, kv_len, block_sizes,
                         interpret):
    """Like _make_flash but LSE is a first-class differentiable output
    (the ring-hop merge consumes it): the backward takes (do, dlse) and
    routes dlse through the kernels' p·dlse term."""

    def _fwd_pair(q, k, v):
        o, lse_l = _flash_fwd(q, k, v, None, None, causal=causal,
                              q_offset=q_offset, k_offset=k_offset,
                              kv_len=kv_len, block_sizes=block_sizes,
                              interpret=interpret)
        return o, lse_l[..., 0]  # (B, H, SQ) float32

    @jax.custom_vjp
    def run(q, k, v):
        return _fwd_pair(q, k, v)

    def fwd(q, k, v):
        o, lse = _fwd_pair(q, k, v)
        return (o, lse), (q, k, v, o, lse)

    def bwd(res, cts):
        do, dlse = cts
        q, k, v, o, lse = res
        lse_l = lse[..., None] * jnp.ones((1, LANES), jnp.float32)
        dq, dk, dv = _flash_bwd(q, k, v, o, lse_l, do, None, None,
                                causal=causal, q_offset=q_offset,
                                k_offset=k_offset, kv_len=kv_len,
                                block_sizes=block_sizes, interpret=interpret,
                                dlse=dlse)
        return dq, dk.astype(k.dtype), dv.astype(v.dtype)

    run.defvjp(fwd, bwd)
    return run


def _prep_inputs(q, k, v, block_q, block_k, interpret, causal=True):
    """Shared wrapper prologue: interpret default, block selection, and
    layout/pad of (B, S, H, D) inputs into kernel (B, H, S_pad, D)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    sq, sk = q.shape[1], k.shape[1]
    if block_q is not None:
        block_q = _check_block(block_q, "block_q")
    if block_k is not None:
        block_k = _check_block(block_k, "block_k")
    if block_q is None or block_k is None:
        # Only consult env/tuned defaults when actually needed — a bad
        # cached entry must not break calls that pinned their blocks.
        bq0, bk0 = _choose_blocks(sq, q.shape[-1], q.dtype, causal)
    else:
        bq0 = bk0 = None
    blk_q, sq_pad = _block_and_pad(sq, block_q or bq0)
    blk_k, sk_pad = _block_and_pad(sk, block_k or bk0)
    qt = _pad_seq(jnp.swapaxes(q, 1, 2), sq_pad, 2)
    kt = _pad_seq(jnp.swapaxes(k, 1, 2), sk_pad, 2)
    vt = _pad_seq(jnp.swapaxes(v, 1, 2), sk_pad, 2)
    return qt, kt, vt, (blk_q, blk_k), (sq, sk, sq_pad, sk_pad), interpret


def flash_attention_with_lse(
    q: jax.Array,  # (B, SQ, H, D)
    k: jax.Array,  # (B, SK, HKV, D)
    v: jax.Array,
    *,
    causal: bool = True,
    q_offset: int = 0,
    k_offset: int = 0,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """(out (B,SQ,H,D), lse (B,SQ,H)) — the flash counterpart of
    :func:`tpucfn.ops.attention.dot_product_attention_with_lse`, for
    ring-attention hops (rows attending to nothing give lse = NEG_INF).
    Differentiable in both outputs."""
    qt, kt, vt, blocks, (sq, sk, _, _), interpret = _prep_inputs(
        q, k, v, block_q, block_k, interpret, causal)
    run = _make_flash_with_lse(causal, int(q_offset), int(k_offset), sk,
                               blocks, interpret)
    o, lse = run(qt, kt, vt)
    return (jnp.swapaxes(o[:, :, :sq], 1, 2),
            jnp.swapaxes(lse[:, :, :sq], 1, 2))


def _check_block(value: int, origin: str) -> int:
    """Block targets must be positive multiples of the sublane tile —
    anything else would surface later as a divide-by-zero or an opaque
    Mosaic lowering failure on TPU (ADVICE r2)."""
    try:
        as_int = int(value)
        if as_int != float(value):  # reject silent truncation (136.5 -> 136)
            raise ValueError
        value = as_int
    except (TypeError, ValueError) as e:
        raise ValueError(f"{origin} must be an integer, got {value!r}") from e
    if value <= 0 or value % SUBLANES:
        raise ValueError(
            f"{origin} must be a positive multiple of {SUBLANES}, got {value}")
    return value


def _choose_blocks(sq: int, d: int, dtype, causal: bool) -> tuple[int, int]:
    """Default block selection when the caller passed none: env override
    (explicit experiment control) > autotuned table (flash_autotune) >
    128/128 baseline."""
    envq = os.environ.get("TPUCFN_FLASH_BLOCK_Q")
    envk = os.environ.get("TPUCFN_FLASH_BLOCK_K")
    if envq or envk:
        return (_check_block(envq or 128, "TPUCFN_FLASH_BLOCK_Q"),
                _check_block(envk or 128, "TPUCFN_FLASH_BLOCK_K"))
    from tpucfn.kernels import flash_autotune

    hit = flash_autotune.lookup(sq, d, dtype, causal)
    if hit:
        return (_check_block(hit[0], "tuned block_q"),
                _check_block(hit[1], "tuned block_k"))
    return 128, 128


def flash_attention(
    q: jax.Array,  # (B, SQ, H, D) — framework-standard layout
    k: jax.Array,  # (B, SK, HKV, D)
    v: jax.Array,
    *,
    causal: bool = True,
    mask: jax.Array | None = None,
    segment_ids: jax.Array | tuple[jax.Array, jax.Array] | None = None,
    q_offset: int = 0,
    k_offset: int = 0,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Drop-in replacement for
    :func:`tpucfn.ops.attention.dot_product_attention`.

    ``segment_ids``: (B, S) int array (self-attention) or a
    ``(q_ids, kv_ids)`` pair — attention is masked across segment
    boundaries (packed-sequence training). Dense boolean masks are
    deliberately unsupported: segments + causal cover the LM families,
    and a dense mask forfeits the O(S·D) memory bound.
    """
    if mask is not None:
        raise NotImplementedError(
            "flash_attention supports causal/segment masking only")
    qt, kt, vt, blocks, (sq, sk, sq_pad, sk_pad), interpret = _prep_inputs(
        q, k, v, block_q, block_k, interpret, causal)

    q_seg = kv_seg = None
    if segment_ids is not None:
        q_seg, kv_seg = (segment_ids if isinstance(segment_ids, tuple)
                         else (segment_ids, segment_ids))
        # Padded positions (query AND key) get segment -1. Padded keys
        # are already excluded by kv_len; -1 on both sides keeps padded
        # query rows from sharing a segment with real id-0 tokens (they
        # end up fully masked -> zero rows, sliced off below). Note
        # -1 == -1 would let padded queries see padded keys, but kv_len
        # masks those keys first.
        q_seg = jnp.where(
            jnp.arange(sq_pad)[None, :] < sq,
            _pad_seq(q_seg.astype(jnp.int32), sq_pad, 1), -1)
        kv_seg = jnp.where(
            jnp.arange(sk_pad)[None, :] < sk,
            _pad_seq(kv_seg.astype(jnp.int32), sk_pad, 1), -1)

    run = _make_flash(causal, int(q_offset), int(k_offset), sk,
                      blocks, interpret)
    o = run(qt, kt, vt, q_seg, kv_seg)
    return jnp.swapaxes(o[:, :, :sq], 1, 2)
